"""Headline benchmark: logistic-GLM training throughput on one TPU chip.

Workload: BASELINE config-1 shape scaled up — L2-regularized logistic
regression via the on-device compiled L-BFGS loop — the per-iteration
broadcast + treeAggregate cycle that dominates the reference's wall-clock
(SURVEY.md §3.1). The problem carries a realistic feature-scale spread
(see ``_make_problem``), so both solvers run the full iteration budget and
the measurement is sustained per-iteration throughput. The objective uses
the fused one-pass Pallas value+grad kernel (``ops/pallas_glm.py``) —
measured 1.35x over the XLA two-pass closed form inside this exact solve
(0.145 s vs 0.196 s for 50 iterations at (200k, 1024) f32 on the axon
v5e, converging to the same objective value). The design stays f32: the
bf16 half-bandwidth path is another ~1.4x but rounds the design matrix
itself, which this parity-checked benchmark doesn't do.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` is the speedup of the compiled on-device solve over a
same-machine scipy L-BFGS-B solve on the identical problem (the closest
available stand-in for the reference's breeze/JVM driver-side solve; the
reference publishes no numbers — BASELINE.json published:{}).

NOTE timing sync: on the axon PJRT platform ``jax.block_until_ready`` does
not block; the reliable barrier is a device→host transfer (``float(x)``).
"""

from __future__ import annotations

import json
import time

import numpy as np

N_SAMPLES = 200_000
N_FEATURES = 1024
NNZ_PER_ROW = 64
L2 = 1.0
MAX_ITERS = 50


def _make_problem(seed=0):
    """Sparse-generated logistic data, densified (dense is the TPU-first
    layout at this dim — SURVEY.md §7 hard-parts #2).

    Feature columns carry a log-uniform scale spread (~3 decades), the
    shape of real name-term-value data (raw counts next to indicator
    features). This conditions the Hessian the way production GLM problems
    are conditioned, so the solve runs tens of L-BFGS iterations instead of
    terminating in a handful — the benchmark then measures sustained
    per-iteration throughput rather than ±1-iteration path noise."""
    rng = np.random.default_rng(seed)
    n, d, k = N_SAMPLES, N_FEATURES, NNZ_PER_ROW
    rows = np.repeat(np.arange(n, dtype=np.int32), k)
    cols = rng.integers(0, d, size=n * k, dtype=np.int32)
    col_scale = np.power(10.0, rng.uniform(-2.0, 1.0, size=d)).astype(np.float32)
    vals = (rng.normal(size=n * k).astype(np.float32) / np.sqrt(k)
            * col_scale[cols])
    x = np.zeros((n, d), np.float32)
    np.add.at(x, (rows, cols), vals)
    w_true = (rng.normal(size=d).astype(np.float32) / col_scale)
    margins = x @ w_true
    y = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-margins))).astype(np.float32)
    return x, y


def _scipy_baseline(x, y):
    import scipy.optimize

    xx = x.astype(np.float64)
    yy = y.astype(np.float64)

    def f(w):
        m = xx @ w
        ym = np.where(yy > 0.5, m, -m)
        loss = np.logaddexp(0.0, -ym).sum() + 0.5 * L2 * w @ w
        p = 1.0 / (1.0 + np.exp(-m))
        g = xx.T @ (p - yy) + L2 * w
        return loss, g

    t0 = time.perf_counter()
    res = scipy.optimize.minimize(
        f, np.zeros(N_FEATURES), jac=True, method="L-BFGS-B",
        options={"maxiter": MAX_ITERS, "ftol": 0.0, "gtol": 1e-12})
    return time.perf_counter() - t0, float(res.fun)


def _tpu_solve(x, y):
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.ops.design import DenseDesign
    from photon_ml_tpu.ops.losses import loss_for_task
    from photon_ml_tpu.ops.objective import GLMData, GLMObjective
    from photon_ml_tpu.optimize import OptimizerConfig, minimize_lbfgs
    from photon_ml_tpu.types import TaskType

    n = x.shape[0]
    data = GLMData(
        design=DenseDesign(x=jnp.asarray(x, jnp.float32)),
        labels=jnp.asarray(y),
        offsets=jnp.zeros((n,), jnp.float32),
        weights=jnp.ones((n,), jnp.float32),
    )
    # fused=True: the one-pass Pallas value+grad kernel (ops/pallas_glm.py,
    # lane-major round-2 formulation) — measured 1.35x over the XLA two-pass
    # closed form at this shape on the axon v5e
    objective = GLMObjective(loss=loss_for_task(TaskType.LOGISTIC_REGRESSION),
                             fused=True)
    cfg = OptimizerConfig(max_iterations=MAX_ITERS, tolerance=1e-12,
                          track_states=False)

    @jax.jit
    def solve(data):
        fun = lambda w: objective.value_and_grad(w, data, L2)
        return minimize_lbfgs(fun, jnp.zeros((N_FEATURES,), jnp.float32), cfg)

    result = solve(data)
    _ = float(result.value)  # compile + first run; D2H is the real barrier
    best = float("inf")
    for _rep in range(3):
        t0 = time.perf_counter()
        result = solve(data)
        val = float(result.value)
        best = min(best, time.perf_counter() - t0)
    return best, val, int(result.iterations)


def main():
    x, y = _make_problem()
    tpu_s, tpu_val, _iters = _tpu_solve(x, y)
    base_s, base_val = _scipy_baseline(x, y)
    rel = abs(tpu_val - base_val) / max(abs(base_val), 1.0)
    assert rel < 5e-3, f"objective mismatch: tpu={tpu_val} scipy={base_val}"
    # samples trained to convergence per second of solve wall-clock: honest
    # about early termination (counting iterations would reward replaying a
    # stalled point), and directly comparable across rounds
    throughput = N_SAMPLES / tpu_s
    print(json.dumps({
        "metric": "glm_logistic_lbfgs_samples_to_convergence_per_sec",
        "value": round(throughput, 1),
        "unit": "samples/s",
        "vs_baseline": round(base_s / tpu_s, 3),
    }))


if __name__ == "__main__":
    main()
