"""Benchmark suite: the reference's headline workloads on one TPU chip.

Emits one JSON line per metric — the HEADLINE metric (config-1-shaped GLM
L-BFGS throughput) first, then the GAME-path metrics (BASELINE configs 4–5
shapes), mixed precision, and ingest:

1. ``glm_logistic_lbfgs_samples_to_convergence_per_sec`` — L2 logistic via
   the on-device compiled L-BFGS loop with the fused Pallas value+grad
   kernel; ``vs_baseline`` = speedup over a same-host scipy L-BFGS-B solve
   of the identical problem (the closest stand-in for the reference's
   breeze/JVM solve; the reference publishes no numbers —
   BASELINE.json published:{}).
2. ``glm_logistic_bf16_design_...`` — the same solve with the design stored
   bfloat16 (the ``--design-dtype bfloat16`` product path): half the HBM
   traffic on the dominant payload; value parity asserted loosely (the
   design itself is rounded).
3. ``re_bucketed_solve_entities_per_sec`` — the random-effect hot loop
   (reference ``algorithm/RandomEffectCoordinate.scala``): 10^5+ power-law
   entities / 10^7 rows bucketed into fixed shapes and solved by vmapped
   compiled L-BFGS; ``vs_baseline`` = speedup over per-entity scipy solves
   (measured on a sample, scaled — the per-entity solves are independent).
4. ``game_cd_sweep_samples_per_sec`` — a full coordinate-descent sweep
   (fixed effect + two random effects, Yahoo!-Music-shaped) through
   GameEstimator, residual accounting and all (reference
   ``algorithm/CoordinateDescent.scala``); ``vs_baseline`` = speedup over a
   numpy/scipy implementation of the same sweep on a proportional slice
   (per-sample work is linear, documented inline).
5. ``avro_ingest_rows_per_sec`` — Avro container → columnar GameData
   through the C++ native decoder (reference ``AvroDataReader.scala``);
   ``vs_baseline`` = speedup over the pure-Python codec on the same data.
6. ``avro_scoring_write_rows_per_sec`` — columnar scores →
   ``ScoringResultAvro`` through the C++ native writer (reference
   ``GameScoringDriver.scala`` output); ``vs_baseline`` = speedup over the
   pure-Python record encoder at the same (null) codec.
7. ``game_end_to_end_rows_per_sec`` — the full GAME training driver on a
   music-shaped Avro file: ingest → index maps → bucket build → CD sweeps →
   model + metadata written (reference ``GameTrainingDriver.scala`` "Read
   data"→"Save models" wall — the number the north-star 200-executor-Spark
   comparison is actually about); ``vs_baseline`` = speedup over a composite
   of the SAME run's measured host rates (pure-Python ingest + host
   numpy/scipy CD sweep), i.e. 1/rate_e2e vs 1/rate_py_ingest +
   1/rate_host_cd — each component measured in this process, composition
   documented inline.

NOTE timing sync: on the axon PJRT platform ``jax.block_until_ready`` does
not block; the reliable barrier is a device→host transfer (``float(x)``).

NOTE compile budget: a fresh process pays ~10–40 s per XLA compile through
the axon remote-compile tunnel, across ~20 distinct shapes in this suite —
that (plus the since-fixed 45 s host bucket build) is what timed out the
round-2 harness run (BENCH_r02.json rc=124). main() therefore enables JAX's
persistent compilation cache (measured here: 66 s cold → 1.6 s warm for a
fresh process) keyed to the repo checkout, and the big Avro fixtures are
content-cached under the system temp dir so reruns skip the pure-Python
encode.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time

import numpy as np

N_SAMPLES = 200_000
N_FEATURES = 1024
NNZ_PER_ROW = 64
L2 = 1.0
MAX_ITERS = 50

# random-effect benchmark shape: "hundreds of millions of entities" is the
# reference's claim; 10^5+ entities / 10^7 rows is what one chip's bench
# minute buys while exercising the same bucketing machinery
RE_ENTITIES = 150_000
RE_ROWS = 10_000_000
RE_DIM = 8
RE_SCIPY_SAMPLE = 150  # entities timed on host, scaled (solves independent)

# CD-sweep shape (music-like: global + per-user + per-song)
CD_ROWS = 1_000_000
CD_D_FIXED = 32
CD_D_RE = 8
CD_USERS = 30_000
CD_SONGS = 10_000
CD_HOST_ROWS = 50_000  # host-baseline slice (scaled proportionally)

INGEST_ROWS = 120_000
INGEST_PY_ROWS = 12_000  # pure-Python codec rows (30x slower; scaled)

# end-to-end driver shape (music-like, sized so the TRAIN stage carries
# real compute — at 200k rows the metric measured driver fixed costs, not
# the pipeline; round-5 raised it to 1M rows / 55k entities)
E2E_ROWS = 1_000_000
E2E_USERS = 40_000
E2E_SONGS = 15_000


def _setup_compile_cache():
    import jax

    cache_dir = os.environ.get(
        "PHOTON_BENCH_COMPILE_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def _probe_op():
    """One trivial device round-trip; the D2H pull is the only reliable
    completion barrier on this platform (block_until_ready is a no-op)."""
    import jax
    import jax.numpy as jnp

    return float(jax.jit(lambda a: a * 2.0)(jnp.float32(1.0)))


def _probe_device(deadline_s: "float | None" = None):
    """Fail LOUDLY if the accelerator is unreachable instead of hanging.

    The device tunnel occasionally goes hard-down: the first device call
    then blocks forever in a native poll loop, the SIGTERM handler never
    runs (the main thread never re-enters Python), and the harness kill
    leaves an EMPTY artifact — `_emit_summary` has nothing to replay.
    This runs a trivial round-trip on the main thread under a watchdog
    thread; a healthy device finishes it in seconds (~20-40 s on a cold
    compile cache). On deadline the watchdog prints a terminal
    suite_summary line that NAMES the environment failure — the
    structured `error` + rc=3 shape `tools/bench_gate.py` classifies as
    `infra-failure` — then exits 3.

    The deadline defaults to 90 s (`PHOTON_BENCH_PROBE_TIMEOUT_S` to
    override): comfortably above the ~20-40 s healthy cold-cache probe,
    and well under the 300 s a dead tunnel used to burn before r05's
    artifact said anything (BENCH_r05.json: 300 s of silence for a
    tunnel that was down from second one)."""
    if deadline_s is None:
        deadline_s = float(os.environ.get(
            "PHOTON_BENCH_PROBE_TIMEOUT_S", 90.0))
    done = threading.Event()

    def _watch():
        if done.wait(deadline_s):
            return
        _emit_summary(error=(
            "device unreachable: a trivial device round-trip did not "
            f"complete within {deadline_s:.0f}s — accelerator tunnel "
            "down; nothing was measured"))
        os._exit(3)

    watchdog = threading.Thread(target=_watch, daemon=True)
    watchdog.start()
    try:
        value = _probe_op()
        assert value == 2.0, f"device probe computed {value}, expected 2.0"
    except Exception as e:
        # fail-FAST mode (connection refused, backend-init error): the
        # raise never reaches a try/finally that emits the summary, so
        # name the failure in a terminal line here before propagating
        _emit_summary(error=(
            f"device probe failed: {type(e).__name__}: {e}"))
        raise
    except BaseException as e:
        # SystemExit/KeyboardInterrupt (e.g. the SIGTERM handler's
        # SystemExit(124) from a harness timeout) is NOT a device
        # failure — label it as the interruption it is, then propagate
        _emit_summary(error=(
            f"interrupted during device probe: {type(e).__name__}: {e}"))
        raise
    finally:
        # cancel the watchdog on EVERY outcome: it exists to catch the
        # probe never returning. Leaving it armed after a fail-fast
        # exception would have it os._exit(3) in whatever the process
        # does next (observed: it hard-killed a pytest run 30 s later)
        done.set()


def _generator_tag(fn, args) -> str:
    """Cache key for a generator function, in two parts: an args hash
    (identifies the fixture VARIANT — several can be live at once, e.g.
    the big and small ingest files) then a code hash over bytecode +
    CONSTANTS (identifies the GENERATION — ``co_code`` alone stores only
    indices into ``co_consts``, so editing a literal like a seed or a
    scale would otherwise silently reuse stale data). The split lets the
    fixture cache GC dead generations of one variant without touching
    its siblings."""
    import hashlib

    ahash = hashlib.sha1(repr(args).encode()).hexdigest()[:8]
    chash = hashlib.sha1(
        fn.__code__.co_code + b"|"
        + repr(fn.__code__.co_consts).encode()).hexdigest()[:8]
    return f"{ahash}-{chash}"


def _fixture_path(name: str, fn, args, ext: str) -> "tuple[str, bool]":
    """Resolve the cache path for (name, fn, args) and return
    ``(path, exists)``; on a cache miss, first GC stale files so dead
    generations don't accumulate (20-500 MB each — dozens were found
    hoarding ~5 GB of /tmp). Collected: other GENERATIONS of this
    variant (same args hash, different code hash) and legacy pre-split
    names (no dash in the tag — all dead by construction under the
    current naming). Sibling variants sharing a name — the big and small
    ingest files — survive.

    NOTE single-writer assumption: the GC unlinks files another bench
    process could in principle still be reading, if a run of an OLDER
    bench.py overlaps a run of an edited one. Benches run one at a time
    on these boxes (1 CPU; the suite cannot share it), so the trade is
    taken for the disk space; per-uid naming still isolates users, and
    the unique staging file keeps same-version runs race-free."""
    import glob

    tag = _generator_tag(fn, args)
    ahash, _chash = tag.split("-")
    prefix = f"photon_bench_{os.getuid()}_{name}_"
    path = os.path.join(tempfile.gettempdir(), f"{prefix}{tag}{ext}")
    if os.path.exists(path):
        return path, True
    for old in glob.glob(os.path.join(tempfile.gettempdir(),
                                      f"{prefix}*{ext}")):
        base_tag = os.path.basename(old)[len(prefix):-len(ext)]
        if base_tag.startswith(f"{ahash}-") or "-" not in base_tag:
            try:
                os.unlink(old)
            except OSError:
                pass  # another process may have raced the same cleanup
    return path, False


def _cached_fixture(name: str, fn, *args) -> str:
    """Deterministic Avro fixtures cached across bench runs (the pure-Python
    encode of a 1e5-row file costs ~10 s — prep, not measurement).

    ``fn(path, *args)`` generates the file. The cache key folds in ``args``
    and ``fn``'s own bytecode, so editing the generator or its parameters
    invalidates the cached file instead of silently benchmarking stale
    data (see :func:`_fixture_path` for the naming and GC rules)."""
    path, exists = _fixture_path(name, fn, args, ".avro")
    if not exists:
        fd, tmp = tempfile.mkstemp(dir=tempfile.gettempdir(),
                                   suffix=".avro.tmp")
        os.close(fd)
        try:
            fn(tmp, *args)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        _heartbeat()  # a cold 1M-row encode is minutes of pre-metric prep
    return path


def _cached_npz(name: str, fn, *args) -> dict:
    """Deterministic numpy fixtures cached across bench runs (generating
    the 10M-row random-effect problem costs ~40 s of rng/alias-sampling —
    prep, not measurement). Same keying discipline as
    :func:`_cached_fixture`: args + the generator's bytecode."""
    path, exists = _fixture_path(name, fn, args, ".npz")
    if not exists:
        arrays = fn(*args)
        fd, tmp = tempfile.mkstemp(dir=tempfile.gettempdir(),
                                   suffix=".npz.tmp")
        os.close(fd)
        try:
            with open(tmp, "wb") as f:
                np.savez(f, **arrays)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    return dict(np.load(path))


_T0 = time.perf_counter()

# every _emit line, in order — the terminal summary line replays them all
_RESULTS: list[dict] = []
# the winning e2e run's perf report + overlap numbers (filled by
# bench_end_to_end via _stash_perf_report; the gate attaches the report
# to a regression verdict so the slowdown arrives with its critical path)
_E2E_PERF_REPORT: list[str] = []
# perf_counter of the latest emit — the stall watchdog's heartbeat
_LAST_PROGRESS: list[float] = [0.0]
# set once the terminal summary has printed; keeps the main thread's
# finally and a firing watchdog from double-printing it
_SUMMARY_LOCK = threading.Lock()
_SUMMARY_DONE: list[bool] = [False]


def _heartbeat():
    """Tell the stall watchdog the suite is making progress. Called from
    `_emit` and from known-long silent stretches (fixture encodes, the
    e2e warm/measured runs) so a healthy cold run — whose FIRST metric
    can be 15-20 min away — is never mistaken for a hang."""
    _LAST_PROGRESS[0] = time.perf_counter()


def _emit(metric: str, value: float, unit: str, vs_baseline: float, **extra):
    line = {"metric": metric, "value": round(value, 1), "unit": unit,
            "vs_baseline": round(vs_baseline, 3)}
    line.update(extra)
    # suite-elapsed stamp: makes the per-bench budget visible in the
    # artifact (the round-2 harness run timed out with 3/6 metrics and no
    # way to see where the time went)
    line["t_s"] = round(time.perf_counter() - _T0, 1)
    _RESULTS.append(line)
    _heartbeat()
    print(json.dumps(line), flush=True)


def _start_stall_watchdog(stall_s: float | None = None):
    """Emit the terminal summary even if a device call hangs MID-suite.

    A tunnel that dies between benches leaves the main thread blocked in
    a native poll loop: the SIGTERM handler can never run (Python signal
    handlers execute on the main thread), the ``finally`` never executes,
    and the harness SIGKILL would discard every metric measured so far.
    A daemon thread watches the `_emit` heartbeat; past the deadline it
    prints the summary itself — partial results plus an ``error`` naming
    where the suite stalled — and exits 4. The deadline (default 30 min,
    ``PHOTON_BENCH_STALL_S`` to override) sits ~2x above the longest
    silent stretch ever observed here (a 5-15 min fresh Pallas compile
    through the remote-compile tunnel)."""
    stall = float(stall_s if stall_s is not None
                  else os.environ.get("PHOTON_BENCH_STALL_S", 1800))
    _heartbeat()

    def _watch():
        while True:
            time.sleep(min(30.0, stall / 4))
            idle = time.perf_counter() - _LAST_PROGRESS[0]
            if idle > stall:
                last = _RESULTS[-1]["metric"] if _RESULTS else "none"
                _emit_summary(error=(
                    f"suite stalled: no metric for {idle:.0f}s "
                    f"(last completed: {last}) — device call hung "
                    "mid-suite; partial results above"))
                os._exit(4)

    threading.Thread(target=_watch, daemon=True).start()


def _tools_module(name: str):
    """Import a module from tools/ (bench.py sits at the repo root)."""
    import importlib
    import sys

    tools = os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    return importlib.import_module(name)


def _stash_perf_report(telemetry_dir: "str | None") -> "dict | None":
    """Render the e2e winner's perf report (before its tempdir vanishes),
    stash the text for the gate, and return the async-I/O overlap numbers
    for the metric line. Never fails the bench — telemetry is evidence,
    not a dependency."""
    if not telemetry_dir:
        return None
    try:
        perf_report = _tools_module("perf_report")
        trace_path, prom_path = perf_report.resolve_inputs(telemetry_dir)
        spans = perf_report.load_spans(trace_path)
        prom_text = ""
        if os.path.exists(prom_path):
            with open(prom_path, encoding="utf-8") as f:
                prom_text = f.read()
        _E2E_PERF_REPORT[:] = [perf_report.build_report(spans, prom_text)]
        return perf_report.io_overlap(spans)
    except Exception:
        return None


def _quality_extras(out_dir: "str | None", train_avro: str) -> dict:
    """Model-quality overhead extras for the e2e metric line: the size of
    the published quality-baseline.json (baseline work is train-side and
    background-thread only — the wall already proves it cost ~0) and the
    canary shadow-scoring wall (the activation-time cost a --canary-gate
    deployment pays, measured by reloading the trained model against a
    64-record reservoir drawn from its own training sample). Never fails
    the bench."""
    if not out_dir:
        return {}
    extras: dict = {}
    baseline_path = os.path.join(out_dir, "quality-baseline.json")
    extras["quality_baseline_bytes"] = (
        os.path.getsize(baseline_path)
        if os.path.exists(baseline_path) else 0)
    try:
        from photon_ml_tpu.cli.config import parse_feature_shard_config
        from photon_ml_tpu.io.avro import iter_avro_file
        from photon_ml_tpu.quality import CanaryConfig
        from photon_ml_tpu.serving import ModelRegistry

        shard_configs = tuple(
            parse_feature_shard_config(s)
            for s in "global=g|intercept,item=it|noIntercept".split(","))
        records = []
        for rec in iter_avro_file(train_avro):
            records.append(rec)
            if len(records) >= 64:
                break
        registry = ModelRegistry(shard_configs, canary=CanaryConfig())
        registry.load(out_dir)
        registry.observe_requests(records)
        # reload the same model: the canary shadow-scores the reservoir
        # through both engines (divergence 0 by construction) — its wall
        # is the pure canary-evaluation cost
        sm = registry.load(out_dir)
        if sm.canary is not None:
            extras["canary_eval_s"] = round(sm.canary["seconds"], 4)
            extras["canary_divergence"] = round(
                sm.canary["divergence"], 6)
    except Exception as e:
        extras["canary_eval_error"] = repr(e)[:200]
    return extras


# gate the FULL suite by default; main() flips this off for --only subset
# runs (every unrun metric would read as "vanished" = regression).
# PHOTON_BENCH_GATE=0/1 overrides either way.
_GATE_DEFAULT = [True]


def _find_baseline() -> "tuple[str, dict] | None":
    """The last SOUND bench artifact next to this file (BENCH_rNN.json,
    newest round first; infra-failed rounds — like r05's device outage —
    are skipped). ``PHOTON_BENCH_BASELINE`` overrides the search."""
    import glob

    bench_gate = _tools_module("bench_gate")
    override = os.environ.get("PHOTON_BENCH_BASELINE")
    here = os.path.dirname(os.path.abspath(__file__))
    candidates = ([override] if override else
                  sorted(glob.glob(os.path.join(here, "BENCH_r*.json")),
                         reverse=True))
    for path in candidates:
        art = bench_gate.load_artifact(path)
        if art is not None and bench_gate.infra_failure(art) is None:
            return path, art
    return None


def _gate_line(summary: dict) -> "dict | None":
    """The auto-gate: this suite's summary vs the last sound artifact,
    as one JSON-able line (``tools/bench_gate.py`` semantics). On a
    ``regression`` verdict the e2e run's perf report rides along, so the
    slowdown arrives with its critical path attached. Returns None (and
    gates nothing) when no sound baseline exists or the gate itself
    errors — the gate must never break the terminal summary.
    ``PHOTON_BENCH_GATE=0`` disables it."""
    flag = os.environ.get("PHOTON_BENCH_GATE")
    enabled = (flag != "0") if flag is not None else _GATE_DEFAULT[0]
    if not enabled:
        return None
    try:
        bench_gate = _tools_module("bench_gate")
        found = _find_baseline()
        current = bench_gate.normalize_artifact({"parsed": summary})
        verdict = bench_gate.gate(current,
                                  found[1] if found else None)
        line = {"metric": "bench_gate",
                "baseline": os.path.basename(found[0]) if found else None}
        line.update(verdict)
        if (verdict.get("verdict") == bench_gate.VERDICT_REGRESSION
                and _E2E_PERF_REPORT):
            line["perf_report"] = _E2E_PERF_REPORT[0][:8000]
        return line
    except Exception:
        return None


def _emit_summary(error: str | None = None):
    """The LAST stdout line: one JSON object holding EVERY metric.

    Two consecutive harness runs produced half-empty official scoreboards
    (round 2: rc=124 truncation; round 3: rc=0 but only the output TAIL is
    preserved, and five of seven metric lines scrolled out of it). The
    driver parses the final JSON line of the tail, so a terminal
    aggregate line makes the artifact complete by construction — including
    each metric's extras (bucket_build_s, per-stage e2e seconds, ...).
    Headline value/vs_baseline = the end-to-end driver metric (the
    north-star-shaped number) when present, else the first metric.

    ``error`` marks an environment failure (device unreachable, mid-suite
    stall): the summary then prints even with zero results, so the
    artifact names the failure instead of being empty. The lock/flag keep
    the main thread's ``finally`` and a firing watchdog thread from
    printing two terminal lines."""
    with _SUMMARY_LOCK:
        if _SUMMARY_DONE[0] or (not _RESULTS and error is None):
            return
        _SUMMARY_DONE[0] = True
    # a retried/process-group SIGTERM landing mid-print would truncate the
    # very line this function exists to guarantee — ignore further TERMs
    # for the final write
    import signal

    try:
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
    except (ValueError, OSError):
        pass  # non-main thread or exotic platform: emit anyway
    head = next((r for r in _RESULTS
                 if r["metric"] == "game_end_to_end_rows_per_sec"),
                _RESULTS[0] if _RESULTS else
                {"metric": "none", "value": 0.0, "unit": "no metrics",
                 "vs_baseline": 0.0})
    summary = {
        "metric": "suite_summary",
        "value": head["value"],
        "unit": head["unit"] + " (headline: " + head["metric"] + ")",
        "vs_baseline": head["vs_baseline"],
        "n_metrics": len(_RESULTS),
        "suite_wall_s": round(time.perf_counter() - _T0, 1),
        "metrics": {r["metric"]: {k: v for k, v in r.items()
                                  if k != "metric"}
                    for r in _RESULTS},
    }
    if error is not None:
        summary["error"] = error
    else:
        # auto-gate against the last sound artifact: the verdict prints as
        # its own JSON line AND rides the summary under "gate" (the
        # summary must stay the FINAL line — the harness parses the last
        # line of the tail as the artifact, and future gates read that
        # artifact's metric set)
        gate_line = _gate_line(summary)
        if gate_line is not None:
            summary["gate"] = {k: v for k, v in gate_line.items()
                               if k not in ("metric", "perf_report")}
            print(json.dumps(gate_line), flush=True)
    print(json.dumps(summary), flush=True)


# --------------------------------------------------------------------------
# 1+2. headline GLM solve (f32 fused kernel; bf16-design variant)
# --------------------------------------------------------------------------

def _make_problem(seed=0):
    """Sparse-generated logistic data, densified (dense is the TPU-first
    layout at this dim — SURVEY.md §7 hard-parts #2). Feature columns carry
    a log-uniform scale spread (~3 decades) so the solve runs the full
    iteration budget and measures sustained per-iteration throughput."""
    rng = np.random.default_rng(seed)
    n, d, k = N_SAMPLES, N_FEATURES, NNZ_PER_ROW
    rows = np.repeat(np.arange(n, dtype=np.int32), k)
    cols = rng.integers(0, d, size=n * k, dtype=np.int32)
    col_scale = np.power(10.0, rng.uniform(-2.0, 1.0, size=d)).astype(np.float32)
    vals = (rng.normal(size=n * k).astype(np.float32) / np.sqrt(k)
            * col_scale[cols])
    x = np.zeros((n, d), np.float32)
    np.add.at(x, (rows, cols), vals)
    w_true = (rng.normal(size=d).astype(np.float32) / col_scale)
    margins = x @ w_true
    y = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-margins))).astype(np.float32)
    return x, y


def _scipy_baseline(x, y):
    import scipy.optimize

    xx = x.astype(np.float64)
    yy = y.astype(np.float64)

    def f(w):
        m = xx @ w
        ym = np.where(yy > 0.5, m, -m)
        loss = np.logaddexp(0.0, -ym).sum() + 0.5 * L2 * w @ w
        p = 1.0 / (1.0 + np.exp(-m))
        g = xx.T @ (p - yy) + L2 * w
        return loss, g

    t0 = time.perf_counter()
    res = scipy.optimize.minimize(
        f, np.zeros(N_FEATURES), jac=True, method="L-BFGS-B",
        options={"maxiter": MAX_ITERS, "ftol": 0.0, "gtol": 1e-12})
    return time.perf_counter() - t0, float(res.fun)


def _tpu_solve(x, y, dtype=None):
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.ops.design import DenseDesign
    from photon_ml_tpu.ops.losses import loss_for_task
    from photon_ml_tpu.ops.objective import GLMData, GLMObjective
    from photon_ml_tpu.optimize import OptimizerConfig, minimize_lbfgs
    from photon_ml_tpu.types import TaskType

    n = x.shape[0]
    xd = jnp.asarray(x, dtype or jnp.float32)
    data = GLMData(
        design=DenseDesign(x=xd),
        labels=jnp.asarray(y),
        offsets=jnp.zeros((n,), jnp.float32),
        weights=jnp.ones((n,), jnp.float32),
    )
    objective = GLMObjective(loss=loss_for_task(TaskType.LOGISTIC_REGRESSION),
                             fused=True)
    cfg = OptimizerConfig(max_iterations=MAX_ITERS, tolerance=1e-12,
                          track_states=False)

    @jax.jit
    def solve(data):
        fun = lambda w: objective.value_and_grad(w, data, L2)
        return minimize_lbfgs(fun, jnp.zeros((N_FEATURES,), jnp.float32), cfg)

    result = solve(data)
    _ = float(result.value)  # compile + first run; D2H is the real barrier
    best = float("inf")
    for _rep in range(3):
        t0 = time.perf_counter()
        result = solve(data)
        val = float(result.value)
        best = min(best, time.perf_counter() - t0)
    return best, val, int(result.iterations)


def bench_glm():
    import jax.numpy as jnp

    x, y = _make_problem()
    tpu_s, tpu_val, _iters = _tpu_solve(x, y)
    _heartbeat()  # fresh kernel compiles can be many minutes of silence
    base_s, base_val = _scipy_baseline(x, y)
    rel = abs(tpu_val - base_val) / max(abs(base_val), 1.0)
    assert rel < 5e-3, f"objective mismatch: tpu={tpu_val} scipy={base_val}"
    _emit("glm_logistic_lbfgs_samples_to_convergence_per_sec",
          N_SAMPLES / tpu_s, "samples/s", base_s / tpu_s)

    bf_s, bf_val, _ = _tpu_solve(x, y, dtype=jnp.bfloat16)
    rel_bf = abs(bf_val - base_val) / max(abs(base_val), 1.0)
    assert rel_bf < 3e-2, f"bf16 objective drift: {bf_val} vs {base_val}"
    _emit("glm_logistic_bf16_design_samples_to_convergence_per_sec",
          N_SAMPLES / bf_s, "samples/s", base_s / bf_s,
          value_rel_err=round(rel_bf, 5))


# --------------------------------------------------------------------------
# 3. random-effect bucketed solve at scale
# --------------------------------------------------------------------------

def _gen_re_arrays(n, n_entities, d, seed):
    prng = np.random.default_rng(4242)
    u = (1.2 * prng.normal(size=(n_entities, d))).astype(np.float32)
    rng = np.random.default_rng(seed)
    xr = rng.normal(size=(n, d)).astype(np.float32)
    # power-law entity sizes (the straggler distribution the bucketing
    # machinery exists for)
    probs = 1.0 / np.arange(1, n_entities + 1, dtype=np.float64)
    probs /= probs.sum()
    ent = rng.choice(n_entities, size=n, p=probs).astype(np.int64)
    margin = np.einsum("nd,nd->n", xr, u[ent])
    y = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-margin))).astype(np.float32)
    return {"xr": xr, "y": y, "ent": ent}


def _make_re_problem(n=None, n_entities=None, d=RE_DIM, seed=0):
    from photon_ml_tpu.game.data import GameData
    from photon_ml_tpu.testing import dense_shard

    n = RE_ROWS if n is None else n
    n_entities = RE_ENTITIES if n_entities is None else n_entities
    a = _cached_npz("re", _gen_re_arrays, n, n_entities, d, seed)
    xr, y, ent = a["xr"], a["y"], a["ent"]
    data = GameData.build(
        labels=y, shards={"re": dense_shard(xr)},
        id_columns={"entityId": ent})
    return data, xr, y, ent


def bench_random_effect():
    from photon_ml_tpu.game.data import RandomEffectDataset, RandomEffectDatasetConfig
    from photon_ml_tpu.game.random_effect import RandomEffectSolver
    from photon_ml_tpu.glm.problem import GLMOptimizationConfiguration
    from photon_ml_tpu.ops.regularization import L2Regularization
    from photon_ml_tpu.optimize import OptimizerConfig
    from photon_ml_tpu.types import TaskType

    data, xr, y, ent = _make_re_problem()
    # histogram bucketing: ≤5 padded shapes (vs ~10 geometric) — every
    # distinct shape is a fresh XLA compile, the cold-run cost that
    # dominates a fresh-process bench through the remote-compile tunnel
    cfg = RandomEffectDatasetConfig("entityId", "re",
                                    bucket_strategy="histogram",
                                    max_sample_buckets=5)
    t0 = time.perf_counter()
    dataset = RandomEffectDataset.build("perEntity", data, cfg)
    build_s = time.perf_counter() - t0
    _heartbeat()  # the 10M-row build + upload precede a long compile

    lam = 1.0
    solver = RandomEffectSolver(
        task=TaskType.LOGISTIC_REGRESSION,
        config=GLMOptimizationConfiguration(
            regularization=L2Regularization,
            optimizer_config=OptimizerConfig(max_iterations=25,
                                             tolerance=1e-6,
                                             track_states=False)))
    offsets = np.zeros(data.n_samples, np.float32)
    model, scores = solver.train(dataset, offsets, lam)  # compile + warm
    _ = float(np.asarray(scores[:1])[0])
    _heartbeat()
    t0 = time.perf_counter()
    model, scores = solver.train(dataset, offsets, lam)
    _ = float(np.asarray(scores[:1])[0])
    solve_s = time.perf_counter() - t0
    n_entities = dataset.n_active_entities

    # host baseline: scipy L-BFGS-B per entity on a sample, scaled (the
    # per-entity solves are independent — per-entity mean time is the
    # honest scaling unit; sample spans the size distribution)
    import scipy.optimize

    order = np.argsort(ent, kind="stable")
    bounds = np.searchsorted(ent[order], np.arange(RE_ENTITIES))
    sizes = np.diff(np.append(bounds, len(ent)))
    live = np.flatnonzero(sizes > 0)
    # UNIFORM random draw over live entities: the sample mean then estimates
    # the true per-entity mean cost. (Spacing the sample over the
    # size-sorted id axis looks stratified but left-weights the power-law
    # head — that inflated the measured host cost ~80x when first tried.)
    sample = np.random.default_rng(7).choice(
        live, size=min(RE_SCIPY_SAMPLE, len(live)), replace=False)
    t0 = time.perf_counter()
    for e in sample:
        sel = order[bounds[e]:bounds[e] + sizes[e]]
        xe, ye = xr[sel].astype(np.float64), y[sel].astype(np.float64)

        def f(w):
            m = xe @ w
            loss = (np.logaddexp(0.0, -np.where(ye > 0.5, m, -m)).sum()
                    + 0.5 * lam * w @ w)
            p = 1.0 / (1.0 + np.exp(-m))
            return loss, xe.T @ (p - ye) + lam * w

        scipy.optimize.minimize(f, np.zeros(RE_DIM), jac=True,
                                method="L-BFGS-B",
                                options={"maxiter": 25})
    host_per_entity = (time.perf_counter() - t0) / len(sample)
    host_entities_per_sec = 1.0 / host_per_entity

    tpu_entities_per_sec = n_entities / solve_s
    _emit("re_bucketed_solve_entities_per_sec", tpu_entities_per_sec,
          "entities/s", tpu_entities_per_sec / host_entities_per_sec,
          n_entities=int(n_entities), n_rows=int(RE_ROWS),
          bucket_build_s=round(build_s, 2))


# --------------------------------------------------------------------------
# 3b. fused Pallas RE sweep kernel vs the XLA per-bucket solve
# --------------------------------------------------------------------------

#: (rows, entities, dim) mixes for the re_sweep microbench: the power-law
#: small-dim default shape, and a fewer-but-fatter mix so the kernel's
#: wider-lane blocks get exercised too
RE_SWEEP_SHAPES = [
    (1_500_000, 25_000, 8),
    (750_000, 4_000, 32),
]


def bench_re_sweep():
    """Microbench the fused Pallas random-effect sweep kernel
    (``ops/pallas_re.py``, engaged by ``RandomEffectSolver(fused=True)``)
    against the XLA ``_solve_bucket`` two-pass path on identical datasets,
    at the ``RE_SWEEP_SHAPES`` bucket mixes × {float32, bfloat16} design
    dtypes. One ``re_sweep_entities_per_sec_*`` line per dtype (aggregate
    entities/s across shapes); ``vs_baseline`` = XLA wall / fused wall on
    the same shapes — >1 means the single-pass kernel is winning. Off-TPU
    both paths lower to the same XLA closed form (the kernel gate is
    inert), so the ratio degenerates to ~1 by construction.
    """
    import dataclasses

    from photon_ml_tpu.game.data import (
        RandomEffectDataset,
        RandomEffectDatasetConfig,
    )
    from photon_ml_tpu.game.random_effect import RandomEffectSolver
    from photon_ml_tpu.glm.problem import GLMOptimizationConfiguration
    from photon_ml_tpu.ops.regularization import L2Regularization
    from photon_ml_tpu.optimize import OptimizerConfig
    from photon_ml_tpu.types import TaskType

    base = RandomEffectSolver(
        task=TaskType.LOGISTIC_REGRESSION,
        config=GLMOptimizationConfiguration(
            regularization=L2Regularization,
            optimizer_config=OptimizerConfig(max_iterations=25,
                                             tolerance=1e-6,
                                             track_states=False)))

    def timed_train(solver, dataset, offsets):
        model, scores = solver.train(dataset, offsets, 1.0)  # compile + warm
        _ = float(np.asarray(scores[:1])[0])
        _heartbeat()
        best = float("inf")
        for _rep in range(2):
            t0 = time.perf_counter()
            model, scores = solver.train(dataset, offsets, 1.0)
            _ = float(np.asarray(scores[:1])[0])
            best = min(best, time.perf_counter() - t0)
        return best

    for dtype_tag, design_dtype in (("f32", "float32"), ("bf16", "bfloat16")):
        fused_s = xla_s = 0.0
        entities = 0
        extras = {}
        for (n, n_ent, d) in RE_SWEEP_SHAPES:
            data, _xr, _y, _ent = _make_re_problem(n, n_ent, d, seed=1)
            cfg = RandomEffectDatasetConfig("entityId", "re",
                                            bucket_strategy="histogram",
                                            max_sample_buckets=4)
            # one dataset per path: the device bucket cache keys by design
            # dtype, not by solver, so sharing one would hide the second
            # path's upload cost asymmetrically
            walls = {}
            offsets = np.zeros(data.n_samples, np.float32)
            for tag, fused in (("fused", True), ("xla", False)):
                dataset = RandomEffectDataset.build("perEntity", data, cfg)
                solver = dataclasses.replace(base, fused=fused,
                                             design_dtype=design_dtype)
                walls[tag] = timed_train(solver, dataset, offsets)
            entities += dataset.n_active_entities
            extras[f"s{n_ent}x{d}_fused_s"] = round(walls["fused"], 3)
            extras[f"s{n_ent}x{d}_xla_s"] = round(walls["xla"], 3)
            fused_s += walls["fused"]
            xla_s += walls["xla"]
        _emit(f"re_sweep_entities_per_sec_{dtype_tag}",
              entities / fused_s, "entities/s", xla_s / fused_s, **extras)


# --------------------------------------------------------------------------
# 4. full coordinate-descent sweep (fixed + 2 random effects)
# --------------------------------------------------------------------------

def _gen_cd_arrays(n, users, songs, seed, d_fixed, d_re):
    prng = np.random.default_rng(777)
    w_fixed = prng.normal(size=d_fixed).astype(np.float32)
    uu = (1.0 * prng.normal(size=(users, d_re))).astype(np.float32)
    us = (0.7 * prng.normal(size=(songs, d_re))).astype(np.float32)
    rng = np.random.default_rng(seed)
    xf = rng.normal(size=(n, d_fixed)).astype(np.float32)
    xi = rng.normal(size=(n, d_re)).astype(np.float32)
    pu = 1.0 / np.arange(1, users + 1); pu /= pu.sum()
    ps = 1.0 / np.arange(1, songs + 1); ps /= ps.sum()
    user = rng.choice(users, size=n, p=pu).astype(np.int64)
    song = rng.choice(songs, size=n, p=ps).astype(np.int64)
    margin = (xf @ w_fixed + np.einsum("nd,nd->n", xi, uu[user])
              + np.einsum("nd,nd->n", xi, us[song]))
    y = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-margin))).astype(np.float32)
    return {"xf": xf, "xi": xi, "user": user, "song": song, "y": y}


def _make_cd_problem(n, users, songs, seed=0):
    from photon_ml_tpu.game.data import GameData
    from photon_ml_tpu.testing import dense_shard

    a = _cached_npz("cd", _gen_cd_arrays, n, users, songs, seed,
                    CD_D_FIXED, CD_D_RE)
    xf, xi, user, song, y = a["xf"], a["xi"], a["user"], a["song"], a["y"]
    data = GameData.build(
        labels=y,
        shards={"fixed": dense_shard(xf),
                "item": dense_shard(xi)},
        id_columns={"userId": user, "songId": song})
    return data, (xf, xi, user, song, y)


def _host_cd_sweep(xf, xi, user, song, y, lam_fixed, lam_re, sweeps=1):
    """numpy/scipy CD sweep: fixed scipy L-BFGS-B + per-entity Newton-ish
    scipy solves, residual-offset accounting — the same algorithm the
    device path runs, in plain host code."""
    import scipy.optimize

    n = len(y)
    yy = y.astype(np.float64)
    scores = {"global": np.zeros(n), "perUser": np.zeros(n),
              "perSong": np.zeros(n)}

    def logistic(xd, yl, off, lam, w0):
        def f(w):
            m = xd @ w + off
            loss = (np.logaddexp(0.0, -np.where(yl > 0.5, m, -m)).sum()
                    + 0.5 * lam * w @ w)
            p = 1.0 / (1.0 + np.exp(-m))
            return loss, xd.T @ (p - yl) + lam * w

        return scipy.optimize.minimize(
            f, w0, jac=True, method="L-BFGS-B",
            options={"maxiter": 25}).x

    w_f = np.zeros(CD_D_FIXED)
    re_models = {"perUser": {}, "perSong": {}}
    for _ in range(sweeps):
        # fixed effect
        off = scores["perUser"] + scores["perSong"]
        w_f = logistic(xf.astype(np.float64), yy, off, lam_fixed, w_f)
        scores["global"] = xf @ w_f
        # random effects
        for cid, ids in (("perUser", user), ("perSong", song)):
            off_all = sum(s for k, s in scores.items() if k != cid)
            order = np.argsort(ids, kind="stable")
            srt = ids[order]
            starts = np.searchsorted(srt, np.unique(srt))
            uniq = np.unique(srt)
            new_scores = np.zeros(n)
            for k, e in enumerate(uniq):
                lo = starts[k]
                hi = starts[k + 1] if k + 1 < len(starts) else n
                sel = order[lo:hi]
                xd = xi[sel].astype(np.float64)
                w0 = re_models[cid].get(e, np.zeros(CD_D_RE))
                w_e = logistic(xd, yy[sel], off_all[sel], lam_re, w0)
                re_models[cid][e] = w_e
                new_scores[sel] = xd @ w_e
            scores[cid] = new_scores
    return w_f


# host baselines for the e2e composite, measured in the e2e bench's own
# process slot (first, cleanest) and cached for reuse WITHIN that bench.
# The cd-sweep/ingest benches deliberately do NOT reuse these: each
# bench's vs_baseline divides a numerator by a baseline measured in the
# SAME process state (``fresh=True``), because host-bound walls on this
# box swing with inter-bench residue — a clean-slot baseline against a
# late-slot numerator would skew the ratio and break round-over-round
# comparability.
_SHARED_RATES: dict[str, float] = {}


def _py_ingest_rate(fresh: bool = False) -> float:
    """Pure-Python Avro ingest rate on the documented INGEST_PY_ROWS slice
    (the read leg of a reference-style host pipeline)."""
    if fresh or "py_ingest" not in _SHARED_RATES:
        from photon_ml_tpu.cli.config import parse_feature_shard_config
        from photon_ml_tpu.io.data_reader import AvroDataReader

        small = _cached_fixture("ingest", _write_ingest_file,
                                INGEST_PY_ROWS)
        t0 = time.perf_counter()
        pdata, _, _ = AvroDataReader(
            shard_configs=(parse_feature_shard_config("f=f|intercept"),),
            use_native=False).read(small, id_columns=["userId"])
        rate = INGEST_PY_ROWS / (time.perf_counter() - t0)
        assert pdata.n_samples == INGEST_PY_ROWS
        _SHARED_RATES["py_ingest"] = rate
    return _SHARED_RATES["py_ingest"]


def _host_cd_rate(fresh: bool = False) -> float:
    """Host numpy/scipy CD sweep rate on a proportional slice (rows AND
    entities scaled by the same factor so per-entity sizes match;
    per-sample work in a CD sweep is linear in rows — documented
    extrapolation)."""
    if fresh or "host_cd" not in _SHARED_RATES:
        frac = CD_HOST_ROWS / CD_ROWS
        _, (hxf, hxi, huser, hsong, hy) = _make_cd_problem(
            CD_HOST_ROWS, max(int(CD_USERS * frac), 1),
            max(int(CD_SONGS * frac), 1), seed=1)
        t0 = time.perf_counter()
        _host_cd_sweep(hxf, hxi, huser, hsong, hy, 1e-3, 1.0)
        _SHARED_RATES["host_cd"] = (
            CD_HOST_ROWS / (time.perf_counter() - t0))
    return _SHARED_RATES["host_cd"]


def bench_cd_sweep():
    from photon_ml_tpu.game.data import RandomEffectDatasetConfig
    from photon_ml_tpu.game.estimator import (
        FixedEffectCoordinateConfig,
        GameEstimator,
        GameOptimizationConfiguration,
        RandomEffectCoordinateConfig,
    )
    from photon_ml_tpu.glm.problem import GLMOptimizationConfiguration
    from photon_ml_tpu.ops.regularization import L2Regularization
    from photon_ml_tpu.optimize import OptimizerConfig
    from photon_ml_tpu.types import TaskType

    data, _ = _make_cd_problem(CD_ROWS, CD_USERS, CD_SONGS)
    opt = GLMOptimizationConfiguration(
        regularization=L2Regularization,
        optimizer_config=OptimizerConfig(max_iterations=25, tolerance=1e-6,
                                         track_states=False))
    est = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinate_configs={
            "global": FixedEffectCoordinateConfig(
                feature_shard_id="fixed", optimization=opt),
            "perUser": RandomEffectCoordinateConfig(
                dataset=RandomEffectDatasetConfig(
                    "userId", "item", bucket_strategy="histogram",
                    max_sample_buckets=4),
                optimization=opt),
            "perSong": RandomEffectCoordinateConfig(
                dataset=RandomEffectDatasetConfig(
                    "songId", "item", bucket_strategy="histogram",
                    max_sample_buckets=4),
                optimization=opt),
        },
        update_sequence=["global", "perUser", "perSong"],
        n_cd_iterations=1)
    config = GameOptimizationConfiguration(
        {"global": 1e-3, "perUser": 1.0, "perSong": 1.0})
    datasets = est.prepare(data)

    def timed_fit():
        t0 = time.perf_counter()
        r = est.fit(data, [config], datasets=datasets)[0]
        # D2H on a result scalar: the only reliable barrier on this
        # platform (see module NOTE) — the last coordinate's score scatter
        # may still be in flight when est.fit returns
        _ = float(np.asarray(
            r.model.coordinates["global"].model.coefficients.means[0]))
        return time.perf_counter() - t0

    timed_fit()  # compile + warm
    _heartbeat()
    tpu_s = timed_fit()
    tpu_rate = CD_ROWS / tpu_s

    # fresh=True: the comparator must share THIS bench's process state
    # (see the note at _SHARED_RATES)
    host_rate = _host_cd_rate(fresh=True)

    _emit("game_cd_sweep_samples_per_sec", tpu_rate, "samples/s",
          tpu_rate / host_rate, n_rows=int(CD_ROWS),
          n_entities=int(CD_USERS + CD_SONGS), sweep_wall_s=round(tpu_s, 2))


# --------------------------------------------------------------------------
# 5. Avro ingest through the native decoder
# --------------------------------------------------------------------------

def _write_ingest_file(path, n):
    from photon_ml_tpu.io.data_reader import write_training_examples

    rng = np.random.default_rng(0)
    d = 40
    recs = []
    for i in range(n):
        idx = rng.choice(d, size=8, replace=False)
        feats = [{"name": f"f.x{j}", "term": "", "value": float(v)}
                 for j, v in zip(idx, rng.normal(size=8))]
        recs.append({"uid": str(i), "response": float(rng.integers(0, 2)),
                     "offset": None, "weight": None, "features": feats,
                     "metadataMap": {"userId": f"u{rng.integers(0, 997)}"}})
    write_training_examples(path, recs)
    return path


def bench_ingest():
    from photon_ml_tpu.cli.config import parse_feature_shard_config
    from photon_ml_tpu.io.data_reader import AvroDataReader

    shard_cfg = (parse_feature_shard_config("f=f|intercept"),)
    big = _cached_fixture("ingest", _write_ingest_file, INGEST_ROWS)
    reader = AvroDataReader(shard_configs=shard_cfg)
    reader.read(big, id_columns=["userId"])  # warm (index build etc.)
    t0 = time.perf_counter()
    reader_n = AvroDataReader(shard_configs=shard_cfg)
    data, _, _ = reader_n.read(big, id_columns=["userId"])
    native_s = time.perf_counter() - t0
    assert data.n_samples == INGEST_ROWS

    native_rate = INGEST_ROWS / native_s
    # fresh=True: the comparator must share THIS bench's process state
    # (see the note at _SHARED_RATES)
    _emit("avro_ingest_rows_per_sec", native_rate, "rows/s",
          native_rate / _py_ingest_rate(fresh=True))

    # scoring OUTPUT: the native columnar writer vs the Python record
    # encoder (the reference's ScoringResultAvro write path)
    from photon_ml_tpu import native
    from photon_ml_tpu.io.avro import write_avro_file
    from photon_ml_tpu.io.schemas import SCORING_RESULT_AVRO

    if native.available():
        rng = np.random.default_rng(1)
        n_w = 400_000
        scores = rng.normal(size=n_w)
        labels = (rng.uniform(size=n_w) < 0.5).astype(np.float64)
        with tempfile.TemporaryDirectory() as tmp:
            t0 = time.perf_counter()
            ok = native.write_scoring_results(
                os.path.join(tmp, "s.avro"), scores, labels)
            nat_w = n_w / (time.perf_counter() - t0)
            if not ok:
                raise RuntimeError("native scoring write failed")
            n_py = 40_000
            recs = ({"uid": str(i), "predictionScore": float(scores[i]),
                     "label": float(labels[i]), "metadataMap": None}
                    for i in range(n_py))
            t0 = time.perf_counter()
            # codec null on BOTH sides: the ratio measures the encoders,
            # not zlib (the native writer emits uncompressed containers)
            write_avro_file(os.path.join(tmp, "p.avro"), recs,
                            SCORING_RESULT_AVRO, codec="null")
            py_w = n_py / (time.perf_counter() - t0)
        _emit("avro_scoring_write_rows_per_sec", nat_w, "rows/s",
              nat_w / py_w)


# --------------------------------------------------------------------------
# 7. end-to-end GAME training driver (Avro in -> model written)
# --------------------------------------------------------------------------

def _write_e2e_file(path, n=E2E_ROWS, users=E2E_USERS, songs=E2E_SONGS,
                    touched_users=0):
    """Music-shaped TrainingExampleAvro: a global bag (6 of 32 features),
    an item bag (4 of 8), user+song ids, labels planted from user/song
    factors so the CD sweep has real structure to recover.  Sampling is
    vectorized per chunk (a per-record rng.choice made the 1M-row prep
    dominate cold bench runs) and the codec is null — the e2e metric
    measures the pipeline, not zlib (the ingest bench keeps deflate).

    ``touched_users`` perturbs the item-bag values on rows of the FIRST k
    user ids (all other rows byte-identical draws) — the refresh bench's
    controlled entity-local change: exactly those users fingerprint as
    touched, everyone else carries."""
    from photon_ml_tpu.io.data_reader import write_training_examples

    rng = np.random.default_rng(99)
    d_fixed, d_item = 32, 8
    w_fixed = rng.normal(size=d_fixed)
    uu = rng.normal(size=(users, d_item))
    us = 0.7 * rng.normal(size=(songs, d_item))
    pu = 1.0 / np.arange(1, users + 1); pu /= pu.sum()
    ps = 1.0 / np.arange(1, songs + 1); ps /= ps.sum()
    user = rng.choice(users, size=n, p=pu)
    song = rng.choice(songs, size=n, p=ps)

    def records():
        chunk = 65536
        for lo in range(0, n, chunk):
            m = min(chunk, n - lo)
            # choice-without-replacement via argsort of uniforms, whole
            # chunk at once
            fi = rng.random((m, d_fixed)).argsort(axis=1)[:, :6]
            fv = rng.normal(size=(m, 6))
            ii = rng.random((m, d_item)).argsort(axis=1)[:, :4]
            iv = rng.normal(size=(m, 4))
            u, s = user[lo:lo + m], song[lo:lo + m]
            if touched_users:
                iv = np.where((u < touched_users)[:, None], iv * 1.05, iv)
            margin = ((np.take_along_axis(
                np.broadcast_to(w_fixed, (m, d_fixed)), fi, 1) * fv).sum(1)
                / np.sqrt(6)
                + (np.take_along_axis(uu[u], ii, 1) * iv).sum(1)
                + (np.take_along_axis(us[s], ii, 1) * iv).sum(1))
            label = rng.uniform(size=m) < 1.0 / (1.0 + np.exp(-margin))
            for j in range(m):
                feats = ([{"name": f"g.x{k}", "term": "", "value": float(v)}
                          for k, v in zip(fi[j], fv[j])]
                         + [{"name": f"it.x{k}", "term": "", "value": float(v)}
                            for k, v in zip(ii[j], iv[j])])
                yield {"uid": str(lo + j), "response": float(label[j]),
                       "offset": None, "weight": None, "features": feats,
                       "metadataMap": {"userId": f"u{u[j]}",
                                       "songId": f"s{s[j]}"}}

    write_training_examples(path, records(), codec="null")


def bench_end_to_end():
    """The whole driver, timed from Avro open to model-on-disk — the
    reference's "Read data"→"Save models" wall (GameTrainingDriver.scala).

    Baseline composition: a reference-style host pipeline pays (at least)
    the pure-Python ingest PLUS the host CD sweep, both measured in this
    same process on this same machine at documented reduced slices
    (`_py_ingest_rate` / `_host_cd_rate`, shared with the cd-sweep and
    ingest benches); serial composition of rates is the lower bound on
    its wall (write/model-IO excluded — favors the baseline)."""
    from photon_ml_tpu.cli import train_game as train_game_cli

    train = _cached_fixture("e2e", _write_e2e_file, E2E_ROWS, E2E_USERS,
                            E2E_SONGS)
    py_ingest_rate = _py_ingest_rate()
    host_cd_rate = _host_cd_rate()
    _heartbeat()

    args = [
        "--training-data", train,
        "--feature-shards", "global=g|intercept,item=it|noIntercept",
        "--coordinates",
        "global=fixed,shard=global,reg=L2,maxIter=25",
        ("perUser=random,entity=userId,shard=item,reg=L2,maxIter=25,"
         "buckets=histogram,maxSampleBuckets=4"),
        ("perSong=random,entity=songId,shard=item,reg=L2,maxIter=25,"
         "buckets=histogram,maxSampleBuckets=4"),
        "--update-sequence", "global,perUser,perSong",
        "--cd-iterations", "1",
        "--grid", "global=0.001", "perUser=1", "perSong=1",
        "--data-validation", "VALIDATE_DISABLED",
        # bfloat16 designs end to end: halves the dominant feed bytes over
        # the ~35 MB/s wire and runs the solves on the MXU's native dtype
        # (recorded rel-err ~3e-4 on the GLM solve, bf16-vs-f32 AUC parity
        # locked by tests/test_game.py)
        "--design-dtype", "bfloat16",
    ]
    # PHOTON_BENCH_SUPERVISE=N runs the measured e2e as an N-process
    # supervised fleet (resilience/supervisor.py); the winner's restart
    # count rides the metric line as an extra either way, so a future
    # round can quantify recovery overhead against the unsupervised walls
    supervise = int(os.environ.get("PHOTON_BENCH_SUPERVISE", "0") or 0)
    if supervise:
        args += ["--supervise", str(supervise)]
    def _residue_drain():
        # drop host/device residue before measuring: freed-but-resident
        # heap from a prior run inflates the next run's read stage 2-5x
        # (page-table pressure on the decode/assembly path — same effect
        # the suite-level drain() guards against). malloc_trim returns the
        # freed arenas to the OS; clear_caches is deliberately NOT called
        # (it would discard the warm jit state the warm run exists to
        # build).
        import ctypes
        import gc

        gc.collect()
        try:
            ctypes.CDLL("libc.so.6").malloc_trim(0)
        except OSError:
            pass

    def _stages_of(out):
        # per-stage breakdown from the driver's own metrics.jsonl (the
        # reference logs the same stage walls via Timed.scala)
        stages = {}
        metrics_path = os.path.join(out, "metrics.jsonl")
        if os.path.exists(metrics_path):
            with open(metrics_path) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # truncated line must not kill the run
                    if "stage" in rec and "seconds" in rec:
                        stages[rec["stage"]] = round(
                            stages.get(rec["stage"], 0.0) + rec["seconds"], 3)
        return stages

    with tempfile.TemporaryDirectory() as tmp:
        train_game_cli.run(args + ["--output-dir", os.path.join(tmp, "w")])
        _heartbeat()  # the warm run's cold compiles can be 15+ min silent
        # measure TWICE (warm jit both times, fresh data path each) and
        # keep the better run: single-run walls on this box swing 1.5-3x
        # with transient host residue/contention, and the cleaner of two
        # is the reproducible property of the code. Each measured run
        # carries --telemetry-dir so the winner ships a span trace: the
        # perf_report async-I/O-overlap section (and a regression gate
        # verdict, see _gate_line) can then PROVE how much of the
        # save/read wall was hidden under train, from artifacts alone.
        wall, stages, best_td, best_out, restarts = None, {}, None, None, None
        for i in range(2):
            _residue_drain()
            out = os.path.join(tmp, f"out{i}")
            td = os.path.join(out, "telemetry")
            t0 = time.perf_counter()
            res = train_game_cli.run(args + ["--output-dir", out,
                                             "--telemetry-dir", td])
            w = time.perf_counter() - t0
            _heartbeat()
            assert os.path.exists(
                os.path.join(out, "best", "model-metadata.json"))
            if wall is None or w < wall:
                wall, stages, best_td, best_out = w, _stages_of(out), td, out
                # supervised runs report their restart count; the extra
                # makes recovery overhead visible round-over-round
                restarts = res.get("restarts")
        overlap = _stash_perf_report(best_td)
        quality_extras = _quality_extras(best_out, train)
    e2e_rate = E2E_ROWS / wall
    base_rate = 1.0 / (1.0 / py_ingest_rate + 1.0 / host_cd_rate)
    extra = {}
    if restarts is not None:
        extra["supervise"] = supervise
        extra["restarts"] = int(restarts)
    if overlap:
        for cls in ("save", "read"):
            if cls in overlap:
                extra[f"{cls}_io_s"] = round(overlap[cls]["seconds"], 3)
                extra[f"{cls}_hidden_pct"] = round(
                    overlap[cls]["hidden_pct"], 1)
    extra.update(quality_extras)
    # self-describing metric line: the run configuration rides as extras so
    # round-over-round artifacts are comparable without reading this source
    _emit("game_end_to_end_rows_per_sec", e2e_rate, "rows/s",
          e2e_rate / base_rate, n_rows=int(E2E_ROWS),
          n_users=int(E2E_USERS), n_songs=int(E2E_SONGS),
          design_dtype="bfloat16", codec="null", best_of=2,
          wall_s=round(wall, 2), stage_s=stages, **extra)


# --------------------------------------------------------------------------
# 8. open-loop serving latency + p99 SLO gate
# --------------------------------------------------------------------------

SERVING_ROWS = 20_000
SERVING_USERS = 500
SERVING_SONGS = 200
SERVING_REQUESTS = 400
SERVING_TARGET_QPS = 100.0
# the R=2 fleet keeps 2x the hosts resident per core, so its knee sits
# below the single-host target on the bench box; an open-loop target
# past the knee measures queue growth, not the routing machinery —
# aim the fleet workload below it
FLEET_TARGET_QPS = 80.0


def bench_serving_slo():
    """Open-loop serving bench (tools/bench_serving.py machinery): train a
    tiny GAME model, serve it in-process, fire a fixed-schedule load at
    ``SERVING_TARGET_QPS``, and report latency-CORRECTED percentiles (the
    closed-loop client's numbers hide coordinated omission — ROADMAP
    "Tail-latency push"). The metric is achieved requests/s;
    ``vs_baseline`` is the p99 SLO headroom (SLO / corrected p99, >1 =
    inside SLO), and the ``slo_verdict`` extra carries the
    ``tools/bench_gate.py`` ok/regression verdict on that headroom.
    ``PHOTON_SERVING_SLO_P99_MS`` overrides the SLO (default 250 ms —
    sized for this box's CPU-serving tail under 100 QPS, not a production
    claim)."""
    import argparse
    import tempfile

    from photon_ml_tpu.cli import serve_game as serve_game_cli
    from photon_ml_tpu.cli import train_game as train_game_cli

    bench_serving = _tools_module("bench_serving")
    slo_ms = float(os.environ.get("PHOTON_SERVING_SLO_P99_MS", 250.0))
    train = _cached_fixture("serving", _write_e2e_file, SERVING_ROWS,
                            SERVING_USERS, SERVING_SONGS)
    shards = "global=g|intercept,item=it|noIntercept"
    with tempfile.TemporaryDirectory() as tmp:
        out = os.path.join(tmp, "model")
        train_game_cli.run([
            "--training-data", train,
            "--output-dir", out,
            "--feature-shards", shards,
            "--coordinates",
            "global=fixed,shard=global,reg=L2,maxIter=25",
            ("perUser=random,entity=userId,shard=item,reg=L2,maxIter=25,"
             "buckets=histogram,maxSampleBuckets=4"),
            "--update-sequence", "global,perUser",
            "--grid", "global=0.001", "perUser=1",
            "--data-validation", "VALIDATE_DISABLED",
            "--evaluators", "",
        ])
        _heartbeat()
        server = serve_game_cli.build_server([
            "--model-dir", out, "--feature-shards", shards,
            "--port", "0", "--max-wait-ms", "1",
        ]).start()
        try:
            from photon_ml_tpu.telemetry.saturation import (
                device_busy_seconds,
            )

            pool = bench_serving._request_pool(
                argparse.Namespace(data=None, pool=128), server)
            metrics0 = bench_serving._scrape_metrics(server.url)
            busy0, wall0 = device_busy_seconds(), time.monotonic()
            run = bench_serving.open_loop_run(
                server.url, pool, [1, 1, 1, 2, 4],
                target_qps=SERVING_TARGET_QPS, requests=SERVING_REQUESTS,
                concurrency=16)
            busy1, wall1 = device_busy_seconds(), time.monotonic()
            conn_peak = server.service.connections.stats()["peak"]
            metrics1 = bench_serving._scrape_metrics(server.url)
        finally:
            server.stop()
    corrected_p99 = bench_serving._percentile(run["corrected_ms"], 99)
    verdict = bench_serving.slo_gate_verdict(corrected_p99, slo_ms)
    extras = {
        "corrected_p50_ms": round(
            bench_serving._percentile(run["corrected_ms"], 50), 3),
        "corrected_p99_ms": round(corrected_p99, 3),
        "uncorrected_p99_ms": round(
            bench_serving._percentile(run["uncorrected_ms"], 99), 3),
        "target_qps": SERVING_TARGET_QPS,
        "slo_p99_ms": slo_ms,
        "slo_verdict": verdict["verdict"],
        "n_errors": len(run["errors"]),
        # capacity-plane extras: device duty over the load window (the
        # USE sampler's utilization source) and the connection high
        # watermark — how close the box ran to its socket budget
        "duty_cycle": round((busy1 - busy0)
                            / max(wall1 - wall0, 1e-9), 4),
        "conn_peak": conn_peak,
    }
    if metrics1 is not None:
        stages = bench_serving.stage_breakdown(metrics0, metrics1)
        if stages:
            extras["stage_ms"] = {k: v["p50_ms"] for k, v in stages.items()}
    _emit("serving_open_loop_qps", run["achieved_qps"],
          "req/s (open loop, latency-corrected percentiles)",
          verdict["headroom"], **extras)


def bench_serving_fleet():
    """Open-loop fleet serving bench (the ISSUE 15 workload, grown by
    ISSUE 16): the same tiny GAME model served from two entity-sharded
    shards at TWO replicas each behind the fleet router
    (``cli/serve_fleet.py``), open-loop /score load through the router,
    then one live reshard epoch driven after the timed window. The
    metric is achieved
    requests/s; ``vs_baseline`` is the p99 SLO headroom
    (``PHOTON_FLEET_SLO_P99_MS``, default 250 ms — one extra local HTTP
    hop vs the single-host SLO). This is the number BENCH_r06 sizes the
    fleet against: compare with ``serving_open_loop_qps`` to read the
    router tax, the per-host entity counts in the extras to read the
    table-byte split, and ``hedge_rate``/``reshard_epochs`` to read the
    elasticity machinery's footprint under load. Two retained-plane
    gates ride along: the history-sampler overhead window pair
    (open-loop p99 with 20 Hz sampling on vs off must stay bounded) and
    ``advisor_detect_ticks`` (a synthetic 10x-skewed shard must latch in
    exactly the hysteresis sustain window)."""
    import argparse
    import tempfile

    from photon_ml_tpu.cli import serve_fleet as serve_fleet_cli
    from photon_ml_tpu.cli import train_game as train_game_cli

    bench_serving = _tools_module("bench_serving")
    slo_ms = float(os.environ.get("PHOTON_FLEET_SLO_P99_MS", 250.0))
    train = _cached_fixture("serving", _write_e2e_file, SERVING_ROWS,
                            SERVING_USERS, SERVING_SONGS)
    shards = "global=g|intercept,item=it|noIntercept"
    with tempfile.TemporaryDirectory() as tmp:
        out = os.path.join(tmp, "model")
        train_game_cli.run([
            "--training-data", train,
            "--output-dir", out,
            "--feature-shards", shards,
            "--coordinates",
            "global=fixed,shard=global,reg=L2,maxIter=25",
            ("perUser=random,entity=userId,shard=item,reg=L2,maxIter=25,"
             "buckets=histogram,maxSampleBuckets=4"),
            "--update-sequence", "global,perUser",
            "--grid", "global=0.001", "perUser=1",
            "--data-validation", "VALIDATE_DISABLED",
            "--evaluators", "",
        ])
        _heartbeat()
        fleet = serve_fleet_cli.build_fleet([
            "--model-dir", out, "--feature-shards", shards,
            "--port", "0", "--max-wait-ms", "1", "--fleet-shards", "2",
            "--replicas", "2",
        ])
        reshard_box = {}

        def _fire_reshard():
            # one live shard-map epoch: move eight buckets that actually
            # hold shard-0 rows, so reshard_epochs and the moved-row
            # counters record the two-phase machinery doing real repack
            # work. Runs AFTER the timed window — the prepare warmup's
            # compile sweep is off the serving path by design, but with
            # four hosts in one process it starves the box's cores and
            # would pollute the qps number (reshard UNDER traffic is the
            # chaos harness's claim, tools/chaos_serving.py --fleet)
            from photon_ml_tpu.fleet.sharding import bucket_of_id
            try:
                smap = fleet.router.shard_map
                donors = sorted({
                    bucket_of_id(str(i))
                    for h in fleet.hosts
                    for store in h.service.registry.active().stores.values()
                    for i in store.row_of_id
                    if smap.shard_of(str(i)) == 0})[:8]
                reshard_box["out"] = bench_serving._http_json(
                    fleet.url + "/reshard",
                    {"moves": {str(b): 1 for b in donors}})
            except Exception as e:
                reshard_box["error"] = repr(e)

        try:
            pool = bench_serving.fleet_request_pool(
                argparse.Namespace(data=None, pool=128), fleet)
            from photon_ml_tpu.telemetry.saturation import (
                device_busy_seconds,
            )

            compiles0 = [bench_serving._http_json(u + "/healthz")["compiles"]
                         for u in fleet.host_urls()]
            folded0 = bench_serving._scrape_metrics(fleet.url)
            metrics0 = bench_serving._scrape_process_metrics()
            busy0, wall0 = device_busy_seconds(), time.monotonic()
            run = bench_serving.open_loop_run(
                fleet.url, pool, [1, 1, 1, 2, 4],
                target_qps=FLEET_TARGET_QPS, requests=SERVING_REQUESTS,
                concurrency=16)
            busy1, wall1 = device_busy_seconds(), time.monotonic()
            # high watermark across the in-process hosts' trackers —
            # the fleet's closest approach to a per-host socket budget
            conn_peak = max(h.service.connections.stats()["peak"]
                            for h in fleet.hosts)
            compiles1 = [bench_serving._http_json(u + "/healthz")["compiles"]
                         for u in fleet.host_urls()]
            folded1 = bench_serving._scrape_metrics(fleet.url)
            proc1 = bench_serving._scrape_process_metrics()
            _fire_reshard()
            metrics1 = bench_serving._scrape_process_metrics()
            entities = [
                sum(s.n_entities
                    for s in h.service.registry.active().stores.values())
                for h in fleet.hosts]
            # retained-plane overhead: the same open-loop window with
            # every history sampler OFF, then ON at an aggressively
            # short period (20 Hz across router + 4 hosts — far past
            # the production default). Sampling is a side thread + one
            # registry render per tick, so the p99 delta it costs the
            # serving path must stay bounded (gated below).
            overhead_n = max(SERVING_REQUESTS // 2, 100)
            run_off = bench_serving.open_loop_run(
                fleet.url, pool, [1, 1, 1, 2, 4],
                target_qps=FLEET_TARGET_QPS, requests=overhead_n,
                concurrency=16)
            fleet.history.start(0.05)
            for h in fleet.hosts:
                h.history.start(0.05)
            run_on = bench_serving.open_loop_run(
                fleet.url, pool, [1, 1, 1, 2, 4],
                target_qps=FLEET_TARGET_QPS, requests=overhead_n,
                concurrency=16)
        finally:
            fleet.stop()
        _heartbeat()
    # fold parity (the fleet observability plane's accounting claim): the
    # router's folded /metrics carries every member's serving-latency
    # histogram once. The in-process hosts share the router's process
    # registry, so the fold sums the SAME histogram (1 + n_hosts) times —
    # the folded count's delta over the load window must be exactly that
    # multiple of the process-registry delta, and the process delta must
    # cover every client-served request (each served request executed on
    # >= 1 host; cross-shard records, hedges and replica retries only ADD
    # host-side observations, never remove them).
    from photon_ml_tpu.telemetry.prometheus import series_value
    lat_count = "photon_serving_request_latency_seconds_count"
    members = 1 + len(entities)  # router + every host, one shared registry
    fold_delta = int(series_value(folded1, lat_count)
                     - series_value(folded0, lat_count))
    proc_delta = int(series_value(proc1, lat_count)
                     - series_value(metrics0, lat_count))
    served = len(run["corrected_ms"]) + run["reconnected"]
    if fold_delta != members * proc_delta:
        raise AssertionError(
            f"fleet /metrics fold parity: folded {lat_count} moved "
            f"{fold_delta} over the load window, expected {members} "
            f"members (router + hosts sharing one registry) x process "
            f"delta {proc_delta} = {members * proc_delta}")
    if proc_delta < served:
        raise AssertionError(
            f"fleet /metrics fold parity: hosts observed {proc_delta} "
            f"admitted /score requests but clients tallied {served} "
            f"served — the fold is missing host observations")
    corrected_p99 = bench_serving._percentile(run["corrected_ms"], 99)
    verdict = bench_serving.slo_gate_verdict(
        corrected_p99, slo_ms,
        shed_rate=run["shed"] / max(run["offered"], 1))
    elastic = bench_serving.fleet_elastic_extras(
        metrics0, metrics1, run["offered"])
    # the sampler-overhead gate: generous (2x + 50 ms) so a noisy
    # 1-core box never flakes it, but a sampler that serializes the
    # request path behind its registry render blows straight through
    sampler_p99_off = bench_serving._percentile(run_off["corrected_ms"], 99)
    sampler_p99_on = bench_serving._percentile(run_on["corrected_ms"], 99)
    if sampler_p99_on > 2.0 * sampler_p99_off + 50.0:
        raise AssertionError(
            f"history-sampler overhead: open-loop p99 went "
            f"{sampler_p99_off:.1f} ms -> {sampler_p99_on:.1f} ms with "
            f"20 Hz sampling on — the retained plane is standing on the "
            f"serving path")
    # hot-shard advisor detection bound: a synthetic 10x-skewed shard
    # fed tick by tick must latch in EXACTLY sustain_ticks ticks —
    # detection latency is the hysteresis design, not heuristics
    from photon_ml_tpu.fleet.advisor import HotShardAdvisor

    class _SynthHistory:
        def __init__(self):
            self.snaps = []

        def snapshots(self, window=0):
            return self.snaps[-window:] if window else list(self.snaps)

    synth = _SynthHistory()
    synth_advisor = HotShardAdvisor(history=synth,
                                    shard_map_fn=lambda: None)
    advisor_detect_ticks = 0
    for t in range(1, 2 * synth_advisor.sustain_ticks + 2):
        synth.snaps.append({"tick": t, "ts": float(t), "series": {
            "shard_p99": {"0": 0.050, "1": 0.005},
            "shard_load": {"0": 6.0, "1": 1.0}}})
        if synth_advisor.tick():
            advisor_detect_ticks = t
            break
    if advisor_detect_ticks != synth_advisor.sustain_ticks:
        raise AssertionError(
            f"hot-shard advisor latched a sustained 10x skew in "
            f"{advisor_detect_ticks} tick(s), want exactly "
            f"{synth_advisor.sustain_ticks} (the sustain window)")
    _emit("serving_fleet_qps", run["achieved_qps"],
          "req/s (open loop /score through the fleet router, 2 local "
          "entity-sharded shards x 2 replicas with hedged fan-out, "
          "latency-corrected percentiles; one live reshard epoch driven "
          "after the window, footprint in the extras)",
          verdict["headroom"],
          corrected_p50_ms=round(
              bench_serving._percentile(run["corrected_ms"], 50), 3),
          corrected_p99_ms=round(corrected_p99, 3),
          target_qps=FLEET_TARGET_QPS,
          n_shards=2,
          replicas=2,
          hedge_rate=elastic["hedge_rate"],
          replica_retries=elastic["replica_retries"],
          reshard_epochs=elastic["reshard_epochs"],
          reshard_moved=(reshard_box.get("out") or {}).get("moved"),
          reshard_error=reshard_box.get("error"),
          entities_per_host=entities,
          recompiles_during_load=[c1 - c0 for c0, c1
                                  in zip(compiles0, compiles1)],
          n_shed=run["shed"], n_errors=len(run["errors"]),
          n_reconnected=run["reconnected"],
          fold_members=members, fold_count_delta=fold_delta,
          host_observations=proc_delta,
          history_p99_off_ms=round(sampler_p99_off, 3),
          history_p99_on_ms=round(sampler_p99_on, 3),
          advisor_detect_ticks=advisor_detect_ticks,
          duty_cycle=round((busy1 - busy0)
                           / max(wall1 - wall0, 1e-9), 4),
          conn_peak=conn_peak,
          slo_p99_ms=slo_ms, slo_verdict=verdict["verdict"])


RANKED_KS = (1, 10, 64)


def bench_serving_ranked():
    """Open-loop ranked-retrieval bench (the `/rank` workload, ISSUE 14):
    train the serving model, serve it with `--rank-item-coordinate`, fire
    a fixed-schedule GET /rank load cycling a k sweep, and report
    latency-corrected percentiles + shed classification. The metric is
    achieved ranked requests/s; ``vs_baseline`` is the p99 SLO headroom
    (``PHOTON_RANK_SLO_P99_MS``, default 250 ms). This is the number
    BENCH_r06 sizes the item-axis sharding claim against: the extras
    carry the item count so rate-per-item is derivable round over
    round."""
    import argparse
    import tempfile

    from photon_ml_tpu.cli import serve_game as serve_game_cli
    from photon_ml_tpu.cli import train_game as train_game_cli

    bench_serving = _tools_module("bench_serving")
    slo_ms = float(os.environ.get("PHOTON_RANK_SLO_P99_MS", 250.0))
    train = _cached_fixture("serving", _write_e2e_file, SERVING_ROWS,
                            SERVING_USERS, SERVING_SONGS)
    shards = "global=g|intercept,item=it|noIntercept"
    with tempfile.TemporaryDirectory() as tmp:
        out = os.path.join(tmp, "model")
        train_game_cli.run([
            "--training-data", train,
            "--output-dir", out,
            "--feature-shards", shards,
            "--coordinates",
            "global=fixed,shard=global,reg=L2,maxIter=25",
            ("perUser=random,entity=userId,shard=item,reg=L2,maxIter=25,"
             "buckets=histogram,maxSampleBuckets=4"),
            "--update-sequence", "global,perUser",
            "--grid", "global=0.001", "perUser=1",
            "--data-validation", "VALIDATE_DISABLED",
            "--evaluators", "",
        ])
        _heartbeat()
        server = serve_game_cli.build_server([
            "--model-dir", out, "--feature-shards", shards,
            "--port", "0", "--max-wait-ms", "1",
            "--rank-item-coordinate", "perUser", "--rank-max-k", "64",
        ]).start()
        try:
            pool = bench_serving._request_pool(
                argparse.Namespace(data=None, pool=128), server)
            users = bench_serving._rank_users(server, pool)
            health0 = bench_serving._http_json(
                server.url + "/healthz")
            run = bench_serving.mixed_open_loop_run(
                server.url, pool, users, [1],
                target_qps=SERVING_TARGET_QPS, requests=SERVING_REQUESTS,
                ks=RANKED_KS, rank_every=1)
            health1 = bench_serving._http_json(server.url + "/healthz")
        finally:
            server.stop()
        _heartbeat()
    book = run["rank"]
    corrected_p99 = bench_serving._percentile(book["corrected_ms"], 99)
    verdict = bench_serving.slo_gate_verdict(
        corrected_p99, slo_ms,
        shed_rate=book["shed"] / max(book["offered"], 1))
    achieved = (len(book["corrected_ms"]) / run["wall_s"]
                if run["wall_s"] > 0 else 0.0)
    _emit("serving_ranked_qps", achieved,
          "ranked req/s (open loop GET /rank, latency-corrected "
          "percentiles)", verdict["headroom"],
          corrected_p50_ms=round(
              bench_serving._percentile(book["corrected_ms"], 50), 3),
          corrected_p99_ms=round(corrected_p99, 3),
          target_qps=SERVING_TARGET_QPS,
          ks=list(RANKED_KS),
          rank_items=health1["rank"]["items"],
          rank_compiles_during_load=(health1["rank"]["compiles"]
                                     - health0["rank"]["compiles"]),
          n_shed=book["shed"], n_errors=len(book["errors"]),
          slo_p99_ms=slo_ms, slo_verdict=verdict["verdict"])


REFRESH_ROWS = 200_000
REFRESH_USERS = 4_000
REFRESH_SONGS = 2_000


def bench_refresh():
    """Incremental continuous-training refresh (cli/refresh_game.py) at
    1% / 10% / 100% touched-entity fractions: train a base model once,
    then refresh it against datasets where exactly that fraction of users'
    rows changed. The metric is re-solved entities per second of refresh
    wall; ``vs_baseline`` is the speedup of the incremental run's
    per-entity rate over the 100%-touched (full-refit-cost) run's — the
    O(touched) vs O(all entities) claim, measured."""
    from photon_ml_tpu.cli import refresh_game as refresh_game_cli
    from photon_ml_tpu.cli import train_game as train_game_cli

    base = _cached_fixture("refresh-base", _write_e2e_file, REFRESH_ROWS,
                           REFRESH_USERS, REFRESH_SONGS)
    shards = "global=g|intercept,item=it|noIntercept"
    coords = [
        "global=fixed,shard=global,reg=L2,maxIter=25",
        ("perUser=random,entity=userId,shard=item,reg=L2,maxIter=25,"
         "buckets=histogram,maxSampleBuckets=4"),
    ]
    common = [
        "--feature-shards", shards,
        "--coordinates", *coords,
        "--update-sequence", "global,perUser",
        "--grid", "global=0.001", "perUser=1",
        "--data-validation", "VALIDATE_DISABLED",
        "--evaluators", "",
    ]
    _heartbeat()
    with tempfile.TemporaryDirectory() as tmp:
        prior = os.path.join(tmp, "base")
        train_game_cli.run(["--training-data", base,
                            "--output-dir", prior] + common)
        _heartbeat()
        runs = []
        for frac in (0.01, 0.10, 1.00):
            touched = max(1, int(REFRESH_USERS * frac))
            data = _cached_fixture(
                f"refresh-t{int(frac * 100)}", _write_e2e_file,
                REFRESH_ROWS, REFRESH_USERS, REFRESH_SONGS, touched)
            out = os.path.join(tmp, f"refresh-{int(frac * 100)}")
            t0 = time.perf_counter()
            res = refresh_game_cli.run(
                ["--prior-dir", prior, "--training-data", data,
                 "--output-dir", out] + common)
            wall = time.perf_counter() - t0
            _heartbeat()
            runs.append((frac, res, wall))
        # baseline = the 100%-touched run's per-entity rate (full refit
        # cost through the identical code path)
        frac100, res100, wall100 = runs[-1]
        base_rate = max(sum(res100["solved"].values()), 1) / wall100
        for frac, res, wall in runs:
            solved = sum(res["solved"].values())
            rate = max(solved, 1) / wall
            _emit(f"refresh_entities_per_sec_{int(frac * 100)}pct", rate,
                  "entities/s", rate / base_rate,
                  touched_fraction=frac,
                  touched_entities=sum(res["touched"].values()),
                  carried_entities=sum(res["carried"].values()),
                  solved_entities=solved, wall_s=round(wall, 2),
                  n_rows=int(REFRESH_ROWS), n_users=int(REFRESH_USERS))


FRESH_ROWS = 50_000
FRESH_USERS = 1_000
FRESH_SONGS = 500
FRESH_ROWS_PER_USER = 16


def bench_freshness():
    """End-to-end freshness lag of the closed loop (CONTINUOUS.md "The
    closed loop") at 1% / 10% touched-user fractions: log labeled traffic
    for exactly that fraction of users, join it
    (``feedback.join_feedback``), refresh with ``--fleet-shards 2``
    (touched-entity solve, everyone else carried), and activate each
    per-shard patch on a fleet-sharded serving registry. The metric is
    the wall from the NEWEST logged request to BOTH shards serving the
    refreshed lineage — the ``photon_freshness_lag_seconds`` number the
    autopilot gauges, measured through the identical code path without
    the drift-event trigger. ``vs_baseline`` on the 1% line is the 10%
    run's lag over the 1% run's (how sublinearly lag scales with touched
    traffic — the O(touched) claim at loop scope)."""
    from photon_ml_tpu.cli import train_game as train_game_cli
    from photon_ml_tpu.cli import refresh_game as refresh_game_cli
    from photon_ml_tpu.cli.config import parse_feature_shard_config
    from photon_ml_tpu.feedback import join_feedback
    from photon_ml_tpu.serving import ModelRegistry, RequestLog

    base = _cached_fixture("fresh-base", _write_e2e_file, FRESH_ROWS,
                           FRESH_USERS, FRESH_SONGS)
    shards = "global=g|intercept,item=it|noIntercept"
    coords = [
        "global=fixed,shard=global,reg=L2,maxIter=25",
        ("perUser=random,entity=userId,shard=item,reg=L2,maxIter=25,"
         "buckets=histogram,maxSampleBuckets=4"),
    ]
    common = [
        "--feature-shards", shards,
        "--coordinates", *coords,
        "--update-sequence", "global,perUser",
        "--grid", "global=0.001", "perUser=1",
        "--data-validation", "VALIDATE_DISABLED",
        "--evaluators", "",
    ]
    shard_configs = tuple(parse_feature_shard_config(s)
                          for s in shards.split(","))
    rng = np.random.default_rng(17)

    def log_traffic(log_dir, touched):
        """Labeled score traffic for the first ``touched`` user ids —
        the log the joiner turns back into training data."""
        rl = RequestLog(log_dir, sample_rate=1.0, segment_records=64)
        try:
            for u in range(touched):
                records = []
                for _ in range(FRESH_ROWS_PER_USER):
                    s = int(rng.integers(FRESH_SONGS))
                    feats = ([{"name": f"g.x{k}", "term": "",
                               "value": float(rng.normal())}
                              for k in rng.choice(32, 6, replace=False)]
                             + [{"name": f"it.x{k}", "term": "",
                                 "value": float(rng.normal())}
                                for k in rng.choice(8, 4, replace=False)])
                    records.append({
                        "features": feats, "offset": None,
                        "label": float(rng.integers(2)),
                        "metadataMap": {"userId": f"u{u}",
                                        "songId": f"s{s}"}})
                rl.log(request_id=f"fresh-u{u}", records=records,
                       scores=[0.0] * len(records), version=1,
                       lineage=None)
        finally:
            rl.close()  # durable segments before the join reads

    _heartbeat()
    with tempfile.TemporaryDirectory() as tmp:
        prior = os.path.join(tmp, "base")
        train_game_cli.run(["--training-data", base,
                            "--output-dir", prior] + common)
        _heartbeat()
        results = []
        for frac in (0.01, 0.10):
            touched = max(1, int(FRESH_USERS * frac))
            pct = int(frac * 100)
            log_dir = os.path.join(tmp, f"reqlog-{pct}")
            joined = os.path.join(tmp, f"joined-{pct}.avro")
            out = os.path.join(tmp, f"refresh-{pct}")
            # two fresh fleet-sharded registries per fraction: activation
            # cost is part of the lag, measured from a cold patch
            registries = [
                ModelRegistry(shard_configs, max_batch=64, warmup=False,
                              fleet_shard=(i, 2))
                for i in range(2)]
            for reg in registries:
                reg.load(prior)
            log_traffic(log_dir, touched)
            join = join_feedback([log_dir], None, joined)
            assert join.joined == touched * FRESH_ROWS_PER_USER, \
                f"join lost rows: {join.as_dict()}"
            res = refresh_game_cli.run(
                ["--prior-dir", prior, "--training-data", joined,
                 "--output-dir", out, "--fleet-shards", "2"] + common)
            for i, reg in enumerate(registries):
                reg.reload(os.path.join(out, f"patch-shard-{i}"))
            lag = time.time() - join.last_ts
            _heartbeat()
            solved = sum(res["solved"].values())
            results.append((frac, lag, solved, res))
        (f1, lag1, solved1, _), (f10, lag10, solved10, _) = results
        _emit("freshness_lag_s", lag1, "s", lag10 / max(lag1, 1e-9),
              touched_fraction=f1, touched_users=int(FRESH_USERS * f1),
              solved_entities=solved1,
              joined_rows=int(FRESH_USERS * f1) * FRESH_ROWS_PER_USER,
              fleet_shards=2, n_users=int(FRESH_USERS))
        _emit("freshness_lag_s_10pct", lag10, "s", 1.0,
              touched_fraction=f10, touched_users=int(FRESH_USERS * f10),
              solved_entities=solved10,
              joined_rows=int(FRESH_USERS * f10) * FRESH_ROWS_PER_USER,
              fleet_shards=2, n_users=int(FRESH_USERS))


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--only",
                   choices=["glm", "re", "re_sweep", "cd", "ingest", "e2e",
                            "refresh", "freshness", "serving", "ranked",
                            "fleet"],
                   help="run a single benchmark instead of the full suite")
    args = p.parse_args(argv)
    _setup_compile_cache()
    # a harness timeout delivers SIGTERM, whose default disposition kills
    # the process without running finally blocks — convert it to SystemExit
    # so the summary still prints (the round-2 rc=124 artifact would have
    # been complete with this)
    import signal

    def _sigterm(signum, frame):
        raise SystemExit(124)

    signal.signal(signal.SIGTERM, _sigterm)
    if args.only != "ingest":
        # the ingest/write bench is host-only (native Avro codecs, no
        # device leg) — it stays runnable, and useful, with the
        # accelerator tunnel down
        _probe_device()
    _start_stall_watchdog()
    _GATE_DEFAULT[0] = not args.only
    if args.only:
        try:
            {"glm": bench_glm, "re": bench_random_effect,
             "re_sweep": bench_re_sweep, "cd": bench_cd_sweep,
             "ingest": bench_ingest, "e2e": bench_end_to_end,
             "refresh": bench_refresh,
             "freshness": bench_freshness,
             "serving": bench_serving_slo,
             "ranked": bench_serving_ranked,
             "fleet": bench_serving_fleet}[args.only]()
        finally:
            _emit_summary()
        return
    # Order = protecting the headline: the e2e metric runs FIRST, in the
    # cleanest process state — residue from earlier benches (10M-row CD
    # fixtures, host scipy baselines) measured 2-6x inflation on its
    # host-bound read stage. It measures its own baseline components at
    # the documented reduced slices (the standalone path). The
    # random-effect bench (slowest, long-stable) stays last so a harness
    # timeout costs the least-new information.
    def drain():
        # drop the previous bench's device buffers/compiled executables and
        # host garbage BEFORE the next one: the native bucket packer's
        # latency-bound walk measured 6 s in a lean process but 19-60 s
        # with earlier benches' multi-GB residue still resident (page-table
        # pressure on the random row gather) — the cleanup keeps each
        # bench's number a property of the bench, not of suite order
        import gc

        import jax

        jax.clear_caches()
        gc.collect()

    # the summary is emitted from a finally so that even a partial run
    # (timeout kill arrives between benches, one bench raises) leaves a
    # terminal line with everything measured so far
    try:
        bench_end_to_end()
        drain()
        bench_glm()
        drain()
        bench_cd_sweep()
        drain()
        bench_refresh()
        drain()
        bench_freshness()
        drain()
        bench_ingest()
        drain()
        bench_serving_slo()
        drain()
        bench_serving_ranked()
        drain()
        bench_serving_fleet()
        drain()
        bench_re_sweep()
        drain()
        bench_random_effect()
    finally:
        _emit_summary()


if __name__ == "__main__":
    main()
