"""Model persistence: GLM and GAME models ↔ the reference's directory layout.

Re-design of ``photon-client/.../data/avro/ModelProcessingUtils.scala``:

    output/
      model-metadata.json
      fixed-effect/<coordinateId>/coefficients/part-00000.avro
      random-effect/<coordinateId>/coefficients/part-00000.avro

Coefficient files are ``BayesianLinearModelAvro`` records — fixed effect =
one record, random effect = one record per entity (modelId = the raw entity
id) — so a Photon-ML user finds the same structure and record shape.
"""

from __future__ import annotations

import json
import os
from typing import Iterator, Optional

import numpy as np

from photon_ml_tpu.game.model import FixedEffectModel, GameModel, RandomEffectModel
from photon_ml_tpu.io.avro import iter_avro_file, write_avro_file
from photon_ml_tpu.io.index import IndexMap
from photon_ml_tpu.io.schemas import BAYESIAN_LINEAR_MODEL_AVRO
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.types import NAME_TERM_DELIMITER, TaskType


def _split_key(key: str) -> tuple[str, str]:
    if NAME_TERM_DELIMITER in key:
        name, term = key.split(NAME_TERM_DELIMITER, 1)
        return name, term
    return key, ""


def _ntv_list(values: np.ndarray, index_map: IndexMap, sparsity_threshold: float):
    names = index_map.names()
    out = []
    for i, v in enumerate(values):
        if abs(float(v)) > sparsity_threshold:
            name, term = _split_key(names[i])
            out.append({"name": name, "term": term, "value": float(v)})
    return out


def _from_ntv_list(entries, index_map: IndexMap) -> np.ndarray:
    from photon_ml_tpu.types import feature_key

    w = np.zeros(len(index_map), np.float32)
    for e in entries or ():
        idx = index_map.key_to_index.get(feature_key(e["name"], e.get("term") or ""))
        if idx is not None:
            w[idx] = e["value"]
    return w


# ---------------------------------------------------------------------------
# single GLM (legacy driver output)
# ---------------------------------------------------------------------------


def save_glm_model(path: str, model: GeneralizedLinearModel,
                   index_map: IndexMap, *, model_id: str = "best",
                   sparsity_threshold: float = 0.0) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    coeffs = model.coefficients
    record = {
        "modelId": model_id,
        "modelClass": model.task.value,
        "lossFunction": model.task.value,
        "means": _ntv_list(np.asarray(coeffs.means), index_map, sparsity_threshold),
        "variances": None if coeffs.variances is None else _ntv_list(
            np.asarray(coeffs.variances), index_map, -1.0),
    }
    write_avro_file(path, [record], BAYESIAN_LINEAR_MODEL_AVRO)


def save_glm_model_text(path: str, model: GeneralizedLinearModel,
                        index_map: IndexMap, *,
                        sparsity_threshold: float = 0.0) -> None:
    """Human-readable model file alongside the Avro (the reference's legacy
    ``Driver`` writes BOTH text and Avro models): one tab-separated
    ``name<TAB>term<TAB>value`` line per surviving coefficient, ordered by
    |value| descending so the strongest features read first."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    means = np.asarray(model.coefficients.means)
    names = index_map.names()
    order = np.argsort(-np.abs(means), kind="stable")
    with open(path, "w") as f:
        for i in order:
            v = float(means[i])
            if abs(v) <= sparsity_threshold:
                continue
            name, term = _split_key(names[int(i)])
            f.write(f"{name}\t{term}\t{v!r}\n")


def load_glm_model(path: str, index_map: IndexMap) -> GeneralizedLinearModel:
    import jax.numpy as jnp

    record = next(iter(iter_avro_file(path)))
    means = _from_ntv_list(record["means"], index_map)
    variances = (None if record.get("variances") is None
                 else _from_ntv_list(record["variances"], index_map))
    task = TaskType(record["modelClass"]) if record.get("modelClass") else \
        TaskType.LOGISTIC_REGRESSION
    return GeneralizedLinearModel(
        coefficients=Coefficients(
            means=jnp.asarray(means),
            variances=None if variances is None else jnp.asarray(variances)),
        task=task)


# ---------------------------------------------------------------------------
# GAME models
# ---------------------------------------------------------------------------


def _coordinate_kind(cm) -> tuple[str, dict]:
    """(directory kind, metadata extras) for one coordinate model."""
    if isinstance(cm, FixedEffectModel):
        return "fixed-effect", {"featureShardId": cm.feature_shard_id}
    return "random-effect", {"featureShardId": cm.feature_shard_id,
                             "randomEffectType": cm.random_effect_type}


def _write_coordinate_part(output_dir: str, cid: str, cm,
                           imap: IndexMap,
                           entity_vocabs: dict[str, dict[str, int]],
                           sparsity_threshold: float) -> str:
    """One coordinate's ``coefficients/part-00000.avro``, under an
    ``io.save.part`` span with the ``photon_save_*`` accounting — the leaf
    task the background saver fans out across its writer pool (the native
    RE writer releases the GIL, so coordinates encode concurrently)."""
    from photon_ml_tpu.io.pipeline import _save_bytes, _save_seconds
    from photon_ml_tpu.telemetry import tracing

    kind, _ = _coordinate_kind(cm)
    part = os.path.join(output_dir, kind, cid, "coefficients",
                        "part-00000.avro")
    os.makedirs(os.path.dirname(part), exist_ok=True)
    with tracing.span("io.save.part", coordinate=cid) as sp:
        if isinstance(cm, FixedEffectModel):
            save_glm_model(part, cm.model, imap, model_id=cid,
                           sparsity_threshold=sparsity_threshold)
        else:
            vocab = entity_vocabs[cm.random_effect_type]
            reverse = {v: k for k, v in vocab.items()}
            if not _save_re_model_native(part, cm, reverse, imap,
                                         sparsity_threshold):
                # codec pinned to null so the fallback emits the same
                # container properties as the native writer, not just the
                # same records
                write_avro_file(
                    part, _re_records(cm, imap, reverse, sparsity_threshold),
                    BAYESIAN_LINEAR_MODEL_AVRO, codec="null")
    _save_seconds().labels(coordinate=cid).inc(sp.seconds)
    _save_bytes().inc(os.path.getsize(part))
    return part


#: the lineage fields every ``model-metadata.json`` carries (null when the
#: writer supplies no lineage — deterministic, so byte-identity contracts
#: on repeated saves of the same model hold): ``parentModel`` is the
#: lineage id of the model this one warm-started from,``trainedAt`` an ISO
#: timestamp stamped by the driver, ``dataManifest`` the digest of the
#: run's ``data-manifest.json`` (continuous/delta.py).
LINEAGE_FIELDS = ("parentModel", "trainedAt", "dataManifest")


def _apply_lineage(metadata: dict, lineage) -> None:
    for field in LINEAGE_FIELDS:
        metadata[field] = (lineage or {}).get(field)


def save_game_model(
    output_dir: str,
    model: GameModel,
    index_maps: dict[str, IndexMap],
    entity_vocabs: dict[str, dict[str, int]],
    *,
    sparsity_threshold: float = 0.0,
    executor=None,
    lineage: Optional[dict] = None,
) -> None:
    """Write the reference's fixed-effect/random-effect directory tree.

    ``executor`` (a ``ThreadPoolExecutor``) fans the per-coordinate
    part-file writers out concurrently — the coordinate files are
    independent — and is how the async pipeline's background saver makes
    the save wall the *max* of the coordinate writes instead of their sum.
    The written bytes are identical either way (same writers, same record
    order; only the spec-mandated random container sync markers differ
    between any two Avro writes). ``lineage`` fills the
    :data:`LINEAGE_FIELDS` (null otherwise, keeping repeated saves of the
    same model deterministic)."""
    os.makedirs(output_dir, exist_ok=True)
    # one combined device→host pull for every coordinate's tables (vs one
    # round trip per coordinate as each writer touches its arrays)
    model.materialize()
    metadata = {"task": model.task.value, "coordinates": {}}
    _apply_lineage(metadata, lineage)
    jobs = []
    for cid, cm in model.coordinates.items():
        kind, extra = _coordinate_kind(cm)
        metadata["coordinates"][cid] = {"type": kind, **extra}
        imap = index_maps[cm.feature_shard_id]
        if executor is None:
            _write_coordinate_part(output_dir, cid, cm, imap, entity_vocabs,
                                   sparsity_threshold)
        else:
            import contextvars
            import functools

            ctx = contextvars.copy_context()
            jobs.append(executor.submit(ctx.run, functools.partial(
                _write_coordinate_part, output_dir, cid, cm, imap,
                entity_vocabs, sparsity_threshold)))
    for job in jobs:
        job.result()  # first writer error propagates to the save
    metadata_path = os.path.join(output_dir, "model-metadata.json")
    with open(metadata_path, "w") as f:
        json.dump(metadata, f, indent=2)
    from photon_ml_tpu.io.pipeline import _save_bytes

    _save_bytes().inc(os.path.getsize(metadata_path))


def _save_re_model_native(path: str, model: RandomEffectModel,
                          reverse_vocab: dict[int, str], index_map: IndexMap,
                          sparsity_threshold: float) -> bool:
    """Columnar fast path for the per-entity model part-file
    (``native/avro_writer.cc::photon_write_re_models``) — the Python record
    encoder made "Save models" the largest stage of a warm GAME driver run
    (~4 s at 11k entities). Record-identical to :func:`_re_records` (see
    tests/test_native.py); False (fall back) when the native library is
    missing or the model needs per-entity back-projection (RANDOM
    projector — a dense matmul per entity, not a columnar stream)."""
    from photon_ml_tpu import native

    if model.projector is not None or not native.available():
        return False
    keys = np.asarray(model.keys)
    coeffs = np.asarray(model.coeffs, np.float64)
    entity_of = keys // model.dim
    feat_of = (keys % model.dim).astype(np.int32)
    # record per distinct entity, in key order (keys are sorted)
    starts = np.flatnonzero(np.r_[True, entity_of[1:] != entity_of[:-1]]) \
        if len(keys) else np.zeros(0, np.int64)
    entities = entity_of[starts]
    n_models = len(entities)
    counts = np.diff(np.append(starts, len(keys)))
    seg_of = np.repeat(np.arange(n_models), counts)
    keep = np.abs(coeffs) > sparsity_threshold
    rec_indptr = np.zeros(n_models + 1, np.int64)
    np.cumsum(np.bincount(seg_of[keep], minlength=n_models),
              out=rec_indptr[1:])
    variances = (np.asarray(model.variances, np.float64)[keep]
                 if model.variances is not None else None)
    split = [_split_key(k) for k in index_map.names()]
    return native.write_re_models(
        path,
        model_ids=[reverse_vocab.get(int(e), str(int(e))) for e in entities],
        model_class=model.task.value,
        rec_indptr=rec_indptr,
        name_ids=feat_of[keep],
        values=coeffs[keep],
        variances=variances,
        names=[s[0] for s in split],
        terms=[s[1] for s in split])


def _re_records(model: RandomEffectModel, index_map: IndexMap,
                reverse_vocab: dict[int, str],
                sparsity_threshold: float) -> Iterator[dict]:
    """Per-entity ``BayesianLinearModelAvro`` records.

    RANDOM-projected models export in original feature space (reference:
    models projected back after training); the back-projection is done one
    entity at a time inside the stream so peak memory stays O(shard_dim)
    regardless of entity count.
    """
    names = index_map.names()
    if not len(model.keys):
        return
    proj = model.projector
    entity_of = model.keys // model.dim
    feat_of = model.keys % model.dim
    starts = np.flatnonzero(np.r_[True, entity_of[1:] != entity_of[:-1]])
    bounds = np.r_[starts, len(model.keys)]
    for s, e in zip(bounds[:-1], bounds[1:]):
        entity = int(entity_of[s])
        if proj is not None:
            v = np.zeros(model.dim, np.float32)
            v[feat_of[s:e]] = model.coeffs[s:e]
            feats = np.arange(proj.shard_dim, dtype=np.int64)
            vals = proj.project_back(v)
            var_vals = None
            if model.variances is not None:
                var_v = np.zeros(model.dim, np.float32)
                var_v[feat_of[s:e]] = model.variances[s:e]
                var_vals = proj.project_back_variances(var_v)
        else:
            feats = feat_of[s:e]
            vals = model.coeffs[s:e]
            var_vals = (model.variances[s:e]
                        if model.variances is not None else None)
        means = []
        variances = [] if var_vals is not None else None
        for idx, (j, v) in enumerate(zip(feats, vals)):
            v = float(v)
            if abs(v) <= sparsity_threshold:
                continue
            name, term = _split_key(names[int(j)])
            means.append({"name": name, "term": term, "value": v})
            if variances is not None:
                variances.append({"name": name, "term": term,
                                  "value": float(var_vals[idx])})
        yield {
            "modelId": reverse_vocab.get(entity, str(entity)),
            "modelClass": model.task.value,
            "lossFunction": model.task.value,
            "means": means,
            "variances": variances,
        }


#: the ``kind`` metadata value marking an entity-level coefficient patch
#: (continuous-training delta publish) instead of a full model tree
PATCH_KIND = "coefficient-patch"


def save_game_model_patch(
    output_dir: str,
    patch_models: dict[str, "FixedEffectModel | RandomEffectModel"],
    index_maps: dict[str, IndexMap],
    entity_vocabs: dict[str, dict[str, int]],
    *,
    task: TaskType,
    parent_model: str,
    model_id: str,
    removed: Optional[dict[str, list[str]]] = None,
    lineage: Optional[dict] = None,
    sparsity_threshold: float = 0.0,
    fleet_shard: Optional[tuple] = None,
) -> None:
    """Write an entity-level coefficient patch (continuous training's
    delta-publish artifact).

    Same directory layout and record shapes as a full model — a patch IS a
    model tree, just a partial one: fixed-effect coordinates in full
    (always retrained, one record each), random-effect coordinates holding
    ONLY the re-solved entities' records. The metadata marks it
    ``kind=coefficient-patch`` and records its lineage: ``parentModel``
    (the lineage id of the model whose serving tables it patches — the
    registry refuses a mismatch) and ``modelId`` (the lineage id of the
    equivalent merged full model, which becomes the patched version's
    identity so the NEXT patch can chain). ``removed`` lists raw entity
    ids per coordinate whose models vanished this refresh; serving zeroes
    their rows. ``fleet_shard=(index, count)`` marks a PER-HOST patch
    (``refresh_game --fleet-shards``): metadata ``fleetShard`` /
    ``fleetShardCount`` name the one serving shard whose rows it carries,
    and a host serving any other shard refuses it at validation.
    """
    os.makedirs(output_dir, exist_ok=True)
    metadata: dict = {"task": task.value, "kind": PATCH_KIND,
                      "modelId": model_id, "parentModel": parent_model,
                      "coordinates": {}}
    if fleet_shard is not None:
        metadata["fleetShard"] = int(fleet_shard[0])
        metadata["fleetShardCount"] = int(fleet_shard[1])
    _apply_lineage(metadata, {**(lineage or {}),
                              "parentModel": parent_model})
    for cid, cm in patch_models.items():
        kind, extra = _coordinate_kind(cm)
        entry = {"type": kind, **extra}
        rm = (removed or {}).get(cid)
        if rm:
            entry["removedEntities"] = sorted(rm)
        metadata["coordinates"][cid] = entry
        _write_coordinate_part(output_dir, cid, cm,
                               index_maps[cm.feature_shard_id],
                               entity_vocabs, sparsity_threshold)
    metadata_path = os.path.join(output_dir, "model-metadata.json")
    with open(metadata_path, "w") as f:
        json.dump(metadata, f, indent=2)
    from photon_ml_tpu.io.pipeline import _save_bytes

    _save_bytes().inc(os.path.getsize(metadata_path))


def model_kind(model_dir: str) -> str:
    """``"model"`` or ``"coefficient-patch"`` for a resolved model dir."""
    with open(os.path.join(model_dir, "model-metadata.json")) as f:
        return json.load(f).get("kind") or "model"


def model_lineage_id(model_dir: str) -> str:
    """Content identity of a saved model: blake2b over the metadata's
    structural fields and every coordinate's DECODED records.

    Writer-agnostic on purpose: Avro container bytes differ between any
    two writes (random sync markers) and alias dirs rewrite metadata with
    ``aliasOf``, but the records — and therefore this id — are identical
    for the same model content. This is the currency of the continuous
    loop's lineage checks: a patch names its parent's lineage id and the
    serving registry refuses to overlay it on any other version's tables.
    """
    import hashlib

    model_dir = resolve_game_model_dir(model_dir)
    with open(os.path.join(model_dir, "model-metadata.json")) as f:
        metadata = json.load(f)
    h = hashlib.blake2b(digest_size=16)
    structural = {
        "task": metadata["task"],
        "kind": metadata.get("kind"),
        "coordinates": {
            cid: {k: info.get(k) for k in ("type", "featureShardId",
                                           "randomEffectType")}
            for cid, info in metadata["coordinates"].items()},
    }
    h.update(json.dumps(structural, sort_keys=True).encode())
    for cid in sorted(metadata["coordinates"]):
        info = metadata["coordinates"][cid]
        part = os.path.join(model_dir, info["type"], cid, "coefficients",
                            "part-00000.avro")
        h.update(cid.encode())
        for rec in iter_avro_file(part):
            h.update(json.dumps(rec, sort_keys=True).encode())
    return h.hexdigest()


def resolve_game_model_dir(path: str) -> str:
    """Accept a ``train_game`` run dir (containing ``best/``) or a model dir
    holding ``model-metadata.json`` directly — the lookup every consumer of
    a saved GAME model (batch scorer, serving registry) shares."""
    path = os.path.normpath(path)
    if os.path.exists(os.path.join(path, "model-metadata.json")):
        return path
    nested = os.path.join(path, "best")
    if os.path.exists(os.path.join(nested, "model-metadata.json")):
        return nested
    raise FileNotFoundError(f"no model-metadata.json under {path!r}")


def find_feature_index_dir(model_dir: str, *, max_up: int = 3) -> str:
    """Locate the run's ``feature-indexes`` directory: it lives at the
    train_game run root, while the model may sit at ``<run>/best`` or
    ``<run>/all/config-N`` — walk up to find it."""
    probe = os.path.normpath(model_dir)
    for _ in range(max_up):
        candidate = os.path.join(probe, "feature-indexes")
        if os.path.isdir(candidate):
            return candidate
        probe = os.path.dirname(probe)
    raise FileNotFoundError(
        f"no feature-indexes directory at or above {model_dir!r}")


def game_model_entity_vocabs(model_dir: str,
                             metadata: Optional[dict] = None,
                             ) -> dict[str, dict[str, int]]:
    """Entity vocabularies derived from the MODEL's own coefficient files
    (raw ``modelId`` strings → dense ids, in record order per part file).

    The batch scorer keys entity lookups off the *dataset*'s vocabulary;
    online serving has no dataset — requests arrive one at a time — so the
    model's saved per-entity records are the authoritative id universe.
    Coordinates sharing a random-effect type merge into one vocabulary
    (ids from the first coordinate's record order, extended by later ones).
    """
    if metadata is None:
        with open(os.path.join(model_dir, "model-metadata.json")) as f:
            metadata = json.load(f)
    vocabs: dict[str, dict[str, int]] = {}
    for cid, info in metadata["coordinates"].items():
        if info["type"] != "random-effect":
            continue
        vocab = vocabs.setdefault(info["randomEffectType"], {})
        part = os.path.join(model_dir, info["type"], cid, "coefficients",
                            "part-00000.avro")
        for rec in iter_avro_file(part):
            raw = rec["modelId"]
            if raw not in vocab:
                vocab[raw] = len(vocab)
    return vocabs


def load_game_model(
    output_dir: str,
    index_maps: dict[str, IndexMap],
    entity_vocabs: dict[str, dict[str, int]],
) -> GameModel:
    import jax.numpy as jnp

    with open(os.path.join(output_dir, "model-metadata.json")) as f:
        metadata = json.load(f)
    task = TaskType(metadata["task"])
    coordinates = {}
    for cid, info in metadata["coordinates"].items():
        shard_id = info["featureShardId"]
        imap = index_maps[shard_id]
        part = os.path.join(output_dir, info["type"], cid, "coefficients",
                            "part-00000.avro")
        if info["type"] == "fixed-effect":
            glm = load_glm_model(part, imap)
            coordinates[cid] = FixedEffectModel(
                model=GeneralizedLinearModel(
                    coefficients=glm.coefficients, task=task),
                feature_shard_id=shard_id)
        else:
            re_type = info["randomEffectType"]
            vocab = entity_vocabs[re_type]
            dim = len(imap)
            keys, coeffs, variances = [], [], []
            has_var = False
            from photon_ml_tpu.types import feature_key

            for rec in iter_avro_file(part):
                entity = vocab.get(rec["modelId"])
                if entity is None:
                    continue  # entity absent from this dataset's vocab
                # variances are keyed by (name, term) just like means; index
                # them so a feature missing from the load-time map drops its
                # variance too (keeping coeffs/variances aligned)
                var_by_key = {
                    feature_key(e["name"], e.get("term") or ""): e["value"]
                    for e in rec.get("variances") or ()}
                for e in rec["means"] or ():
                    key = feature_key(e["name"], e.get("term") or "")
                    j = imap.key_to_index.get(key)
                    if j is not None:
                        keys.append(entity * dim + j)
                        coeffs.append(e["value"])
                        if var_by_key:
                            has_var = True
                            variances.append(var_by_key.get(key, 0.0))
            keys = np.asarray(keys, np.int64)
            order = np.argsort(keys, kind="stable")
            coordinates[cid] = RandomEffectModel(
                random_effect_type=re_type, feature_shard_id=shard_id,
                task=task, dim=dim, keys=keys[order],
                coeffs=np.asarray(coeffs, np.float32)[order],
                variances=(np.asarray(variances, np.float32)[order]
                           if has_var else None))
    return GameModel(coordinates=coordinates, task=task)
