"""The Photon-ML Avro schemas, as Python dicts.

Counterparts of ``photon-avro-schemas/src/main/avro/*.avsc``: the training
record (response/offset/weight/id + feature list of (name, term, value)),
the Bayesian linear model output (means + variances as name-term-value
lists), the scoring output, and per-feature summarization stats. Namespaces
kept Photon-compatible so files interchange with reference tooling.
"""

NAMESPACE = "com.linkedin.photon.avro.generated"

FEATURE_AVRO = {
    "type": "record",
    "name": "FeatureAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string", "default": ""},
        {"name": "value", "type": "double"},
    ],
}

TRAINING_EXAMPLE_AVRO = {
    "type": "record",
    "name": "TrainingExampleAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "response", "type": "double"},
        {"name": "offset", "type": ["null", "double"], "default": None},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "features", "type": {"type": "array", "items": FEATURE_AVRO}},
        # entity-id tags for GAME (userId, songId, ...) and grouped metrics
        {"name": "metadataMap", "type": ["null", {"type": "map", "values": "string"}],
         "default": None},
    ],
}

NAME_TERM_VALUE_AVRO = {
    "type": "record",
    "name": "NameTermValueAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string", "default": ""},
        {"name": "value", "type": "double"},
    ],
}

BAYESIAN_LINEAR_MODEL_AVRO = {
    "type": "record",
    "name": "BayesianLinearModelAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "modelId", "type": "string"},
        {"name": "modelClass", "type": ["null", "string"], "default": None},
        {"name": "lossFunction", "type": ["null", "string"], "default": None},
        {"name": "means", "type": {"type": "array", "items": NAME_TERM_VALUE_AVRO}},
        {"name": "variances",
         "type": ["null", {"type": "array", "items": "NameTermValueAvro"}],
         "default": None},
    ],
}

SCORING_RESULT_AVRO = {
    "type": "record",
    "name": "ScoringResultAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "predictionScore", "type": "double"},
        {"name": "label", "type": ["null", "double"], "default": None},
        {"name": "metadataMap", "type": ["null", {"type": "map", "values": "string"}],
         "default": None},
    ],
}

FEATURE_SUMMARIZATION_RESULT_AVRO = {
    "type": "record",
    "name": "FeatureSummarizationResultAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "featureName", "type": "string"},
        {"name": "featureTerm", "type": "string", "default": ""},
        {"name": "metrics", "type": {"type": "map", "values": "double"}},
    ],
}
