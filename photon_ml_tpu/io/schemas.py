"""The Photon-ML Avro schemas, as Python dicts.

Counterparts of ``photon-avro-schemas/src/main/avro/*.avsc``: the training
record (response/offset/weight/id + feature list of (name, term, value)),
the Bayesian linear model output (means + variances as name-term-value
lists), the scoring output, and per-feature summarization stats. Namespaces
kept Photon-compatible so files interchange with reference tooling.
"""

NAMESPACE = "com.linkedin.photon.avro.generated"

FEATURE_AVRO = {
    "type": "record",
    "name": "FeatureAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string", "default": ""},
        {"name": "value", "type": "double"},
    ],
}

TRAINING_EXAMPLE_AVRO = {
    "type": "record",
    "name": "TrainingExampleAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "response", "type": "double"},
        {"name": "offset", "type": ["null", "double"], "default": None},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "features", "type": {"type": "array", "items": FEATURE_AVRO}},
        # entity-id tags for GAME (userId, songId, ...) and grouped metrics
        {"name": "metadataMap", "type": ["null", {"type": "map", "values": "string"}],
         "default": None},
    ],
}

NAME_TERM_VALUE_AVRO = {
    "type": "record",
    "name": "NameTermValueAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string", "default": ""},
        {"name": "value", "type": "double"},
    ],
}

BAYESIAN_LINEAR_MODEL_AVRO = {
    "type": "record",
    "name": "BayesianLinearModelAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "modelId", "type": "string"},
        {"name": "modelClass", "type": ["null", "string"], "default": None},
        {"name": "lossFunction", "type": ["null", "string"], "default": None},
        {"name": "means", "type": {"type": "array", "items": NAME_TERM_VALUE_AVRO}},
        {"name": "variances",
         "type": ["null", {"type": "array", "items": "NameTermValueAvro"}],
         "default": None},
    ],
}

SCORING_RESULT_AVRO = {
    "type": "record",
    "name": "ScoringResultAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "predictionScore", "type": "double"},
        {"name": "label", "type": ["null", "double"], "default": None},
        {"name": "metadataMap", "type": ["null", {"type": "map", "values": "string"}],
         "default": None},
    ],
}

# Serving request/score log (serving/reqlog.py — the one sanctioned writer,
# telemetry hygiene rule 7). One record per SERVED REQUEST: the request id
# assigned at the HTTP layer, the model lineage that answered it, the
# per-stage timings the front end measured, and the full scored records
# (features + entity ids + score) so ``tools/reqlog_replay.py`` can re-score
# the exact inputs against the named lineage and assert bit-parity.
REQUEST_LOG_SCORED_RECORD_AVRO = {
    "type": "record",
    "name": "RequestLogScoredRecordAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "features", "type": {"type": "array", "items": FEATURE_AVRO}},
        {"name": "metadataMap",
         "type": ["null", {"type": "map", "values": "string"}],
         "default": None},
        {"name": "offset", "type": ["null", "double"], "default": None},
        # the served f32 score widened to double — exact, so replay
        # comparison is bit-level
        {"name": "score", "type": "double"},
        # optional ground truth attached AT REQUEST TIME (backfill/replay
        # clients that already know the outcome); most live traffic leaves
        # it null and the feedback joiner attaches labels later from an
        # external source keyed by request id. Readers decode with the
        # embedded writer schema, so old segments without the field stay
        # readable (feedback/joiner.py uses .get)
        {"name": "label", "type": ["null", "double"], "default": None},
    ],
}

# Ranked requests log their returned top-k (ids best-first + the served
# f32 scores widened to double) so ``tools/reqlog_replay.py`` can re-rank
# the logged request against the named lineage and assert the ids AND
# scores come back bit-identical.
REQUEST_LOG_TOPK_AVRO = {
    "type": "record",
    "name": "RequestLogTopKAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "k", "type": "long"},
        {"name": "ids", "type": {"type": "array", "items": "string"}},
        {"name": "scores", "type": {"type": "array", "items": "double"}},
    ],
}

REQUEST_LOG_AVRO = {
    "type": "record",
    "name": "RequestLogAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "requestId", "type": "string"},
        {"name": "ts", "type": "double"},  # wall-clock timestamp (epoch s)
        # which serving workload answered: "score" (records carry served
        # scores) or "rank" (records carry the REQUEST record; the
        # result lands in topk)
        {"name": "kind", "type": "string", "default": "score"},
        {"name": "modelVersion", "type": "long"},
        {"name": "modelLineage", "type": ["null", "string"], "default": None},
        {"name": "stageMs", "type": {"type": "map", "values": "double"},
         "default": {}},
        {"name": "records",
         "type": {"type": "array", "items": REQUEST_LOG_SCORED_RECORD_AVRO}},
        {"name": "topk", "type": ["null", REQUEST_LOG_TOPK_AVRO],
         "default": None},
    ],
}

# External label source for the feedback joiner (feedback/joiner.py): one
# record per observed outcome, keyed by the request id the serving front
# end assigned (and echoed to the client) plus the record's index within
# that request. The joiner matches these against logged
# RequestLogScoredRecordAvro rows to build incremental training data.
FEEDBACK_LABEL_AVRO = {
    "type": "record",
    "name": "FeedbackLabelAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "requestId", "type": "string"},
        {"name": "recordIndex", "type": "long", "default": 0},
        {"name": "label", "type": "double"},
    ],
}

FEATURE_SUMMARIZATION_RESULT_AVRO = {
    "type": "record",
    "name": "FeatureSummarizationResultAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "featureName", "type": "string"},
        {"name": "featureTerm", "type": "string", "default": ""},
        {"name": "metrics", "type": {"type": "map", "values": "double"}},
    ],
}
