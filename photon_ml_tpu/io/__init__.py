"""Host-side IO: Avro, feature indexing, model persistence, checkpoints.

Re-design of the reference's IO surface (``photon-avro-schemas/``,
``photon-client/.../data/avro/``, ``photon-client/.../index/``): Avro stays
the on-disk interchange format (a self-contained codec — no fastavro in this
environment), the PalDB feature store becomes a host dict with a compact
on-disk form, and model directories mirror the reference's HDFS layout so a
Photon-ML user finds the same structure.
"""

from photon_ml_tpu.io.avro import (  # noqa: F401
    read_avro_file,
    write_avro_file,
)
from photon_ml_tpu.io.index import (  # noqa: F401
    DefaultIndexMap,
    IndexMap,
    build_index_map,
)
from photon_ml_tpu.io.data_reader import AvroDataReader, FeatureShardConfig  # noqa: F401
from photon_ml_tpu.io.model_io import (  # noqa: F401
    find_feature_index_dir,
    game_model_entity_vocabs,
    load_game_model,
    load_glm_model,
    resolve_game_model_dir,
    save_game_model,
    save_glm_model,
    save_glm_model_text,
)
from photon_ml_tpu.io.checkpoint import CheckpointManager  # noqa: F401
from photon_ml_tpu.io.pipeline import (  # noqa: F401
    BackgroundSaver,
    DecodePrefetcher,
    publish_model_alias,
    read_in_background,
    save_game_model_atomic,
)
