"""Avro training data → columnar :class:`GameData` with feature shards.

Re-design of ``photon-client/.../data/avro/AvroDataReader.scala`` +
``GameConverters.scala``: each record's feature list is split into
**feature shards** (named bags of features + optional intercept, the
reference's ``featureShardConfigurations``), feature keys map to dense ids
through an :class:`IndexMap` per shard, entity-id columns come from the
record's metadata map, and everything lands in flat numpy arrays (the
host-side layout the device path consumes) instead of an RDD of
``GameDatum``.
"""

from __future__ import annotations

import dataclasses
import glob as globmod
import os
from typing import Iterable, Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu.game.data import FeatureShard, GameData
from photon_ml_tpu.io.avro import iter_avro_file
from photon_ml_tpu.io.index import IndexMap, build_index_map
from photon_ml_tpu.types import INTERCEPT_KEY, feature_key


@dataclasses.dataclass(frozen=True)
class FeatureShardConfig:
    """One shard: which feature bags it includes and whether it gets an
    intercept column (reference ``FeatureShardConfiguration``).

    With ``feature_bags=None`` the shard takes every feature in the record
    (the single-shard legacy GLM path).
    """

    shard_id: str
    feature_bags: Optional[Sequence[str]] = None
    has_intercept: bool = True


def _record_features(record: dict, bags: Optional[Sequence[str]]):
    """Yield (key, value) for the record's features, filtered by bag.

    Reference records carry features in a flat list; "bags" select by the
    feature's ``name`` prefix ``bag.`` or by exact bag-name match of the
    Avro field. We use the common LinkedIn layout: one flat ``features``
    array, bag = prefix before the first ``.`` in ``name`` when present.
    """
    for f in record.get("features") or ():
        name = f["name"]
        if bags is not None:
            bag = name.split(".", 1)[0] if "." in name else name
            if bag not in bags:
                continue
        yield feature_key(name, f.get("term") or ""), float(f["value"])


@dataclasses.dataclass
class AvroDataReader:
    """Reads Avro container files into :class:`GameData`."""

    shard_configs: Sequence[FeatureShardConfig] = (
        FeatureShardConfig(shard_id="global"),)
    #: per-shard index maps; built from data when absent (training) and
    #: reused for validation/scoring reads so ids line up.
    index_maps: Optional[dict[str, IndexMap]] = None

    def paths(self, input_path: str) -> list[str]:
        if os.path.isdir(input_path):
            found = sorted(globmod.glob(os.path.join(input_path, "*.avro")))
        else:
            found = sorted(globmod.glob(input_path)) or [input_path]
        if not found:
            raise FileNotFoundError(f"no avro files under {input_path!r}")
        return found

    def build_index_maps(self, records: Iterable[dict]) -> dict[str, IndexMap]:
        keys: dict[str, set] = {c.shard_id: set() for c in self.shard_configs}
        for rec in records:
            for cfg in self.shard_configs:
                for key, _ in _record_features(rec, cfg.feature_bags):
                    keys[cfg.shard_id].add(key)
        return {
            cfg.shard_id: build_index_map(keys[cfg.shard_id],
                                          add_intercept=cfg.has_intercept)
            for cfg in self.shard_configs}

    def read(self, input_path: str,
             id_columns: Sequence[str] = (),
             entity_vocabs: Optional[dict[str, dict[str, int]]] = None,
             ) -> tuple[GameData, dict[str, IndexMap], dict[str, dict[str, int]]]:
        """Read records → (GameData, index maps, entity vocabularies).

        ``id_columns`` names metadataMap keys to turn into entity-id columns
        (GAME random-effect types and grouped-metric tags). Vocabularies map
        raw string ids → dense ints; pass training vocabs when reading
        validation data so entity ids align.
        """
        files = self.paths(input_path)
        records = [r for p in files for r in iter_avro_file(p)]

        index_maps = self.index_maps or self.build_index_maps(records)
        vocabs: dict[str, dict[str, int]] = {
            c: dict(v) for c, v in (entity_vocabs or {}).items()}
        frozen_vocab = entity_vocabs is not None

        n = len(records)
        labels = np.zeros(n, np.float32)
        offsets = np.zeros(n, np.float32)
        weights = np.ones(n, np.float32)
        ids = {c: np.full(n, -1, np.int64) for c in id_columns}

        shard_rows: dict[str, list] = {c.shard_id: [] for c in self.shard_configs}
        shard_cols: dict[str, list] = {c.shard_id: [] for c in self.shard_configs}
        shard_vals: dict[str, list] = {c.shard_id: [] for c in self.shard_configs}

        for i, rec in enumerate(records):
            labels[i] = rec["response"]
            if rec.get("offset") is not None:
                offsets[i] = rec["offset"]
            if rec.get("weight") is not None:
                weights[i] = rec["weight"]
            meta = rec.get("metadataMap") or {}
            for c in id_columns:
                raw = meta.get(c)
                if raw is None:
                    continue
                vocab = vocabs.setdefault(c, {})
                if raw not in vocab:
                    if frozen_vocab:
                        continue  # unseen entity at validation time: no id
                    vocab[raw] = len(vocab)
                ids[c][i] = vocab[raw]
            for cfg in self.shard_configs:
                imap = index_maps[cfg.shard_id]
                rs, cs, vs = (shard_rows[cfg.shard_id],
                              shard_cols[cfg.shard_id], shard_vals[cfg.shard_id])
                for key, value in _record_features(rec, cfg.feature_bags):
                    j = imap.key_to_index.get(key)
                    if j is not None:
                        rs.append(i)
                        cs.append(j)
                        vs.append(value)
                if cfg.has_intercept:
                    rs.append(i)
                    cs.append(imap.key_to_index[INTERCEPT_KEY])
                    vs.append(1.0)

        shards = {
            cfg.shard_id: FeatureShard.from_coo(
                np.asarray(shard_rows[cfg.shard_id], np.int64),
                np.asarray(shard_cols[cfg.shard_id], np.int32),
                np.asarray(shard_vals[cfg.shard_id], np.float32),
                n, len(index_maps[cfg.shard_id]))
            for cfg in self.shard_configs}

        data = GameData(labels=labels, offsets=offsets, weights=weights,
                        shards=shards, id_columns=ids)
        return data, index_maps, vocabs


def write_training_examples(path: str, data_records: Iterable[dict]) -> int:
    """Convenience writer for tests/examples (TrainingExampleAvro rows)."""
    from photon_ml_tpu.io.avro import write_avro_file
    from photon_ml_tpu.io.schemas import TRAINING_EXAMPLE_AVRO

    return write_avro_file(path, data_records, TRAINING_EXAMPLE_AVRO)
