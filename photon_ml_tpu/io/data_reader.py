"""Avro training data → columnar :class:`GameData` with feature shards.

Re-design of ``photon-client/.../data/avro/AvroDataReader.scala`` +
``GameConverters.scala``: each record's feature list is split into
**feature shards** (named bags of features + optional intercept, the
reference's ``featureShardConfigurations``), feature keys map to dense ids
through an :class:`IndexMap` per shard, entity-id columns come from the
record's metadata map, and everything lands in flat numpy arrays (the
host-side layout the device path consumes) instead of an RDD of
``GameDatum``.
"""

from __future__ import annotations

import dataclasses
import glob as globmod
import os
from typing import Iterable, Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu.game.data import FeatureShard, GameData
from photon_ml_tpu.io.avro import iter_avro_file
from photon_ml_tpu.io.index import IndexMap, build_index_map
from photon_ml_tpu.types import INTERCEPT_KEY, NAME_TERM_DELIMITER, feature_key


@dataclasses.dataclass(frozen=True)
class FeatureShardConfig:
    """One shard: which feature bags it includes and whether it gets an
    intercept column (reference ``FeatureShardConfiguration``).

    With ``feature_bags=None`` the shard takes every feature in the record
    (the single-shard legacy GLM path).
    """

    shard_id: str
    feature_bags: Optional[Sequence[str]] = None
    has_intercept: bool = True


@dataclasses.dataclass(frozen=True)
class InputColumnsNames:
    """Logical → physical record-field remapping
    (reference ``data/InputColumnsNames.scala``): datasets whose fields are
    named differently (e.g. ``label`` instead of ``response``) read without
    rewriting the files."""

    response: str = "response"
    offset: str = "offset"
    weight: str = "weight"
    #: accepted for reference-config parity; the reader never consumes uids
    #: (scoring output numbers records), so remapping it changes nothing
    uid: str = "uid"
    features: str = "features"
    metadata_map: str = "metadataMap"

    #: fields that actually drive decoding (uid excluded — see above)
    _DECODE_FIELDS = ("response", "offset", "weight", "features",
                      "metadata_map")

    @property
    def is_default(self) -> bool:
        default = InputColumnsNames()
        return all(getattr(self, f) == getattr(default, f)
                   for f in self._DECODE_FIELDS)


def parse_input_columns(spec: str) -> InputColumnsNames:
    """'response=label,weight=w' → :class:`InputColumnsNames` (the CLI
    drivers' shared ``--input-columns`` parser)."""
    if not spec:
        return InputColumnsNames()
    overrides = {}
    valid = {f.name for f in dataclasses.fields(InputColumnsNames)}
    for part in spec.split(","):
        logical, _, physical = part.partition("=")
        logical = logical.strip()
        physical = physical.strip()
        if logical not in valid or not physical:
            raise SystemExit(
                f"bad --input-columns entry {part!r}; logical names: "
                f"{sorted(valid)}")
        overrides[logical] = physical
    return InputColumnsNames(**overrides)


def _read_records_with_retry(path: str) -> list:
    """One file's records, under the resilience retry policy (transient
    read errors — flaky network filesystems, injected ``io.read`` faults —
    are retried with backoff; persistent ones re-raise unchanged)."""
    from photon_ml_tpu.resilience import fault_point, heartbeat, retry

    def attempt() -> list:
        heartbeat("io.read")
        fault_point("io.read", path=path)
        return list(iter_avro_file(path))

    return retry(attempt, name=f"io.read:{os.path.basename(path)}")


def _record_features(record: dict, bags: Optional[Sequence[str]],
                     features_field: str = "features"):
    """Yield (key, value) for the record's features, filtered by bag.

    Reference records carry features in a flat list; "bags" select by the
    feature's ``name`` prefix ``bag.`` or by exact bag-name match of the
    Avro field. We use the common LinkedIn layout: one flat ``features``
    array, bag = prefix before the first ``.`` in ``name`` when present.
    """
    for f in record.get(features_field) or ():
        name = f["name"]
        if bags is not None:
            bag = name.split(".", 1)[0] if "." in name else name
            if bag not in bags:
                continue
        yield feature_key(name, f.get("term") or ""), float(f["value"])


@dataclasses.dataclass
class AvroDataReader:
    """Reads Avro container files into :class:`GameData`.

    Decoding prefers the native C++ fast path
    (:mod:`photon_ml_tpu.native`, ~30x the pure-Python codec) and falls back
    transparently when the library or the file's schema shape is unsuitable.
    """

    shard_configs: Sequence[FeatureShardConfig] = (
        FeatureShardConfig(shard_id="global"),)
    #: per-shard index maps; built from data when absent (training) and
    #: reused for validation/scoring reads so ids line up.
    index_maps: Optional[dict[str, IndexMap]] = None
    use_native: bool = True
    #: physical field names (reference InputColumnsNames); non-default
    #: mappings use the Python codec (the native decoder reads the
    #: canonical layout only).
    input_columns: InputColumnsNames = InputColumnsNames()

    def paths(self, input_path) -> list[str]:
        """Resolve a directory / glob / single file — or an explicit list
        of files (the multi-process drivers partition the file list across
        processes, the reference's executor-local read assignment)."""
        if isinstance(input_path, (list, tuple)):
            found = [str(p) for p in input_path]
        elif os.path.isdir(input_path):
            found = sorted(globmod.glob(os.path.join(input_path, "*.avro")))
        else:
            found = sorted(globmod.glob(input_path)) or [input_path]
        if not found:
            raise FileNotFoundError(f"no avro files under {input_path!r}")
        return found

    def build_index_maps(self, records: Iterable[dict]) -> dict[str, IndexMap]:
        keys: dict[str, set] = {c.shard_id: set() for c in self.shard_configs}
        for rec in records:
            for cfg in self.shard_configs:
                for key, _ in _record_features(rec, cfg.feature_bags,
                                               self.input_columns.features):
                    keys[cfg.shard_id].add(key)
        return {
            cfg.shard_id: build_index_map(keys[cfg.shard_id],
                                          add_intercept=cfg.has_intercept)
            for cfg in self.shard_configs}

    def read(self, input_path: "str | Sequence[str]",
             id_columns: Sequence[str] = (),
             entity_vocabs: Optional[dict[str, dict[str, int]]] = None,
             ) -> tuple[GameData, dict[str, IndexMap], dict[str, dict[str, int]]]:
        """Read records → (GameData, index maps, entity vocabularies).

        ``input_path`` is a directory / glob / single file, or an explicit
        list of files (multi-process drivers pass each process's share of
        the file list — see :meth:`paths`). ``id_columns`` names
        metadataMap keys to turn into entity-id columns (GAME
        random-effect types and grouped-metric tags). Vocabularies map
        raw string ids → dense ints; pass training vocabs when reading
        validation data so entity ids align.
        """
        files = self.paths(input_path)
        if self.use_native and self.input_columns.is_default:
            native_out = self._read_native(files, id_columns, entity_vocabs)
            if native_out is not None:
                return native_out
        records = [r for p in files for r in _read_records_with_retry(p)]

        index_maps = self.index_maps or self.build_index_maps(records)
        vocabs: dict[str, dict[str, int]] = {
            c: dict(v) for c, v in (entity_vocabs or {}).items()}
        frozen_vocab = entity_vocabs is not None

        n = len(records)
        labels = np.zeros(n, np.float32)
        offsets = np.zeros(n, np.float32)
        weights = np.ones(n, np.float32)
        ids = {c: np.full(n, -1, np.int64) for c in id_columns}

        shard_rows: dict[str, list] = {c.shard_id: [] for c in self.shard_configs}
        shard_cols: dict[str, list] = {c.shard_id: [] for c in self.shard_configs}
        shard_vals: dict[str, list] = {c.shard_id: [] for c in self.shard_configs}

        cols = self.input_columns
        for i, rec in enumerate(records):
            labels[i] = rec[cols.response]
            if rec.get(cols.offset) is not None:
                offsets[i] = rec[cols.offset]
            if rec.get(cols.weight) is not None:
                weights[i] = rec[cols.weight]
            meta = rec.get(cols.metadata_map) or {}
            for c in id_columns:
                raw = meta.get(c)
                if raw is None:
                    continue
                vocab = vocabs.setdefault(c, {})
                if raw not in vocab:
                    if frozen_vocab:
                        continue  # unseen entity at validation time: no id
                    vocab[raw] = len(vocab)
                ids[c][i] = vocab[raw]
            for cfg in self.shard_configs:
                imap = index_maps[cfg.shard_id]
                rs, cs, vs = (shard_rows[cfg.shard_id],
                              shard_cols[cfg.shard_id], shard_vals[cfg.shard_id])
                for key, value in _record_features(rec, cfg.feature_bags,
                                                   cols.features):
                    j = imap.key_to_index.get(key)
                    if j is not None:
                        rs.append(i)
                        cs.append(j)
                        vs.append(value)
                if cfg.has_intercept:
                    rs.append(i)
                    cs.append(imap.key_to_index[INTERCEPT_KEY])
                    vs.append(1.0)

        shards = {
            cfg.shard_id: FeatureShard.from_coo(
                np.asarray(shard_rows[cfg.shard_id], np.int64),
                np.asarray(shard_cols[cfg.shard_id], np.int32),
                np.asarray(shard_vals[cfg.shard_id], np.float32),
                n, len(index_maps[cfg.shard_id]))
            for cfg in self.shard_configs}

        data = GameData(labels=labels, offsets=offsets, weights=weights,
                        shards=shards, id_columns=ids)
        return data, index_maps, vocabs


    # --- native fast path --------------------------------------------------
    def _read_native(self, files, id_columns, entity_vocabs):
        """All-numpy assembly from the C++ decoder; None -> fall back.

        The decode is a bounded double-buffered PIPELINE
        (:class:`photon_ml_tpu.io.pipeline.DecodePrefetcher`): up to the
        worker window of files decode concurrently — the decoder is
        stateless per call and the ctypes FFI releases the GIL, the
        reference gets the same from executor-parallel HDFS reads
        (SURVEY.md §7 hard-parts #7 ingest throughput) — while this
        consumer does each already-decoded file's key-table merge, id
        remap and (with preset index maps) CSR shard split. The old
        decode-ALL-then-concatenate barrier paid the whole assembly after
        the last decode; here assembly of file *i* overlaps the decode of
        file *i+1*.
        """
        from photon_ml_tpu import native

        if not native.available():
            return None

        from photon_ml_tpu.io.pipeline import (
            DecodePrefetcher,
            _ingest_decode_seconds,
            _ingest_files,
        )
        from photon_ml_tpu.resilience import fault_point, heartbeat, retry
        from photon_ml_tpu.telemetry import tracing

        def decode(p):
            def attempt():
                heartbeat("io.read")
                fault_point("io.read", path=p)
                return native.decode_training_file(p,
                                                   id_keys=tuple(id_columns))

            with tracing.span("io.read.file", path=p) as sp:
                d = retry(attempt, name=f"io.read:{os.path.basename(p)}")
            _ingest_decode_seconds().inc(sp.seconds)
            _ingest_files().inc()
            return d

        # cap workers: each in-flight decode holds the whole file blob,
        # so peak RSS ≈ window × file size
        workers = min(len(files), os.cpu_count() or 4, 8)
        preset_maps = self.index_maps

        # streamed accumulators (per file, in file order — identical
        # ordering semantics to the old all-at-once assembly)
        labels_p, offsets_p, weights_p = [], [], []
        all_keys: dict[str, int] = {}
        pending_splits: list = []  # (decoded, remap) until maps exist
        split_parts: dict[str, list] = {c.shard_id: []
                                        for c in self.shard_configs}
        vocabs: dict[str, dict[str, int]] = {
            c: dict(v) for c, v in (entity_vocabs or {}).items()}
        frozen = entity_vocabs is not None
        ids_p: dict[str, list] = {c: [] for c in id_columns}

        def split_file(d):
            """CSR-split one decoded file into every shard (native
            count+fill pass per (shard, file) — record order preserved by
            construction, so no sort or from_coo monotonicity pass).
            ``k2c`` maps the file's LOCAL key ids straight to shard
            columns, so no per-nnz global-key gather is needed."""
            for cfg in self.shard_configs:
                imap = index_maps[cfg.shard_id]
                k2c = np.empty(len(d.feature_keys), np.int32)
                for i, k in enumerate(d.feature_keys):
                    k2c[i] = imap.key_to_index.get(k, -1)
                icol = (imap.key_to_index[INTERCEPT_KEY]
                        if cfg.has_intercept else -1)
                split = native.shard_split(
                    d.feat_indptr, d.feat_key_id, d.feat_val,
                    np.ascontiguousarray(k2c), icol)
                if split is None:  # library vanished mid-run
                    return False
                split_parts[cfg.shard_id].append(split)
            return True

        index_maps = preset_maps
        for d in DecodePrefetcher(decode, files, workers=workers):
            if d is None:  # incompatible schema: fall back (prefetcher
                return None  # cancels the files still queued)
            with tracing.span("io.read.assemble",
                              n_records=int(d.n_records)):
                labels_p.append(d.response)
                offsets_p.append(d.offset)
                weights_p.append(d.weight)
                if preset_maps is None:
                    # merge this file's feature-key table into the global
                    # universe the index maps are built from after the
                    # stream (preset maps skip the merge entirely)
                    for k in d.feature_keys:
                        all_keys.setdefault(k, len(all_keys))
                # id columns through the (possibly frozen) vocab
                for c in id_columns:
                    local = d.id_cols[c]
                    local_vocab = d.id_vocabs[c]
                    vocab = vocabs.setdefault(c, {})
                    id_remap = np.full(len(local_vocab) + 1, -1, np.int64)
                    for i, raw in enumerate(local_vocab):
                        if raw not in vocab:
                            if frozen:
                                continue
                            vocab[raw] = len(vocab)
                        id_remap[i] = vocab[raw]
                    ids_p[c].append(id_remap[local])
                if preset_maps is not None:
                    # maps are known up front: this file's CSR split runs
                    # NOW, overlapped with the next file's decode
                    if not split_file(d):
                        return None
                else:
                    # training read: column ids depend on the FULL key
                    # universe — buffer the decode, split after the stream
                    pending_splits.append(d)

        n = int(sum(len(p) for p in labels_p))
        labels = (np.concatenate(labels_p) if labels_p
                  else np.zeros(0)).astype(np.float32)
        offsets = np.nan_to_num(
            np.concatenate(offsets_p) if offsets_p else np.zeros(0),
            nan=0.0).astype(np.float32)
        weights = (np.concatenate(weights_p) if weights_p
                   else np.zeros(0))
        weights = np.where(np.isnan(weights), 1.0, weights).astype(np.float32)

        if index_maps is None:
            global_keys = [None] * len(all_keys)
            for k, j in all_keys.items():
                global_keys[j] = k
            index_maps = {}
            # bag of a key = name prefix before the first '.' (see
            # _record_features); key layout is "name\x01term"
            names_only = [k.split(NAME_TERM_DELIMITER, 1)[0]
                          for k in global_keys]
            bags = [nm.split(".", 1)[0] if "." in nm else nm
                    for nm in names_only]
            for cfg in self.shard_configs:
                keep = (global_keys if cfg.feature_bags is None else
                        [k for k, b in zip(global_keys, bags)
                         if b in cfg.feature_bags])
                index_maps[cfg.shard_id] = build_index_map(
                    keep, add_intercept=cfg.has_intercept)
            for d in pending_splits:
                if not split_file(d):
                    return None

        shards = {}
        for cfg in self.shard_configs:
            parts = split_parts[cfg.shard_id]
            imap = index_maps[cfg.shard_id]
            if not parts:
                # zero decoded parts: an empty CSR, not an IndexError on
                # parts[0] below (n is 0 here, so indptr is [0])
                indptr = np.zeros(n + 1, np.int64)
                cols = np.zeros(0, np.int32)
                vals = np.zeros(0, np.float32)
            elif len(parts) == 1:
                indptr, cols, vals = parts[0]
            else:
                indptr_parts = [p[0] for p in parts]
                nnz0 = np.cumsum([0] + [int(p[-1]) for p in indptr_parts])
                indptr = np.concatenate(
                    [indptr_parts[0]]
                    + [p[1:] + off for p, off
                       in zip(indptr_parts[1:], nnz0[1:-1])])
                cols = np.concatenate([p[1] for p in parts])
                vals = np.concatenate([p[2] for p in parts])
            shards[cfg.shard_id] = FeatureShard(
                indptr=indptr, cols=cols, vals=vals, dim=len(imap))

        ids = {c: (np.concatenate(ids_p[c]) if ids_p[c]
                   else np.full(0, -1, np.int64))
               for c in id_columns}

        data = GameData(labels=labels, offsets=offsets, weights=weights,
                        shards=shards, id_columns=ids)
        return data, index_maps, vocabs


def write_training_examples(path: str, data_records: Iterable[dict], *,
                            codec: str = "deflate",
                            sync: "bytes | None" = None) -> int:
    """Convenience writer for tests/examples (TrainingExampleAvro rows).
    ``sync`` passes through to :func:`~photon_ml_tpu.io.avro.
    write_avro_file` for writers that need byte-deterministic output."""
    from photon_ml_tpu.io.avro import write_avro_file
    from photon_ml_tpu.io.schemas import TRAINING_EXAMPLE_AVRO

    return write_avro_file(path, data_records, TRAINING_EXAMPLE_AVRO,
                           codec=codec, sync=sync)
