"""Async I/O pipeline: background model publication + overlapped ingest.

The e2e GAME driver wall (BENCH_r04) was ~59% "Save models" and ~25%
"Read" — training itself was a quarter of the run. The reference hides
exactly this class of latency behind executor-parallel HDFS writers and
readers (SURVEY.md §7 "ingest throughput"); this module is the TPU-native
port's equivalent, built from three pieces:

- :class:`BackgroundSaver` — a small two-pool writer service the drivers
  own. Whole-model saves run on *orchestrator* threads and fan their
  per-coordinate part-file writes out on a shared *part-writer* pool (the
  native RE writer releases the GIL, so coordinate part files encode
  concurrently even on one core). Every model directory is staged in a
  hidden temp sibling and published with the crash-safe retire-then-rename
  protocol of :mod:`photon_ml_tpu.io.checkpoint`, under the resilience
  retry policy with the ``io.model_save`` fault site in the crash window —
  a kill or injected fault mid-save never exposes a partial model to the
  serving registry. The driver submits saves the moment each result
  exists, keeps training, and :meth:`BackgroundSaver.join`\\ s before exit
  (first writer error propagates).
- :class:`DecodePrefetcher` — a bounded, double-buffered file pipeline:
  up to ``window`` Avro decodes stay in flight while the consumer does
  key-remap/CSR assembly on already-decoded files, replacing the
  decode-ALL-then-concatenate barrier in the reader.
- :func:`read_in_background` — one background read (the drivers kick the
  validation-data read off here so it overlaps training-data upload and
  sweep 1; the result is joined at first use).

All background work runs under a *copy of the submitter's context*, so
spans opened in worker threads parent correctly under the driver's stage
spans: ``io.save.model`` / ``io.save.part`` / ``io.save.index`` /
``io.read.file`` / ``io.read.validation`` land on the run's one timeline
and ``tools/perf_report.py`` can show how much of the I/O wall was hidden
under train (the ``-- async I/O overlap --`` section).
"""

from __future__ import annotations

import contextvars
import json
import os
import shutil
import tempfile
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Iterator, Optional, Sequence

from photon_ml_tpu.telemetry import metrics as tmetrics
from photon_ml_tpu.telemetry import tracing


def _save_seconds():
    return tmetrics.counter(
        "photon_save_seconds_total",
        "Wall seconds spent writing model part-files, per coordinate "
        "(background writers included — compare with the driver's "
        "'Save models' join wall to see the hidden fraction)",
        labels=("coordinate",))


def _save_bytes():
    return tmetrics.counter(
        "photon_save_bytes_total",
        "Bytes of model/index artifacts written (part-files, metadata, "
        "feature indexes)")


def _ingest_decode_seconds():
    return tmetrics.counter(
        "photon_ingest_decode_seconds_total",
        "Wall seconds spent decoding input Avro files (prefetcher worker "
        "side; overlaps assembly on the consumer side)")


def _ingest_files():
    return tmetrics.counter(
        "photon_ingest_files_total",
        "Input Avro files decoded through the ingest prefetcher")


# ---------------------------------------------------------------------------
# atomic directory publication (the checkpoint protocol, generalized)
# ---------------------------------------------------------------------------


def publish_dir(staging: str, final: str) -> None:
    """Atomically publish a fully-written ``staging`` directory at
    ``final`` using the retire-then-rename protocol from
    :mod:`photon_ml_tpu.io.checkpoint`: an existing ``final`` is first
    renamed aside (a ``.tmp`` suffix keeps it invisible to directory
    probes), the staging dir takes its place, then the retired copy is
    deleted — at no instant is ``final`` absent or partially written."""
    final = os.path.normpath(final)
    parent = os.path.dirname(os.path.abspath(final))
    if os.path.exists(final):
        retired = tempfile.mkdtemp(
            prefix=f".{os.path.basename(final)}-retired-", suffix=".tmp",
            dir=parent)
        os.rmdir(retired)
        os.rename(final, retired)
        os.rename(staging, final)
        shutil.rmtree(retired, ignore_errors=True)
    else:
        os.rename(staging, final)


def _gc_stale_staging(parent: str, base: str) -> None:
    """Drop staging/retired leftovers of a crashed or fault-injected
    earlier attempt at publishing ``base`` (the atomic protocol means they
    are never the live copy). Only this target's prefix is touched, so
    concurrent saves of sibling model dirs are never collected."""
    for name in os.listdir(parent):
        if name.endswith(".tmp") and (
                name.startswith(f".{base}-stage-")
                or name.startswith(f".{base}-retired-")):
            shutil.rmtree(os.path.join(parent, name), ignore_errors=True)


def save_game_model_atomic(output_dir: str, model, index_maps, entity_vocabs,
                           *, sparsity_threshold: float = 0.0,
                           executor: Optional[ThreadPoolExecutor] = None,
                           lineage: Optional[dict] = None,
                           ) -> None:
    """:func:`photon_ml_tpu.io.model_io.save_game_model` with crash-safe
    publication: the model tree is written into a hidden staging sibling
    and atomically renamed into place (retire-then-rename), under the
    resilience retry policy. The ``io.model_save`` fault site sits in the
    crash window — staging fully written, rename not yet done — so an
    injected fault or a kill there leaves the previous model (or nothing)
    visible, never a partial tree."""
    from photon_ml_tpu.io.model_io import save_game_model
    from photon_ml_tpu.resilience import fault_point, retry

    output_dir = os.path.normpath(output_dir)
    parent = os.path.dirname(os.path.abspath(output_dir))
    os.makedirs(parent, exist_ok=True)
    base = os.path.basename(output_dir)

    def attempt() -> None:
        _gc_stale_staging(parent, base)
        staging = tempfile.mkdtemp(prefix=f".{base}-stage-", suffix=".tmp",
                                   dir=parent)
        try:
            save_game_model(staging, model, index_maps, entity_vocabs,
                            sparsity_threshold=sparsity_threshold,
                            executor=executor, lineage=lineage)
            fault_point("io.model_save", path=output_dir)
            publish_dir(staging, output_dir)
        except BaseException:
            shutil.rmtree(staging, ignore_errors=True)
            raise

    retry(attempt, name=f"io.model_save:{base}")


def save_model_patch_atomic(output_dir: str, patch_models, index_maps,
                            entity_vocabs, *, task, parent_model: str,
                            model_id: str, removed=None,
                            lineage: Optional[dict] = None,
                            sparsity_threshold: float = 0.0,
                            fleet_shard: Optional[tuple] = None) -> int:
    """:func:`photon_ml_tpu.io.model_io.save_game_model_patch` with the
    same staged atomic publication as full models, under the
    ``io.delta_publish`` fault site (staging fully written, rename not yet
    done). A fault or crash there leaves the previous patch — or nothing —
    visible; a registry or watch-dir poll can never observe a partial
    patch. Returns the published patch's payload bytes (the
    ``photon_refresh_patch_bytes_total`` increment)."""
    from photon_ml_tpu.io.model_io import save_game_model_patch
    from photon_ml_tpu.resilience import fault_point, retry

    output_dir = os.path.normpath(output_dir)
    parent = os.path.dirname(os.path.abspath(output_dir))
    os.makedirs(parent, exist_ok=True)
    base = os.path.basename(output_dir)

    def attempt() -> None:
        _gc_stale_staging(parent, base)
        staging = tempfile.mkdtemp(prefix=f".{base}-stage-", suffix=".tmp",
                                   dir=parent)
        try:
            with tracing.span("refresh.publish", path=output_dir):
                save_game_model_patch(
                    staging, patch_models, index_maps, entity_vocabs,
                    task=task, parent_model=parent_model, model_id=model_id,
                    removed=removed, lineage=lineage,
                    sparsity_threshold=sparsity_threshold,
                    fleet_shard=fleet_shard)
                fault_point("io.delta_publish", path=output_dir)
                publish_dir(staging, output_dir)
        except BaseException:
            shutil.rmtree(staging, ignore_errors=True)
            raise

    retry(attempt, name=f"io.delta_publish:{base}")
    total = 0
    for dirpath, _dirs, files in os.walk(output_dir):
        for name in files:
            total += os.path.getsize(os.path.join(dirpath, name))
    return total


def publish_model_alias(src_dir: str, dst_dir: str) -> None:
    """Publish ``dst_dir`` as an alias of the finished model at
    ``src_dir`` WITHOUT re-serializing it: part-files (and any other
    payload files) are hardlinked — copied when the filesystem refuses
    links — into a staging tree, ``model-metadata.json`` is rewritten with
    an ``aliasOf`` key naming the source, and the tree is published
    atomically. This is how ``--output-all-models`` gets its ``best/``
    directory for free instead of serializing the winning model twice."""
    from photon_ml_tpu.resilience import fault_point, retry

    src_dir = os.path.normpath(src_dir)
    dst_dir = os.path.normpath(dst_dir)
    parent = os.path.dirname(os.path.abspath(dst_dir))
    os.makedirs(parent, exist_ok=True)
    base = os.path.basename(dst_dir)

    def attempt() -> None:
        _gc_stale_staging(parent, base)
        staging = tempfile.mkdtemp(prefix=f".{base}-stage-", suffix=".tmp",
                                   dir=parent)
        try:
            with tracing.span("io.save.alias", src=src_dir, dst=dst_dir):
                for dirpath, _dirnames, filenames in os.walk(src_dir):
                    rel = os.path.relpath(dirpath, src_dir)
                    out = (staging if rel == "." else
                           os.path.join(staging, rel))
                    os.makedirs(out, exist_ok=True)
                    for name in filenames:
                        s = os.path.join(dirpath, name)
                        d = os.path.join(out, name)
                        if name == "model-metadata.json":
                            with open(s) as f:
                                metadata = json.load(f)
                            metadata["aliasOf"] = os.path.relpath(
                                src_dir, parent)
                            with open(d, "w") as f:
                                json.dump(metadata, f, indent=2)
                            continue
                        try:
                            os.link(s, d)
                        except OSError:
                            shutil.copy2(s, d)
            fault_point("io.model_save", path=dst_dir)
            publish_dir(staging, dst_dir)
        except BaseException:
            shutil.rmtree(staging, ignore_errors=True)
            raise

    retry(attempt, name=f"io.model_save:{base}")


# ---------------------------------------------------------------------------
# the background writer service
# ---------------------------------------------------------------------------


class BackgroundSaver:
    """Driver-owned background writer: saves run off the critical path and
    are joined (with error propagation) before the driver returns.

    Two pools, so a whole-model save blocking on its own part-file writes
    can never deadlock: orchestrators (one per in-flight model save) on
    ``_saves``, leaf part-file/index writers on the shared ``_parts``
    pool. Submission copies the caller's context, so worker-side spans
    parent under whatever stage the driver was in when it submitted."""

    def __init__(self, part_workers: int = 4, save_workers: int = 2):
        self._parts = ThreadPoolExecutor(
            max_workers=part_workers, thread_name_prefix="photon-save-part")
        self._saves = ThreadPoolExecutor(
            max_workers=save_workers, thread_name_prefix="photon-save")
        self._lock = threading.Lock()
        self._pending: list[tuple[str, Future]] = []  # guarded-by: _lock

    @property
    def save_executor(self):
        """The orchestrator pool — what the capacity plane's
        ``saver_pool`` probe (telemetry/saturation.py) watches."""
        return self._saves

    # --- submission -------------------------------------------------------
    def _track(self, label: str, fut: Future) -> Future:
        with self._lock:
            self._pending.append((label, fut))
        return fut

    def submit_game_save(self, output_dir: str, model, index_maps,
                         entity_vocabs, *, sparsity_threshold: float = 0.0,
                         lineage: Optional[dict] = None,
                         ) -> Future:
        """Stage + atomically publish a GAME model at ``output_dir`` in the
        background, fanning its per-coordinate part-files out on the
        writer pool. Returns the save's future; :meth:`join` collects it."""
        ctx = contextvars.copy_context()

        def job() -> None:
            with tracing.span("io.save.model", path=output_dir):
                save_game_model_atomic(
                    output_dir, model, index_maps, entity_vocabs,
                    sparsity_threshold=sparsity_threshold,
                    executor=self._parts, lineage=lineage)

        return self._track(f"model:{output_dir}",
                           self._saves.submit(ctx.run, job))

    def submit_file_write(self, fn: Callable[[str], Any], path: str, *,
                          label: str = "io.save.file", **attrs) -> Future:
        """Run ``fn(path)`` (e.g. ``IndexMap.save``) on the writer pool
        under an I/O span; the written file's size feeds
        ``photon_save_bytes_total``."""
        ctx = contextvars.copy_context()

        def job() -> None:
            with tracing.span(label, path=path, **attrs):
                fn(path)
            if os.path.exists(path):
                _save_bytes().inc(os.path.getsize(path))

        return self._track(f"{label}:{path}",
                           self._parts.submit(ctx.run, job))

    def submit(self, fn: Callable[[], Any], *, label: str = "io.save.task",
               **attrs) -> Future:
        """Run an arbitrary write task on the writer pool under a span."""
        ctx = contextvars.copy_context()

        def job():
            with tracing.span(label, **attrs):
                return fn()

        return self._track(f"{label}",
                           self._parts.submit(ctx.run, job))

    # --- completion -------------------------------------------------------
    def collect(self) -> list:
        """Prune completed background writes WITHOUT blocking; returns the
        ``(label, exception)`` pairs of completed writes that failed (empty
        when everything so far succeeded). Long-lived owners — the serving
        request log submits writes for a process's whole lifetime — call
        this periodically so the pending list stays bounded and write
        errors surface as counters instead of an unbounded deferred
        :meth:`join`. In-flight writes stay tracked for the final join."""
        with self._lock:
            pending = self._pending
            done = [(label, fut) for label, fut in pending if fut.done()]
            self._pending = [(label, fut) for label, fut in pending
                             if not fut.done()]
        errors = []
        for label, fut in done:
            exc = fut.exception()
            if exc is not None:
                errors.append((label, exc))
        return errors

    def join(self) -> None:
        """Wait for every submitted write; the first error (in submission
        order) propagates — a failed background save must fail the run,
        not be discovered by the next reader of a missing model."""
        import logging

        with self._lock:
            pending, self._pending = self._pending, []
        first_error: Optional[BaseException] = None
        for label, fut in pending:
            try:
                fut.result()
            except BaseException as e:
                logging.getLogger(__name__).error(
                    "background write %s failed: %r", label, e)
                if first_error is None:
                    first_error = e
        if first_error is not None:
            raise first_error

    def close(self) -> None:
        """Shut both pools down, waiting for in-flight writes (a writer
        must never outlive the driver into a directory the harness is
        about to delete). Errors of never-joined futures are dropped here
        — the happy path joins first; close() runs on the failure path
        where a second raise would mask the original error."""
        self._saves.shutdown(wait=True)
        self._parts.shutdown(wait=True)
        with self._lock:
            self._pending.clear()


# ---------------------------------------------------------------------------
# overlapped ingest
# ---------------------------------------------------------------------------


class DecodePrefetcher:
    """Bounded double-buffered pipeline over ``fn(item)`` calls.

    Up to ``window`` calls run on a worker pool while the consumer
    iterates results strictly in submission order — the overlap that
    replaces the reader's decode-all-then-concatenate barrier. An error
    in any call cancels everything still queued and re-raises on the
    consumer side; breaking out of the iteration (e.g. a fall-back
    signal) likewise cancels the remainder."""

    def __init__(self, fn: Callable[[Any], Any], items: Sequence[Any], *,
                 workers: int = 2, window: Optional[int] = None):
        self._fn = fn
        self._items = list(items)
        self._workers = max(1, workers)
        # one extra in-flight slot beyond the workers keeps the pool fed
        # while the consumer holds the head result (double buffering)
        self._window = window if window is not None else self._workers + 1

    def __iter__(self) -> Iterator[Any]:
        from collections import deque

        pool = ThreadPoolExecutor(max_workers=self._workers,
                                  thread_name_prefix="photon-ingest")
        queue: deque[Future] = deque()
        it = iter(self._items)
        try:
            for item in it:
                ctx = contextvars.copy_context()
                queue.append(pool.submit(ctx.run, self._fn, item))
                if len(queue) >= self._window:
                    break
            while queue:
                head = queue.popleft()
                try:
                    result = head.result()
                except BaseException:
                    for f in queue:
                        f.cancel()
                    raise
                for item in it:
                    ctx = contextvars.copy_context()
                    queue.append(pool.submit(ctx.run, self._fn, item))
                    break
                yield result
        finally:
            for f in queue:
                f.cancel()
            pool.shutdown(wait=True)


def read_in_background(fn: Callable[..., Any], *args,
                       label: str = "io.read.validation",
                       **kwargs) -> Future:
    """Run one read on a background thread under an I/O span (in the
    caller's context, so the span parents under the current stage) and
    return its :class:`~concurrent.futures.Future`. The drivers use this
    to kick the validation-data read off while training data uploads and
    the first sweep runs; ``future.result()`` at first use is the join."""
    ctx = contextvars.copy_context()
    fut: Future = Future()

    def run() -> None:
        try:
            with tracing.span(label):
                result = fn(*args, **kwargs)
        except BaseException as e:  # delivered at the join, not lost
            fut.set_exception(e)
        else:
            fut.set_result(result)

    threading.Thread(target=lambda: ctx.run(run), daemon=True,
                     name="photon-read-bg").start()
    return fut
