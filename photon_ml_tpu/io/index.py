"""Feature index maps: (name, term) string → dense int id.

Re-design of the reference's indexing layer
(``photon-client/.../index/{IndexMap, DefaultIndexMap, DefaultIndexMapLoader,
PalDBIndexMap, PalDBIndexMapLoader, FeatureIndexingDriver}.scala``). The
reference needs an off-heap PalDB store because every JVM executor holds the
map; here one host process feeds the chips, so the map is a plain dict with
a compact sorted-strings on-disk form. Partitioned stores (PalDB's
``hash(name) % n`` with global offset arithmetic) are unnecessary and
intentionally not reproduced.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Iterable, Mapping, Optional

from photon_ml_tpu.types import INTERCEPT_KEY, feature_key


@dataclasses.dataclass(frozen=True)
class IndexMap:
    """Immutable feature-key → index map (+ reverse lookup)."""

    key_to_index: Mapping[str, int]

    def __post_init__(self):
        n = len(self.key_to_index)
        vals = set(self.key_to_index.values())
        if vals and (min(vals) < 0 or max(vals) >= n or len(vals) != n):
            raise ValueError("index map values must be a permutation of range(n)")

    def __len__(self) -> int:
        return len(self.key_to_index)

    def __contains__(self, key: str) -> bool:
        return key in self.key_to_index

    def index_of(self, name: str, term: str = "") -> Optional[int]:
        return self.key_to_index.get(feature_key(name, term))

    def names(self) -> list[str]:
        """Keys ordered by index (reverse map)."""
        out = [""] * len(self.key_to_index)
        for k, i in self.key_to_index.items():
            out[i] = k
        return out

    @property
    def has_intercept(self) -> bool:
        return INTERCEPT_KEY in self.key_to_index

    # --- persistence (one JSON-lines file; replaces the PalDB store) ------
    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump({"version": 1, "keys": self.names()}, f)

    @staticmethod
    def load(path: str) -> "IndexMap":
        with open(path) as f:
            payload = json.load(f)
        return IndexMap({k: i for i, k in enumerate(payload["keys"])})


#: alias matching the reference's in-memory implementation name
DefaultIndexMap = IndexMap


def build_index_map(feature_keys: Iterable[str], *,
                    add_intercept: bool = True) -> IndexMap:
    """Build from the distinct feature keys observed in data
    (reference ``FeatureIndexingDriver`` / ``DefaultIndexMapLoader``:
    distinct → stable order → contiguous ids; intercept appended last
    when requested)."""
    uniq = sorted(set(feature_keys) - {INTERCEPT_KEY})
    if add_intercept:
        uniq.append(INTERCEPT_KEY)
    return IndexMap({k: i for i, k in enumerate(uniq)})
