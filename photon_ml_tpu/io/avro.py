"""Minimal Avro object-container-file codec (pure Python + zlib).

The environment has no ``fastavro``/``avro`` package, and Avro is the
reference's interchange format (``photon-avro-schemas/src/main/avro/*.avsc``;
read/written by ``photon-client/.../data/avro/AvroUtils.scala``), so this
module implements the subset of the Avro 1.x spec those schemas need:

- primitives: null, boolean, int, long, float, double, bytes, string;
- complex: record, array, map, union, enum, fixed;
- binary encoding: zigzag-varint longs, length-prefixed bytes, block-encoded
  arrays/maps, union = long index + value;
- container files: ``Obj\\x01`` magic, metadata map (schema JSON + codec),
  16-byte sync marker, data blocks with ``null``, ``deflate``, or ``snappy``
  codec (snappy implemented here from the format spec — no wheel needed).

Schemas are plain Python dicts in the ``.avsc`` JSON form. Unknown/unneeded
spec corners (recursive types, aliases, logical types) raise cleanly.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, BinaryIO, Iterable, Iterator, Union

MAGIC = b"Obj\x01"
SYNC_SIZE = 16

Schema = Union[str, list, dict]


# ---------------------------------------------------------------------------
# binary encoding
# ---------------------------------------------------------------------------


def _zigzag_encode(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _zigzag_decode(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def write_long(out: BinaryIO, n: int) -> None:
    n = _zigzag_encode(n)
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.write(bytes([b | 0x80]))
        else:
            out.write(bytes([b]))
            return


def read_long(buf: BinaryIO) -> int:
    shift = 0
    acc = 0
    while True:
        byte = buf.read(1)
        if not byte:
            raise EOFError("truncated varint")
        b = byte[0]
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            return _zigzag_decode(acc)
        shift += 7


def _schema_type(schema: Schema) -> str:
    if isinstance(schema, str):
        return schema
    if isinstance(schema, list):
        return "union"
    return schema["type"]


def _resolve_named(schema: Schema, names: dict) -> Schema:
    """Register/lookup named types so a schema can reference them by name."""
    if isinstance(schema, str) and schema in names:
        return names[schema]
    if isinstance(schema, dict) and schema.get("type") in ("record", "enum", "fixed"):
        name = schema.get("name")
        if name:
            names[name] = schema
            ns = schema.get("namespace")
            if ns:
                names[f"{ns}.{name}"] = schema
    return schema


def write_datum(out: BinaryIO, datum: Any, schema: Schema, names: dict) -> None:
    schema = _resolve_named(schema, names)
    t = _schema_type(schema)
    if t == "null":
        return
    if t == "boolean":
        out.write(b"\x01" if datum else b"\x00")
    elif t in ("int", "long"):
        write_long(out, int(datum))
    elif t == "float":
        out.write(struct.pack("<f", float(datum)))
    elif t == "double":
        out.write(struct.pack("<d", float(datum)))
    elif t == "bytes":
        write_long(out, len(datum))
        out.write(datum)
    elif t == "string":
        raw = datum.encode("utf-8")
        write_long(out, len(raw))
        out.write(raw)
    elif t == "union":
        idx = _union_branch(datum, schema, names)
        write_long(out, idx)
        write_datum(out, datum, schema[idx], names)
    elif t == "record":
        for field in schema["fields"]:
            name = field["name"]
            if name in datum:
                value = datum[name]
            elif "default" in field:
                value = field["default"]
            else:
                raise ValueError(f"record field {name!r} missing and has no default")
            write_datum(out, value, field["type"], names)
    elif t == "array":
        if datum:
            write_long(out, len(datum))
            for item in datum:
                write_datum(out, item, schema["items"], names)
        write_long(out, 0)
    elif t == "map":
        if datum:
            write_long(out, len(datum))
            for k, v in datum.items():
                write_datum(out, k, "string", names)
                write_datum(out, v, schema["values"], names)
        write_long(out, 0)
    elif t == "enum":
        out_idx = schema["symbols"].index(datum)
        write_long(out, out_idx)
    elif t == "fixed":
        if len(datum) != schema["size"]:
            raise ValueError("fixed size mismatch")
        out.write(datum)
    else:
        raise NotImplementedError(f"avro type {t!r}")


def _union_branch(datum: Any, union: list, names: dict) -> int:
    for i, branch in enumerate(union):
        bt = _schema_type(_resolve_named(branch, names))
        if datum is None and bt == "null":
            return i
        if datum is not None and bt != "null":
            # first non-null branch wins (our schemas use [null, X] only)
            return i
    raise ValueError(f"no union branch for {type(datum)} in {union}")


def read_datum(buf: BinaryIO, schema: Schema, names: dict) -> Any:
    schema = _resolve_named(schema, names)
    t = _schema_type(schema)
    if t == "null":
        return None
    if t == "boolean":
        return buf.read(1) == b"\x01"
    if t in ("int", "long"):
        return read_long(buf)
    if t == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if t == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if t == "bytes":
        return buf.read(read_long(buf))
    if t == "string":
        return buf.read(read_long(buf)).decode("utf-8")
    if t == "union":
        return read_datum(buf, schema[read_long(buf)], names)
    if t == "record":
        return {f["name"]: read_datum(buf, f["type"], names)
                for f in schema["fields"]}
    if t == "array":
        out = []
        while True:
            count = read_long(buf)
            if count == 0:
                return out
            if count < 0:  # block with byte size
                count = -count
                read_long(buf)
            for _ in range(count):
                out.append(read_datum(buf, schema["items"], names))
    if t == "map":
        out = {}
        while True:
            count = read_long(buf)
            if count == 0:
                return out
            if count < 0:
                count = -count
                read_long(buf)
            for _ in range(count):
                k = read_datum(buf, "string", names)
                out[k] = read_datum(buf, schema["values"], names)
    if t == "enum":
        return schema["symbols"][read_long(buf)]
    if t == "fixed":
        return buf.read(schema["size"])
    raise NotImplementedError(f"avro type {t!r}")


# ---------------------------------------------------------------------------
# container files
# ---------------------------------------------------------------------------


def write_avro_file(path: str, records: Iterable[dict], schema: Schema,
                    *, codec: str = "deflate", block_records: int = 4096,
                    sync: "bytes | None" = None) -> int:
    """Write an Avro object-container file; returns the record count.
    ``sync`` pins the container's 16-byte sync marker — writers that
    promise byte-identical output for identical records (the feedback
    joiner) pass a deterministic one; the default stays random per spec
    recommendation."""
    if codec not in ("null", "deflate", "snappy"):
        raise ValueError(f"unsupported codec {codec!r}")
    if sync is None:
        sync = os.urandom(SYNC_SIZE)
    elif len(sync) != SYNC_SIZE:
        raise ValueError(f"sync marker must be {SYNC_SIZE} bytes, "
                         f"got {len(sync)}")
    names: dict = {}
    n_total = 0
    with open(path, "wb") as f:
        f.write(MAGIC)
        meta = {"avro.schema": json.dumps(schema).encode(),
                "avro.codec": codec.encode()}
        write_long(f, len(meta))
        for k, v in meta.items():
            write_datum(f, k, "string", names)
            write_long(f, len(v))
            f.write(v)
        write_long(f, 0)
        f.write(sync)

        block: list[dict] = []

        def flush():
            nonlocal n_total
            if not block:
                return
            buf = io.BytesIO()
            for rec in block:
                write_datum(buf, rec, schema, names)
            payload = buf.getvalue()
            if codec == "deflate":
                payload = zlib.compress(payload)[2:-4]  # raw deflate per spec
            elif codec == "snappy":
                crc = (zlib.crc32(payload) & 0xFFFFFFFF).to_bytes(4, "big")
                payload = snappy_compress(payload) + crc
            write_long(f, len(block))
            write_long(f, len(payload))
            f.write(payload)
            f.write(sync)
            n_total += len(block)
            block.clear()

        for rec in records:
            block.append(rec)
            if len(block) >= block_records:
                flush()
        flush()
    return n_total


def iter_avro_file(path: str) -> Iterator[dict]:
    """Stream records from an Avro object-container file."""
    with open(path, "rb") as f:
        if f.read(4) != MAGIC:
            raise ValueError(f"{path}: not an Avro container file")
        names: dict = {}
        meta = {}
        while True:
            count = read_long(f)
            if count == 0:
                break
            if count < 0:
                count = -count
                read_long(f)
            for _ in range(count):
                k = read_datum(f, "string", names)
                size = read_long(f)
                meta[k] = f.read(size)
        schema = json.loads(meta["avro.schema"].decode())
        codec = meta.get("avro.codec", b"null").decode()
        if codec not in ("null", "deflate", "snappy"):
            raise ValueError(f"unsupported codec {codec!r} "
                             f"(supported: null, deflate, snappy)")
        sync = f.read(SYNC_SIZE)
        while True:
            try:
                n_records = read_long(f)
            except EOFError:
                return
            size = read_long(f)
            payload = f.read(size)
            if codec == "deflate":
                payload = zlib.decompress(payload, -15)
            elif codec == "snappy":
                payload = snappy_decode_block(payload, context=path)
            if f.read(SYNC_SIZE) != sync:
                raise ValueError(f"{path}: sync marker mismatch (corrupt block)")
            buf = io.BytesIO(payload)
            for _ in range(n_records):
                yield read_datum(buf, schema, names)


def read_avro_file(path: str) -> list[dict]:
    return list(iter_avro_file(path))


# ---------------------------------------------------------------------------
# Snappy block codec (pure Python)
# ---------------------------------------------------------------------------
# Hadoop-written Avro is very commonly snappy-compressed; there is no snappy
# wheel in this environment, so decompression is implemented directly from
# the format spec (https://github.com/google/snappy/blob/main/format_description.txt).
# Avro's snappy codec frames each block as snappy(payload) + 4-byte big-endian
# CRC32 of the UNCOMPRESSED payload.


def snappy_decode_block(payload: bytes, context: str = "") -> bytes:
    """Decode one Avro snappy block payload: decompress + verify the CRC.

    The single home of the Avro-snappy frame contract — both the pure-Python
    reader above and the native fast path (:mod:`photon_ml_tpu.native`)
    call this."""
    if len(payload) < 4:
        raise ValueError(f"{context}: snappy block too short for CRC")
    body, crc = payload[:-4], payload[-4:]
    data = snappy_decompress(body)
    if zlib.crc32(data) & 0xFFFFFFFF != int.from_bytes(crc, "big"):
        raise ValueError(f"{context}: snappy block CRC mismatch")
    return data


def snappy_decompress(data: bytes) -> bytes:
    """Decompress one raw snappy block."""
    pos = 0
    # varint32 uncompressed length
    length = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("snappy: truncated preamble")
        b = data[pos]
        pos += 1
        length |= (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            break
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:  # literal
            elem = tag >> 2
            if elem < 60:
                lit_len = elem + 1
            else:
                n_bytes = elem - 59
                if pos + n_bytes > n:
                    raise ValueError("snappy: truncated literal length")
                lit_len = int.from_bytes(data[pos:pos + n_bytes], "little") + 1
                pos += n_bytes
            if pos + lit_len > n:
                raise ValueError("snappy: truncated literal")
            out += data[pos:pos + lit_len]
            pos += lit_len
            continue
        if kind == 1:  # copy, 1-byte offset
            if pos + 1 > n:
                raise ValueError("snappy: truncated copy")
            cp_len = ((tag >> 2) & 0x7) + 4
            offset = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:  # copy, 2-byte offset
            if pos + 2 > n:
                raise ValueError("snappy: truncated copy")
            cp_len = (tag >> 2) + 1
            offset = int.from_bytes(data[pos:pos + 2], "little")
            pos += 2
        else:  # copy, 4-byte offset
            if pos + 4 > n:
                raise ValueError("snappy: truncated copy")
            cp_len = (tag >> 2) + 1
            offset = int.from_bytes(data[pos:pos + 4], "little")
            pos += 4
        if offset == 0 or offset > len(out):
            raise ValueError("snappy: invalid copy offset")
        start = len(out) - offset
        if offset >= cp_len:  # non-overlapping (the common case): one slice
            out += out[start:start + cp_len]
        else:  # overlapping copy: byte-at-a-time semantics
            for i in range(cp_len):
                out.append(out[start + i])
    if len(out) != length:
        raise ValueError(
            f"snappy: decompressed {len(out)} bytes, expected {length}")
    return bytes(out)


def snappy_compress(data: bytes) -> bytes:
    """Literal-only snappy encoding (valid, not size-optimal) — enough to
    WRITE snappy files other readers accept; real compression only matters
    for data we produce, which defaults to deflate."""
    out = bytearray()
    # varint32 length
    length = len(data)
    while True:
        b = length & 0x7F
        length >>= 7
        if length:
            out.append(b | 0x80)
        else:
            out.append(b)
            break
    pos = 0
    while pos < len(data):
        chunk = data[pos:pos + 65536]
        lit_len = len(chunk) - 1
        if lit_len < 60:
            out.append(lit_len << 2)
        else:
            n_bytes = (lit_len.bit_length() + 7) // 8
            out.append((59 + n_bytes) << 2)
            out += lit_len.to_bytes(n_bytes, "little")
        out += chunk
        pos += len(chunk)
    return bytes(out)
