"""Checkpoint/resume for coordinate descent and regularization sweeps.

The reference has no optimizer-state checkpointing — recovery is Spark
lineage plus manually restarting from written models (SURVEY.md §5.4). Here
checkpointing is first-class: at every coordinate boundary the manager can
persist (sweep position, per-coordinate models, score decomposition) and a
crashed run resumes from the last boundary with warm starts intact.

Format: one directory per step — numpy arrays via ``np.savez`` plus a JSON
manifest — written atomically (tmp + rename) so a crash mid-write never
corrupts the latest checkpoint. (orbax is available in-environment but its
async machinery buys nothing for host-resident numpy state this small.)
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import shutil
import tempfile
from typing import Optional

import numpy as np

from photon_ml_tpu.game.model import FixedEffectModel, GameModel, RandomEffectModel
from photon_ml_tpu.game.projector import RandomProjector
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.types import TaskType

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class CoordinateDescentState:
    """Resumable CD position: models + score decomposition + sweep index."""

    sweep: int
    coordinate_index: int  # next coordinate to train within the sweep
    model: GameModel
    scores: dict[str, np.ndarray]


class CheckpointManager:
    """Writes/reads checkpoint steps under a root directory.

    ``read_only=True`` turns :meth:`save` into a no-op — for non-chief
    processes of a multi-controller job sharing one filesystem, which must
    resume from (and stay in lockstep with) the chief's checkpoints but
    must not race its writes.
    """

    def __init__(self, root: str, *, keep: int = 3, read_only: bool = False):
        self.root = root
        self.keep = keep
        self.read_only = read_only
        self._pinned = False
        self._pinned_step: Optional[int] = None
        if not read_only:
            os.makedirs(root, exist_ok=True)

    def pin_step(self, step: Optional[int]) -> None:
        """Freeze what :meth:`latest_step` answers. Multi-controller jobs
        must agree on the resume point BEFORE training (each process polling
        the shared filesystem independently races the chief's own saves —
        a late worker would resume from a step the chief wrote after
        starting, desynchronizing the collective schedules); the chief
        reads the filesystem once and broadcasts the step to everyone."""
        self._pinned = True
        self._pinned_step = step

    # --- step bookkeeping -------------------------------------------------
    def steps(self) -> list[int]:
        if not os.path.isdir(self.root):
            # read-only managers never mkdir; a worker may probe before the
            # chief's first save lands on the shared filesystem
            return []
        out = []
        for name in os.listdir(self.root):
            if name.startswith("step-") and not name.endswith(".tmp"):
                try:
                    out.append(int(name.split("-", 1)[1]))
                except ValueError:
                    pass
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        if self._pinned:
            return self._pinned_step
        steps = self.steps()
        return steps[-1] if steps else None

    def _gc(self) -> None:
        for step in self.steps()[:-self.keep]:
            shutil.rmtree(os.path.join(self.root, f"step-{step}"),
                          ignore_errors=True)
        # stale tmp dirs from a crashed/injected-fault save attempt (the
        # atomic-rename protocol means they are never the live checkpoint)
        for name in os.listdir(self.root):
            if name.endswith(".tmp"):
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)

    # --- save/restore -----------------------------------------------------
    def save(self, step: int, state: CoordinateDescentState,
             fingerprint: Optional[str] = None) -> str:
        """``fingerprint`` identifies the training configuration (e.g. the
        regularization weights); restore() refuses state written under a
        different configuration — resuming lambda=0.1 state into a
        lambda=10 run would silently mis-attribute the model."""
        if self.read_only:
            return os.path.join(self.root, f"step-{step}")
        manifest = {
            "step": step,
            "sweep": state.sweep,
            "coordinate_index": state.coordinate_index,
            "task": state.model.task.value,
            "fingerprint": fingerprint,
            "coordinates": {},
        }
        arrays: dict[str, np.ndarray] = {}
        for cid, cm in state.model.coordinates.items():
            if isinstance(cm, FixedEffectModel):
                manifest["coordinates"][cid] = {
                    "type": "fixed", "featureShardId": cm.feature_shard_id,
                    "has_variances": cm.model.coefficients.variances is not None}
                arrays[f"fixed:{cid}:means"] = np.asarray(
                    cm.model.coefficients.means)
                if cm.model.coefficients.variances is not None:
                    arrays[f"fixed:{cid}:variances"] = np.asarray(
                        cm.model.coefficients.variances)
            else:
                manifest["coordinates"][cid] = {
                    "type": "random", "featureShardId": cm.feature_shard_id,
                    "randomEffectType": cm.random_effect_type, "dim": cm.dim,
                    "has_variances": cm.variances is not None,
                    "has_projector": cm.projector is not None}
                arrays[f"re:{cid}:keys"] = cm.keys
                arrays[f"re:{cid}:coeffs"] = cm.coeffs
                if cm.variances is not None:
                    arrays[f"re:{cid}:variances"] = cm.variances
                if cm.projector is not None:
                    arrays[f"re:{cid}:projector"] = cm.projector.matrix
        for cid, sc in state.scores.items():
            arrays[f"scores:{cid}"] = sc

        from photon_ml_tpu.resilience import fault_point, retry

        final = os.path.join(self.root, f"step-{step}")

        def attempt() -> None:
            tmp = tempfile.mkdtemp(prefix=f"step-{step}-", suffix=".tmp",
                                   dir=self.root)
            np.savez(os.path.join(tmp, "arrays.npz"), **arrays)
            with open(os.path.join(tmp, "manifest.json"), "w") as f:
                json.dump(manifest, f, indent=2)
            # the crash-mid-write window: tmp is fully written, the atomic
            # rename has not happened — a kill here must leave the previous
            # step as the loadable latest
            fault_point("ckpt.save", step=step, path=final)
            if os.path.exists(final):
                # retire the old copy aside FIRST (".tmp" suffix keeps it
                # out of steps()): rmtree-before-rename would open a window
                # where a crash loses BOTH copies of this step
                retired = tempfile.mkdtemp(prefix=f"step-{step}-retired-",
                                           suffix=".tmp", dir=self.root)
                os.rmdir(retired)
                os.rename(final, retired)
                os.rename(tmp, final)
                shutil.rmtree(retired, ignore_errors=True)
            else:
                os.rename(tmp, final)

        retry(attempt, name=f"ckpt.save:step-{step}")
        self._gc()
        return final

    def restore(self, step: Optional[int] = None,
                expected_fingerprint: Optional[str] = None,
                ) -> CoordinateDescentState:
        from photon_ml_tpu.resilience import retry

        if step is not None or self._pinned:
            if step is None:
                step = self.latest_step()
                if step is None:
                    raise FileNotFoundError(
                        f"no checkpoints under {self.root}")
            return retry(
                lambda: self._restore_step(step, expected_fingerprint),
                name=f"ckpt.restore:step-{step}")
        steps = self.steps()
        if not steps:
            raise FileNotFoundError(f"no checkpoints under {self.root}")
        # auto-select: walk back from the newest step past corrupt ones (a
        # crashed writer can't corrupt a renamed step, but disks can) —
        # resuming one boundary earlier beats dying. Fingerprint mismatches
        # still propagate: older steps share the configuration.
        last_error: Optional[BaseException] = None
        for s in reversed(steps):
            try:
                return retry(
                    lambda s=s: self._restore_step(s, expected_fingerprint),
                    name=f"ckpt.restore:step-{s}")
            except ValueError:
                raise
            except Exception as e:
                logger.warning("checkpoint step-%d unreadable (%r); "
                               "falling back to the previous step", s, e)
                last_error = e
        raise last_error

    def _restore_step(self, step: int, expected_fingerprint: Optional[str],
                      ) -> CoordinateDescentState:
        import jax.numpy as jnp

        path = os.path.join(self.root, f"step-{step}")
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
        saved_fp = manifest.get("fingerprint")
        if (expected_fingerprint is not None and saved_fp is not None
                and saved_fp != expected_fingerprint):
            raise ValueError(
                f"checkpoint at {path} was written under configuration "
                f"{saved_fp!r}, but this run is {expected_fingerprint!r}; "
                f"refusing to resume across configurations")
        arrays = np.load(os.path.join(path, "arrays.npz"))
        task = TaskType(manifest["task"])
        coordinates = {}
        for cid, info in manifest["coordinates"].items():
            if info["type"] == "fixed":
                coordinates[cid] = FixedEffectModel(
                    model=GeneralizedLinearModel(
                        coefficients=Coefficients(
                            means=jnp.asarray(arrays[f"fixed:{cid}:means"]),
                            variances=(jnp.asarray(arrays[f"fixed:{cid}:variances"])
                                       if info["has_variances"] else None)),
                        task=task),
                    feature_shard_id=info["featureShardId"])
            else:
                coordinates[cid] = RandomEffectModel(
                    random_effect_type=info["randomEffectType"],
                    feature_shard_id=info["featureShardId"], task=task,
                    dim=info["dim"], keys=arrays[f"re:{cid}:keys"],
                    coeffs=arrays[f"re:{cid}:coeffs"],
                    variances=(arrays[f"re:{cid}:variances"]
                               if info["has_variances"] else None),
                    projector=(RandomProjector(
                        matrix=arrays[f"re:{cid}:projector"])
                        if info.get("has_projector") else None))
        scores = {k.split(":", 1)[1]: arrays[k]
                  for k in arrays.files if k.startswith("scores:")}
        return CoordinateDescentState(
            sweep=manifest["sweep"],
            coordinate_index=manifest["coordinate_index"],
            model=GameModel(coordinates=coordinates, task=task),
            scores=scores)
