from photon_ml_tpu.evaluation.evaluator import (  # noqa: F401
    EvaluationResults,
    Evaluator,
    evaluate_all,
    parse_evaluator,
    parse_evaluators,
)
from photon_ml_tpu.evaluation.grouped import (  # noqa: F401
    grouped_auc,
    grouped_precision_at_k,
)
from photon_ml_tpu.evaluation.metrics import (  # noqa: F401
    area_under_roc_curve,
    mean_pointwise_loss,
    root_mean_squared_error,
)
