"""Evaluator abstraction, evaluator-string parsing, evaluation results.

Re-design of ``photon-api/.../evaluation/{Evaluator, EvaluatorType,
EvaluationResults}.scala``. Evaluator strings follow the reference CLI
vocabulary:

- ``AUC``, ``RMSE``, ``LOGISTIC_LOSS``, ``SQUARED_LOSS``, ``POISSON_LOSS``,
  ``SMOOTHED_HINGE_LOSS`` — whole-dataset metrics;
- ``AUC:<idTag>`` — per-group AUC averaged over groups (sharded AUC);
- ``PRECISION@<k>:<idTag>`` — per-group precision at k.

The *first* validation evaluator is the model-selection criterion, as in
``GameEstimator``/``ModelSelection``; ``better_than`` encodes direction.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu.evaluation.grouped import grouped_auc, grouped_precision_at_k
from photon_ml_tpu.evaluation.metrics import (
    area_under_roc_curve,
    mean_pointwise_loss,
    root_mean_squared_error,
)
from photon_ml_tpu.ops import losses as losses_mod

_LOSS_BY_NAME = {
    "LOGISTIC_LOSS": losses_mod.LogisticLoss,
    "SQUARED_LOSS": losses_mod.SquaredLoss,
    "POISSON_LOSS": losses_mod.PoissonLoss,
    "SMOOTHED_HINGE_LOSS": losses_mod.SmoothedHingeLoss,
}

_PRECISION_RE = re.compile(r"^PRECISION@(\d+):(.+)$", re.IGNORECASE)


@dataclasses.dataclass(frozen=True)
class Evaluator:
    """A named metric over scored data.

    ``id_tag`` is the grouping column for sharded metrics (None for global
    metrics); ``maximize`` gives the model-selection direction.
    """

    name: str
    maximize: bool
    id_tag: Optional[str] = None
    k: Optional[int] = None  # PRECISION@k only

    def evaluate(self, scores, labels, weights=None,
                 id_tags: Optional[Mapping[str, np.ndarray]] = None) -> float:
        """Compute the metric. ``id_tags`` maps tag name -> per-sample group
        ids (the reference's GameDatum ``idTagToValueMap``)."""
        if self.id_tag is not None:
            if id_tags is None or self.id_tag not in id_tags:
                raise KeyError(
                    f"evaluator {self.name} needs id tag '{self.id_tag}' "
                    f"but scored data has {sorted(id_tags or {})}")
            groups = np.asarray(id_tags[self.id_tag])
            # drop rows that don't carry the tag (-1 = missing), matching the
            # reference MultiEvaluator joining scores with present tags only
            present = groups >= 0 if np.issubdtype(groups.dtype, np.integer) \
                else np.ones(groups.shape, bool)
            scores = np.asarray(scores)[present]
            labels = np.asarray(labels)[present]
            groups = groups[present]
            if weights is not None:
                weights = np.asarray(weights)[present]
            if self.k is not None:
                return grouped_precision_at_k(scores, labels, groups, self.k)
            return grouped_auc(scores, labels, groups, weights)
        base = self.name.split(":", 1)[0].upper()
        if base == "AUC":
            return float(area_under_roc_curve(scores, labels, weights))
        if base == "RMSE":
            return float(root_mean_squared_error(scores, labels, weights))
        if base in _LOSS_BY_NAME:
            return float(mean_pointwise_loss(_LOSS_BY_NAME[base], scores, labels, weights))
        raise ValueError(f"unknown evaluator {self.name!r}")

    def better_than(self, a: float, b: Optional[float]) -> bool:
        """Is score ``a`` better than ``b`` (None = no incumbent)?"""
        if b is None or np.isnan(b):
            return not np.isnan(a)
        return a > b if self.maximize else a < b


def parse_evaluator(spec: str) -> Evaluator:
    """Parse a reference-vocabulary evaluator string (see module docstring)."""
    spec = spec.strip()
    m = _PRECISION_RE.match(spec)
    if m:
        k = int(m.group(1))
        if k < 1:
            raise ValueError(f"PRECISION@k needs k >= 1, got {spec!r}")
        return Evaluator(name=spec, maximize=True, id_tag=m.group(2), k=k)
    upper = spec.upper()
    if ":" in spec:
        base, tag = spec.split(":", 1)
        if base.upper() != "AUC":
            raise ValueError(f"only AUC and PRECISION@k support an id tag, got {spec!r}")
        return Evaluator(name=spec, maximize=True, id_tag=tag)
    if upper == "AUC":
        return Evaluator(name="AUC", maximize=True)
    if upper == "RMSE":
        return Evaluator(name="RMSE", maximize=False)
    if upper in _LOSS_BY_NAME:
        return Evaluator(name=upper, maximize=False)
    raise ValueError(f"unknown evaluator spec {spec!r}")


def parse_evaluators(specs: Sequence[str]) -> list[Evaluator]:
    return [parse_evaluator(s) for s in specs]


@dataclasses.dataclass(frozen=True)
class EvaluationResults:
    """Ordered evaluator results; the first entry drives model selection
    (reference ``EvaluationResults.scala``)."""

    results: tuple[tuple[Evaluator, float], ...]

    @property
    def primary(self) -> tuple[Evaluator, float]:
        return self.results[0]

    def as_dict(self) -> dict[str, float]:
        return {ev.name: val for ev, val in self.results}

    def __repr__(self) -> str:
        inner = ", ".join(f"{ev.name}={val:.6g}" for ev, val in self.results)
        return f"EvaluationResults({inner})"


def evaluate_all(evaluators: Sequence[Evaluator], scores, labels, weights=None,
                 id_tags: Optional[Mapping[str, np.ndarray]] = None) -> EvaluationResults:
    return EvaluationResults(tuple(
        (ev, ev.evaluate(scores, labels, weights, id_tags)) for ev in evaluators))
