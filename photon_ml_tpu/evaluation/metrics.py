"""Core single-metric implementations: AUC, RMSE, average pointwise losses.

Re-design of the reference evaluators
(``photon-api/.../evaluation/AreaUnderROCCurveEvaluator.scala``,
``evaluation/RMSEEvaluator.scala`` and the loss evaluators): pure jittable
functions over ``(scores, labels, weights)`` arrays instead of RDD folds.

AUC uses the weighted Mann-Whitney statistic with exact tie handling
(ties contribute half), computed by one sort + two ``searchsorted`` passes —
equivalent to trapezoidal ROC integration with tie groups collapsed, which is
what the reference's sort-based integration computes. This is the "AUC to
1e-4" parity surface (SURVEY.md §7 hard part 5), so tie semantics matter.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from photon_ml_tpu.ops.losses import PointwiseLoss

Array = jax.Array


def area_under_roc_curve(scores: Array, labels: Array, weights: Array | None = None) -> Array:
    """Weighted AUC with average-rank tie handling.

    ``labels`` are binary {0,1}; padded rows must carry weight 0. Returns NaN
    when either class has zero total weight (the reference skips such
    evaluations).
    """
    if weights is None:
        weights = jnp.ones_like(scores)
    pos_w = weights * labels
    neg_w = weights * (1.0 - labels)

    order = jnp.argsort(scores)
    s = scores[order]
    pw = pos_w[order]
    nw = neg_w[order]

    # Cumulative negative weight up to (inclusive) each sorted position;
    # prepend 0 so cum[i] = total neg weight of the first i elements.
    cum = jnp.concatenate([jnp.zeros((1,), nw.dtype), jnp.cumsum(nw)])
    lo = jnp.searchsorted(s, s, side="left")
    hi = jnp.searchsorted(s, s, side="right")
    strictly_lower = cum[lo]
    tied = cum[hi] - cum[lo]

    total = jnp.sum(pw * (strictly_lower + 0.5 * tied))
    p = jnp.sum(pos_w)
    n = jnp.sum(neg_w)
    return total / (p * n)


def root_mean_squared_error(scores: Array, labels: Array, weights: Array | None = None) -> Array:
    """Weighted RMSE of raw scores vs labels (reference ``RMSEEvaluator``)."""
    if weights is None:
        weights = jnp.ones_like(scores)
    se = jnp.sum(weights * jnp.square(scores - labels))
    return jnp.sqrt(se / jnp.sum(weights))


def mean_pointwise_loss(loss: PointwiseLoss, scores: Array, labels: Array,
                        weights: Array | None = None) -> Array:
    """Weighted average of a pointwise loss over scored data (the reference's
    ``{Logistic,Squared,Poisson,SmoothedHinge}LossEvaluator`` family)."""
    if weights is None:
        weights = jnp.ones_like(scores)
    return jnp.sum(weights * loss.loss(scores, labels)) / jnp.sum(weights)


area_under_roc_curve_jit = jax.jit(area_under_roc_curve)
