"""Grouped ("sharded") metrics: per-entity AUC and Precision@K averaged over groups.

Re-design of the reference's multi-evaluators
(``photon-api/.../evaluation/{MultiEvaluator, AreaUnderROCCurveMultiEvaluator,
PrecisionAtKMultiEvaluator}.scala``): scores are joined with an id tag (e.g.
``queryId``, ``documentId``), the metric is computed per group, and the result
is the unweighted mean over groups where the metric is defined.

The reference does this with an RDD groupBy; here the whole computation is a
handful of vectorized sorts/segment reductions on host numpy — group counts
can reach hundreds of millions but the arithmetic is a few passes over flat
arrays, far from the training hot loop, so the host is the right place (device
arrays would pay a gather-heavy irregular reduction for no win).
"""

from __future__ import annotations

import numpy as np

from photon_ml_tpu.util import group_starts as _group_starts


def grouped_auc(scores, labels, groups, weights=None) -> float:
    """Mean per-group weighted AUC over groups with both classes present.

    ``groups`` is an integer (or any sortable) id per sample. Matches
    ``AreaUnderROCCurveMultiEvaluator``: groups with only one class are
    skipped; the average over groups is unweighted.
    """
    scores = np.asarray(scores, dtype=np.float64)
    labels = np.asarray(labels, dtype=np.float64)
    groups = np.asarray(groups)
    weights = np.ones_like(scores) if weights is None else np.asarray(weights, np.float64)

    order = np.lexsort((scores, groups))
    g = groups[order]
    s = scores[order]
    y = labels[order]
    w = weights[order]
    nw = w * (1.0 - y)
    pw = w * y

    starts = _group_starts(g)
    n = g.shape[0]
    if n == 0:
        return float("nan")
    # Per-element index of its group's start.
    group_start = np.zeros(n, dtype=np.int64)
    group_start[starts] = starts
    np.maximum.accumulate(group_start, out=group_start)

    # Tie blocks: same (group, score). Block start index per element.
    new_block = np.empty(n, dtype=bool)
    new_block[0] = True
    new_block[1:] = (g[1:] != g[:-1]) | (s[1:] != s[:-1])
    block_ids = np.cumsum(new_block) - 1
    block_starts = np.flatnonzero(new_block)
    block_start = block_starts[block_ids]
    # Block end (exclusive): start of next block, or n.
    block_end = np.empty(n, dtype=np.int64)
    block_end[:] = np.append(block_starts[1:], n)[block_ids]

    cum = np.concatenate([[0.0], np.cumsum(nw)])
    cum_at_group_start = cum[group_start]
    strictly_lower = cum[block_start] - cum_at_group_start
    tied = cum[block_end] - cum[block_start]

    contrib = pw * (strictly_lower + 0.5 * tied)
    # Per-group reductions.
    contrib_g = np.add.reduceat(contrib, starts)
    pos_g = np.add.reduceat(pw, starts)
    neg_g = np.add.reduceat(nw, starts)

    valid = (pos_g > 0) & (neg_g > 0)
    if not np.any(valid):
        return float("nan")
    auc_g = contrib_g[valid] / (pos_g[valid] * neg_g[valid])
    return float(np.mean(auc_g))


def grouped_precision_at_k(scores, labels, groups, k: int) -> float:
    """Mean per-group Precision@K (reference ``PrecisionAtKMultiEvaluator``).

    Per group: sort by score descending, precision = (# positive labels among
    the top ``k``) / ``k``. Groups smaller than ``k`` still divide by ``k``
    (missing items count as misses), matching the reference's fixed-k
    denominator. Unweighted average over all groups.
    """
    scores = np.asarray(scores, dtype=np.float64)
    labels = np.asarray(labels, dtype=np.float64)
    groups = np.asarray(groups)
    if scores.shape[0] == 0:
        return float("nan")

    order = np.lexsort((-scores, groups))
    g = groups[order]
    y = labels[order]

    starts = _group_starts(g)
    n = g.shape[0]
    group_start = np.zeros(n, dtype=np.int64)
    group_start[starts] = starts
    np.maximum.accumulate(group_start, out=group_start)
    rank = np.arange(n, dtype=np.int64) - group_start

    hits = np.where(rank < k, (y > 0).astype(np.float64), 0.0)
    hits_g = np.add.reduceat(hits, starts)
    return float(np.mean(hits_g / float(k)))
