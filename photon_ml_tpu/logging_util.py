"""Run logging and stage timing.

Re-design of the reference's observability idioms: ``util/PhotonLogger.scala``
(driver-side logger teeing to a durable file users read for iteration
tables) and ``util/Timed.scala`` (named wall-clock stage sections logged at
start/end). Same contract — one human-readable training log per run on
durable storage — plus structured JSONL metrics alongside.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
from typing import Iterator, Optional

logger = logging.getLogger("photon_ml_tpu")


class RunLogger:
    """Tees log lines to the console and a run-directory log file, and
    appends structured metrics to ``metrics.jsonl``."""

    def __init__(self, run_dir: Optional[str] = None, level=logging.INFO):
        self.run_dir = run_dir
        self._handlers: list[logging.Handler] = []
        root = logging.getLogger("photon_ml_tpu")
        root.setLevel(level)
        fmt = logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        if not any(isinstance(h, logging.StreamHandler) for h in root.handlers):
            sh = logging.StreamHandler()
            sh.setFormatter(fmt)
            root.addHandler(sh)
            self._handlers.append(sh)
        if run_dir:
            os.makedirs(run_dir, exist_ok=True)
            fh = logging.FileHandler(os.path.join(run_dir, "photon.log"))
            fh.setFormatter(fmt)
            root.addHandler(fh)
            self._handlers.append(fh)
        self._metrics_path = (os.path.join(run_dir, "metrics.jsonl")
                              if run_dir else None)
        # ONE append handle for the logger's lifetime: reopening per metric
        # costs an open/close syscall pair per line, and interleaved opens
        # from serving threads can shear lines — the lock serializes writers
        # and the flush keeps the file durable line-by-line
        self._metrics_lock = threading.Lock()
        self._metrics_fh = (open(self._metrics_path, "a", encoding="utf-8")
                            if self._metrics_path else None)

    def metric(self, **kwargs) -> None:
        kwargs.setdefault("ts", time.time())
        line = json.dumps(kwargs) + "\n"
        with self._metrics_lock:
            if self._metrics_fh is not None:
                self._metrics_fh.write(line)
                self._metrics_fh.flush()
        logger.info("metric %s", kwargs)

    def close(self) -> None:
        with self._metrics_lock:
            if self._metrics_fh is not None:
                self._metrics_fh.close()
                self._metrics_fh = None
        root = logging.getLogger("photon_ml_tpu")
        for h in self._handlers:
            root.removeHandler(h)
            h.close()
        self._handlers.clear()


def log_optimizer_trace(result, label: str,
                        run_logger: Optional[RunLogger] = None) -> None:
    """Dump the per-iteration (value, gradient-norm) table to the run log —
    the reference's ``OptimizationStatesTracker`` dump users read in the
    photon log (``enableOptimizationStateTracker``). ``result`` is an
    :class:`photon_ml_tpu.optimize.OptimizerResult` with traces recorded
    (``track_states=True``)."""
    import numpy as np

    values = np.asarray(result.values)
    gnorms = np.asarray(result.grad_norms)
    if values.size == 0:
        return  # traces off
    n = min(int(result.iterations) + 1, len(values))
    logger.info("%s: optimization states (%d iterations, converged=%s)",
                label, max(n - 1, 0), bool(result.converged))
    # collapse runs of CONSECUTIVE identical finite (value, |g|) lines — a
    # stalled tail would otherwise spam max_iterations copies of one state;
    # a non-finite entry breaks a run and is logged explicitly
    run_start = None
    run_end = None
    for i in range(n):
        same = (run_start is not None and np.isfinite(values[i])
                and i == run_end + 1
                and values[i] == values[run_start]
                and gnorms[i] == gnorms[run_start])
        if same:
            run_end = i
            continue
        if run_start is not None and run_end > run_start:
            logger.info("%s:   ... unchanged through iter %d", label, run_end)
        logger.info("%s: iter %4d  f=%.8e  |g|=%.4e",
                    label, i, values[i], gnorms[i])
        run_start = run_end = i
    if run_start is not None and run_end > run_start:
        logger.info("%s:   ... unchanged through iter %d", label, run_end)
    if run_logger is not None:
        run_logger.metric(stage="optimizer_states", label=label,
                          iterations=int(result.iterations),
                          converged=bool(result.converged),
                          final_value=float(values[min(n - 1, len(values) - 1)]))


@contextlib.contextmanager
def profiled(output_dir: Optional[str]) -> Iterator[None]:
    """``jax.profiler`` trace of a stage (SURVEY.md §5.1: the tracing story
    replacing the reference's Spark-UI/event-log). View with TensorBoard or
    xprof; no-op when ``output_dir`` is None."""
    if not output_dir:
        yield
        return
    import jax

    os.makedirs(output_dir, exist_ok=True)
    try:
        with jax.profiler.trace(output_dir):
            yield
    finally:
        # the trace file exists even when the body raised (the profiler's
        # own exit wrote it) — confirm in a finally so a failing run still
        # tells the user where its trace landed
        logger.info("profiler trace written to %s", output_dir)


@contextlib.contextmanager
def timed(stage: str, run_logger: Optional[RunLogger] = None) -> Iterator[None]:
    """``with timed("Read data"): ...`` — the reference's ``Timed`` wrapper,
    now a thin layer over a telemetry span: the stage appears in the run's
    ``trace.jsonl`` tree (when ``--telemetry-dir`` is configured) with the
    same name.

    Also posts ``stage_started``/``stage_finished`` lifecycle events to the
    global :mod:`photon_ml_tpu.events` bus so observers see stage boundaries.
    """
    from photon_ml_tpu.events import GLOBAL_BUS
    from photon_ml_tpu.telemetry.tracing import span

    logger.info("%s: start", stage)
    GLOBAL_BUS.post("stage_started", stage=stage)
    sp = None
    try:
        with span(stage, kind="stage") as sp:
            yield
    finally:
        # the span IS the stage clock (telemetry hygiene rule 5: one
        # timing source, visible in trace.jsonl) — read its seconds
        # instead of running a second perf_counter pair
        dt = sp.seconds if sp is not None else 0.0
        logger.info("%s: done in %.2fs", stage, dt)
        GLOBAL_BUS.post("stage_finished", stage=stage, seconds=dt)
        if run_logger is not None:
            run_logger.metric(stage=stage, seconds=round(dt, 3))
