"""Build/load the native ingest library and decode Avro training files.

Pairs with ``native/avro_reader.cc`` (see its header comment for the role).
The module compiles the shared library on first use (g++ -O2, linked against
zlib), caches it under ``native/build/``, and exposes
:func:`decode_training_file` returning flat numpy arrays. Callers must treat
this as an optional fast path: :data:`available` is False when no compiler
or library is usable, and ``AvroDataReader`` falls back to the pure-Python
codec (:mod:`photon_ml_tpu.io.avro`).
"""

from __future__ import annotations

import ctypes
import dataclasses
import io
import json
import os
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

from photon_ml_tpu.io import avro as avro_mod

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_REPO_ROOT, "native", "avro_reader.cc")
_SRC_WRITER = os.path.join(_REPO_ROOT, "native", "avro_writer.cc")
_SRC_BUCKET = os.path.join(_REPO_ROOT, "native", "bucket_pack.cc")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")
_LIB = os.path.join(_BUILD_DIR, "libphoton_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False

#: canonical field order we emit; the file's order is matched against names
_FIELDS = ("uid", "response", "offset", "weight", "features", "metadataMap")


def _build() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    # -march=native first (measured ~7% on the decode hot loop; the library
    # is always compiled on the machine that runs it), plain -O2 fallback
    # for toolchains that reject it
    for extra in (["-O3", "-march=native"], ["-O2"]):
        cmd = (["g++", "-std=c++17"] + extra
               + ["-shared", "-fPIC", "-o", _LIB,
                  _SRC, _SRC_WRITER, _SRC_BUCKET, "-lz"])
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=120)
        except (OSError, subprocess.TimeoutExpired):
            continue  # let the plainer flag set have its try
        if proc.returncode == 0 and os.path.exists(_LIB):
            return True
    return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            src_mtime = max(os.path.getmtime(_SRC),
                            os.path.getmtime(_SRC_WRITER),
                            os.path.getmtime(_SRC_BUCKET))
        except OSError:
            # sources absent (installed wheel without the native tree):
            # unbuildable → degrade to the Python fallback, never raise
            src_mtime = None
        if src_mtime is None and not os.path.exists(_LIB):
            _load_failed = True
            return None
        if not os.path.exists(_LIB) or (
                src_mtime is not None
                and os.path.getmtime(_LIB) < src_mtime):
            if not _build():
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _load_failed = True
            return None
        try:
            lib.photon_decode_blocks.restype = ctypes.c_void_p
            lib.photon_decode_blocks.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int,
                ctypes.POINTER(ctypes.c_int), ctypes.c_char_p, ctypes.c_char_p]
            lib.photon_result_error.restype = ctypes.c_char_p
            lib.photon_result_error.argtypes = [ctypes.c_void_p]
            for name, res in (("n_records", ctypes.c_int64),
                              ("nnz", ctypes.c_int64),
                              ("n_feature_keys", ctypes.c_int32),
                              ("feature_bytes_len", ctypes.c_int64)):
                fn = getattr(lib, f"photon_result_{name}")
                fn.restype = res
                fn.argtypes = [ctypes.c_void_p]
            lib.photon_result_copy_core.argtypes = [ctypes.c_void_p] + \
                [np.ctypeslib.ndpointer(dtype=d, flags="C_CONTIGUOUS")
                 for d in (np.float64, np.float64, np.float64, np.int64,
                           np.int32, np.float64)]
            lib.photon_result_copy_feature_keys.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
                np.ctypeslib.ndpointer(dtype=np.int64, flags="C_CONTIGUOUS")]
            lib.photon_result_id_vocab_size.restype = ctypes.c_int32
            lib.photon_result_id_vocab_size.argtypes = [ctypes.c_void_p,
                                                        ctypes.c_int32]
            lib.photon_result_id_vocab_bytes_len.restype = ctypes.c_int64
            lib.photon_result_id_vocab_bytes_len.argtypes = [ctypes.c_void_p,
                                                             ctypes.c_int32]
            lib.photon_result_copy_id_col.argtypes = [
                ctypes.c_void_p, ctypes.c_int32,
                np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS"),
                ctypes.c_char_p,
                np.ctypeslib.ndpointer(dtype=np.int64, flags="C_CONTIGUOUS")]
            lib.photon_result_free.argtypes = [ctypes.c_void_p]
            _i64p = np.ctypeslib.ndpointer(dtype=np.int64, flags="C_CONTIGUOUS")
            _i32p = np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS")
            _f32p = np.ctypeslib.ndpointer(dtype=np.float32, flags="C_CONTIGUOUS")
            _f64p = np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS")
            lib.photon_shard_split_count.restype = None
            lib.photon_shard_split_count.argtypes = [
                _i64p, _i32p, ctypes.c_int64, _i32p, ctypes.c_int32, _i64p]
            lib.photon_shard_split_fill.restype = None
            lib.photon_shard_split_fill.argtypes = [
                _i64p, _i32p, _f64p, ctypes.c_int64, _i32p, ctypes.c_int32,
                _i64p, _i32p, _f32p]
            lib.photon_counting_sort.restype = None
            lib.photon_counting_sort.argtypes = [
                _i64p, ctypes.c_int64, _i64p, _i64p]
            lib.photon_re_feature_counts.restype = None
            lib.photon_re_feature_counts.argtypes = [
                _i64p, _i32p, _i64p, _i64p,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                _i64p, _i64p, _i64p]
            lib.photon_re_bucket_fill.restype = None
            lib.photon_re_bucket_fill.argtypes = [
                _i64p, _i32p, _f32p, _i64p, _i64p, _f32p, _f32p, _i64p,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64, _i64p, _i64p, _i64p, _i64p,
                _f32p, _f32p, _f32p, _i64p, _i64p]
            lib.photon_re_bucket_indices.restype = None
            lib.photon_re_bucket_indices.argtypes = [
                _i64p, _i32p, _i64p, _i64p, _i64p,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                _i64p, _i64p, _i64p, _i64p]
            lib.photon_write_scoring_results.restype = ctypes.c_int64
            lib.photon_write_scoring_results.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64,
                np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS"),
                ctypes.c_void_p,  # labels (f64*) or NULL
                ctypes.c_char_p,  # uid bytes or NULL
                ctypes.c_void_p,  # uid offsets (i64*) or NULL
                ctypes.c_int64, ctypes.c_int64]
            _f64p = np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS")
            lib.photon_write_re_models.restype = ctypes.c_int64
            lib.photon_write_re_models.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_char_p, _i64p,
                ctypes.c_char_p, ctypes.c_int64,
                _i64p, _i32p, _f64p,
                ctypes.c_void_p,  # variances (f64*) or NULL
                ctypes.c_char_p, _i64p, ctypes.c_char_p, _i64p,
                ctypes.c_int64]
        except AttributeError:
            # a stale prebuilt library (sources absent, no
            # rebuild possible) missing newer symbols must
            # degrade to the pure-Python fallback, never raise
            _load_failed = True
            return None
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


@dataclasses.dataclass
class DecodedFile:
    """Columnar decode of one TrainingExampleAvro container file."""

    response: np.ndarray  # (n,) f64, NaN never (response is required)
    offset: np.ndarray  # (n,) f64, NaN = null
    weight: np.ndarray  # (n,) f64, NaN = null
    feat_indptr: np.ndarray  # (n+1,) i64
    feat_key_id: np.ndarray  # (nnz,) i32 -> feature_keys
    feat_val: np.ndarray  # (nnz,) f64
    feature_keys: list[str]  # interned "name\x01term" strings
    id_cols: dict[str, np.ndarray]  # (n,) i32, -1 missing
    id_vocabs: dict[str, list[str]]

    @property
    def n_records(self) -> int:
        return int(self.response.shape[0])


def _schema_layout(schema) -> Optional[tuple[list[int], bytes]]:
    """Match the file schema against TrainingExampleAvro; return
    (field_order, null_first) or None if incompatible."""
    if not isinstance(schema, dict) or schema.get("type") != "record":
        return None
    fields = schema.get("fields", [])
    if len(fields) != len(_FIELDS):
        return None
    order: list[int] = []
    null_first = bytearray(len(_FIELDS))
    for f in fields:
        name = f.get("name")
        if name not in _FIELDS:
            return None
        idx = _FIELDS.index(name)
        order.append(idx)
        t = f.get("type")
        if name in ("uid", "offset", "weight", "metadataMap"):
            if not (isinstance(t, list) and len(t) == 2 and "null" in t):
                return None
            null_first[idx] = 1 if t[0] == "null" else 0
            other = t[1] if t[0] == "null" else t[0]
            if name == "uid" and other != "string":
                return None
            if name in ("offset", "weight") and other != "double":
                return None
            if name == "metadataMap" and not (
                    isinstance(other, dict) and other.get("type") == "map"
                    and other.get("values") == "string"):
                return None
        elif name == "response":
            if t != "double":
                return None
        else:  # features
            if not (isinstance(t, dict) and t.get("type") == "array"):
                return None
            items = t.get("items")
            if not (isinstance(items, dict) and items.get("type") == "record"):
                return None
            fnames = [x.get("name") for x in items.get("fields", [])]
            ftypes = [x.get("type") for x in items.get("fields", [])]
            if fnames != ["name", "term", "value"] or \
                    ftypes != ["string", "string", "double"]:
                return None
    return order, bytes(null_first)


def _snappy_blocks_to_null(blocks: bytes, sync: bytes, path: str) -> bytes:
    """Rewrite a snappy-codec block stream as a null-codec stream.

    Each container block is ``long(count) long(size) payload sync``; the
    frame decode (decompress + CRC) is :func:`io.avro.snappy_decode_block`.
    CRC mismatches raise — matching the pure-Python reader's behavior rather
    than None-falling-back, since the file is genuinely corrupt.

    Memory note: this materializes the file's full UNCOMPRESSED block stream
    (the native decoder consumes one contiguous buffer); the caller drops the
    compressed blob before invoking the decoder so peak overhead vs the
    deflate path is one uncompressed copy per in-flight decode."""
    src = io.BytesIO(blocks)
    out = io.BytesIO()
    total = len(blocks)
    while src.tell() < total:
        count = avro_mod.read_long(src)
        size = avro_mod.read_long(src)
        data = avro_mod.snappy_decode_block(src.read(size), context=path)
        block_sync = src.read(avro_mod.SYNC_SIZE)
        if block_sync != sync:
            raise ValueError(f"sync marker mismatch in {path!r}")
        avro_mod.write_long(out, count)
        avro_mod.write_long(out, len(data))
        out.write(data)
        out.write(sync)
    return out.getvalue()


def decode_training_file(path: str, id_keys: Sequence[str] = ()
                         ) -> Optional[DecodedFile]:
    """Decode via the native library; None if unavailable/incompatible
    (caller falls back to the pure-Python reader)."""
    lib = _load()
    if lib is None:
        return None
    with open(path, "rb") as f:
        blob = f.read()
    buf = io.BytesIO(blob)
    if buf.read(4) != avro_mod.MAGIC:
        return None
    # header: metadata map + sync (python-side; cheap)
    names: dict = {}
    meta = {}
    while True:
        count = avro_mod.read_long(buf)
        if count == 0:
            break
        if count < 0:
            count = -count
            avro_mod.read_long(buf)
        for _ in range(count):
            k = avro_mod.read_datum(buf, "string", names)
            size = avro_mod.read_long(buf)
            meta[k] = buf.read(size)
    codec = meta.get("avro.codec", b"null").decode()
    if codec not in ("null", "deflate", "snappy"):
        return None
    layout = _schema_layout(json.loads(meta["avro.schema"].decode()))
    if layout is None:
        return None
    field_order, null_first = layout
    sync = buf.read(avro_mod.SYNC_SIZE)
    blocks = blob[buf.tell():]
    if codec == "snappy":
        # the native decoder speaks null/deflate; snappy blocks are small in
        # number (thousands of records each) — decompress them here and hand
        # the decoder an equivalent null-codec block stream, keeping the
        # C++ fast path instead of silently dropping to the Python reader
        blocks = _snappy_blocks_to_null(blocks, sync, path)
        codec = "null"
        del blob, buf  # free the compressed copy before the decode

    order_arr = (ctypes.c_int * len(field_order))(*field_order)
    rp = lib.photon_decode_blocks(
        blocks, len(blocks), sync, int(codec == "deflate"), order_arr,
        null_first, "\n".join(id_keys).encode())
    if not rp:
        return None
    try:
        err = lib.photon_result_error(rp)
        if err:
            raise ValueError(f"native avro decode failed for {path!r}: "
                             f"{err.decode()}")
        n = lib.photon_result_n_records(rp)
        nnz = lib.photon_result_nnz(rp)
        n_keys = lib.photon_result_n_feature_keys(rp)
        key_bytes_len = lib.photon_result_feature_bytes_len(rp)

        response = np.empty(n, np.float64)
        offset = np.empty(n, np.float64)
        weight = np.empty(n, np.float64)
        indptr = np.empty(n + 1, np.int64)
        key_id = np.empty(nnz, np.int32)
        val = np.empty(nnz, np.float64)
        lib.photon_result_copy_core(rp, response, offset, weight, indptr,
                                    key_id, val)

        kb = ctypes.create_string_buffer(max(int(key_bytes_len), 1))
        koff = np.empty(n_keys + 1, np.int64)
        lib.photon_result_copy_feature_keys(rp, kb, koff)
        kraw = kb.raw[:key_bytes_len]
        feature_keys = [kraw[koff[i]:koff[i + 1]].decode()
                        for i in range(n_keys)]

        id_cols = {}
        id_vocabs = {}
        for c, key in enumerate(id_keys):
            vsize = lib.photon_result_id_vocab_size(rp, c)
            vbytes = lib.photon_result_id_vocab_bytes_len(rp, c)
            ids = np.empty(n, np.int32)
            vb = ctypes.create_string_buffer(max(int(vbytes), 1))
            voff = np.empty(vsize + 1, np.int64)
            lib.photon_result_copy_id_col(rp, c, ids, vb, voff)
            vraw = vb.raw[:vbytes]
            id_cols[key] = ids
            id_vocabs[key] = [vraw[voff[i]:voff[i + 1]].decode()
                              for i in range(vsize)]
        return DecodedFile(
            response=response, offset=offset, weight=weight,
            feat_indptr=indptr, feat_key_id=key_id, feat_val=val,
            feature_keys=feature_keys, id_cols=id_cols, id_vocabs=id_vocabs)
    finally:
        lib.photon_result_free(rp)


def write_scoring_results(path: str, scores: np.ndarray,
                          labels: Optional[np.ndarray] = None,
                          uids: Optional[Sequence[str]] = None,
                          block_records: int = 65536) -> bool:
    """Write a ``ScoringResultAvro`` container via the native writer.

    Columns in, container out — the output half of the native IO path
    (measured ~5M rows/s vs ~100k for the pure-Python record encoder —
    ~50x; see ``native/avro_writer.cc``).
    ``uids=None`` writes decimal record indices (what ``score_game``
    emits). Returns False when the native library is unavailable, in which
    case the caller falls back to :func:`photon_ml_tpu.io.avro.write_avro_file`.
    """
    lib = _load()
    if lib is None:
        return False
    from photon_ml_tpu.io.schemas import SCORING_RESULT_AVRO

    schema = json.dumps(SCORING_RESULT_AVRO).encode()
    scores = np.ascontiguousarray(scores, np.float64)
    if scores.ndim != 1:
        raise ValueError(f"scores must be 1-D, got shape {scores.shape}")
    n = scores.shape[0]
    labels_ptr = None
    labels_arr = None
    if labels is not None:
        labels_arr = np.ascontiguousarray(labels, np.float64)
        if labels_arr.shape != (n,):
            raise ValueError(
                f"labels must be shape ({n},), got {labels_arr.shape}")
        labels_ptr = labels_arr.ctypes.data_as(ctypes.c_void_p)
    uid_bytes = None
    uid_off_ptr = None
    uid_off = None
    if uids is not None:
        encoded = [u.encode() for u in uids]
        if len(encoded) != n:
            raise ValueError("uids length mismatch")
        uid_off = np.zeros(n + 1, np.int64)
        np.cumsum([len(b) for b in encoded], out=uid_off[1:])
        uid_bytes = b"".join(encoded)
        uid_off_ptr = uid_off.ctypes.data_as(ctypes.c_void_p)
    wrote = lib.photon_write_scoring_results(
        path.encode(), schema, len(schema), scores, labels_ptr,
        uid_bytes, uid_off_ptr, n, block_records)
    return wrote == n


class BucketPackScratch:
    """Shared dim-sized scratch for one dataset build's packer calls.

    The stamp arrays are -1-initialized once here and shared across every
    pass-A/pass-B call of a single build (the C side stamps with dense
    entity ids, which never repeat across calls — see bucket_pack.cc's
    scratch contract). Pass A and pass B need DISTINCT stamp arrays."""

    def __init__(self, dim: int):
        self.stamp_a = np.full(dim, -1, np.int64)
        self.stamp_b = np.full(dim, -1, np.int64)
        self.kept_stamp = np.full(dim, -1, np.int64)
        self.support = np.empty(dim, np.int64)
        self.local = np.empty(dim, np.int64)


def _concat_strings(strings) -> tuple[bytes, np.ndarray]:
    """Concatenated utf-8 bytes + (n+1,) offsets for a string sequence."""
    encoded = [s.encode() for s in strings]
    offs = np.zeros(len(encoded) + 1, np.int64)
    np.cumsum([len(b) for b in encoded], out=offs[1:])
    return b"".join(encoded), offs


def write_re_models(path: str, model_ids, model_class: str,
                    rec_indptr: np.ndarray, name_ids: np.ndarray,
                    values: np.ndarray, variances: Optional[np.ndarray],
                    names, terms, block_records: int = 4096) -> bool:
    """Write per-entity ``BayesianLinearModelAvro`` records via the native
    writer (``native/avro_writer.cc::photon_write_re_models``).

    ``rec_indptr`` gives each record's [lo, hi) span in the flat
    ``name_ids``/``values``/``variances`` columns; ``name_ids`` index the
    ``names``/``terms`` tables. ``model_class`` is written as both
    modelClass and lossFunction (matching the Python path). Returns False
    when the native library is unavailable; the caller falls back to
    :func:`photon_ml_tpu.io.avro.write_avro_file`."""
    lib = _load()
    if lib is None:
        return False
    from photon_ml_tpu.io.schemas import BAYESIAN_LINEAR_MODEL_AVRO

    schema = json.dumps(BAYESIAN_LINEAR_MODEL_AVRO).encode()
    id_bytes, id_offs = _concat_strings(model_ids)
    name_bytes, name_offs = _concat_strings(names)
    term_bytes, term_offs = _concat_strings(terms)
    rec_indptr = np.ascontiguousarray(rec_indptr, np.int64)
    name_ids = np.ascontiguousarray(name_ids, np.int32)
    values = np.ascontiguousarray(values, np.float64)
    n_models = len(rec_indptr) - 1
    var_ptr = None
    var_arr = None
    if variances is not None:
        var_arr = np.ascontiguousarray(variances, np.float64)
        var_ptr = var_arr.ctypes.data_as(ctypes.c_void_p)
    mc = model_class.encode()
    wrote = lib.photon_write_re_models(
        path.encode(), schema, len(schema), n_models, id_bytes, id_offs,
        mc, len(mc), rec_indptr, name_ids, values, var_ptr,
        name_bytes, name_offs, term_bytes, term_offs, block_records)
    return wrote == n_models


def re_feature_counts(indptr: np.ndarray, cols: np.ndarray,
                      all_active: np.ndarray, ent_starts: np.ndarray,
                      dim: int, max_active_features: Optional[int],
                      scratch: BucketPackScratch) -> Optional[np.ndarray]:
    """Per-entity distinct-feature counts (post-pruning) over entity-grouped
    active rows — pass A of the native bucket packer
    (``native/bucket_pack.cc``). None when the library is unavailable; the
    caller falls back to the numpy formulation. Arrays must be C-contiguous
    with the documented dtypes (ctypes ndpointer enforces this)."""
    lib = _load()
    if lib is None:
        return None
    n_entities = len(ent_starts) - 1
    out = np.empty(n_entities, np.int64)
    lib.photon_re_feature_counts(
        indptr, cols, all_active, ent_starts, n_entities, int(dim),
        -1 if max_active_features is None else int(max_active_features),
        scratch.stamp_a, scratch.support, out)
    return out


def re_bucket_fill(indptr, cols, vals, all_active, ent_starts,
                   labels_all, weights_all, sel, S: int, D: int,
                   dim: int, max_active_features: Optional[int],
                   scratch: BucketPackScratch):
    """Pack one bucket's (E, S, D) tensors — pass B of the native bucket
    packer. Returns ``(x, labels, weights, sample_idx, feature_index)``
    matching the numpy path exactly, or None when unavailable."""
    lib = _load()
    if lib is None:
        return None
    sel = np.ascontiguousarray(sel, np.int64)
    e = len(sel)
    x = np.zeros((e, S, D), np.float32)
    labels = np.zeros((e, S), np.float32)
    weights = np.zeros((e, S), np.float32)
    sample_idx = np.full((e, S), -1, np.int64)
    feature_index = np.full((e, D), -1, np.int64)
    lib.photon_re_bucket_fill(
        indptr, cols, vals, all_active, ent_starts, labels_all, weights_all,
        sel, e, int(S), int(D), int(dim),
        -1 if max_active_features is None else int(max_active_features),
        scratch.stamp_b, scratch.support, scratch.kept_stamp, scratch.local,
        x, labels, weights, sample_idx, feature_index)
    return x, labels, weights, sample_idx, feature_index


def re_bucket_indices(indptr, cols, all_active, ent_starts, sel,
                      S: int, D: int, max_active_features: Optional[int],
                      scratch: BucketPackScratch):
    """Pack one bucket's index maps ONLY (pass B'): the compact device path
    reconstructs the (E, S, D) tensors by on-device gathers, so the host
    fill is skipped. Returns ``(sample_idx, feature_index)`` identical to
    :func:`re_bucket_fill`'s, or None when unavailable."""
    lib = _load()
    if lib is None:
        return None
    sel = np.ascontiguousarray(sel, np.int64)
    e = len(sel)
    sample_idx = np.full((e, S), -1, np.int64)
    feature_index = np.full((e, D), -1, np.int64)
    lib.photon_re_bucket_indices(
        indptr, cols, all_active, ent_starts, sel, e, int(S), int(D),
        -1 if max_active_features is None else int(max_active_features),
        scratch.stamp_b, scratch.support, sample_idx, feature_index)
    return sample_idx, feature_index


def shard_split(feat_indptr, feat_key_id, feat_val, key_to_col,
                intercept_col: int):
    """CSR split of one decoded file's flat feature stream into one shard
    (``avro_reader.cc::photon_shard_split_{count,fill}``): record order
    preserved, values cast to f32 in-pass, optional per-record intercept
    entry appended. Replaces the numpy remap/mask/gather assembly (~1 s on
    a 1M-record file). Returns ``(indptr, cols, vals)`` or None when the
    library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    n = len(feat_indptr) - 1
    counts = np.empty(n, np.int64)
    lib.photon_shard_split_count(feat_indptr, feat_key_id, n, key_to_col,
                                 intercept_col, counts)
    indptr = np.zeros(n + 1, np.int64)
    np.cumsum(counts, out=indptr[1:])
    nnz = int(indptr[-1]) if n else 0
    cols = np.empty(nnz, np.int32)
    vals = np.empty(nnz, np.float32)
    lib.photon_shard_split_fill(feat_indptr, feat_key_id, feat_val, n,
                                key_to_col, intercept_col, indptr, cols,
                                vals)
    return indptr, cols, vals


def counting_sort(ids: np.ndarray) -> Optional[np.ndarray]:
    """Stable group-order of dense non-negative int ids — the native O(n)
    counting sort (``bucket_pack.cc::photon_counting_sort``). Returns the
    same permutation as ``np.argsort(ids, kind="stable")``; None when the
    library is unavailable (caller falls back).

    Counting sort allocates O(max(ids)) counter arrays — correct only for
    PRE-INDEXED dense ids. A sparse column (raw 64-bit hashes, say) would
    silently allocate gigabytes, so large-and-sparse inputs take the
    comparison-sort fallback here instead of gambling on the caller."""
    ids = np.ascontiguousarray(ids, np.int64)
    if ids.size == 0:
        return np.zeros(0, np.int64)
    if int(ids.max()) > 4 * ids.size:
        return np.argsort(ids, kind="stable")
    lib = _load()
    if lib is None:
        return None
    cnt = np.bincount(ids)
    cursors = np.zeros(len(cnt), np.int64)
    np.cumsum(cnt[:-1], out=cursors[1:])
    order = np.empty(ids.size, np.int64)
    lib.photon_counting_sort(ids, ids.size, cursors, order)
    return order
