"""Shared optimizer configuration, result, and per-iteration state tracking.

Counterparts of the reference's optimizer plumbing
(``photon-lib/.../optimization/{Optimizer, OptimizerConfig, OptimizerState,
OptimizationStatesTracker}.scala``) re-imagined for XLA: the whole optimizer
runs on-device inside one ``lax.while_loop``, so the state "tracker" is a pair
of fixed-length device arrays (value, gradient-norm per iteration) written with
dynamic indexing — readable after the fact exactly like the reference's
iteration table in the Photon log.

Convergence semantics follow the reference/breeze:
- gradient-norm tolerance **relative to the initial gradient norm**
  (``normOfGradient <= tolerance * initialNormOfGradient``), and
- maximum iteration cap.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

#: ``fun(w) -> (value, grad)`` — the only thing optimizers know about models.
ValueAndGrad = Callable[[Array], Tuple[Array, Array]]
#: ``hvp(w, v) -> H @ v`` for TRON.
Hvp = Callable[[Array, Array], Array]


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Static optimizer configuration (shapes compile into the XLA program).

    Defaults mirror the reference's ``OptimizerConfig`` /
    ``GLMOptimizationConfiguration`` defaults: tolerance 1e-6 relative
    gradient norm (breeze's practical floor for an Armijo-type search in
    double precision), L-BFGS history 10.
    """

    max_iterations: int = 80
    tolerance: float = 1e-6
    history: int = 10  # L-BFGS/OWLQN memory
    max_line_search: int = 25
    cg_max_iterations: int = 30  # TRON inner CG cap
    track_states: bool = True

    def __post_init__(self) -> None:
        if self.max_iterations < 1:
            raise ValueError("max_iterations must be >= 1")
        if self.history < 1:
            raise ValueError("history must be >= 1")
        if not self.tolerance > 0:
            raise ValueError("tolerance must be > 0")


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class OptimizerResult:
    """What every minimizer returns (a pytree, so it can flow out of jit/vmap).

    ``values``/``grad_norms`` are fixed-length ``(max_iterations + 1,)`` traces
    padded with +inf beyond ``iterations`` — the reference's
    ``OptimizationStatesTracker`` as arrays.
    """

    w: Array
    value: Array
    grad_norm: Array
    iterations: Array  # int32 scalar
    converged: Array  # bool scalar
    values: Array
    grad_norms: Array


def init_trace(config: OptimizerConfig, f0: Array, gnorm0: Array) -> tuple[Array, Array]:
    """Allocate the per-iteration (value, grad-norm) trace, or empty arrays
    when ``track_states`` is off (e.g. vmapped per-entity solves where the
    trace would be carried through every lane)."""
    if not config.track_states:
        empty = jnp.zeros((0,), dtype=jnp.float32)
        return empty, empty
    n = config.max_iterations + 1
    # +inf (not NaN) padding beyond the recorded iterations: consumers
    # filter with isfinite either way, and NaN padding would trip
    # jax_debug_nans (the --debug-nans driver flag) on allocation
    values = jnp.full((n,), jnp.inf, dtype=jnp.float32).at[0].set(
        f0.astype(jnp.float32))
    gnorms = jnp.full((n,), jnp.inf, dtype=jnp.float32).at[0].set(
        gnorm0.astype(jnp.float32))
    return values, gnorms


def record_trace(values: Array, gnorms: Array, it: Array, f: Array, gnorm: Array):
    if values.shape[0] == 0:  # tracking disabled
        return values, gnorms
    return values.at[it].set(f.astype(jnp.float32)), gnorms.at[it].set(
        gnorm.astype(jnp.float32))


def armijo_backtracking(trial, sufficient, alpha0: Array, max_steps: int):
    """Generic halving backtracking search shared by L-BFGS and OWL-QN.

    ``trial(alpha) -> (w_t, f_t, g_t)`` evaluates a candidate step (OWL-QN's
    trial includes the orthant projection); ``sufficient(alpha, w_t, f_t) ->
    bool`` is the acceptance predicate and MUST be written so NaN trial values
    return False (e.g. ``f_t <= bound``), which makes overflowing trial steps
    shrink instead of exiting the loop.
    """
    def cond(st):
        alpha, w_t, f_t, _, ls = st
        return (~sufficient(alpha, w_t, f_t)) & (ls < max_steps)

    def body(st):
        alpha = st[0] * 0.5
        w_t, f_t, g_t = trial(alpha)
        return alpha, w_t, f_t, g_t, st[4] + 1

    w1, f1, g1 = trial(alpha0)
    alpha, w_t, f_t, g_t, _ = jax.lax.while_loop(
        cond, body, (alpha0, w1, f1, g1, jnp.int32(0)))
    ok = sufficient(alpha, w_t, f_t) & jnp.isfinite(f_t)
    return alpha, w_t, f_t, g_t, ok


def update_history(s_hist: Array, y_hist: Array, rho: Array, n_pairs: Array,
                   step: Array, y: Array, accept: Array, eps: float = 1e-10):
    """Conditionally push an (s, y) curvature pair into the ring buffers.

    Shared by L-BFGS and OWL-QN; pairs are stored only when the step was
    accepted and the curvature ``s.y`` is meaningfully positive.
    """
    m = s_hist.shape[0]
    sy = jnp.vdot(step, y)
    store = accept & (sy > eps * jnp.linalg.norm(step) * jnp.linalg.norm(y))
    pos = jnp.mod(n_pairs, m)
    s_hist = jnp.where(store, s_hist.at[pos].set(step), s_hist)
    y_hist = jnp.where(store, y_hist.at[pos].set(y), y_hist)
    rho = jnp.where(store, rho.at[pos].set(1.0 / jnp.maximum(sy, eps)), rho)
    n_pairs = jnp.where(store, n_pairs + 1, n_pairs)
    return s_hist, y_hist, rho, n_pairs
