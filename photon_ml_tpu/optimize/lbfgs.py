"""L-BFGS as a single on-device ``lax.while_loop``.

TPU-first replacement for the reference's
``photon-lib/.../optimization/LBFGS.scala`` (a wrapper over
``breeze.optimize.LBFGS`` with history 10 and strong-Wolfe line search).

Design: instead of a JVM driver loop calling out to executors per gradient,
the *entire* optimization — two-loop recursion, backtracking line search,
curvature-pair ring buffer, convergence test — compiles into one XLA program.
``value_and_grad_fn`` is a pure closure; on a sharded mesh it contains a
``psum`` (see :mod:`photon_ml_tpu.parallel.distributed`) and the same loop
drives a whole pod with one launch, replacing a broadcast + ``treeAggregate``
round-trip per iteration.

Ring-buffer history with validity masking keeps every shape static; the solver
is ``vmap``-able, which is how millions of per-entity random-effect solves
batch onto the MXU (SURVEY.md §7 "vmap-batched block solves").

Line search: backtracking Armijo with adaptive growth. For the convex GLM
objectives this framework trains, the minimizer is unique, so solutions agree
with the reference's strong-Wolfe breeze implementation to tolerance even
though the iteration paths differ; parity is asserted on solutions, not paths
(tests vs scipy L-BFGS-B).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optimize.common import (
    OptimizerConfig,
    OptimizerResult,
    ValueAndGrad,
    armijo_backtracking,
    init_trace,
    record_trace,
    update_history,
)

Array = jax.Array

_EPS = 1e-10
_ARMIJO_C1 = 1e-4


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class _State:
    w: Array
    f: Array
    g: Array
    s_hist: Array  # (m, d) ring buffer of steps
    y_hist: Array  # (m, d) ring buffer of gradient diffs
    rho: Array  # (m,) 1 / (s.y)
    n_pairs: Array  # int32: total pairs ever stored (ring position = n % m)
    it: Array
    converged: Array
    failed: Array  # line search found no decrease
    stalls: Array  # int32: consecutive accepted steps with zero fp progress
    values: Array
    grad_norms: Array


def two_loop_direction(g: Array, s_hist: Array, y_hist: Array, rho: Array,
                       n_pairs: Array, history: int) -> Array:
    """Masked L-BFGS two-loop recursion; returns the descent direction -H g.

    Statically unrolled over the (small) history length with dynamic ring
    indices — XLA-friendly, no data-dependent shapes.
    """
    m = history
    valid = jnp.minimum(n_pairs, m)

    def idx_newest(k):  # k = 0 is the newest pair
        return jnp.mod(n_pairs - 1 - k, m)

    q = g
    alphas = []
    for k in range(m):
        i = idx_newest(k)
        use = k < valid
        a = jnp.where(use, rho[i] * jnp.vdot(s_hist[i], q), 0.0)
        q = q - a * y_hist[i]
        alphas.append((i, use, a))

    # Initial Hessian scaling gamma = s.y / y.y of the newest pair.
    i0 = idx_newest(0)
    yy = jnp.vdot(y_hist[i0], y_hist[i0])
    sy = jnp.vdot(s_hist[i0], y_hist[i0])
    gamma = jnp.where((valid > 0) & (yy > _EPS), sy / jnp.maximum(yy, _EPS), 1.0)
    r = gamma * q

    for i, use, a in reversed(alphas):
        b = jnp.where(use, rho[i] * jnp.vdot(y_hist[i], r), 0.0)
        r = r + (a - b) * s_hist[i]

    return -r


def backtracking_line_search(fun: ValueAndGrad, w: Array, f: Array, g: Array,
                             d: Array, alpha0: Array, max_steps: int):
    """Armijo backtracking: shrink alpha until sufficient decrease.

    Returns ``(alpha, f_new, g_new, w_new, ok)``. On total failure returns the
    last trial point with ``ok=False`` (the reference's breeze throws a
    ``LineSearchFailed``; here the outer loop terminates via the flag). The
    acceptance predicate is NaN-safe: an overflowing trial (f=NaN/inf) shrinks
    alpha rather than exiting.
    """
    gd = jnp.vdot(g, d)

    def trial(alpha):
        f_t, g_t = fun(w + alpha * d)
        return w + alpha * d, f_t, g_t

    def sufficient(alpha, w_t, f_t):
        return f_t <= f + _ARMIJO_C1 * alpha * gd

    alpha, w_new, f_new, g_new, ok = armijo_backtracking(
        trial, sufficient, alpha0, max_steps)
    return alpha, f_new, g_new, w_new, ok


def minimize_lbfgs(fun: ValueAndGrad, w0: Array,
                   config: OptimizerConfig = OptimizerConfig()) -> OptimizerResult:
    """Minimize ``fun`` starting at ``w0``; fully jittable and vmappable."""
    m, d = config.history, w0.shape[-1]
    f0, g0 = fun(w0)
    gnorm0 = jnp.linalg.norm(g0)
    values, gnorms = init_trace(config, f0, gnorm0)
    tol = config.tolerance * jnp.maximum(gnorm0, 1.0)

    init = _State(
        w=w0, f=f0, g=g0,
        s_hist=jnp.zeros((m, d), w0.dtype),
        y_hist=jnp.zeros((m, d), w0.dtype),
        rho=jnp.zeros((m,), w0.dtype),
        n_pairs=jnp.int32(0),
        it=jnp.int32(0),
        converged=gnorm0 <= tol,
        failed=jnp.asarray(False),
        stalls=jnp.int32(0),
        values=values, grad_norms=gnorms,
    )

    def cond(s: _State):
        return (~s.converged) & (~s.failed) & (s.it < config.max_iterations)

    def body(s: _State):
        d_dir = two_loop_direction(s.g, s.s_hist, s.y_hist, s.rho, s.n_pairs, m)
        # Safeguard: fall back to steepest descent on a non-descent direction.
        descent = jnp.vdot(s.g, d_dir) < 0
        d_dir = jnp.where(descent, d_dir, -s.g)
        # First step scales by 1/||g||, later steps start at 1 (standard L-BFGS).
        alpha0 = jnp.where(s.n_pairs > 0, 1.0,
                           1.0 / jnp.maximum(jnp.linalg.norm(d_dir), 1.0))
        alpha, f_new, g_new, w_new, ok = backtracking_line_search(
            fun, s.w, s.f, s.g, d_dir, alpha0, config.max_line_search)

        s_hist, y_hist, rho, n_pairs = update_history(
            s.s_hist, s.y_hist, s.rho, s.n_pairs, w_new - s.w, g_new - s.g, ok,
            _EPS)

        it = s.it + 1
        gnorm = jnp.linalg.norm(g_new)
        # Record only accepted iterates: a rejected final step must not leave
        # a NaN/increased value inside the valid trace prefix.
        values, gnorms = record_trace(
            s.values, s.grad_norms, it,
            jnp.where(ok, f_new, s.f), jnp.where(ok, gnorm, jnp.linalg.norm(s.g)))
        # Stall: an "accepted" step with no representable decrease (the
        # Armijo bound rounds to f at working precision). A single flat step
        # can still precede useful movement near the optimum, so require TWO
        # consecutive stalls before terminating; convergence is still judged
        # by the gradient test alone.
        stalls = jnp.where(ok & (f_new >= s.f), s.stalls + 1, jnp.int32(0))
        return _State(
            w=jnp.where(ok, w_new, s.w),
            f=jnp.where(ok, f_new, s.f),
            g=jnp.where(ok, g_new, s.g),
            s_hist=s_hist, y_hist=y_hist, rho=rho, n_pairs=n_pairs,
            it=it,
            converged=ok & (gnorm <= tol),
            failed=(~ok) | (stalls >= 2),
            stalls=stalls,
            values=values, grad_norms=gnorms,
        )

    final = lax.while_loop(cond, body, init)
    return OptimizerResult(
        w=final.w, value=final.f, grad_norm=jnp.linalg.norm(final.g),
        iterations=final.it, converged=final.converged,
        values=final.values, grad_norms=final.grad_norms,
    )
