"""OWL-QN (orthant-wise L-BFGS) for L1 / elastic-net, as a ``lax.while_loop``.

Replacement for ``photon-lib/.../optimization/OWLQN.scala`` (a wrapper over
``breeze.optimize.OWLQN``). Implements Andrew & Gao (2007): the smooth part of
the objective flows through the L-BFGS machinery (curvature pairs built from
*smooth* gradients), while the L1 term enters only via

- the **pseudo-gradient** (sub-gradient choice that locally steepest-descends
  the full objective),
- **direction alignment** (zero the quasi-Newton direction where it disagrees
  with the pseudo-gradient's descent orthant),
- **orthant projection** of each line-search trial point (coordinates that
  cross zero are clamped to zero — this is what produces exact sparsity).

The hard part on TPU (SURVEY.md §7 "hard parts" #3) is that all of this is
data-dependent per-coordinate control flow; here it is expressed branch-free
with ``jnp.where`` masks so the whole solver stays one compiled loop.

``l1_weight`` may be a scalar or a per-coordinate vector (e.g. to exempt the
intercept from L1, matching the reference's intercept handling).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optimize.common import (
    OptimizerConfig,
    OptimizerResult,
    ValueAndGrad,
    armijo_backtracking,
    init_trace,
    record_trace,
    update_history,
)
from photon_ml_tpu.optimize.lbfgs import _ARMIJO_C1, _EPS, two_loop_direction

Array = jax.Array


def pseudo_gradient(w: Array, g: Array, l1: Array) -> Array:
    """Sub-gradient selection for f(w) + ||l1 * w||_1 (Andrew & Gao eq. 4)."""
    right = g + l1  # derivative moving toward +
    left = g - l1  # derivative moving toward -
    pg_zero = jnp.where(right < 0, right, jnp.where(left > 0, left, 0.0))
    return jnp.where(w > 0, right, jnp.where(w < 0, left, pg_zero))


def _l1_norm(w: Array, l1: Array) -> Array:
    return jnp.sum(l1 * jnp.abs(w))


def minimize_owlqn(fun: ValueAndGrad, w0: Array, l1_weight,
                   config: OptimizerConfig = OptimizerConfig()) -> OptimizerResult:
    """Minimize ``fun(w) + ||l1_weight * w||_1``. Jittable and vmappable.

    ``fun`` must be the *smooth* part only (loss + L2); pass the L1 weight
    separately exactly as the reference passes ``l1RegWeight`` to breeze OWLQN
    apart from the smooth objective.
    """
    m, d = config.history, w0.shape[-1]
    l1 = jnp.broadcast_to(jnp.asarray(l1_weight, w0.dtype), w0.shape)

    f0_s, g0 = fun(w0)
    f0 = f0_s + _l1_norm(w0, l1)
    pg0 = pseudo_gradient(w0, g0, l1)
    pgnorm0 = jnp.linalg.norm(pg0)
    values, gnorms = init_trace(config, f0, pgnorm0)
    tol = config.tolerance * jnp.maximum(pgnorm0, 1.0)

    State = _State
    init = State(
        w=w0, f=f0, g=g0, pg=pg0,
        s_hist=jnp.zeros((m, d), w0.dtype),
        y_hist=jnp.zeros((m, d), w0.dtype),
        rho=jnp.zeros((m,), w0.dtype),
        n_pairs=jnp.int32(0), it=jnp.int32(0),
        converged=pgnorm0 <= tol, failed=jnp.asarray(False),
        stalls=jnp.int32(0),
        values=values, grad_norms=gnorms,
    )

    def cond(s):
        return (~s.converged) & (~s.failed) & (s.it < config.max_iterations)

    def body(s):
        d_dir = two_loop_direction(s.pg, s.s_hist, s.y_hist, s.rho, s.n_pairs, m)
        # Align with the pseudo-gradient descent orthant (A&G constraint):
        # keep components where d and -pg agree in sign.
        d_dir = jnp.where(d_dir * s.pg < 0, d_dir, 0.0)
        # Fallback to steepest descent on degenerate direction.
        degenerate = jnp.vdot(d_dir, s.pg) >= 0
        d_dir = jnp.where(degenerate, -s.pg, d_dir)

        # Chosen orthant: sign(w), or sign(-pg) at zero coordinates.
        xi = jnp.where(s.w != 0, jnp.sign(s.w), jnp.sign(-s.pg))

        alpha0 = jnp.where(s.n_pairs > 0, 1.0,
                           1.0 / jnp.maximum(jnp.linalg.norm(d_dir), 1.0))

        def trial(alpha):
            w_t = s.w + alpha * d_dir
            w_t = jnp.where(jnp.sign(w_t) == xi, w_t, 0.0)  # orthant projection
            f_s, g_t = fun(w_t)
            return w_t, f_s + _l1_norm(w_t, l1), g_t

        def sufficient(alpha, w_t, f_t):
            # Armijo on the projected step, directional derivative pg.(w_t - w).
            return f_t <= s.f + _ARMIJO_C1 * jnp.vdot(s.pg, w_t - s.w)

        alpha, w_new, f_new, g_new, ok = armijo_backtracking(
            trial, sufficient, alpha0, config.max_line_search)

        # Curvature pairs from smooth-gradient differences (A&G).
        s_hist, y_hist, rho, n_pairs = update_history(
            s.s_hist, s.y_hist, s.rho, s.n_pairs, w_new - s.w, g_new - s.g, ok,
            _EPS)

        pg_new = pseudo_gradient(w_new, g_new, l1)
        pgnorm = jnp.linalg.norm(pg_new)
        it = s.it + 1
        values, gnorms = record_trace(
            s.values, s.grad_norms, it,
            jnp.where(ok, f_new, s.f),
            jnp.where(ok, pgnorm, jnp.linalg.norm(s.pg)))
        # stall termination: two consecutive accepted steps with no
        # representable decrease (see minimize_lbfgs)
        stalls = jnp.where(ok & (f_new >= s.f), s.stalls + 1, jnp.int32(0))
        return State(
            w=jnp.where(ok, w_new, s.w),
            f=jnp.where(ok, f_new, s.f),
            g=jnp.where(ok, g_new, s.g),
            pg=jnp.where(ok, pg_new, s.pg),
            s_hist=s_hist, y_hist=y_hist, rho=rho, n_pairs=n_pairs,
            it=it, converged=ok & (pgnorm <= tol),
            failed=(~ok) | (stalls >= 2), stalls=stalls,
            values=values, grad_norms=gnorms,
        )

    final = lax.while_loop(cond, body, init)
    return OptimizerResult(
        w=final.w, value=final.f, grad_norm=jnp.linalg.norm(final.pg),
        iterations=final.it, converged=final.converged,
        values=final.values, grad_norms=final.grad_norms,
    )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class _State:
    w: Array
    f: Array
    g: Array
    pg: Array
    s_hist: Array
    y_hist: Array
    rho: Array
    n_pairs: Array
    it: Array
    converged: Array
    failed: Array
    stalls: Array
    values: Array
    grad_norms: Array
