"""TRON: trust-region Newton with conjugate-gradient inner solves.

Replacement for ``photon-lib/.../optimization/TRON.scala`` (the reference's
port of LIBLINEAR's TRON). Same structure — an outer trust-region loop whose
radius adapts via the LIBLINEAR constants (eta0/1/2, sigma1/2/3), and an inner
Steihaug conjugate-gradient solve that touches the Hessian **only through
Hessian-vector products** — but both loops are nested ``lax.while_loop``s
compiled into one XLA program (SURVEY.md §7 hard part #4), and the Hvp comes
from forward-over-reverse autodiff (:meth:`GLMObjective.hvp`) instead of a
hand-written ``HessianVectorAggregator``.

On a sharded mesh each Hvp carries one ``psum``, so the inner CG is k
collectives back-to-back on ICI — the pattern that replaces the reference's
k × ``treeAggregate`` per Newton step.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optimize.common import (
    Hvp,
    OptimizerConfig,
    OptimizerResult,
    ValueAndGrad,
    init_trace,
    record_trace,
)

Array = jax.Array

# LIBLINEAR tron.cpp trust-region update constants (mirrored by the
# reference's TRON.scala).
_ETA0, _ETA1, _ETA2 = 1e-4, 0.25, 0.75
_SIGMA1, _SIGMA2, _SIGMA3 = 0.25, 0.5, 4.0
_CG_TOL = 0.1  # inner CG stops at ||r|| <= 0.1 * ||g||


def _trcg(hvp, g: Array, delta: Array, max_cg: int):
    """Steihaug truncated CG: approximately solve H s = -g within ||s||<=delta.

    Returns ``(s, at_boundary, prered)`` where ``prered = -(g.s + 0.5 s.Hs)``
    is the quadratic-model reduction, tracked incrementally from CG internals
    (interior step: q -= 0.5*alpha*r.r; boundary step: q += -tau*r.r +
    0.5*tau^2*p.Hp, using the invariant r.p = r.r) so the outer loop never
    pays an extra Hessian-vector product — on a sharded mesh that is one
    avoided collective per Newton iteration. Fixed iteration cap with
    tolerance masking keeps the loop shape static for XLA.
    """
    cg_tol = _CG_TOL * jnp.linalg.norm(g)

    def cond(st):
        s, r, p, rr, q, i, done = st
        return (~done) & (i < max_cg)

    def body(st):
        s, r, p, rr, q, i, _ = st
        hp = hvp(p)
        php = jnp.vdot(p, hp)
        alpha = rr / jnp.where(php > 0, php, 1.0)
        s_next = s + alpha * p
        crossed = (jnp.linalg.norm(s_next) > delta) | (php <= 0)

        # Backtrack to the trust-region boundary along p.
        ps = jnp.vdot(p, s)
        pp = jnp.vdot(p, p)
        ss = jnp.vdot(s, s)
        disc = ps * ps + pp * (delta * delta - ss)
        tau = (-ps + jnp.sqrt(jnp.maximum(disc, 0.0))) / jnp.where(pp > 0, pp, 1.0)
        s_bound = s + tau * p

        q_interior = q - 0.5 * alpha * rr
        q_bound = q - tau * rr + 0.5 * tau * tau * php

        s_new = jnp.where(crossed, s_bound, s_next)
        q_new = jnp.where(crossed, q_bound, q_interior)
        r_new = r - alpha * hp
        rr_new = jnp.vdot(r_new, r_new)
        converged = jnp.sqrt(rr_new) <= cg_tol
        beta = rr_new / jnp.where(rr > 0, rr, 1.0)
        p_new = r_new + beta * p
        done = crossed | converged
        return (s_new, jnp.where(crossed, r, r_new), p_new,
                jnp.where(crossed, rr, rr_new), q_new, i + 1, done)

    s0 = jnp.zeros_like(g)
    r0 = -g
    init = (s0, r0, r0, jnp.vdot(r0, r0), jnp.zeros_like(jnp.vdot(r0, r0)),
            jnp.int32(0), jnp.linalg.norm(r0) <= cg_tol)
    s, r, p, rr, q, i, done = lax.while_loop(cond, body, init)
    at_boundary = jnp.linalg.norm(s) >= delta * (1.0 - 1e-6)
    return s, at_boundary, -q


def minimize_tron(fun: ValueAndGrad, hvp: Hvp, w0: Array,
                  config: OptimizerConfig = OptimizerConfig(),
                  *, hvp_at=None) -> OptimizerResult:
    """Trust-region Newton minimization of a twice-differentiable ``fun``.

    ``hvp(w, v)`` must return the exact Hessian-vector product at ``w``.
    ``hvp_at(w) -> (v -> Hv)``, when given, takes precedence: the operator
    is built once per outer iteration, so work that depends only on ``w``
    (a GLM's margin/d2 pass over the design) is hoisted out of the inner
    CG loop explicitly instead of trusting XLA's loop-invariant code
    motion, and the product itself can be a fused one-pass kernel.
    Jittable and vmappable.
    """
    f0, g0 = fun(w0)
    gnorm0 = jnp.linalg.norm(g0)
    values, gnorms = init_trace(config, f0, gnorm0)
    tol = config.tolerance * jnp.maximum(gnorm0, 1.0)

    init = _State(
        w=w0, f=f0, g=g0, delta=gnorm0,
        it=jnp.int32(0), converged=gnorm0 <= tol, failed=jnp.asarray(False),
        values=values, grad_norms=gnorms,
    )

    def cond(s):
        return (~s.converged) & (~s.failed) & (s.it < config.max_iterations)

    def body(s):
        op = hvp_at(s.w) if hvp_at is not None else (lambda v: hvp(s.w, v))
        step, at_boundary, prered = _trcg(op, s.g, s.delta,
                                          config.cg_max_iterations)
        snorm = jnp.linalg.norm(step)
        w_new = s.w + step
        f_new, g_new = fun(w_new)

        gs = jnp.vdot(s.g, step)
        # NaN-safe actual reduction: a non-finite trial value (overflowing
        # loss) must behave like "no reduction" so the radius SHRINKS and the
        # solver recovers — NaN propagating into delta would otherwise disable
        # the trust region permanently (every comparison False).
        actred = jnp.where(jnp.isfinite(f_new), s.f - f_new, -jnp.inf)

        # LIBLINEAR step-size interpolation for the radius update.
        denom = f_new - s.f - gs
        alpha = jnp.where(jnp.isfinite(denom) & (denom > 0),
                          jnp.maximum(_SIGMA1, -0.5 * (gs / jnp.where(
                              denom == 0, 1.0, denom))),
                          jnp.where(jnp.isfinite(f_new), _SIGMA3, _SIGMA1))
        delta = s.delta
        # On the very first iteration LIBLINEAR shrinks delta to min(delta, snorm).
        delta = jnp.where(s.it == 0, jnp.minimum(delta, snorm), delta)
        delta = jnp.where(
            actred < _ETA0 * prered,
            jnp.minimum(jnp.maximum(alpha, _SIGMA1) * snorm, _SIGMA2 * delta),
            jnp.where(
                actred < _ETA1 * prered,
                jnp.maximum(_SIGMA1 * delta,
                            jnp.minimum(alpha * snorm, _SIGMA2 * delta)),
                jnp.where(
                    actred < _ETA2 * prered,
                    jnp.maximum(_SIGMA1 * delta,
                                jnp.minimum(alpha * snorm, _SIGMA3 * delta)),
                    jnp.maximum(delta,
                                jnp.minimum(alpha * snorm, _SIGMA3 * delta)))))

        accept = (actred > _ETA0 * prered) & jnp.isfinite(f_new)
        # A vanishing radius means no further progress is possible.
        stuck = delta < 1e-12

        it = s.it + 1
        gnorm_acc = jnp.linalg.norm(jnp.where(accept, g_new, s.g))
        values, gnorms = record_trace(
            s.values, s.grad_norms, it,
            jnp.where(accept, f_new, s.f), gnorm_acc)
        return _State(
            w=jnp.where(accept, w_new, s.w),
            f=jnp.where(accept, f_new, s.f),
            g=jnp.where(accept, g_new, s.g),
            delta=delta, it=it,
            converged=accept & (jnp.linalg.norm(g_new) <= tol),
            failed=stuck,
            values=values, grad_norms=gnorms,
        )

    final = lax.while_loop(cond, body, init)
    return OptimizerResult(
        w=final.w, value=final.f, grad_norm=jnp.linalg.norm(final.g),
        iterations=final.it, converged=final.converged,
        values=final.values, grad_norms=final.grad_norms,
    )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class _State:
    w: Array
    f: Array
    g: Array
    delta: Array
    it: Array
    converged: Array
    failed: Array
    values: Array
    grad_norms: Array
