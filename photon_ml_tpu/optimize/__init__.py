from photon_ml_tpu.optimize.common import (  # noqa: F401
    OptimizerConfig,
    OptimizerResult,
)
from photon_ml_tpu.optimize.lbfgs import minimize_lbfgs  # noqa: F401
from photon_ml_tpu.optimize.owlqn import minimize_owlqn  # noqa: F401
from photon_ml_tpu.optimize.tron import minimize_tron  # noqa: F401
