"""Small host-side helpers shared across modules
(counterpart of the reference's ``util/`` grab-bag, e.g. ``MathUtils.scala``)."""

from __future__ import annotations

import numpy as np


def group_starts(sorted_ids: np.ndarray) -> np.ndarray:
    """Indices where a new group begins in a group-sorted id array."""
    n = sorted_ids.shape[0]
    if n == 0:
        return np.zeros((0,), np.int64)
    new = np.empty(n, bool)
    new[0] = True
    np.not_equal(sorted_ids[1:], sorted_ids[:-1], out=new[1:])
    return np.flatnonzero(new)


def hash_uniform(ids: np.ndarray, seed: int) -> np.ndarray:
    """Uniform [0,1) key per id via a splitmix64 finalizer — a stateless,
    partition-invariant substitute for a sequential rng stream: the key of
    a row depends only on (seed, its global id), never on which other rows
    share the batch. This is what makes subsampling and down-sampling draws
    identical under ANY row partition (multi-process training equals the
    single-process run by construction)."""
    z = (np.asarray(ids, np.uint64)
         + np.uint64((seed * 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF))
    z = (z + np.uint64(0x9E3779B97F4A7C15))
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    z = z ^ (z >> np.uint64(31))
    return z.astype(np.float64) / float(2**64)


def materialize_thunk(obj, fields: tuple, lock) -> None:
    """Run a lazy materialization thunk at most once, double-checked under
    ``lock``: ``fields[0]`` holds either the materialized array or a zero-arg
    thunk returning one value per field; install the results with
    ``object.__setattr__`` (the holders are frozen dataclasses). Thunks share
    mutable solver/native scratch, so a racing double-run would corrupt the
    tensors — the shared invariant behind REBucket's deferred native fills
    and RandomEffectModel's deferred device pulls."""
    with lock:
        val = object.__getattribute__(obj, fields[0])
        if callable(val):
            for f, v in zip(fields, val()):
                object.__setattr__(obj, f, v)
