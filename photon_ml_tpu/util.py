"""Small host-side helpers shared across modules
(counterpart of the reference's ``util/`` grab-bag, e.g. ``MathUtils.scala``)."""

from __future__ import annotations

import numpy as np


def group_starts(sorted_ids: np.ndarray) -> np.ndarray:
    """Indices where a new group begins in a group-sorted id array."""
    n = sorted_ids.shape[0]
    if n == 0:
        return np.zeros((0,), np.int64)
    new = np.empty(n, bool)
    new[0] = True
    np.not_equal(sorted_ids[1:], sorted_ids[:-1], out=new[1:])
    return np.flatnonzero(new)
