"""Divergence guard: NaN/Inf detection + rollback/freeze bookkeeping.

After every coordinate step the training drivers can ask the guard whether
the step's outputs (new scores, new model coefficients) are healthy. The
checks are **pure reads** — ``np.isfinite`` over host copies — so a guarded
healthy run produces bit-identical models to an unguarded one.

On divergence the guard decides, per its policy:

- ``"fail"`` — post ``divergence_detected`` and raise
  :class:`DivergenceError` (fail fast with an actionable message instead of
  silently writing a NaN model);
- ``"rollback"`` — roll the coordinate back to its last good state, bump
  the coordinate's regularization by ``reg_backoff`` (stronger curvature is
  the standard fix for a diverged GLM solve), and retry, up to
  ``max_retries`` times — then freeze;
- ``"freeze"`` — immediately lock the coordinate at its last good model
  (the existing ``locked`` mechanism) and continue the run degraded.

The guard only *decides*; the drivers own the state restore (in-process
rollback at the coordinate boundary, which at that granularity coincides
with the last ``CheckpointManager`` step — see RESILIENCE.md "Rollback
semantics"). In the multi-process driver the verdict is allreduce-maxed so
every process rolls back in lockstep; the guard's own bookkeeping is
deterministic, so per-process counters never diverge.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Iterable, Optional

import numpy as np

logger = logging.getLogger(__name__)

_MODES = ("fail", "rollback", "freeze")


class DivergenceError(RuntimeError):
    """Raised under ``mode="fail"`` (and when a coordinate diverges before
    ever producing a good model, leaving nothing to freeze to)."""


@dataclasses.dataclass(frozen=True)
class DivergencePolicy:
    """What to do when a coordinate step produces NaN/Inf (or throws).

    ``reg_backoff`` multiplies the coordinate's regularization weight on
    every rollback-retry (a backoff schedule in curvature space);
    ``max_retries`` bounds rollback-retries per coordinate before freezing.
    """

    mode: str = "fail"
    max_retries: int = 2
    reg_backoff: float = 10.0

    def __post_init__(self):
        if self.mode not in _MODES:
            raise ValueError(
                f"divergence mode must be one of {_MODES}, got {self.mode!r}")
        if self.max_retries < 0:
            raise ValueError(
                f"max_retries must be >= 0, got {self.max_retries}")


def arrays_finite(arrays: Iterable) -> bool:
    """True when every non-None array is fully finite (pure read)."""
    for a in arrays:
        if a is None:
            continue
        if not np.isfinite(np.asarray(a, np.float32)).all():
            return False
    return True


def model_arrays(model) -> list:
    """Coefficient leaves of a coordinate model (fixed or random effect),
    duck-typed so the guard needs no import of the game layer."""
    out = []
    glm = getattr(model, "model", None)
    if glm is not None and hasattr(glm, "coefficients"):  # FixedEffectModel
        out.append(glm.coefficients.means)
    coeffs = getattr(model, "coeffs", None)  # RandomEffectModel
    if coeffs is not None:
        out.append(coeffs)
    return out


class DivergenceGuard:
    """Per-run divergence bookkeeping (one instance per training run)."""

    def __init__(self, policy: DivergencePolicy = DivergencePolicy(),
                 bus=None):
        self.policy = policy
        self.bus = bus
        self.failures: dict[str, int] = {}
        self.frozen: set[str] = set()

    def _post(self, name: str, **payload) -> None:
        bus = self.bus
        if bus is None:
            from photon_ml_tpu.events import GLOBAL_BUS as bus
        bus.post(name, **payload)

    # --- detection (pure reads) ------------------------------------------
    def healthy(self, model, scores) -> bool:
        """True when the step's outputs carry no NaN/Inf."""
        checks = [] if scores is None else [scores]
        if model is not None:
            checks.extend(model_arrays(model))
        return arrays_finite(checks)

    def next_lam(self, lam: float) -> float:
        """The rollback-retry's bumped regularization weight. An
        unregularized coordinate (lam=0) seeds at ``reg_backoff`` itself —
        multiplying zero would retry the identical diverging solve."""
        return (lam * self.policy.reg_backoff if lam > 0
                else self.policy.reg_backoff)

    # --- decision ---------------------------------------------------------
    def on_divergence(self, coordinate_id: str, *, sweep: int,
                      has_good_model: bool,
                      error: Optional[BaseException] = None) -> str:
        """Record a failure and return the action: ``"retry"`` (roll back,
        bump regularization, try again) or ``"freeze"`` (lock the
        coordinate). Raises :class:`DivergenceError` under ``mode="fail"``
        or when freezing is impossible (no good model yet)."""
        n = self.failures.get(coordinate_id, 0) + 1
        self.failures[coordinate_id] = n
        detail = (f": {error!r}" if error is not None
                  else " (non-finite update)")
        self._post("divergence_detected", coordinate=coordinate_id,
                   sweep=sweep, failures=n,
                   error=None if error is None else repr(error))
        if self.policy.mode == "fail":
            raise DivergenceError(
                f"coordinate {coordinate_id!r} diverged at sweep {sweep}"
                f"{detail}; re-run with --on-divergence=rollback to "
                f"recover automatically, or raise its regularization"
            ) from error
        retry_ok = (self.policy.mode == "rollback"
                    and n <= self.policy.max_retries)
        if retry_ok:
            self._post("coordinate_rollback", coordinate=coordinate_id,
                       sweep=sweep, attempt=n,
                       reg_backoff=self.policy.reg_backoff)
            logger.warning(
                "coordinate %s diverged at sweep %d (failure %d/%d): "
                "rolling back and retrying with regularization x%g",
                coordinate_id, sweep, n, self.policy.max_retries,
                self.policy.reg_backoff)
            return "retry"
        if not has_good_model:
            raise DivergenceError(
                f"coordinate {coordinate_id!r} diverged at sweep {sweep}"
                f"{detail} before producing any model — nothing to freeze "
                f"to; fix its optimization configuration") from error
        self.frozen.add(coordinate_id)
        self._post("coordinate_frozen", coordinate=coordinate_id,
                   sweep=sweep, failures=n)
        logger.warning(
            "coordinate %s diverged at sweep %d (failure %d): freezing at "
            "its last good model and continuing degraded",
            coordinate_id, sweep, n)
        return "freeze"
