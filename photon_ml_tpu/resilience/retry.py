"""``retry(fn, policy)`` — the single retry/backoff primitive.

Every transient-fault recovery in the framework goes through this one
function: Avro file reads, checkpoint save/restore, and multihost
initialization all wrap their attempt bodies in :func:`retry` so backoff
behavior, deadline enforcement, and event emission cannot drift apart
between call sites.

Semantics:

- attempts run up to ``policy.max_attempts`` times, sleeping a
  deterministic exponentially-backed-off, jittered delay between attempts
  (the jitter sequence is a pure function of ``policy.seed`` — a retry
  schedule is reproducible, like everything else in a training run);
- ``policy.deadline_s`` bounds the *total* elapsed time including the next
  planned sleep: the primitive never sleeps into a deadline it would then
  blow — it gives up immediately instead (a hung coordinator resolves in
  ``deadline_s``, not ``deadline_s + max_delay``);
- on exhaustion the **original** exception is re-raised, so a wrapped call
  site's error contract is unchanged — with no faults and default
  policies, wrapped paths behave bit-identically to unwrapped ones;
- every attempt failure posts ``retry_attempt``; exhaustion posts
  ``retry_exhausted``; success after at least one failure posts
  ``retry_succeeded`` — all through :mod:`photon_ml_tpu.events`, so runs
  are auditable.

This module owns the ONE sanctioned ``time.sleep`` in the package
(``tools/check_resilience_hygiene.py`` enforces it): stalls anywhere else
would be invisible to the retry/deadline accounting.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Iterator, Optional, TypeVar

import numpy as np

T = TypeVar("T")

#: the package's only sleep — fault stalls and backoff waits both route
#: here so a chaos run's entire wait budget is one greppable chokepoint
_sleep = time.sleep


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Backoff schedule + attempt/deadline budget.

    ``delay_k = min(base_delay_s * multiplier**k, max_delay_s)`` scaled by
    ``1 + jitter * u_k`` with ``u_k ~ Uniform[-1, 1)`` drawn from a
    generator seeded with ``seed`` — deterministic per policy instance.
    ``retry_on`` filters which exception types are retried at all; anything
    else propagates immediately.
    """

    max_attempts: int = 3
    base_delay_s: float = 0.05
    multiplier: float = 2.0
    max_delay_s: float = 2.0
    jitter: float = 0.1
    deadline_s: Optional[float] = None
    seed: int = 0
    retry_on: tuple = (Exception,)

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, "
                             f"got {self.max_attempts}")

    def delays(self) -> Iterator[float]:
        """The deterministic inter-attempt delay sequence (unbounded)."""
        rng = np.random.default_rng(self.seed)
        k = 0
        while True:
            base = min(self.base_delay_s * self.multiplier ** k,
                       self.max_delay_s)
            u = 2.0 * float(rng.random()) - 1.0
            yield max(0.0, base * (1.0 + self.jitter * u))
            k += 1


#: no-retry policy — for call sites that want the fault hooks and events
#: without any recovery (e.g. collectives, which must never retry
#: unilaterally: a second attempt on one process desyncs every other)
NO_RETRY = RetryPolicy(max_attempts=1)

DEFAULT_POLICY = RetryPolicy()

_default_policy = DEFAULT_POLICY


def set_default_policy(policy: RetryPolicy) -> RetryPolicy:
    """Install the process-wide default (the CLI's --max-retries /
    --retry-deadline-s flags land here). Returns the previous default."""
    global _default_policy
    prev = _default_policy
    _default_policy = policy
    return prev


def get_default_policy() -> RetryPolicy:
    return _default_policy


def retry(fn: Callable[[], T], policy: Optional[RetryPolicy] = None, *,
          name: Optional[str] = None, bus=None,
          sleep: Optional[Callable[[float], None]] = None,
          clock: Callable[[], float] = time.monotonic) -> T:
    """Call ``fn()`` under ``policy``; see the module docstring for the
    full semantics. ``sleep``/``clock`` are injectable for tests."""
    if policy is None:
        policy = _default_policy
    if bus is None:
        from photon_ml_tpu.events import GLOBAL_BUS as bus
    if sleep is None:
        sleep = _sleep
    if name is None:
        name = getattr(fn, "__name__", "op")
    start = clock()
    delays = policy.delays()
    for attempt in range(1, policy.max_attempts + 1):
        try:
            result = fn()
        except policy.retry_on as e:
            elapsed = clock() - start
            delay = next(delays)
            over_deadline = (policy.deadline_s is not None
                             and elapsed + delay >= policy.deadline_s)
            if attempt >= policy.max_attempts or over_deadline:
                bus.post("retry_exhausted", op=name, attempts=attempt,
                         elapsed_s=elapsed, deadline_hit=over_deadline,
                         error=repr(e))
                raise
            bus.post("retry_attempt", op=name, attempt=attempt,
                     delay_s=delay, elapsed_s=elapsed, error=repr(e))
            sleep(delay)
        else:
            if attempt > 1:
                bus.post("retry_succeeded", op=name, attempt=attempt,
                         elapsed_s=clock() - start)
            return result
    raise AssertionError("unreachable")  # pragma: no cover
