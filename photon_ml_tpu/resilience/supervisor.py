"""Fleet supervision: heartbeat liveness + automatic restart-from-checkpoint.

The in-process resilience story (retry, divergence rollback, symmetric fault
plans) covers every fault ALL processes can observe together. The remaining
class is asymmetric: one process of a multi-controller job dies or stalls
mid-collective, the survivors block forever inside jax's allgather, and the
job is dead with no process in a position to recover it — SPMD recovery
requires symmetric decisions (RESILIENCE.md). The reference survives this
class with Spark driver restarts (SURVEY.md §5.4); this module is the
TPU-native equivalent: an external supervisor that owns the fleet's process
lifecycle.

:class:`FleetSupervisor` launches the N training processes as subprocesses,
watches two liveness signals, and on any failure kills the survivors and
relaunches the WHOLE fleet — the restarted processes resume from the latest
agreed checkpoint (``_mp_ckpt_latest`` / ``CheckpointManager`` already
enforce pre-agreed resume points), under a bounded restart budget with
exponential backoff and a hard wall-clock deadline.

Liveness signals:

- **exit**: ``Popen.poll`` — any nonzero exit (crash, ``os._exit``,
  OOM-kill) fails the attempt immediately; success is every process
  exiting 0.
- **heartbeat**: each process touches a per-process file
  (``PHOTON_HEARTBEAT_FILE``) at sweep, coordinate-step, and collective
  boundaries (:func:`heartbeat`, threaded through
  ``game/coordinate_descent.py``, ``game/multiprocess.py``,
  ``glm/training.py``, ``parallel/multihost.py`` and the Avro readers). A
  file older than ``heartbeat_timeout_s`` declares the process stalled. A
  long healthy collective does not beat while inside the collective, so
  the timeout must exceed the longest healthy inter-boundary gap — size it
  from the sweep wall, not the step wall.

Every recovery action posts :class:`~photon_ml_tpu.events.TrainingEvent`s
(``supervisor_*``) which the telemetry bridge translates into
``photon_supervisor_*`` metrics, and each launch runs under a
``supervisor.attempt`` span.

This module is the ONLY place in ``photon_ml_tpu/`` allowed to spawn or
signal processes (``tools/check_resilience_hygiene.py`` rule 6): process
lifecycle must stay visible to the supervisor, or a driver-forked child
would be invisible to the restart logic that claims to own recovery.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import subprocess
import sys
import time
from typing import Optional, Sequence

logger = logging.getLogger(__name__)

#: per-process heartbeat file (set by the supervisor; drivers touch it)
HEARTBEAT_ENV = "PHOTON_HEARTBEAT_FILE"
#: where the chief driver writes its result dict as JSON (set by the
#: supervisor so a supervised run can return the same payload a direct
#: driver call returns)
RESULT_ENV = "PHOTON_RESULT_FILE"
#: which supervisor attempt a process belongs to (0 = first launch) —
#: read by FaultSpec.attempts gating and exported for log correlation
RESTART_COUNT_ENV = "PHOTON_RESTART_COUNT"


# ---------------------------------------------------------------------------
# The worker-side hook
# ---------------------------------------------------------------------------


def heartbeat(site: str = "") -> None:
    """Touch this process's heartbeat file (no-op unsupervised).

    Called at sweep/coordinate/collective boundaries in the training hot
    paths; with no ``PHOTON_HEARTBEAT_FILE`` in the environment (the
    production default outside supervised runs) the cost is one environ
    lookup. Never raises: a failing beat must degrade to "supervisor may
    restart us", not kill a healthy training step.
    """
    path = os.environ.get(HEARTBEAT_ENV)
    if not path:
        return
    try:
        os.utime(path, None)
    except OSError:
        try:
            with open(path, "w") as f:
                f.write(site)
        except OSError:
            logger.warning("heartbeat touch failed for %s", path)


def write_result_file(result: dict) -> None:
    """Driver-side: persist the run's result dict where the supervisor
    asked for it (``PHOTON_RESULT_FILE``; no-op unsupervised). Written
    atomically so a kill mid-write cannot hand the supervisor half a
    JSON document."""
    path = os.environ.get(RESULT_ENV)
    if not path:
        return
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f)
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# The supervisor
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SupervisorPolicy:
    """Restart budget + liveness thresholds.

    ``max_restarts`` bounds RESTARTS (not attempts; 0 = launch once).
    ``heartbeat_timeout_s`` declares a running process stalled when its
    beat file goes this stale (None disables stall detection — exit codes
    only). ``deadline_s`` is the hard wall across ALL attempts including
    backoff sleeps; like :func:`~photon_ml_tpu.resilience.retry.retry`,
    the supervisor never sleeps into a deadline it would then blow.
    """

    max_restarts: int = 2
    heartbeat_timeout_s: Optional[float] = 300.0
    deadline_s: Optional[float] = None
    poll_interval_s: float = 0.2
    grace_s: float = 5.0
    base_backoff_s: float = 0.5
    backoff_multiplier: float = 2.0
    max_backoff_s: float = 30.0

    def __post_init__(self):
        if self.max_restarts < 0:
            raise ValueError(
                f"max_restarts must be >= 0, got {self.max_restarts}")
        if (self.heartbeat_timeout_s is not None
                and self.heartbeat_timeout_s <= 0):
            raise ValueError(
                f"heartbeat_timeout_s must be > 0 or None, "
                f"got {self.heartbeat_timeout_s}")


@dataclasses.dataclass
class FleetResult:
    """One supervised run's outcome: the chief's result payload (when the
    driver wrote one) plus the recovery accounting."""

    restarts: int
    attempts: int
    result: Optional[dict]


class FleetExhaustedError(RuntimeError):
    """The fleet kept failing past its restart budget (or deadline)."""


@dataclasses.dataclass(frozen=True)
class _Fault:
    """What the watch loop observed: ``reason`` is ``"exit"`` (a nonzero
    returncode) or ``"stall"`` (a stale heartbeat)."""

    reason: str
    process: int
    returncode: Optional[int] = None
    heartbeat_age_s: Optional[float] = None


class FleetSupervisor:
    """Launch, watch, and restart one N-process training fleet.

    ``command`` is the argv every process runs (multi-controller SPMD: one
    program). The supervisor adds per-process environment:
    ``PHOTON_PROCESS_ID``, ``PHOTON_HEARTBEAT_FILE``,
    ``PHOTON_RESTART_COUNT``, ``PHOTON_RESULT_FILE`` (chief only) and — at
    ``n_processes > 1`` — ``PHOTON_COORDINATOR_ADDRESS`` /
    ``PHOTON_NUM_PROCESSES`` with a freshly-bound loopback port per
    attempt (re-binding the dead attempt's port would race TIME_WAIT).

    ``run_dir`` receives heartbeat files and per-attempt process logs
    (``attempt-K/proc-I.log``) — the post-mortem surface the exhaustion
    error quotes from.
    """

    def __init__(self, command: Sequence[str], n_processes: int,
                 run_dir: str, policy: SupervisorPolicy = SupervisorPolicy(),
                 *, env: Optional[dict] = None, bus=None):
        if n_processes < 1:
            raise ValueError(f"n_processes must be >= 1, got {n_processes}")
        self.command = list(command)
        self.n_processes = int(n_processes)
        self.run_dir = run_dir
        self.policy = policy
        self.base_env = dict(os.environ if env is None else env)
        if bus is None:
            from photon_ml_tpu.events import GLOBAL_BUS as bus
        self.bus = bus
        self.restarts = 0
        self._procs: list[subprocess.Popen] = []
        self._hb_files: list[str] = []
        self._spawn_t = 0.0

    # --- lifecycle --------------------------------------------------------
    def run(self) -> FleetResult:
        """Supervise to completion. Returns on an all-zero fleet exit;
        raises :class:`FleetExhaustedError` past the restart budget or
        deadline (with the failing processes' log tails in the message)."""
        from photon_ml_tpu.resilience.retry import _sleep
        from photon_ml_tpu.telemetry import tracing

        os.makedirs(self.run_dir, exist_ok=True)
        result_path = os.path.join(self.run_dir, "result.json")
        t0 = time.monotonic()
        attempt = 0
        self.bus.post("supervisor_started", processes=self.n_processes,
                      max_restarts=self.policy.max_restarts,
                      command=" ".join(self.command))
        with tracing.span("supervisor.run", processes=self.n_processes):
            while True:
                with tracing.span("supervisor.attempt", attempt=attempt):
                    self._spawn(attempt, result_path)
                    fault = self._watch(t0)
                    if fault is None:
                        self.bus.post("supervisor_completed",
                                      attempts=attempt + 1,
                                      restarts=self.restarts,
                                      elapsed_s=time.monotonic() - t0)
                        return FleetResult(
                            restarts=self.restarts, attempts=attempt + 1,
                            result=self._read_result(result_path))
                    # an installed flight recorder (telemetry.flightrec)
                    # treats reason="stall" as a black-box dump trigger —
                    # the last spans/events/history hit disk before the
                    # stalled fleet is killed and restarted below
                    self.bus.post(
                        "supervisor_fault_detected", attempt=attempt,
                        reason=fault.reason, process=fault.process,
                        returncode=fault.returncode,
                        heartbeat_age_s=fault.heartbeat_age_s)
                    logger.warning(
                        "fleet fault (attempt %d): %s on process %d "
                        "(rc=%s, heartbeat age %s)", attempt, fault.reason,
                        fault.process, fault.returncode,
                        fault.heartbeat_age_s)
                    self._kill_fleet()
                backoff = min(
                    self.policy.base_backoff_s
                    * self.policy.backoff_multiplier ** attempt,
                    self.policy.max_backoff_s)
                elapsed = time.monotonic() - t0
                over_deadline = (
                    self.policy.deadline_s is not None
                    and elapsed + backoff >= self.policy.deadline_s)
                if attempt >= self.policy.max_restarts or over_deadline:
                    self.bus.post("supervisor_exhausted",
                                  attempts=attempt + 1,
                                  restarts=self.restarts,
                                  deadline_hit=over_deadline,
                                  elapsed_s=elapsed)
                    raise FleetExhaustedError(
                        f"fleet failed {attempt + 1} time(s) over "
                        f"{elapsed:.1f}s ({fault.reason} on process "
                        f"{fault.process}"
                        + (f", rc={fault.returncode}"
                           if fault.returncode is not None else "")
                        + (f"; deadline {self.policy.deadline_s}s hit"
                           if over_deadline else
                           f"; restart budget {self.policy.max_restarts} "
                           f"spent")
                        + f"); last logs:\n"
                        + self._log_tails(attempt))
                self.restarts += 1
                self.bus.post("supervisor_restart", attempt=attempt + 1,
                              backoff_s=backoff, reason=fault.reason)
                _sleep(backoff)
                attempt += 1

    # --- internals --------------------------------------------------------
    def _spawn(self, attempt: int, result_path: str) -> None:
        port = _free_loopback_port() if self.n_processes > 1 else None
        attempt_dir = os.path.join(self.run_dir, f"attempt-{attempt}")
        os.makedirs(attempt_dir, exist_ok=True)
        self._procs, self._hb_files = [], []
        self._spawn_t = time.monotonic()
        for pid in range(self.n_processes):
            hb = os.path.join(self.run_dir, f"proc-{pid}.heartbeat")
            # pre-touch so staleness counts from spawn, with no
            # missing-file special case in the watch loop
            with open(hb, "w") as f:
                f.write(f"attempt-{attempt}")
            env = dict(self.base_env)
            env["PHOTON_PROCESS_ID"] = str(pid)
            env[RESTART_COUNT_ENV] = str(attempt)
            env[HEARTBEAT_ENV] = hb
            if pid == 0:
                env[RESULT_ENV] = result_path
            else:
                env.pop(RESULT_ENV, None)
            if port is not None:
                env["PHOTON_COORDINATOR_ADDRESS"] = f"localhost:{port}"
                env["PHOTON_NUM_PROCESSES"] = str(self.n_processes)
            log = open(os.path.join(attempt_dir, f"proc-{pid}.log"), "w")
            try:
                proc = subprocess.Popen(
                    self.command, env=env, stdout=log,
                    stderr=subprocess.STDOUT,
                    start_new_session=True)
            finally:
                log.close()  # the child holds its own descriptor
            self._procs.append(proc)
            self._hb_files.append(hb)

    def _watch(self, t0: float) -> Optional[_Fault]:
        """Block until the attempt resolves: None on all-zero exit, a
        :class:`_Fault` on the first nonzero exit or stale heartbeat.
        Raises :class:`FleetExhaustedError` straight away on deadline —
        a deadline admits no further restart."""
        from photon_ml_tpu.resilience.retry import _sleep

        while True:
            rcs = [p.poll() for p in self._procs]
            for pid, rc in enumerate(rcs):
                if rc is not None and rc != 0:
                    return _Fault(reason="exit", process=pid, returncode=rc)
            if all(rc == 0 for rc in rcs):
                return None
            if self.policy.heartbeat_timeout_s is not None:
                now = time.time()
                for pid, rc in enumerate(rcs):
                    if rc is not None:
                        continue  # already exited 0; no beats expected
                    try:
                        age = now - os.stat(self._hb_files[pid]).st_mtime
                    except OSError:
                        age = time.monotonic() - self._spawn_t
                    if age > self.policy.heartbeat_timeout_s:
                        return _Fault(reason="stall", process=pid,
                                      heartbeat_age_s=age)
            if (self.policy.deadline_s is not None
                    and time.monotonic() - t0 > self.policy.deadline_s):
                self._kill_fleet()
                self.bus.post("supervisor_exhausted",
                              attempts=self.restarts + 1,
                              restarts=self.restarts, deadline_hit=True,
                              elapsed_s=time.monotonic() - t0)
                raise FleetExhaustedError(
                    f"fleet ran past the {self.policy.deadline_s}s "
                    f"deadline; killed. Last logs:\n"
                    + self._log_tails(self.restarts))
            _sleep(self.policy.poll_interval_s)

    def _kill_fleet(self) -> None:
        """SIGTERM every survivor, grace, then SIGKILL — survivors are
        typically blocked inside a collective and cannot exit on their
        own (that inability is the fault class this module exists for)."""
        from photon_ml_tpu.resilience.retry import _sleep

        for p in self._procs:
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + self.policy.grace_s
        while (any(p.poll() is None for p in self._procs)
               and time.monotonic() < deadline):
            _sleep(min(0.05, self.policy.poll_interval_s))
        for p in self._procs:
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass
                p.wait()

    def _read_result(self, path: str) -> Optional[dict]:
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def _log_tails(self, attempt: int, n_bytes: int = 2000) -> str:
        out = []
        for pid in range(self.n_processes):
            path = os.path.join(self.run_dir, f"attempt-{attempt}",
                                f"proc-{pid}.log")
            try:
                with open(path, "rb") as f:
                    f.seek(0, os.SEEK_END)
                    f.seek(max(0, f.tell() - n_bytes))
                    tail = f.read().decode("utf-8", "replace")
            except OSError:
                tail = "<no log>"
            out.append(f"--- process {pid} ({path}) ---\n{tail}")
        return "\n".join(out)


def _free_loopback_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


# ---------------------------------------------------------------------------
# Driver integration (the CLI --supervise path)
# ---------------------------------------------------------------------------

#: value-taking supervision flags stripped from the worker command (the
#: workers must TRAIN, not recursively supervise)
_SUPERVISION_FLAGS = ("--supervise", "--max-restarts",
                      "--heartbeat-timeout-s", "--restart-deadline-s")


def strip_supervision_flags(argv: Sequence[str]) -> list[str]:
    out: list[str] = []
    skip = False
    for a in argv:
        if skip:
            skip = False
            continue
        if a in _SUPERVISION_FLAGS:
            skip = True
            continue
        if any(a.startswith(f + "=") for f in _SUPERVISION_FLAGS):
            continue
        out.append(a)
    return out


def supervise_from_args(driver: str, raw_argv: Sequence[str], args,
                        *, worker_flags: Sequence[str] = ()) -> dict:
    """The drivers' ``--supervise N`` entry point: relaunch THIS command
    (minus the supervision flags, plus ``worker_flags`` — e.g.
    ``--checkpoint --resume --multihost``) as an N-process supervised
    fleet and return the chief's result dict with a ``restarts`` count
    added."""
    command = [sys.executable, "-m", "photon_ml_tpu", driver]
    command += strip_supervision_flags(raw_argv)
    for f in worker_flags:
        if f not in command:
            command.append(f)
    hb = args.heartbeat_timeout_s
    policy = SupervisorPolicy(
        max_restarts=args.max_restarts,
        heartbeat_timeout_s=(hb if hb and hb > 0 else None),
        deadline_s=args.restart_deadline_s)
    sup = FleetSupervisor(command, args.supervise,
                          os.path.join(args.output_dir, "supervisor"),
                          policy)
    fleet = sup.run()
    out = dict(fleet.result or {})
    out.setdefault("output_dir", args.output_dir)
    out["restarts"] = fleet.restarts
    return out
