"""Deterministic, seedable fault injection.

A :class:`FaultPlan` names *injection sites* — fixed strings the framework
threads through its hot paths as :func:`fault_point` / :func:`fault_value`
calls — and decides, deterministically, which invocations of each site
misbehave. The registered sites:

========================  ====================================================
``io.read``               one visit per (file, attempt) in the Avro readers
``ckpt.save``             one visit per save attempt, *between* the tmp write
                          and the atomic rename — the crash-mid-write window
``io.model_save``         one visit per model-publish attempt, between the
                          fully-written staging tree and the atomic
                          retire-then-rename (``io/pipeline.py``) — the
                          background saver's crash window
``io.delta_publish``      the continuous-training delta path: one visit per
                          patch-publish attempt (``io/pipeline.py::
                          save_model_patch_atomic``, same crash window as
                          ``io.model_save``) and one per patch ACTIVATION
                          (``serving/registry.py::load_patch``, after
                          validation, before the version registers) — a
                          fault in either leaves the previously active
                          version serving with no partial patch visible
``collective``            host-side collectives (allgather/allreduce) and
                          ``jax.distributed.initialize``
``optimizer.step``        one visit per coordinate-descent coordinate step
                          (value hook: ``mode="nan"`` corrupts the scores)
``worker.stall``          one visit per sweep (``mode="stall"`` sleeps;
                          ``mode="kill"`` dies abruptly — the supervised-
                          recovery crash site)
``serving.parse``         one visit per POST parse in the serving front end
                          (``serving/http.py``) — a fault surfaces as a 500
                          on that request only
``serving.execute``       one visit per scoring call
                          (``serving/engine.py::ScoringEngine.score``) — a
                          fault fails that batch's requests; the batcher
                          worker and every other request survive
``serving.reload``        one visit per ``/reload``/watch-dir activation
                          attempt (``serving/registry.py::reload``) — a
                          fault rejects the candidate and the incumbent
                          keeps serving
``serving.watch_tick``    one visit per watch-dir poll
                          (``serving/watcher.py::scan_once``) — the poll
                          loop retries next tick, no candidate is lost
``io.save.reqlog``        one visit per request-log segment write on the
                          background pool (``serving/reqlog.py``) — a
                          fault counts the segment as dropped (loss, not
                          retention) and never disturbs traffic
``fleet.fanout``          one visit per per-host leg of a fleet-router
                          fan-out (``fleet/router.py::HostClient``) — a
                          fault surfaces as that host being unreachable:
                          the router maps it to a typed 503
                          (``reason=upstream``) for the affected request
                          and a two-phase reload epoch ABORTS with the
                          incumbent serving fleet-wide
``fleet.replica``         one visit per replica retry/hedge launch inside a
                          shard's replica group (``fleet/router.py::
                          FleetRouter._fanout_leg``) — a fault fails that
                          backup launch: the leg falls back to the remaining
                          replicas, or surfaces as a typed 503
                          (``reason=upstream``) when the rotation is
                          exhausted
``feedback.join``         one visit per feedback-join pass
                          (``feedback/joiner.py::join_feedback``) — a fault
                          aborts that join cleanly (counted in
                          ``photon_feedback_aborts_total{stage=join}`` when
                          the autopilot drove it); serving and the request
                          log are untouched and the next drift event retries
``feedback.refresh_launch``  one visit per autopilot refresh launch
                          (``feedback/autopilot.py``), before any join or
                          refresh work — a fault aborts the launch with the
                          incumbent serving; a wedged or faulted refresh
                          never blocks the score path
========================  ====================================================

Activation is explicit only: :func:`activate` / the :func:`injected` context
manager, or the ``PHOTON_FAULT_PLAN`` environment variable (a JSON object or
an ``@/path/to/plan.json`` reference) read once at import. With no active
plan every hook returns after a single module-global ``is None`` check, so
production paths pay nothing.

Determinism: explicit ``at`` invocation indices always fire; ``rate`` draws
ride a per-site ``numpy`` generator seeded from ``(plan.seed, crc32(site))``,
so two plans built from the same spec fire identically — what makes a chaos
sweep reproducible and a bisection meaningful.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
from typing import Any, Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu.fleet.sharding import stable_hash_u32

#: canonical site names (free-form strings are accepted; these are the ones
#: the framework threads)
SITES = ("io.read", "ckpt.save", "io.model_save", "io.delta_publish",
         "collective", "optimizer.step", "worker.stall",
         "serving.parse", "serving.execute", "serving.reload",
         "serving.watch_tick", "io.save.reqlog", "fleet.fanout",
         "fleet.replica", "feedback.join", "feedback.refresh_launch")

_MODES = ("raise", "nan", "stall", "kill")


def _process_index() -> int:
    """This process's fleet index, for ``FaultSpec.processes`` gating.
    ``PHOTON_PROCESS_ID`` (set by the fleet supervisor and by manual
    multi-controller launches) wins; 0 when unset — single-process runs
    and in-process tests are process 0."""
    try:
        return int(os.environ.get("PHOTON_PROCESS_ID", "0"))
    except ValueError:
        return 0


def _restart_count() -> int:
    """Which supervisor attempt this process belongs to (0 = first
    launch), for ``FaultSpec.attempts`` gating."""
    try:
        return int(os.environ.get("PHOTON_RESTART_COUNT", "0"))
    except ValueError:
        return 0


class InjectedFault(RuntimeError):
    """The exception raised by ``mode="raise"`` specs (retryable)."""

    def __init__(self, site: str, index: int, message: str = ""):
        self.site = site
        self.index = index
        super().__init__(
            message or f"injected fault at site {site!r} (invocation "
                       f"#{index})")


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One rule: which invocations of ``site`` misbehave, and how.

    ``at`` lists explicit 0-based invocation indices; ``rate`` adds a
    seeded per-invocation probability on top. ``max_fires`` caps total
    firings (None = unlimited). ``mode``: ``"raise"`` raises
    :class:`InjectedFault`; ``"nan"`` corrupts the value passing through a
    :func:`fault_value` hook; ``"stall"`` sleeps ``stall_seconds`` (through
    the retry module's sanctioned sleep); ``"kill"`` terminates the process
    abruptly with ``exit_code`` (``os._exit`` — no cleanup, no atexit: the
    crash the fleet supervisor exists to recover from).

    ``processes`` restricts the spec to specific process indices
    (``PHOTON_PROCESS_ID``, 0 when unset) — the ASYMMETRIC fault class:
    unlike the symmetric default, a process-restricted spec fires on some
    processes only, so it must simulate faults the surviving processes
    cannot recover from in-process (kill/stall), not divergences the
    lockstep guard handles. ``attempts`` restricts to specific supervisor
    restart attempts (``PHOTON_RESTART_COUNT``, 0 when unset) — a kill
    gated ``attempts=(0,)`` fires on the first launch only, so the
    restarted fleet completes instead of dying deterministically forever.
    """

    site: str
    at: tuple[int, ...] = ()
    rate: float = 0.0
    max_fires: Optional[int] = None
    mode: str = "raise"
    stall_seconds: float = 0.0
    message: str = ""
    exit_code: int = 113
    processes: Optional[tuple[int, ...]] = None
    attempts: Optional[tuple[int, ...]] = None

    def __post_init__(self):
        if self.mode not in _MODES:
            raise ValueError(f"fault mode must be one of {_MODES}, "
                             f"got {self.mode!r}")
        if not (0.0 <= self.rate <= 1.0):
            raise ValueError(f"rate must be in [0, 1], got {self.rate}")


@dataclasses.dataclass
class FaultRecord:
    """Audit entry for one firing (mirrored as a ``fault_injected`` event)."""

    site: str
    index: int
    mode: str
    context: dict


class FaultPlan:
    """Deterministic registry of :class:`FaultSpec` rules.

    Thread-compatibility note: visits mutate per-site counters; the
    training drivers visit sites from the main thread only (the reader's
    decode pool calls :func:`fault_point` from workers, where the GIL makes
    the counter increment atomic — ordering across files is then
    nondeterministic, so specs targeting ``io.read`` in multi-file runs
    should prefer ``rate`` over ``at``).
    """

    def __init__(self, specs: Sequence[FaultSpec] = (), seed: int = 0,
                 bus=None):
        self.specs = tuple(specs)
        self.seed = int(seed)
        self.bus = bus
        self.records: list[FaultRecord] = []
        self._counts: dict[str, int] = {}
        self._fires: dict[int, int] = {i: 0 for i in range(len(self.specs))}
        self._rngs: dict[str, np.random.Generator] = {}

    # --- bookkeeping ------------------------------------------------------
    def visits(self, site: str) -> int:
        """How many times ``site`` has been visited so far."""
        return self._counts.get(site, 0)

    def fired(self, site: Optional[str] = None) -> list[FaultRecord]:
        if site is None:
            return list(self.records)
        return [r for r in self.records if r.site == site]

    def _rng(self, site: str) -> np.random.Generator:
        rng = self._rngs.get(site)
        if rng is None:
            rng = np.random.default_rng(
                (self.seed, stable_hash_u32(site)))
            self._rngs[site] = rng
        return rng

    # --- the decision -----------------------------------------------------
    def visit(self, site: str, context: Mapping[str, Any]) -> Optional[str]:
        """Advance ``site``'s invocation counter and apply the first firing
        spec. Returns the fired mode (``"nan"``/``"stall"``) for value
        hooks, raises for ``"raise"`` specs, None when nothing fires.

        ``processes``/``attempts``-restricted specs still consume their
        seeded ``rate`` draw on every process and attempt — the draw
        sequence stays aligned with the unrestricted plan, so restricting
        a spec never shifts which invocations OTHER specs hit."""
        index = self._counts.get(site, 0)
        self._counts[site] = index + 1
        for i, spec in enumerate(self.specs):
            if spec.site != site:
                continue
            if spec.max_fires is not None and self._fires[i] >= spec.max_fires:
                continue
            fire = index in spec.at
            if not fire and spec.rate > 0.0:
                fire = float(self._rng(site).random()) < spec.rate
            if fire and spec.processes is not None:
                fire = _process_index() in spec.processes
            if fire and spec.attempts is not None:
                fire = _restart_count() in spec.attempts
            if not fire:
                continue
            self._fires[i] += 1
            record = FaultRecord(site=site, index=index, mode=spec.mode,
                                 context=dict(context))
            self.records.append(record)
            self._post(record)
            if spec.mode == "raise":
                raise InjectedFault(site, index, spec.message)
            if spec.mode == "stall":
                from photon_ml_tpu.resilience.retry import _sleep

                _sleep(spec.stall_seconds)
                return "stall"
            if spec.mode == "kill":
                # an abrupt death, not an exit: no finally blocks, no
                # atexit, no flushing — the asymmetric crash class only a
                # SUPERVISOR can recover (surviving processes are left
                # stuck in their next collective)
                os._exit(spec.exit_code)
            return spec.mode
        return None

    def _post(self, record: FaultRecord) -> None:
        bus = self.bus
        if bus is None:
            from photon_ml_tpu.events import GLOBAL_BUS as bus
        bus.post("fault_injected", site=record.site, index=record.index,
                 mode=record.mode, **record.context)

    # --- (de)serialization ------------------------------------------------
    @classmethod
    def from_json(cls, obj: "str | Mapping") -> "FaultPlan":
        """Build from a JSON object/string:
        ``{"seed": 0, "specs": [{"site": "io.read", "at": [0]}, ...]}``."""
        if isinstance(obj, str):
            obj = json.loads(obj)
        specs = [FaultSpec(site=s["site"],
                           at=tuple(int(x) for x in s.get("at", ())),
                           rate=float(s.get("rate", 0.0)),
                           max_fires=(None if s.get("max_fires") is None
                                      else int(s["max_fires"])),
                           mode=s.get("mode", "raise"),
                           stall_seconds=float(s.get("stall_seconds", 0.0)),
                           message=s.get("message", ""),
                           exit_code=int(s.get("exit_code", 113)),
                           processes=(None if s.get("processes") is None
                                      else tuple(int(x)
                                                 for x in s["processes"])),
                           attempts=(None if s.get("attempts") is None
                                     else tuple(int(x)
                                                for x in s["attempts"])))
                 for s in obj.get("specs", ())]
        return cls(specs, seed=int(obj.get("seed", 0)))

    def to_json(self) -> str:
        return json.dumps({
            "seed": self.seed,
            "specs": [{
                "site": s.site, "at": list(s.at), "rate": s.rate,
                "max_fires": s.max_fires, "mode": s.mode,
                "stall_seconds": s.stall_seconds, "message": s.message,
                "exit_code": s.exit_code,
                "processes": (None if s.processes is None
                              else list(s.processes)),
                "attempts": (None if s.attempts is None
                             else list(s.attempts)),
            } for s in self.specs],
        }, sort_keys=True)


# ---------------------------------------------------------------------------
# Global activation + the hooks
# ---------------------------------------------------------------------------

_ACTIVE: Optional[FaultPlan] = None


def active_plan() -> Optional[FaultPlan]:
    return _ACTIVE


def activate(plan: FaultPlan) -> FaultPlan:
    global _ACTIVE
    _ACTIVE = plan
    return plan


def deactivate() -> None:
    global _ACTIVE
    _ACTIVE = None


@contextlib.contextmanager
def injected(plan: FaultPlan):
    """Scope a plan's activation (test/chaos-sweep entry point)."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = plan
    try:
        yield plan
    finally:
        _ACTIVE = prev


def fault_point(site: str, **context: Any) -> None:
    """Injection hook for control-flow sites. No active plan (the
    production default): returns after one global ``is None`` check."""
    plan = _ACTIVE
    if plan is None:
        return
    plan.visit(site, context)


def fault_value(site: str, value, **context: Any):
    """Injection hook threaded through a data value (e.g. the coordinate
    step's new scores). ``mode="nan"`` corrupts the value; ``"raise"``
    raises; inactive plans pass the value through untouched."""
    plan = _ACTIVE
    if plan is None:
        return value
    if plan.visit(site, context) == "nan":
        return value * float("nan")
    return value


def _activate_from_env() -> None:
    spec = os.environ.get("PHOTON_FAULT_PLAN")
    if not spec:
        return
    if spec.startswith("@"):
        with open(spec[1:]) as f:
            spec = f.read()
    activate(FaultPlan.from_json(spec))


_activate_from_env()
