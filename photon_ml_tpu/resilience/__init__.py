"""Resilience subsystem: deterministic fault injection, retry/backoff, and
divergence guard/rollback for GAME training.

The reference leans on Spark lineage + driver restarts for fault tolerance
(SURVEY.md §5.4); the TPU-native port replaces that with first-class
checkpointing plus this subsystem, which makes the training stack *use* the
checkpoints to recover:

- :mod:`photon_ml_tpu.resilience.faults` — a seedable, deterministic
  :class:`FaultPlan` with named injection sites (``io.read``, ``ckpt.save``,
  ``collective``, ``optimizer.step``, ``worker.stall``) threaded as no-op
  hooks through the io/parallel/game layers. Inactive (the production
  default) the hooks cost one module-global ``is None`` check.
- :mod:`photon_ml_tpu.resilience.retry` — one ``retry(fn, policy)``
  primitive (exponential backoff, deterministic jitter, deadline,
  per-attempt :class:`~photon_ml_tpu.events.EventBus` emission) wrapped
  around Avro reads, checkpoint save/restore, and multihost initialization.
- :mod:`photon_ml_tpu.resilience.guard` — NaN/Inf divergence detection at
  coordinate boundaries with rollback / regularization-backoff / freeze
  semantics (see RESILIENCE.md).
- :mod:`photon_ml_tpu.resilience.supervisor` — the ASYMMETRIC fault class
  (one process of a multi-controller job dies or stalls mid-collective):
  a :class:`FleetSupervisor` owns the fleet's process lifecycle, watches
  exit codes + per-process :func:`heartbeat` files, and relaunches the
  whole fleet from the latest agreed checkpoint under a bounded restart
  budget (the drivers' ``--supervise N`` flag).
"""

from photon_ml_tpu.resilience.faults import (
    FaultPlan,
    FaultSpec,
    InjectedFault,
    active_plan,
    fault_point,
    fault_value,
    injected,
)
from photon_ml_tpu.resilience.guard import (
    DivergenceError,
    DivergenceGuard,
    DivergencePolicy,
)
from photon_ml_tpu.resilience.retry import (
    RetryPolicy,
    get_default_policy,
    retry,
    set_default_policy,
)
from photon_ml_tpu.resilience.supervisor import (
    FleetExhaustedError,
    FleetSupervisor,
    SupervisorPolicy,
    heartbeat,
)

__all__ = [
    "FaultPlan",
    "FaultSpec",
    "InjectedFault",
    "active_plan",
    "fault_point",
    "fault_value",
    "injected",
    "DivergenceError",
    "DivergenceGuard",
    "DivergencePolicy",
    "RetryPolicy",
    "get_default_policy",
    "retry",
    "set_default_policy",
    "FleetExhaustedError",
    "FleetSupervisor",
    "SupervisorPolicy",
    "heartbeat",
]
