from photon_ml_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    ENTITY_AXIS,
    FEATURE_AXIS,
    data_sharded,
    make_mesh,
    replicated,
)
from photon_ml_tpu.parallel.distributed import (  # noqa: F401
    DistributedGLMObjective,
    FeatureShardedGLMObjective,
    ShardBudget,
    shard_budget,
    shard_glm_data,
    shard_glm_data_features,
)
from photon_ml_tpu.parallel.multihost import (  # noqa: F401
    allgather_concat,
    allreduce_max,
    allreduce_shard_budget,
    allreduce_sum,
    global_glm_data_from_local,
    global_glm_data_multihost,
    local_axis_blocks,
    make_multihost_mesh,
)
