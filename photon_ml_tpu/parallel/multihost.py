"""Multi-host (multi-slice) support: global meshes and host-local data feed.

The reference scales across racks with Spark's driver/executor tree
(``RDD.treeAggregate`` over netty RPC — SURVEY.md §5.8). The TPU-native
equivalent is multi-controller JAX: every host runs THIS same program,
``jax.distributed.initialize`` forms the job, and one global
:class:`jax.sharding.Mesh` spans all slices — collectives ride ICI within a
slice and DCN between slices. No framework code changes between 1 host and
N: the mesh axes are the same, the ``shard_map`` bodies are the same.

Mesh layout rule (the scaling-book recipe): put the axis with the
highest-volume collectives (``data`` — one psum of grad-sized arrays per
optimizer iteration) INNERMOST so it maps to ICI; put low-volume axes
(``entity`` — zero collectives; only host-side gather at sweep end) across
DCN. :func:`make_multihost_mesh` orders axes accordingly.

Data feed: each host reads its own Avro shard (the reference's executor-local
HDFS reads) and contributes host-local blocks;
:func:`global_glm_data_from_local` assembles the global sharded
:class:`GLMData` with ``jax.make_array_from_process_local_data``.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.ops.design import DenseDesign
from photon_ml_tpu.ops.objective import GLMData
from photon_ml_tpu.parallel.mesh import DATA_AXIS, ENTITY_AXIS


_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Form the multi-controller job (idempotent). On single-host runs this
    is a no-op; on TPU pods the args come from the environment.

    Must run before ANY backend-touching JAX call — even
    ``jax.process_count()`` initializes the XLA backend, after which
    ``jax.distributed.initialize`` refuses to run; hence the module-level
    flag rather than querying JAX state.
    """
    global _initialized
    if _initialized:
        return
    if coordinator_address is None and num_processes is None:
        return  # single-host
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True


def make_multihost_mesh(data_per_slice: Optional[int] = None,
                        entity_over_slices: bool = False) -> Mesh:
    """Global mesh over all processes' devices.

    Default: one ``data`` axis over every chip (psum tree spans DCN exactly
    once at the top, like treeAggregate's depth-2 tree). With
    ``entity_over_slices``, a 2D ``(entity, data)`` grid: the ``entity``
    axis runs across slices (DCN) and ``data`` stays within a slice (ICI) —
    the right layout when random-effect solves dominate, because they need
    no collectives at all. ``data_per_slice`` overrides the data-axis width
    (default: one process's device count).
    """
    devices = np.array(jax.devices())
    n = len(devices)
    if not entity_over_slices and data_per_slice is None:
        return jax.make_mesh((n,), (DATA_AXIS,))
    per = (data_per_slice if data_per_slice is not None
           else n // max(jax.process_count(), 1))
    if per <= 0 or n % per:
        raise ValueError(
            f"data axis width {per} must divide device count {n}")
    dev_grid = devices.reshape(n // per, per)
    return Mesh(dev_grid, (ENTITY_AXIS, DATA_AXIS))


def global_glm_data_from_local(local: GLMData, mesh: Mesh,
                               axis: str = DATA_AXIS) -> GLMData:
    """Assemble a globally-sharded :class:`GLMData` from each process's
    host-local block (stacked per-local-device layout, as produced by
    ``shard_glm_data(local, jax.local_device_count())``).

    Every process contributes its own rows; the result's leading dim is the
    global device count, laid out for the ``data``-axis ``shard_map``
    objective. Labels/offsets/weights and a dense design all feed through
    ``jax.make_array_from_process_local_data`` (the host→device bridge the
    reference gets from Spark partition locality).
    """
    sharding = NamedSharding(mesh, P(axis))

    def feed(x: np.ndarray) -> jax.Array:
        x = np.asarray(x)
        global_shape = (x.shape[0] * jax.process_count(),) + x.shape[1:]
        return jax.make_array_from_process_local_data(sharding, x, global_shape)

    if not isinstance(local.design, DenseDesign):
        raise NotImplementedError(
            "multi-host feed currently supports dense stacked designs; "
            "pack sparse shards per-host first")
    return GLMData(
        design=DenseDesign(x=feed(local.design.x)),
        labels=feed(local.labels),
        offsets=feed(local.offsets),
        weights=feed(local.weights),
    )
