"""Multi-host (multi-slice) support: global meshes and host-local data feed.

The reference scales across racks with Spark's driver/executor tree
(``RDD.treeAggregate`` over netty RPC — SURVEY.md §5.8). The TPU-native
equivalent is multi-controller JAX: every host runs THIS same program,
``jax.distributed.initialize`` forms the job, and one global
:class:`jax.sharding.Mesh` spans all slices — collectives ride ICI within a
slice and DCN between slices. No framework code changes between 1 host and
N: the mesh axes are the same, the ``shard_map`` bodies are the same.

Mesh layout rule (the scaling-book recipe): put the axis with the
highest-volume collectives (``data`` — one psum of grad-sized arrays per
optimizer iteration) INNERMOST so it maps to ICI; put low-volume axes
(``entity`` — zero collectives; only host-side gather at sweep end) across
DCN. :func:`make_multihost_mesh` orders axes accordingly.

Data feed: each host reads its own Avro shard (the reference's executor-local
HDFS reads) and contributes host-local blocks;
:func:`global_glm_data_from_local` assembles the global sharded
:class:`GLMData` with ``jax.make_array_from_process_local_data``.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.ops.design import ChunkedSparseDesign, DenseDesign
from photon_ml_tpu.ops.objective import GLMData
from photon_ml_tpu.parallel.distributed import (
    ShardBudget,
    shard_budget,
    shard_glm_data,
)
from photon_ml_tpu.parallel.mesh import DATA_AXIS, ENTITY_AXIS


_initialized = False


def _enable_cpu_collectives() -> None:
    """Multi-process jobs on the CPU backend (loopback test fleets, the
    supervised 2-process chaos cells) need a cross-process collectives
    implementation — the bare CPU client refuses multiprocess computations
    outright ("Multiprocess computations aren't implemented"). jaxlib
    ships gloo in the wheel but leaves it off by default, and the config
    flag only takes effect BEFORE backend/client creation — which is why
    this lives in :func:`initialize` (documented to run before any
    backend-touching call) rather than at first collective. TPU/GPU
    platforms keep their native ICI/NCCL paths untouched."""
    import os

    platforms = str(jax.config.jax_platforms
                    or os.environ.get("JAX_PLATFORMS", ""))
    if "cpu" not in platforms.split(","):
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):  # pre-0.4.35 jax: no such flag
        pass


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               *, auto: bool = False, retry_policy=None) -> None:
    """Form the multi-controller job (idempotent). On single-host runs this
    is a no-op; on TPU pods the args come from the environment.

    Resolution order: explicit args → ``PHOTON_COORDINATOR_ADDRESS`` /
    ``PHOTON_NUM_PROCESSES`` / ``PHOTON_PROCESS_ID`` env vars (how the
    drivers' ``--multihost`` flag is fed on CPU/GPU clusters) → with
    ``auto=True``, bare ``jax.distributed.initialize()`` (JAX's own cluster
    auto-detection: TPU pod metadata, Slurm, etc.).

    Connection attempts run under ``retry_policy`` (default: the
    process-wide resilience policy — the drivers' ``--max-retries`` /
    ``--retry-deadline-s`` flags), and a coordinator that stays
    unreachable raises a :class:`RuntimeError` naming the address, this
    process's index, and the attempt budget — not a raw backend hang or
    traceback.

    Must run before ANY backend-touching JAX call — even
    ``jax.process_count()`` initializes the XLA backend, after which
    ``jax.distributed.initialize`` refuses to run; hence the module-level
    flag rather than querying JAX state.
    """
    global _initialized
    if _initialized:
        return
    if coordinator_address is None and num_processes is None:
        import os

        coordinator_address = os.environ.get("PHOTON_COORDINATOR_ADDRESS")
        n = os.environ.get("PHOTON_NUM_PROCESSES")
        if bool(coordinator_address) != bool(n):
            # one without the other would fall through to
            # jax.distributed.initialize with a None field and die with an
            # obscure backend error; name the missing variable instead.
            # (PHOTON_PROCESS_ID stays optional: it defaults to the
            # process_id argument, and a leftover value on a single-host
            # run is harmless.)
            missing = ("PHOTON_NUM_PROCESSES" if coordinator_address
                       else "PHOTON_COORDINATOR_ADDRESS")
            raise ValueError(
                f"multi-host environment is partially set: {missing} is "
                "missing — set both PHOTON_COORDINATOR_ADDRESS and "
                "PHOTON_NUM_PROCESSES (or neither, for single-host)")
        num_processes = int(n) if n else None
        pid = os.environ.get("PHOTON_PROCESS_ID")
        process_id = int(pid) if pid else process_id
        if coordinator_address is None and num_processes is None:
            if auto:
                jax.distributed.initialize()
                _initialized = True
            return  # single-host
    from photon_ml_tpu.resilience import fault_point, get_default_policy, \
        retry

    _enable_cpu_collectives()
    policy = retry_policy if retry_policy is not None \
        else get_default_policy()
    # the deadline must be HARD: jax.distributed.initialize BLOCKS
    # internally (~300s default) waiting for the coordinator, so without
    # capping its own timeout the retry deadline would never get a chance
    # to fire. Budget each attempt an equal share of the deadline.
    init_kwargs = {}
    if policy.deadline_s is not None:
        import inspect as _inspect

        if ("initialization_timeout"
                in _inspect.signature(jax.distributed.initialize).parameters):
            init_kwargs["initialization_timeout"] = max(
                1, int(np.ceil(policy.deadline_s / policy.max_attempts)))
    attempts = [0]

    def attempt() -> None:
        attempts[0] += 1
        from photon_ml_tpu.resilience import heartbeat

        heartbeat("initialize")
        fault_point("collective", op="initialize",
                    coordinator=coordinator_address)
        if (process_id not in (None, 0) and coordinator_address
                and ":" in coordinator_address):
            # reachability preflight (non-chief only — process 0 hosts the
            # coordinator itself): some jax versions answer an unreachable
            # coordinator with a C++ LOG(FATAL) process abort, which no
            # Python handler can turn into the actionable error below;
            # probing the socket first keeps the failure catchable. A
            # worker legitimately starting BEFORE the coordinator must
            # wait, not die — poll within this attempt's budget (jax's own
            # default wait is 300s), through the retry module's sanctioned
            # sleep so the wait is visible to the hygiene accounting.
            import socket

            from photon_ml_tpu.resilience.retry import _sleep

            host, port = coordinator_address.rsplit(":", 1)
            budget = init_kwargs.get("initialization_timeout", 300)
            t_start = _time.monotonic()
            while True:
                try:
                    socket.create_connection((host, int(port)),
                                             timeout=min(budget, 10)).close()
                    break
                except OSError:
                    if _time.monotonic() - t_start >= budget:
                        raise
                    _sleep(0.2)
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id, **init_kwargs)

    import time as _time

    t0 = _time.monotonic()
    try:
        retry(attempt, policy, name="multihost.initialize")
    except Exception as e:
        raise RuntimeError(
            f"could not join the multi-controller job: coordinator "
            f"{coordinator_address!r} unreachable from process "
            f"{process_id if process_id is not None else '?'} of "
            f"{num_processes} after {attempts[0]} attempt(s) over "
            f"{_time.monotonic() - t0:.1f}s "
            f"(deadline {policy.deadline_s}s, max attempts "
            f"{policy.max_attempts}). Check that the coordinator process "
            f"is up, PHOTON_COORDINATOR_ADDRESS is its reachable "
            f"host:port, and every process agrees on "
            f"PHOTON_NUM_PROCESSES; last error: {e!r}") from e
    _initialized = True


def is_chief() -> bool:
    """True on the process that should write outputs (the reference's
    driver/executor asymmetry collapses to "process 0 writes, everyone
    computes" — collectives keep all processes in lockstep either way)."""
    return jax.process_index() == 0


def make_multihost_mesh(data_per_slice: Optional[int] = None,
                        entity_over_slices: bool = False) -> Mesh:
    """Global mesh over all processes' devices.

    Default: one ``data`` axis over every chip (psum tree spans DCN exactly
    once at the top, like treeAggregate's depth-2 tree). With
    ``entity_over_slices``, a 2D ``(entity, data)`` grid: the ``entity``
    axis runs across slices (DCN) and ``data`` stays within a slice (ICI) —
    the right layout when random-effect solves dominate, because they need
    no collectives at all. ``data_per_slice`` overrides the data-axis width
    (default: one process's device count).
    """
    devices = np.array(jax.devices())
    n = len(devices)
    if not entity_over_slices and data_per_slice is None:
        return jax.make_mesh((n,), (DATA_AXIS,))
    per = (data_per_slice if data_per_slice is not None
           else n // max(jax.process_count(), 1))
    if per <= 0 or n % per:
        raise ValueError(
            f"data axis width {per} must divide device count {n}")
    dev_grid = devices.reshape(n // per, per)
    return Mesh(dev_grid, (ENTITY_AXIS, DATA_AXIS))


def allreduce_shard_budget(local: ShardBudget) -> ShardBudget:
    """Max-reduce a :class:`ShardBudget` across all processes so every host
    builds identically-shaped shard stacks (identity on single-process
    runs). The max is correct field-wise: a larger rows-per-shard or chunk
    count only adds inert zero-padding on the smaller hosts."""
    if jax.process_count() == 1:
        return local
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(local.to_array())
    return ShardBudget.from_array(np.max(np.asarray(gathered), axis=0))


def _gather_stack(x: np.ndarray) -> np.ndarray:
    """``process_allgather`` with a stacked leading process axis, safe for
    any 64-bit payload even when ``jax_enable_x64`` is off (jax would
    silently downcast; entity keys ``entity*dim + feature`` overflow int32,
    and float64 would lose precision only on P>1 runs — the worst kind of
    divergence). 8-byte dtypes ride through as uint32 word pairs."""
    from jax.experimental import multihost_utils

    from photon_ml_tpu.resilience import fault_point, heartbeat

    # injection-only, never retried: a unilateral second attempt at a
    # collective would desync every other process — fault recovery for
    # collectives is the caller's (symmetric) job. The heartbeat marks
    # the collective BOUNDARY: a process whose peer died blocks inside
    # the gather below with this beat as its last sign of life, which is
    # exactly the staleness the fleet supervisor's stall detection reads.
    heartbeat("collective")
    fault_point("collective", op="allgather", shape=tuple(x.shape))
    x = np.ascontiguousarray(x)
    if x.dtype.itemsize == 8 and not jax.config.jax_enable_x64:
        dtype = x.dtype
        words = x.view(np.uint32).reshape(x.shape + (2,))
        gathered = np.asarray(multihost_utils.process_allgather(words))
        assert gathered.dtype == np.uint32, gathered.dtype
        return np.ascontiguousarray(gathered).view(dtype).reshape(
            gathered.shape[:-1])
    return np.asarray(multihost_utils.process_allgather(x))


def allgather_concat(x: np.ndarray) -> np.ndarray:
    """Concatenate each process's (variable-length, axis-0) array in process
    order — the host-side collective behind multi-process model assembly and
    the entity-shuffle (reference: Spark's shuffle/collect). Identity on
    single-process runs. Shapes beyond axis 0 must agree; axis-0 lengths are
    equalized by zero-padding to the max before the gather (collectives need
    equal shapes), then the padding is dropped per-process."""
    x = np.asarray(x)
    if jax.process_count() == 1:
        return x
    lens = _gather_stack(np.array([x.shape[0]], np.int64)).reshape(-1)
    m = int(lens.max())
    if m == 0:
        return x
    pad = [(0, m - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
    gathered = _gather_stack(np.pad(x, pad))
    return np.concatenate(
        [gathered[p, :int(lens[p])] for p in range(len(lens))], axis=0)


def allreduce_sum(x: np.ndarray) -> np.ndarray:
    """Element-wise sum across processes (identity single-process) — e.g.
    global entity row counts from per-process bincounts."""
    x = np.asarray(x)
    if jax.process_count() == 1:
        return x
    return _gather_stack(x).sum(axis=0).astype(x.dtype)


def allgather_concat_strings(strings) -> list[str]:
    """Concatenate every process's list of strings in process order
    (identity single-process) — the collective behind global feature-index
    and entity-vocabulary agreement. Strings ride as a lengths gather plus
    one flat utf-8 byte gather (jax collectives carry no string dtype)."""
    strings = list(strings)
    if jax.process_count() == 1:
        return strings
    data = [s.encode("utf-8") for s in strings]
    lens = allgather_concat(np.array([len(b) for b in data], np.int64))
    buf = allgather_concat(
        np.frombuffer(b"".join(data), np.uint8).copy()
        if data else np.zeros(0, np.uint8))
    out, off = [], 0
    for ln in lens:
        ln = int(ln)
        out.append(bytes(buf[off:off + ln]).decode("utf-8"))
        off += ln
    return out


def allgather_text(text: str) -> list[str]:
    """Every process's ``text`` in process order (identity single-process)
    — the transport behind the fleet metrics fold
    (:mod:`photon_ml_tpu.telemetry.aggregate`): each process contributes
    one rendered registry snapshot per sweep boundary and process 0 merges
    the gathered list. One string per process keeps the collective at a
    single lengths-gather plus one flat byte gather."""
    return allgather_concat_strings([text])


def allreduce_max(x: np.ndarray) -> np.ndarray:
    """Element-wise max across processes (identity single-process)."""
    x = np.asarray(x)
    if jax.process_count() == 1:
        return x
    return _gather_stack(x).max(axis=0).astype(x.dtype)


def local_axis_blocks(mesh: Mesh, axis: str = DATA_AXIS) -> int:
    """How many distinct ``axis`` coordinates this process's devices cover —
    the number of data blocks this process must feed. NOT simply
    ``local_device_count``: on a 2D ``(entity, data)`` mesh each data block
    is replicated across the entity lanes, so feeding one block per local
    device would over-split the data (and the per-device leading dim would
    silently drop rows in the shard_map body's ``[0]`` unstack)."""
    names = list(mesh.axis_names)
    axis_pos = names.index(axis)
    devs = np.asarray(mesh.devices)
    me = jax.process_index()
    coords = {idx[axis_pos] for idx in np.ndindex(devs.shape)
              if devs[idx].process_index == me}
    if not coords:
        raise ValueError(f"process {me} owns no devices in mesh {mesh}")
    return len(coords)


def global_glm_data_multihost(host_data: GLMData, mesh: Mesh,
                              axis: str = DATA_AXIS) -> GLMData:
    """One-call multi-host feed: shard this process's host-resident data
    into its share of the mesh's ``axis`` blocks, reconcile the layout
    budget across processes, and assemble the globally-sharded
    :class:`GLMData`.

    The two-pass build (local layout → budget allreduce → rebuild only when
    another host needs bigger blocks) is the TPU-native analog of the
    reference letting Spark pick partition sizes per executor: here shapes
    must agree globally, so hosts agree on the max and pad with weight-0
    rows / zero-value chunks, which contribute exactly nothing.
    """
    n_local = local_axis_blocks(mesh, axis)
    # host_stage: the stack stays in numpy — make_array_from_process_local_data
    # below is the one host→device transfer (a jnp stack would detour the
    # whole local dataset through the default device's HBM).
    #
    # Two agreement rounds, both unconditional (allgather is a collective —
    # every process must call it the same number of times):
    # 1. agree on the bucket GEOMETRY (rows-per-shard, chunk widths) — a
    #    host given a larger ``per`` re-buckets rows into fewer, denser
    #    blocks, so chunk COUNTS measured at the old geometry are invalid;
    # 2. re-measure chunk counts at the agreed geometry, then agree on
    #    their max. Padding to a larger count is always legal, so round 2
    #    is a fixed point — no host can need a third round.
    local = shard_glm_data(host_data, n_local, host_stage=True)
    b0 = shard_budget(local)
    geo = allreduce_shard_budget(b0)
    if (geo.rows_per_shard, geo.row_chunk, geo.col_chunk) != (
            b0.rows_per_shard, b0.row_chunk, b0.col_chunk):
        local = shard_glm_data(
            host_data, n_local, host_stage=True,
            budget=ShardBudget(rows_per_shard=geo.rows_per_shard,
                               row_chunk=geo.row_chunk,
                               col_chunk=geo.col_chunk))
    b1 = shard_budget(local)
    final = allreduce_shard_budget(b1)
    if final != b1:
        local = shard_glm_data(host_data, n_local, budget=final,
                               host_stage=True)
    return global_glm_data_from_local(local, mesh, axis)


def global_glm_data_from_local(local: GLMData, mesh: Mesh,
                               axis: str = DATA_AXIS) -> GLMData:
    """Assemble a globally-sharded :class:`GLMData` from each process's
    host-local block (stacked per-block layout, as produced by
    ``shard_glm_data(local, local_axis_blocks(mesh))``).

    Every process contributes its own rows; the result's leading dim is the
    global device count, laid out for the ``data``-axis ``shard_map``
    objective. Labels/offsets/weights and the design — dense, or the
    chunked sparse layout (each of whose six leaves stacks the same way) —
    all feed through ``jax.make_array_from_process_local_data`` (the
    host→device bridge the reference gets from Spark partition locality;
    ``function/glm/DistributedGLMLossFunction.scala`` reads its partitions
    off executor-local HDFS the same one-host-one-block way).

    Cross-host contract (unverifiable locally, like any SPMD invariant):
    every process must present identical leaf shapes — same rows-per-device
    ``per``, and for sparse designs the same chunk widths and padded chunk
    counts. :func:`allreduce_shard_budget` reconciles per-host budgets;
    :func:`global_glm_data_multihost` does the whole dance in one call.
    """
    sharding = NamedSharding(mesh, P(axis))
    n_local = local_axis_blocks(mesh, axis)
    n_axis = mesh.shape[axis]
    if n_axis % n_local:
        raise ValueError(
            f"this process covers {n_local} of the {n_axis} {axis!r}-axis "
            f"blocks — non-uniform process layouts are not supported")
    scale = n_axis // n_local
    if jax.process_count() > 1:
        # Each data-axis block must be OWNED by exactly one process: if a
        # block's replicas span processes (e.g. the entity axis crosses
        # hosts), every owner would feed its own different rows into what
        # the sharding declares to be one replicated block — silently
        # dropping every non-zeroth host's data from psums. Partition the
        # data axis across processes (make_multihost_mesh() default) and
        # put cross-host axes on entity only when data is within-host.
        names = list(mesh.axis_names)
        axis_pos = names.index(axis)
        devs = np.asarray(mesh.devices)
        owners: dict[int, set[int]] = {}
        for idx in np.ndindex(devs.shape):
            owners.setdefault(idx[axis_pos], set()).add(
                devs[idx].process_index)
        shared = [c for c, procs in owners.items() if len(procs) > 1]
        if shared:
            raise ValueError(
                f"{axis!r}-axis blocks {shared[:4]} are replicated across "
                f"processes in this mesh; the per-process feed cannot "
                f"guarantee replicas agree — use a mesh whose {axis!r} "
                f"axis partitions processes")

    def feed(x) -> jax.Array:
        x = np.asarray(x)
        if x.shape[0] != n_local:
            raise ValueError(
                f"local stack has {x.shape[0]} blocks; this process's "
                f"devices cover {n_local} {axis!r}-axis blocks — build with "
                f"shard_glm_data(data, local_axis_blocks(mesh))")
        global_shape = (x.shape[0] * scale,) + x.shape[1:]
        return jax.make_array_from_process_local_data(sharding, x, global_shape)

    design = local.design
    from photon_ml_tpu.game.factored import FactoredDesign

    if isinstance(design, DenseDesign):
        fed = DenseDesign(x=feed(design.x))
    elif isinstance(design, FactoredDesign):
        fed = FactoredDesign(x=feed(design.x), v=feed(design.v),
                             latent_dim=design.latent_dim)
    elif isinstance(design, ChunkedSparseDesign):
        fed = ChunkedSparseDesign(
            rvals=feed(design.rvals), rcols=feed(design.rcols),
            rrow=feed(design.rrow), cvals=feed(design.cvals),
            crows=feed(design.crows), ccol=feed(design.ccol),
            n_rows=design.n_rows, n_cols=design.n_cols)
    else:
        raise TypeError(
            f"multi-host feed takes the stacked per-block layout from "
            f"shard_glm_data (DenseDesign, FactoredDesign, or "
            f"ChunkedSparseDesign); got "
            f"{type(design).__name__} — run shard_glm_data("
            f"local, local_axis_blocks(mesh)) first, or use "
            f"global_glm_data_multihost for the whole dance")
    return GLMData(
        design=fed,
        labels=feed(local.labels),
        offsets=feed(local.offsets),
        weights=feed(local.weights),
    )
