"""Device-mesh construction helpers.

The reference's execution substrate is a Spark cluster (driver + executors);
ours is a :class:`jax.sharding.Mesh` over TPU chips. Axis vocabulary used
throughout the framework (SURVEY.md §2.10):

- ``"data"`` — sample sharding for the fixed effect (replaces RDD partitions
  + ``treeAggregate``),
- ``"entity"`` — random-effect entity sharding (replaces the
  ``RandomEffectDatasetPartitioner`` hash sharding),
- ``"feature"`` — optional coefficient-dimension sharding for very wide
  fixed-effect models (no reference equivalent; breeze held the full vector
  on the driver).

Multi-host: pass the global device list; the same axis names ride ICI within
a slice and DCN across slices (mesh construction orders devices so the
fastest-varying axis maps to ICI neighbours, which `jax.make_mesh` handles).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

try:  # jax >= 0.5: meshes carry per-axis types
    from jax.sharding import AxisType
except ImportError:  # older jax: every axis is implicitly Auto
    AxisType = None

DATA_AXIS = "data"
ENTITY_AXIS = "entity"
FEATURE_AXIS = "feature"


def make_mesh(
    axis_sizes: Optional[dict[str, int]] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a mesh; default is all devices on one ``"data"`` axis."""
    devices = list(devices) if devices is not None else jax.devices()
    if not axis_sizes:
        axis_sizes = {DATA_AXIS: len(devices)}
    names = tuple(axis_sizes)
    shape = tuple(axis_sizes[n] for n in names)
    n_needed = 1
    for s in shape:
        n_needed *= s
    if n_needed > len(devices):
        raise ValueError(f"mesh {axis_sizes} needs {n_needed} devices, have {len(devices)}")
    # Auto axis types: GSPMD propagates shardings; shard_map enters Manual
    # mode explicitly where we want hand-placed psums (JAX >= 0.9 defaults
    # to Explicit mode, which demands a global set_mesh context instead).
    if AxisType is None:  # pre-AxisType jax: Auto is the only behavior
        return jax.make_mesh(shape, names, devices=devices[:n_needed])
    return jax.make_mesh(shape, names, axis_types=(AxisType.Auto,) * len(names),
                         devices=devices[:n_needed])


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def data_sharded(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Leading-dim sharding over ``axis``."""
    return NamedSharding(mesh, PartitionSpec(axis))
