"""Distributed GLM objective: ``shard_map`` + ``psum`` over the data axis.

TPU-native replacement for the reference's
``photon-api/.../function/glm/DistributedGLMLossFunction.scala``: where the
reference broadcasts the coefficient vector to executors and reduces
per-partition aggregator arrays through ``RDD.treeAggregate`` (depth 1–2 tree
over netty RPC), here every chip computes its shard's (value, gradient) with
the SAME pure math as the single-chip path and one ``lax.psum`` over ICI
produces the global result — inside the compiled optimizer loop, so a whole
L-BFGS/TRON run is ONE device program with no host round-trips per iteration
(the reference pays a broadcast + treeAggregate per iteration).

Data layout: :func:`shard_glm_data` splits samples into per-device blocks on
host (padding the tail block with weight-0 rows, which contribute exactly
zero), stacks them on a leading mesh-axis dimension, and the objective's
``shard_map`` consumes one block per device. The L2 term is added OUTSIDE the
psum so it is counted once globally, not once per shard.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from photon_ml_tpu.compat import shard_map

from photon_ml_tpu.ops.design import ChunkedSparseDesign, CsrDesign, DenseDesign
from photon_ml_tpu.ops.objective import GLMData, GLMObjective
from photon_ml_tpu.parallel.mesh import DATA_AXIS, FEATURE_AXIS

Array = jax.Array


def _unstack(tree):
    """Drop the per-device leading axis inside a shard_map body."""
    return jax.tree.map(lambda x: x[0], tree)


def _l2_value_and_grad(objective: GLMObjective, w: Array, l2):
    wr = w if objective.reg_mask is None else w * objective.reg_mask
    l2 = jnp.asarray(l2, w.dtype)
    return 0.5 * l2 * jnp.vdot(wr, wr), l2 * wr


@dataclasses.dataclass(frozen=True)
class ShardBudget:
    """Shared shape budget for building agreeing shard layouts on
    independent hosts (SPMD demands identical leaf shapes on every process;
    a host with more rows or denser data would otherwise stack taller or
    wider blocks). Sparse-only fields are 0 for dense designs ("local
    choice"). Computed per-host via :func:`shard_budget`, max-reduced by
    :func:`photon_ml_tpu.parallel.multihost.allreduce_shard_budget`."""

    rows_per_shard: int
    row_chunk: int = 0
    col_chunk: int = 0
    row_chunks: int = 0  # padded per-block row-major chunk count (mr)
    col_chunks: int = 0  # padded per-block col-major chunk count (mc)

    def to_array(self) -> np.ndarray:
        return np.array([self.rows_per_shard, self.row_chunk, self.col_chunk,
                         self.row_chunks, self.col_chunks], np.int64)

    @staticmethod
    def from_array(a) -> "ShardBudget":
        a = np.asarray(a, np.int64)
        return ShardBudget(*(int(v) for v in a))


def shard_budget(sharded: GLMData) -> ShardBudget:
    """Read back the shape budget a stacked layout was built with, so hosts
    can compare (and max-reduce) theirs before a multi-host feed."""
    per = int(sharded.labels.shape[1])
    design = sharded.design
    if isinstance(design, ChunkedSparseDesign):
        return ShardBudget(
            rows_per_shard=per,
            row_chunk=int(design.rvals.shape[2]),
            col_chunk=int(design.cvals.shape[2]),
            row_chunks=int(design.rvals.shape[1]),
            col_chunks=int(design.cvals.shape[1]))
    return ShardBudget(rows_per_shard=per)


def shard_glm_data(data: GLMData, n_shards: int, *, device_put_mesh: Optional[Mesh] = None,
                   axis: str = DATA_AXIS,
                   budget: Optional[ShardBudget] = None,
                   host_stage: bool = False) -> GLMData:
    """Split a host-resident :class:`GLMData` into ``n_shards`` equal blocks.

    Returns a GLMData whose leaves have a leading ``n_shards`` dimension
    (block i = device i's shard). Sample counts are padded up to a multiple of
    ``n_shards`` with zero-weight rows; a sparse design's nnz budget is padded
    to the max per-block nnz. If ``device_put_mesh`` is given, leaves are
    placed with the leading dim sharded over ``axis`` so each block lives on
    its device (the host→device feed the reference does via Spark partition
    locality). ``host_stage=True`` keeps the leaves as numpy arrays — for
    feeds that do their own host→device transfer (the multihost path), so
    the full local dataset never detours through one device's HBM.
    """
    _j = np.ascontiguousarray if host_stage else jnp.asarray
    n = data.n_samples
    per = math.ceil(n / n_shards)
    if budget is not None:
        if budget.rows_per_shard < per:
            raise ValueError(
                f"budget.rows_per_shard={budget.rows_per_shard} cannot hold "
                f"{n} rows over {n_shards} shards (need ≥ {per})")
        per = budget.rows_per_shard
    n_pad = per * n_shards

    labels = np.zeros((n_pad,), np.asarray(data.labels).dtype)
    labels[:n] = np.asarray(data.labels)
    offsets = np.zeros((n_pad,), np.asarray(data.offsets).dtype)
    offsets[:n] = np.asarray(data.offsets)
    weights = np.zeros((n_pad,), np.asarray(data.weights).dtype)
    weights[:n] = np.asarray(data.weights)

    design = data.design
    from photon_ml_tpu.game.factored import FactoredDesign

    if isinstance(design, DenseDesign):
        x = np.asarray(design.x)
        xp = np.zeros((n_pad, x.shape[1]), x.dtype)
        xp[:n] = x
        sharded_design = DenseDesign(x=_j(xp.reshape(n_shards, per, x.shape[1])))
    elif isinstance(design, FactoredDesign):
        # the factored projection solve's implicit Khatri-Rao design: both
        # row arrays (raw features x, per-sample latents v) stack like a
        # dense design; matvec/rmatvec work per block unchanged
        x = np.asarray(design.x)
        v = np.asarray(design.v)
        xp = np.zeros((n_pad, x.shape[1]), x.dtype)
        xp[:n] = x
        vp = np.zeros((n_pad, v.shape[1]), v.dtype)
        vp[:n] = v
        sharded_design = FactoredDesign(
            x=_j(xp.reshape(n_shards, per, x.shape[1])),
            v=_j(vp.reshape(n_shards, per, v.shape[1])),
            latent_dim=design.latent_dim)
    elif isinstance(design, (CsrDesign, ChunkedSparseDesign)):
        if isinstance(design, ChunkedSparseDesign):
            raise TypeError(
                "shard_glm_data splits by row from COO; pass the host "
                "CsrDesign and the sharded layout is built chunked per block")
        rows = np.asarray(design.rows)
        cols = np.asarray(design.cols)
        vals = np.asarray(design.values)
        block_of = rows // per
        local_row = rows % per
        # per-block chunked layouts (ChunkedSparseDesign: the dual
        # gather+partial-sum form that replaces the big scatters), with
        # common chunk widths and chunk counts padded to the block max so
        # the blocks stack into one leading-device-dim pytree
        live = vals != 0
        # per-BLOCK key counts pick the width: blocks partition rows, so
        # global per-row counts equal per-block ones; columns appear in
        # every block, so count (block, col) pairs — merging across blocks
        # would inflate the medians (and the padding) ~n_shards x
        if budget is not None and budget.row_chunk and budget.col_chunk:
            row_chunk, col_chunk = budget.row_chunk, budget.col_chunk
        else:
            row_chunk = ChunkedSparseDesign.default_chunk(
                np.bincount(rows[live], minlength=n))
            # unique, not bincount: a dense (n_shards * n_cols) count array
            # would be tens of GB in the wide-sparse regime this path
            # serves; default_chunk only looks at nonzero counts anyway
            _, blockcol_counts = np.unique(
                block_of[live] * np.int64(design.n_cols) + cols[live],
                return_counts=True)
            col_chunk = ChunkedSparseDesign.default_chunk(blockcol_counts)
        lays = []
        for b in range(n_shards):
            sel = block_of == b
            lays.append(ChunkedSparseDesign.layout_numpy(
                local_row[sel], cols[sel], vals[sel],
                row_chunk=row_chunk, col_chunk=col_chunk))
        mr = max(lay["rrow"].shape[0] for lay in lays)
        mc = max(lay["ccol"].shape[0] for lay in lays)
        if budget is not None and budget.row_chunks and budget.col_chunks:
            if budget.row_chunks < mr or budget.col_chunks < mc:
                raise ValueError(
                    f"budget chunk counts (mr={budget.row_chunks}, "
                    f"mc={budget.col_chunks}) below this host's layout "
                    f"(mr={mr}, mc={mc}) — compute the budget from the "
                    f"same data")
            mr, mc = budget.row_chunks, budget.col_chunks

        def pad_stack(key, m, fill):
            outs = []
            for lay in lays:
                a = lay[key]
                pad_n = m - a.shape[0]
                if pad_n:
                    pad_block = np.full((pad_n,) + a.shape[1:], fill, a.dtype)
                    a = np.concatenate([a, pad_block])
                outs.append(a)
            return _j(np.stack(outs))

        sharded_design = ChunkedSparseDesign(
            rvals=pad_stack("rvals", mr, 0.0),
            rcols=pad_stack("rcols", mr, 0),
            # pad segment ids with the LAST id so sortedness holds; padded
            # chunks carry value 0 and contribute nothing
            rrow=pad_stack("rrow", mr, max(per - 1, 0)),
            cvals=pad_stack("cvals", mc, 0.0),
            crows=pad_stack("crows", mc, 0),
            ccol=pad_stack("ccol", mc, max(design.n_cols - 1, 0)),
            n_rows=per, n_cols=design.n_cols)
    else:
        raise TypeError(type(design))

    out = GLMData(
        design=sharded_design,
        labels=_j(labels.reshape(n_shards, per)),
        offsets=_j(offsets.reshape(n_shards, per)),
        weights=_j(weights.reshape(n_shards, per)),
    )
    if device_put_mesh is not None:
        sharding = NamedSharding(device_put_mesh, P(axis))
        out = jax.tree.map(lambda x: jax.device_put(x, sharding), out)
    return out


@dataclasses.dataclass(frozen=True)
class DistributedGLMObjective:
    """The fixed-effect objective over a sharded dataset.

    Drop-in for :class:`GLMObjective` (same value / value_and_grad / hvp
    signatures) but ``data`` must be the stacked per-device layout from
    :func:`shard_glm_data`. Feed its closures straight into
    ``minimize_lbfgs/owlqn/tron`` — the optimizers don't know they're driving
    a pod (the reference needed a separate Distributed vs SingleNode class
    hierarchy for this).
    """

    objective: GLMObjective
    mesh: Mesh
    axis: str = DATA_AXIS

    def _global_value_fn(self, blk, l2):
        """Inside a shard_map body: the GLOBAL objective as a function of w.

        The ``psum`` sits INSIDE the differentiated function, so shard_map's
        varying-axis-aware autodiff derives the correct global gradient and
        Hvp (an explicit psum on an inner-autodiff gradient would double-count
        — the cotangent of the replicated ``w`` is already all-reduced). The
        L2 term is added after the psum so it counts once, not per shard.
        """
        data = _unstack(blk)

        def global_value(wv):
            local = self.objective.value(wv, data, 0.0)
            return jax.lax.psum(local, self.axis) + self.objective._l2_term(wv, l2)

        return global_value

    def value_and_grad(self, w: Array, sharded: GLMData, l2=0.0):
        def body(wv, blk):
            # loss-only per shard (closed-form fast path inside), explicit
            # psums: the global gradient is the sum of shard gradients; L2
            # added after so it counts once
            val, g = self.objective.value_and_grad(wv, _unstack(blk), 0.0)
            val = jax.lax.psum(val, self.axis)
            g = jax.lax.psum(g, self.axis)
            l2_val, l2_grad = _l2_value_and_grad(self.objective, wv, l2)
            return val + l2_val, g + l2_grad

        return shard_map(body, mesh=self.mesh,
                         in_specs=(P(), P(self.axis)), out_specs=(P(), P()))(w, sharded)

    def value(self, w: Array, sharded: GLMData, l2=0.0):
        def body(wv, blk):
            return self._global_value_fn(blk, l2)(wv)

        return shard_map(body, mesh=self.mesh,
                         in_specs=(P(), P(self.axis)), out_specs=P())(w, sharded)

    def grad(self, w: Array, sharded: GLMData, l2=0.0):
        return self.value_and_grad(w, sharded, l2)[1]

    def hvp(self, w: Array, v: Array, sharded: GLMData, l2=0.0):
        # closed form per shard for every normalization (GLMObjective.hvp
        # expands the affine transform by chain rule; autodiff's gather
        # backward would re-create the per-nnz scatter the chunked sparse
        # layout exists to avoid), psum'd; L2 curvature added once outside
        def body(wv, tangent, blk):
            local = self.objective.hvp(wv, tangent, _unstack(blk), 0.0)
            return jax.lax.psum(local, self.axis)

        hv = shard_map(body, mesh=self.mesh,
                       in_specs=(P(), P(), P(self.axis)),
                       out_specs=P())(w, v, sharded)
        return hv + jnp.asarray(self.objective.reg_curvature(l2),
                                w.dtype) * v

    # NOTE no hvp_operator here, deliberately: single-chip measurement
    # showed force-hoisting the plain closed form out of TRON's CG loop is
    # SLOWER than XLA's own loop-invariant code motion (1280 ms vs 987 ms
    # on the bench shape), so distributed TRON stays on the per-call hvp
    # above. That per-call hvp still gets the fused one-pass Pallas Hvp
    # kernel INSIDE the shard_map body when the wrapped objective is
    # fused-eligible — validated on-chip through a mesh: dp TRON 1295 ms →
    # 675 ms (1.9x), identical objective value (XLA hoists the d2 pass out
    # of the CG loop; the kernel halves each product's design traffic).

    def margins(self, w: Array, sharded: GLMData) -> Array:
        """Per-sample margins in the stacked (n_shards, per) layout."""
        def local(wv, blk):
            return self.objective.margins(wv, _unstack(blk))[None, :]

        return shard_map(local, mesh=self.mesh,
                         in_specs=(P(), P(self.axis)), out_specs=P(self.axis))(w, sharded)

    # --- second-order contractions (variance computation) ------------------
    def _psum_of_local(self, fn_name: str, w: Array, sharded: GLMData):
        """psum of a per-shard l2-free contraction; L2 added once outside."""
        def body(wv, blk):
            local = getattr(self.objective, fn_name)(wv, _unstack(blk), 0.0)
            return jax.lax.psum(local, self.axis)

        return shard_map(body, mesh=self.mesh,
                         in_specs=(P(), P(self.axis)), out_specs=P())(w, sharded)

    def hessian_diagonal(self, w: Array, sharded: GLMData, l2=0.0) -> Array:
        """Distributed VarianceComputationType SIMPLE (the reference's
        ``HessianDiagonalAggregator`` treeAggregate)."""
        diag = self._psum_of_local("hessian_diagonal", w, sharded)
        return diag + self.objective.reg_curvature(l2)

    def hessian_matrix(self, w: Array, sharded: GLMData, l2=0.0) -> Array:
        """Distributed VarianceComputationType FULL
        (``HessianMatrixAggregator``)."""
        h = self._psum_of_local("hessian_matrix", w, sharded)
        d = w.shape[0]
        return h + jnp.diag(jnp.broadcast_to(
            jnp.asarray(self.objective.reg_curvature(l2)), (d,)))


# ---------------------------------------------------------------------------
# Feature-dimension (tensor-parallel) sharding
# ---------------------------------------------------------------------------


def shard_glm_data_features(data: GLMData, n_shards: int, *,
                            device_put_mesh: Optional[Mesh] = None,
                            axis: str = FEATURE_AXIS) -> tuple[GLMData, int]:
    """Split a :class:`GLMData`'s FEATURE dimension into ``n_shards`` blocks.

    The TP analog of :func:`shard_glm_data` (SURVEY.md §2.10 "TP" row — no
    reference equivalent: breeze held the whole coefficient vector on the
    Spark driver; sharding the feature dim is what lets a fixed-effect model
    outgrow one chip's HBM). Returns ``(sharded, d_pad)`` where ``d_pad`` is
    the feature dim padded to a multiple of ``n_shards``; solve in the padded
    dim (padded columns are all-zero → their coefficients stay exactly 0) and
    slice the model back to ``data.dim``.

    Layouts: dense → ``x`` padded to ``(n, d_pad)``, columns split by the
    mesh axis at shard_map time; sparse → nnz triplets partitioned by column
    block into a stacked ``(n_shards, budget)`` layout with block-local
    column ids.
    """
    d = data.dim
    per = math.ceil(d / n_shards)
    d_pad = per * n_shards

    design = data.design
    if isinstance(design, DenseDesign):
        x = np.asarray(design.x)
        xp = np.zeros((x.shape[0], d_pad), x.dtype)
        xp[:, :d] = x
        sharded_design = DenseDesign(x=jnp.asarray(xp))
        spec = P(None, axis)
    elif isinstance(design, CsrDesign):
        rows = np.asarray(design.rows)
        cols = np.asarray(design.cols)
        vals = np.asarray(design.values)
        block_of = cols // per
        local_col = cols % per
        counts = np.bincount(block_of, minlength=n_shards)
        budget = int(counts.max()) if counts.size else 0
        r = np.zeros((n_shards, budget), np.int32)
        c = np.zeros((n_shards, budget), np.int32)
        v = np.zeros((n_shards, budget), vals.dtype)
        for b in range(n_shards):
            sel = block_of == b
            k = int(counts[b])
            r[b, :k] = rows[sel]
            c[b, :k] = local_col[sel]
            v[b, :k] = vals[sel]
        sharded_design = CsrDesign(
            rows=jnp.asarray(r), cols=jnp.asarray(c), values=jnp.asarray(v),
            n_rows=design.n_rows, n_cols=per)
        spec = P(axis)
    else:
        raise TypeError(type(design))

    out = GLMData(design=sharded_design, labels=jnp.asarray(data.labels),
                  offsets=jnp.asarray(data.offsets),
                  weights=jnp.asarray(data.weights))
    if device_put_mesh is not None:
        dspec = {"design": spec, "rest": P()}
        out = GLMData(
            design=jax.tree.map(
                lambda a: jax.device_put(
                    a, NamedSharding(device_put_mesh, dspec["design"])),
                sharded_design),
            labels=jax.device_put(out.labels, NamedSharding(device_put_mesh, P())),
            offsets=jax.device_put(out.offsets, NamedSharding(device_put_mesh, P())),
            weights=jax.device_put(out.weights, NamedSharding(device_put_mesh, P())),
        )
    return out, d_pad


@dataclasses.dataclass(frozen=True)
class FeatureShardedGLMObjective:
    """Fixed-effect objective with the COEFFICIENT dimension sharded (TP).

    Drop-in for :class:`GLMObjective` over data from
    :func:`shard_glm_data_features`: ``w`` stays replicated from the
    optimizer's point of view (so L-BFGS/OWLQN/TRON run unchanged), but each
    device touches only its feature block — one ``psum`` of the partial
    margins over the ``feature`` axis per evaluation, one ``psum`` to
    assemble the (block-disjoint) gradient. Identity normalization only (the
    normalization reparameterization is a per-feature transform; fold it
    into the data before sharding).
    """

    objective: GLMObjective
    mesh: Mesh
    axis: str = FEATURE_AXIS

    def __post_init__(self):
        if not self.objective.normalization.is_identity:
            raise ValueError(
                "feature-sharded objective requires identity normalization; "
                "pre-transform the design instead")

    # --- per-device helpers -------------------------------------------------
    # Derivatives are CLOSED-FORM here (g = X'(weight*dl), Hv = X'(d2*weight*Xv))
    # rather than autodiff-through-psum: transposing a psum whose operand the
    # varying-axis system cannot prove device-varying re-psums the (replicated)
    # cotangent — an axis-size-fold overcount. The hand-written form needs one
    # margin psum forward and one gradient psum back, nothing subtle.

    def _local(self, blk: GLMData) -> GLMData:
        return blk if isinstance(blk.design, DenseDesign) else \
            dataclasses.replace(blk, design=_unstack(blk.design))

    def _w_local(self, data: GLMData, w_full: Array) -> Array:
        per = data.design.dim
        idx = jax.lax.axis_index(self.axis)
        return jax.lax.dynamic_slice_in_dim(w_full, idx * per, per)

    def _margins_local(self, data: GLMData, w_full: Array) -> Array:
        partial = data.design.matvec(self._w_local(data, w_full))
        return jax.lax.psum(partial, self.axis) + data.offsets

    def _scatter_block(self, data: GLMData, g_local: Array, d_full: int) -> Array:
        """Place this device's block gradient at its offset in a (d_full,)
        zero vector; the caller's psum then assembles disjoint blocks."""
        per = data.design.dim
        idx = jax.lax.axis_index(self.axis)
        z = jnp.zeros((d_full,), g_local.dtype)
        return jax.lax.dynamic_update_slice_in_dim(z, g_local, idx * per, 0)

    def _masked(self, w: Array) -> Array:
        mask = self.objective.reg_mask
        if mask is None:
            return w
        if mask.shape[0] < w.shape[0]:  # pad mask to the padded dim
            mask = jnp.pad(mask, (0, w.shape[0] - mask.shape[0]))
        return w * mask

    def _l2_value(self, w: Array, l2) -> Array:
        wr = self._masked(w)
        return 0.5 * jnp.asarray(l2, w.dtype) * jnp.vdot(wr, wr)

    def _l2_parts(self, w: Array, l2):
        wr = self._masked(w)
        l2 = jnp.asarray(l2, w.dtype)
        return 0.5 * l2 * jnp.vdot(wr, wr), l2 * wr

    def _design_spec(self, sharded: GLMData):
        if isinstance(sharded.design, DenseDesign):
            return DenseDesign(x=P(None, self.axis))
        return CsrDesign(rows=P(self.axis), cols=P(self.axis),
                         values=P(self.axis),
                         n_rows=sharded.design.n_rows,
                         n_cols=sharded.design.n_cols)

    def _data_spec(self, sharded: GLMData) -> GLMData:
        return GLMData(design=self._design_spec(sharded), labels=P(),
                       offsets=P(), weights=P())

    def value_and_grad(self, w: Array, sharded: GLMData, l2=0.0):
        d_full = w.shape[0]

        def body(wv, blk):
            data = self._local(blk)
            m = self._margins_local(data, wv)
            live = data.weights > 0
            m_safe = jnp.where(live, m, 0.0)
            val = jnp.sum(jnp.where(
                live, data.weights * self.objective.loss.loss(m_safe, data.labels),
                0.0))
            dl = jnp.where(live,
                           data.weights * self.objective.loss.d1(m_safe, data.labels),
                           0.0)
            g_local = data.design.rmatvec(dl.astype(wv.dtype))
            g = jax.lax.psum(self._scatter_block(data, g_local, d_full), self.axis)
            return val, g

        val, g = shard_map(body, mesh=self.mesh,
                           in_specs=(P(), self._data_spec(sharded)),
                           out_specs=(P(), P()), check_vma=False)(w, sharded)
        l2_val, l2_grad = self._l2_parts(w, l2)
        return val + l2_val, g + l2_grad

    def value(self, w: Array, sharded: GLMData, l2=0.0):
        def body(wv, blk):
            data = self._local(blk)
            m = self._margins_local(data, wv)
            live = data.weights > 0
            m_safe = jnp.where(live, m, 0.0)
            return jnp.sum(jnp.where(
                live, data.weights * self.objective.loss.loss(m_safe, data.labels),
                0.0))

        val = shard_map(body, mesh=self.mesh,
                        in_specs=(P(), self._data_spec(sharded)),
                        out_specs=P(), check_vma=False)(w, sharded)
        return val + self._l2_value(w, l2)

    def grad(self, w: Array, sharded: GLMData, l2=0.0):
        return self.value_and_grad(w, sharded, l2)[1]

    def hvp(self, w: Array, v: Array, sharded: GLMData, l2=0.0):
        d_full = w.shape[0]

        def body(wv, tangent, blk):
            data = self._local(blk)
            m = self._margins_local(data, wv)
            xv = self._margins_local(
                dataclasses.replace(data, offsets=jnp.zeros_like(data.offsets)),
                tangent)
            live = data.weights > 0
            m_safe = jnp.where(live, m, 0.0)
            d2 = jnp.where(live,
                           data.weights * self.objective.loss.d2(m_safe, data.labels),
                           0.0)
            hv_local = data.design.rmatvec((d2 * xv).astype(wv.dtype))
            return jax.lax.psum(
                self._scatter_block(data, hv_local, d_full), self.axis)

        hv = shard_map(body, mesh=self.mesh,
                       in_specs=(P(), P(), self._data_spec(sharded)),
                       out_specs=P(), check_vma=False)(w, v, sharded)
        return hv + jnp.asarray(l2, w.dtype) * self._masked(v)

    def margins(self, w: Array, sharded: GLMData) -> Array:
        def body(wv, blk):
            data = self._local(blk)
            return self._margins_local(data, wv)

        return shard_map(body, mesh=self.mesh,
                         in_specs=(P(), self._data_spec(sharded)),
                         out_specs=P(), check_vma=False)(w, sharded)
