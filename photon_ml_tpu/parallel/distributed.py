"""Distributed GLM objective: ``shard_map`` + ``psum`` over the data axis.

TPU-native replacement for the reference's
``photon-api/.../function/glm/DistributedGLMLossFunction.scala``: where the
reference broadcasts the coefficient vector to executors and reduces
per-partition aggregator arrays through ``RDD.treeAggregate`` (depth 1–2 tree
over netty RPC), here every chip computes its shard's (value, gradient) with
the SAME pure math as the single-chip path and one ``lax.psum`` over ICI
produces the global result — inside the compiled optimizer loop, so a whole
L-BFGS/TRON run is ONE device program with no host round-trips per iteration
(the reference pays a broadcast + treeAggregate per iteration).

Data layout: :func:`shard_glm_data` splits samples into per-device blocks on
host (padding the tail block with weight-0 rows, which contribute exactly
zero), stacks them on a leading mesh-axis dimension, and the objective's
``shard_map`` consumes one block per device. The L2 term is added OUTSIDE the
psum so it is counted once globally, not once per shard.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from photon_ml_tpu.ops.design import CsrDesign, DenseDesign
from photon_ml_tpu.ops.objective import GLMData, GLMObjective
from photon_ml_tpu.parallel.mesh import DATA_AXIS

Array = jax.Array


def _unstack(tree):
    """Drop the per-device leading axis inside a shard_map body."""
    return jax.tree.map(lambda x: x[0], tree)


def shard_glm_data(data: GLMData, n_shards: int, *, device_put_mesh: Optional[Mesh] = None,
                   axis: str = DATA_AXIS) -> GLMData:
    """Split a host-resident :class:`GLMData` into ``n_shards`` equal blocks.

    Returns a GLMData whose leaves have a leading ``n_shards`` dimension
    (block i = device i's shard). Sample counts are padded up to a multiple of
    ``n_shards`` with zero-weight rows; a sparse design's nnz budget is padded
    to the max per-block nnz. If ``device_put_mesh`` is given, leaves are
    placed with the leading dim sharded over ``axis`` so each block lives on
    its device (the host→device feed the reference does via Spark partition
    locality).
    """
    n = data.n_samples
    per = math.ceil(n / n_shards)
    n_pad = per * n_shards

    labels = np.zeros((n_pad,), np.asarray(data.labels).dtype)
    labels[:n] = np.asarray(data.labels)
    offsets = np.zeros((n_pad,), np.asarray(data.offsets).dtype)
    offsets[:n] = np.asarray(data.offsets)
    weights = np.zeros((n_pad,), np.asarray(data.weights).dtype)
    weights[:n] = np.asarray(data.weights)

    design = data.design
    if isinstance(design, DenseDesign):
        x = np.asarray(design.x)
        xp = np.zeros((n_pad, x.shape[1]), x.dtype)
        xp[:n] = x
        sharded_design = DenseDesign(x=jnp.asarray(xp.reshape(n_shards, per, x.shape[1])))
    elif isinstance(design, CsrDesign):
        rows = np.asarray(design.rows)
        cols = np.asarray(design.cols)
        vals = np.asarray(design.values)
        block_of = rows // per
        local_row = rows % per
        counts = np.bincount(block_of, minlength=n_shards)
        budget = int(counts.max()) if counts.size else 0
        r = np.zeros((n_shards, budget), np.int32)
        c = np.zeros((n_shards, budget), np.int32)
        v = np.zeros((n_shards, budget), vals.dtype)
        for b in range(n_shards):
            sel = block_of == b
            k = int(counts[b])
            r[b, :k] = local_row[sel]
            c[b, :k] = cols[sel]
            v[b, :k] = vals[sel]
        sharded_design = CsrDesign(
            rows=jnp.asarray(r), cols=jnp.asarray(c), values=jnp.asarray(v),
            n_rows=per, n_cols=design.n_cols)
    else:
        raise TypeError(type(design))

    out = GLMData(
        design=sharded_design,
        labels=jnp.asarray(labels.reshape(n_shards, per)),
        offsets=jnp.asarray(offsets.reshape(n_shards, per)),
        weights=jnp.asarray(weights.reshape(n_shards, per)),
    )
    if device_put_mesh is not None:
        sharding = NamedSharding(device_put_mesh, P(axis))
        out = jax.tree.map(lambda x: jax.device_put(x, sharding), out)
    return out


@dataclasses.dataclass(frozen=True)
class DistributedGLMObjective:
    """The fixed-effect objective over a sharded dataset.

    Drop-in for :class:`GLMObjective` (same value / value_and_grad / hvp
    signatures) but ``data`` must be the stacked per-device layout from
    :func:`shard_glm_data`. Feed its closures straight into
    ``minimize_lbfgs/owlqn/tron`` — the optimizers don't know they're driving
    a pod (the reference needed a separate Distributed vs SingleNode class
    hierarchy for this).
    """

    objective: GLMObjective
    mesh: Mesh
    axis: str = DATA_AXIS

    def _global_value_fn(self, blk, l2):
        """Inside a shard_map body: the GLOBAL objective as a function of w.

        The ``psum`` sits INSIDE the differentiated function, so shard_map's
        varying-axis-aware autodiff derives the correct global gradient and
        Hvp (an explicit psum on an inner-autodiff gradient would double-count
        — the cotangent of the replicated ``w`` is already all-reduced). The
        L2 term is added after the psum so it counts once, not per shard.
        """
        data = _unstack(blk)

        def global_value(wv):
            local = self.objective.value(wv, data, 0.0)
            return jax.lax.psum(local, self.axis) + self.objective._l2_term(wv, l2)

        return global_value

    def value_and_grad(self, w: Array, sharded: GLMData, l2=0.0):
        def body(wv, blk):
            return jax.value_and_grad(self._global_value_fn(blk, l2))(wv)

        return shard_map(body, mesh=self.mesh,
                         in_specs=(P(), P(self.axis)), out_specs=(P(), P()))(w, sharded)

    def value(self, w: Array, sharded: GLMData, l2=0.0):
        def body(wv, blk):
            return self._global_value_fn(blk, l2)(wv)

        return shard_map(body, mesh=self.mesh,
                         in_specs=(P(), P(self.axis)), out_specs=P())(w, sharded)

    def grad(self, w: Array, sharded: GLMData, l2=0.0):
        return self.value_and_grad(w, sharded, l2)[1]

    def hvp(self, w: Array, v: Array, sharded: GLMData, l2=0.0):
        def body(wv, tangent, blk):
            g = jax.grad(self._global_value_fn(blk, l2))
            return jax.jvp(g, (wv,), (tangent,))[1]

        return shard_map(body, mesh=self.mesh,
                         in_specs=(P(), P(), P(self.axis)), out_specs=P())(w, v, sharded)

    def margins(self, w: Array, sharded: GLMData) -> Array:
        """Per-sample margins in the stacked (n_shards, per) layout."""
        def local(wv, blk):
            return self.objective.margins(wv, _unstack(blk))[None, :]

        return shard_map(local, mesh=self.mesh,
                         in_specs=(P(), P(self.axis)), out_specs=P(self.axis))(w, sharded)
