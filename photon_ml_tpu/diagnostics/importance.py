"""Feature-importance diagnostics.

Re-design of the reference's ``photon-client/.../diagnostics/featureimportance/``
(``ExpectedMagnitudeFeatureImportanceDiagnostic`` and
``VarianceFeatureImportanceDiagnostic``): rank features by the expected
contribution of each coefficient to the margin —

- expected magnitude: ``|w_j| * E[|x_j|]``, with ``E|x_j|`` bounded from
  summary statistics as ``nnz_j/n * maxMagnitude_j`` (a stats-only pass cannot
  recover the exact mean absolute value), and
- variance: ``|w_j| * std(x_j)`` (how much margin variance the feature drives).

Pure NumPy over the already-computed :class:`FeatureDataStatistics`; no device
work needed — this is a report-time diagnostic, not a training-path op.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from photon_ml_tpu.stat import FeatureDataStatistics


@dataclasses.dataclass(frozen=True)
class FeatureImportanceReport:
    """Ranked importance table (descending)."""

    kind: str                      # "EXPECTED_MAGNITUDE" | "VARIANCE"
    ranked_indices: np.ndarray     # (d,) feature indices, most important first
    importance: np.ndarray         # (d,) scores aligned with ranked_indices
    names: Optional[list[str]] = None  # aligned with ranked_indices when given

    def top(self, k: int) -> list[tuple[str, float]]:
        k = min(k, len(self.ranked_indices))
        names = (self.names if self.names is not None
                 else [str(i) for i in self.ranked_indices])
        return [(names[i], float(self.importance[i])) for i in range(k)]


def _rank(kind: str, scores: np.ndarray, names: Optional[Sequence[str]]
          ) -> FeatureImportanceReport:
    order = np.argsort(-scores, kind="stable")
    return FeatureImportanceReport(
        kind=kind,
        ranked_indices=order,
        importance=scores[order],
        names=[names[i] for i in order] if names is not None else None,
    )


def expected_magnitude_importance(
    coefficients: np.ndarray,
    stats: FeatureDataStatistics,
    names: Optional[Sequence[str]] = None,
) -> FeatureImportanceReport:
    """``|w_j| * E[|x_j|]`` with ``E|x_j|`` bounded from summary statistics
    by ``nnz/n * maxMagnitude`` (tight for indicator features, the dominant
    kind in Photon-ML's name-term universe) — the stats-only estimate the
    reference's expected-magnitude diagnostic uses.
    """
    w = np.abs(np.asarray(coefficients, np.float64))
    n = max(stats.count, 1)
    exp_abs = stats.num_nonzeros / n * stats.max_magnitude
    return _rank("EXPECTED_MAGNITUDE", w * exp_abs, names)


def variance_importance(
    coefficients: np.ndarray,
    stats: FeatureDataStatistics,
    names: Optional[Sequence[str]] = None,
) -> FeatureImportanceReport:
    """``|w_j| * std(x_j)`` — margin-variance contribution per feature."""
    w = np.abs(np.asarray(coefficients, np.float64))
    return _rank("VARIANCE", w * np.sqrt(np.maximum(stats.variance, 0.0)), names)
