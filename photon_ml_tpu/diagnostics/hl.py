"""Hosmer–Lemeshow goodness-of-fit (calibration) test for logistic models.

Re-design of the reference's ``photon-client/.../diagnostics/hl/``
(``HosmerLemeshowDiagnostic``): bin validation samples into G equal-count
bins by predicted probability, compare observed vs expected positives per
bin, and report the chi-squared statistic with ``G - 2`` degrees of freedom.

TPU shape: fixed-shape quantile binning (``searchsorted`` on G-quantile
cutpoints) + segment sums; the p-value is the regularized upper incomplete
gamma function, all inside one jittable function.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class HosmerLemeshowReport:
    """Per-bin calibration table plus the aggregate test."""

    bin_counts: np.ndarray          # (G,) weighted sample count per bin
    observed_positives: np.ndarray  # (G,) weighted positive count
    expected_positives: np.ndarray  # (G,) sum of predicted probabilities
    mean_predicted: np.ndarray      # (G,) mean predicted prob per bin
    chi_square: float
    degrees_of_freedom: int
    p_value: float

    @property
    def n_bins(self) -> int:
        return int(self.bin_counts.shape[0])

    def well_calibrated(self, significance: float = 0.05) -> bool:
        """True when the test fails to reject calibration at ``significance``."""
        return self.p_value > significance


def _hl_core(probs: Array, labels: Array, weights: Array, n_bins: int):
    live = weights > 0
    w = jnp.where(live, weights, 0.0)
    p = jnp.clip(probs, 1e-7, 1.0 - 1e-7)

    # equal-count cutpoints from the live-sample quantiles; padding rows bin
    # by their raw probability but contribute nothing — their weight is 0 in
    # every segment sum. +inf (not NaN) sentinel for dead rows so the flow
    # stays jax_debug_nans-clean: sort floats them to the top and the
    # quantile positions are computed over the live count only.
    qs = jnp.linspace(0.0, 1.0, n_bins + 1)[1:-1]
    p_sorted = jnp.sort(jnp.where(live, p, jnp.inf))
    n_live = jnp.sum(live.astype(jnp.int32))
    pos = jnp.clip((qs * (n_live - 1)).astype(jnp.int32), 0,
                   jnp.maximum(n_live - 1, 0))
    cuts = p_sorted[pos]
    bins = jnp.searchsorted(cuts, p, side="right")

    counts = jax.ops.segment_sum(w, bins, num_segments=n_bins)
    obs = jax.ops.segment_sum(w * labels, bins, num_segments=n_bins)
    exp = jax.ops.segment_sum(w * p, bins, num_segments=n_bins)
    mean_p = jnp.where(counts > 0, exp / jnp.maximum(counts, 1e-30), 0.0)

    # chi^2 over both outcome cells; empty bins contribute 0
    exp_neg = counts - exp
    safe = counts > 0
    t1 = jnp.where(safe, (obs - exp) ** 2 / jnp.maximum(exp, 1e-10), 0.0)
    t0 = jnp.where(safe, ((counts - obs) - exp_neg) ** 2
                   / jnp.maximum(exp_neg, 1e-10), 0.0)
    chi2 = jnp.sum(t1 + t0)
    return counts, obs, exp, mean_p, chi2


def hosmer_lemeshow(probs, labels, weights=None, n_bins: int = 10
                    ) -> HosmerLemeshowReport:
    """Run the HL test on predicted probabilities vs binary labels."""
    probs = jnp.asarray(probs)
    labels = jnp.asarray(labels, probs.dtype)
    weights = (jnp.ones_like(probs) if weights is None
               else jnp.asarray(weights, probs.dtype))
    counts, obs, exp, mean_p, chi2 = jax.jit(
        _hl_core, static_argnums=3)(probs, labels, weights, n_bins)

    dof = max(n_bins - 2, 1)
    # chi-square survival function: Q(dof/2, chi2/2)
    p_value = float(jax.scipy.special.gammaincc(
        jnp.asarray(dof / 2.0), jnp.asarray(float(chi2) / 2.0)))
    return HosmerLemeshowReport(
        bin_counts=np.asarray(counts),
        observed_positives=np.asarray(obs),
        expected_positives=np.asarray(exp),
        mean_predicted=np.asarray(mean_p),
        chi_square=float(chi2),
        degrees_of_freedom=dof,
        p_value=p_value,
    )
