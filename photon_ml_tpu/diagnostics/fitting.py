"""Fitting (learning-curve) diagnostic: metric vs training-set fraction.

Re-design of the reference's ``photon-client/.../diagnostics/fitting/``
(``FittingDiagnostic``): train on growing portions of the training data and
report the training and validation metric at each portion — the classic
over/under-fitting read (gap widening ⇒ variance problem; both flat and poor
⇒ bias problem).

TPU shape: a portion is a *weight mask* (first ``k`` samples keep their
weight, the rest get 0) — the design matrix is untouched, every portion
reuses ONE compiled solve, and all portions run as a single ``vmap`` batch.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.glm.problem import OptimizationProblem
from photon_ml_tpu.ops.objective import GLMData

Array = jax.Array

DEFAULT_PORTIONS = (0.25, 0.5, 0.75, 1.0)


@dataclasses.dataclass(frozen=True)
class FittingReport:
    """Aligned arrays over the swept portions."""

    portions: np.ndarray          # (P,) fraction of training data used
    train_objective: np.ndarray   # (P,) mean per-weight training loss
    validation_objective: np.ndarray  # (P,) mean per-weight validation loss
    coefficients: np.ndarray      # (P, d)

    def generalization_gap(self) -> np.ndarray:
        return self.validation_objective - self.train_objective


def fitting_curve(
    problem: OptimizationProblem,
    train: GLMData,
    validation: GLMData,
    w0: Array,
    lam=0.0,
    portions: Sequence[float] = DEFAULT_PORTIONS,
    key: Optional[Array] = None,
) -> FittingReport:
    """Train at each portion (vmapped) and evaluate the *unregularized* mean
    objective on the used-training subset and the full validation set.

    Samples are shuffled once (``key``) before taking prefixes so portions are
    i.i.d. subsets, as in the reference's random-split portions.
    """
    n = train.n_samples
    if key is None:
        key = jax.random.PRNGKey(7)
    # random sample order -> portion k = first ceil(p*n) shuffled positions
    rank = jnp.argsort(jax.random.uniform(key, (n,))).argsort()
    fractions = jnp.asarray(portions)
    keep = rank[None, :] < jnp.ceil(fractions[:, None] * n)  # (P, n)
    masked_weights = jnp.where(keep, train.weights[None, :], 0.0)

    obj = problem.objective

    def solve_one(weights: Array):
        sub = dataclasses.replace(train, weights=weights)
        w = problem.run(sub, w0, lam).w
        wsum = jnp.maximum(jnp.sum(weights), 1e-30)
        train_loss = obj.value(w, sub, 0.0) / wsum
        vsum = jnp.maximum(jnp.sum(validation.weights), 1e-30)
        val_loss = obj.value(w, validation, 0.0) / vsum
        return w, train_loss, val_loss

    ws, tr, va = jax.jit(jax.vmap(solve_one))(masked_weights)
    return FittingReport(
        portions=np.asarray(fractions, np.float64),
        train_objective=np.asarray(tr, np.float64),
        validation_objective=np.asarray(va, np.float64),
        coefficients=np.asarray(ws),
    )
