"""Bootstrap training diagnostic: coefficient confidence intervals.

Re-design of the reference's ``photon-client/.../diagnostics/bootstrap/``
(``BootstrapTrainingDiagnostic``): train B models on bootstrap resamples of
the training data and summarize the per-coefficient distribution (mean, std,
percentile confidence bounds, sign stability).

TPU shape: instead of materializing B resampled datasets (B gathers of the
design matrix), each replicate is a *multinomial reweighting* — counts
``c ~ Multinomial(n, 1/n)`` multiply the original sample weights, which is the
classical weighted bootstrap and exactly equivalent in the weighted-loss
objective. The design matrix is shared (broadcast) across replicates and the
whole B-replicate sweep is ONE ``vmap``-ped, jitted solve: the MXU sees a
batched matmul, HBM holds one copy of X.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.glm.problem import OptimizationProblem
from photon_ml_tpu.ops.objective import GLMData

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class BootstrapReport:
    """Per-coefficient bootstrap distribution summary.

    All arrays are ``(d,)`` except ``coefficients`` which is ``(B, d)``
    (kept so callers can compute further statistics).
    """

    coefficients: np.ndarray
    mean: np.ndarray
    std: np.ndarray
    ci_lower: np.ndarray
    ci_upper: np.ndarray
    #: fraction of replicates whose coefficient sign matches the point
    #: estimate's sign — the reference's "importance" notion of how stable
    #: each learned weight is under resampling.
    sign_stability: np.ndarray
    confidence_level: float
    n_replicates: int

    def zero_crossing(self) -> np.ndarray:
        """True where the CI straddles zero (coefficient not significant)."""
        return (self.ci_lower <= 0.0) & (self.ci_upper >= 0.0)


def bootstrap_weights(key: Array, base_weights: Array, n_replicates: int) -> Array:
    """(B, n) multinomial bootstrap reweighting of per-sample weights.

    Padding rows (weight 0) never receive counts: the multinomial draws over
    the live-sample probability simplex.
    """
    n = base_weights.shape[0]
    live = base_weights > 0
    logits = jnp.where(live, 0.0, -jnp.inf)
    # counts via binned categorical draws: n draws per replicate over the
    # live rows => counts ~ Multinomial(n, uniform-over-live). (When padding
    # is present the draw count is n, not n_live — n_live is traced and
    # cannot size the draw; the expected per-row count scales uniformly by
    # n/n_live, which leaves the bootstrap distribution's shape intact.)
    draws = jax.random.categorical(key, logits, shape=(n_replicates, n))
    counts = jax.vmap(lambda d: jnp.bincount(d, length=n))(draws)
    return counts.astype(base_weights.dtype) * base_weights


def bootstrap_coefficients(
    problem: OptimizationProblem,
    data: GLMData,
    w_point: Array,
    lam=0.0,
    n_replicates: int = 16,
    confidence_level: float = 0.95,
    key: Optional[Array] = None,
    transform: Optional[Callable[[Array], Array]] = None,
) -> BootstrapReport:
    """Run the bootstrap diagnostic: B reweighted solves, vmapped.

    ``w_point`` (the already-trained point estimate) warm-starts every
    replicate — bootstrap optima are near it, so replicate solves converge in
    a few iterations. ``transform`` maps each replicate solution (and the
    point estimate) to reporting space — e.g.
    ``NormalizationContext.model_to_original`` so CIs are stated in the same
    original feature space as the published model coefficients.
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    rep_weights = bootstrap_weights(key, data.weights, n_replicates)

    def solve_one(weights: Array) -> Array:
        rep = dataclasses.replace(data, weights=weights)
        w = problem.run(rep, w_point, lam).w
        return transform(w) if transform is not None else w

    ws = jax.jit(jax.vmap(solve_one))(rep_weights)
    ws = np.asarray(ws)
    point = np.asarray(transform(w_point) if transform is not None else w_point)

    alpha = (1.0 - confidence_level) / 2.0
    lo, hi = np.percentile(ws, [100 * alpha, 100 * (1 - alpha)], axis=0)
    point_sign = np.sign(point)
    stability = np.mean(np.sign(ws) == point_sign[None, :], axis=0)
    return BootstrapReport(
        coefficients=ws,
        mean=ws.mean(axis=0),
        std=ws.std(axis=0, ddof=1) if n_replicates > 1 else np.zeros(ws.shape[1]),
        ci_lower=lo,
        ci_upper=hi,
        sign_stability=stability,
        confidence_level=confidence_level,
        n_replicates=n_replicates,
    )
