"""Self-contained HTML diagnostics report.

Re-design of the reference's ``photon-client/.../diagnostics/reporting/``
(the HTML report the legacy GLM ``Driver`` writes under
``--training-diagnostics``): one dependency-free HTML file assembling the
bootstrap, Hosmer–Lemeshow, feature-importance, and fitting sections, with a
small inline-SVG line chart for the fitting curve.
"""

from __future__ import annotations

import html
import os
from typing import Optional, Sequence

import numpy as np

from photon_ml_tpu.diagnostics.bootstrap import BootstrapReport
from photon_ml_tpu.diagnostics.fitting import FittingReport
from photon_ml_tpu.diagnostics.hl import HosmerLemeshowReport
from photon_ml_tpu.diagnostics.importance import FeatureImportanceReport

_STYLE = """
body{font-family:sans-serif;margin:2em;max-width:70em}
table{border-collapse:collapse;margin:1em 0}
td,th{border:1px solid #999;padding:.3em .6em;text-align:right}
th{background:#eee}
h2{border-bottom:2px solid #444;padding-bottom:.2em}
.ok{color:#070}.bad{color:#a00}
"""


def _table(headers: Sequence[str], rows: Sequence[Sequence]) -> str:
    head = "".join(f"<th>{html.escape(str(h))}</th>" for h in headers)
    body = "".join(
        "<tr>" + "".join(
            f"<td>{html.escape(f'{c:.6g}' if isinstance(c, float) else str(c))}</td>"
            for c in row) + "</tr>"
        for row in rows)
    return f"<table><tr>{head}</tr>{body}</table>"


def _svg_curve(report: FittingReport, width=480, height=240) -> str:
    """Train/validation objective vs portion as a minimal inline SVG."""
    x = report.portions
    series = [("train", report.train_objective, "#1f77b4"),
              ("validation", report.validation_objective, "#d62728")]
    ys = np.concatenate([s[1] for s in series])
    y_lo, y_hi = float(ys.min()), float(ys.max())
    span = (y_hi - y_lo) or 1.0
    pad, w, h = 40, width, height

    def pt(xv, yv):
        px = pad + (xv - x[0]) / max(x[-1] - x[0], 1e-9) * (w - 2 * pad)
        py = h - pad - (yv - y_lo) / span * (h - 2 * pad)
        return f"{px:.1f},{py:.1f}"

    lines = []
    for name, y, color in series:
        pts = " ".join(pt(float(a), float(b)) for a, b in zip(x, y))
        lines.append(f'<polyline fill="none" stroke="{color}" stroke-width="2" '
                     f'points="{pts}"/>')
        lines.append(f'<text x="{w - pad}" y="{15 * (len(lines) // 2 + 1)}" '
                     f'fill="{color}" text-anchor="end">{name}</text>')
    axis = (f'<line x1="{pad}" y1="{h-pad}" x2="{w-pad}" y2="{h-pad}" stroke="#000"/>'
            f'<line x1="{pad}" y1="{pad}" x2="{pad}" y2="{h-pad}" stroke="#000"/>'
            f'<text x="{w//2}" y="{h-8}" text-anchor="middle">training portion</text>'
            f'<text x="{pad}" y="{pad-8}">mean objective</text>')
    return (f'<svg width="{w}" height="{h}" xmlns="http://www.w3.org/2000/svg">'
            + axis + "".join(lines) + "</svg>")


def render_report(
    model_summary: dict,
    bootstrap: Optional[BootstrapReport] = None,
    hosmer_lemeshow: Optional[HosmerLemeshowReport] = None,
    importance: Sequence[FeatureImportanceReport] = (),
    fitting: Optional[FittingReport] = None,
    feature_names: Optional[Sequence[str]] = None,
    top_k: int = 25,
) -> str:
    """Render all available sections into one HTML document."""
    parts = [f"<html><head><meta charset='utf-8'><style>{_STYLE}</style>"
             "<title>Photon-ML TPU training diagnostics</title></head><body>",
             "<h1>Training diagnostics</h1>"]

    parts.append("<h2>Model</h2>")
    parts.append(_table(["key", "value"],
                        [(k, v) for k, v in model_summary.items()]))

    if bootstrap is not None:
        parts.append("<h2>Bootstrap coefficient confidence intervals</h2>")
        parts.append(
            f"<p>{bootstrap.n_replicates} replicates, "
            f"{bootstrap.confidence_level:.0%} confidence.</p>")
        order = np.argsort(-np.abs(bootstrap.mean))[:top_k]
        names = (feature_names if feature_names is not None
                 else [str(i) for i in range(len(bootstrap.mean))])
        rows = [(names[i], float(bootstrap.mean[i]), float(bootstrap.std[i]),
                 float(bootstrap.ci_lower[i]), float(bootstrap.ci_upper[i]),
                 float(bootstrap.sign_stability[i]),
                 "yes" if bootstrap.zero_crossing()[i] else "no")
                for i in order]
        parts.append(_table(
            ["feature", "mean", "std", "ci lower", "ci upper",
             "sign stability", "CI crosses 0"], rows))

    if hosmer_lemeshow is not None:
        r = hosmer_lemeshow
        cls = "ok" if r.well_calibrated() else "bad"
        parts.append("<h2>Hosmer–Lemeshow calibration</h2>")
        parts.append(
            f"<p>&chi;&sup2; = {r.chi_square:.4g} on {r.degrees_of_freedom} "
            f"d.o.f. &rarr; p = <span class='{cls}'>{r.p_value:.4g}</span></p>")
        rows = [(g, float(r.bin_counts[g]), float(r.mean_predicted[g]),
                 float(r.observed_positives[g]), float(r.expected_positives[g]))
                for g in range(r.n_bins)]
        parts.append(_table(
            ["bin", "count", "mean p&#770;", "observed +", "expected +"], rows))

    for rep in importance:
        parts.append(f"<h2>Feature importance — {html.escape(rep.kind)}</h2>")
        parts.append(_table(["feature", "importance"], rep.top(top_k)))

    if fitting is not None:
        parts.append("<h2>Fitting curve</h2>")
        parts.append(_svg_curve(fitting))
        rows = list(zip(
            [float(p) for p in fitting.portions],
            [float(v) for v in fitting.train_objective],
            [float(v) for v in fitting.validation_objective],
            [float(v) for v in fitting.generalization_gap()]))
        parts.append(_table(
            ["portion", "train objective", "validation objective", "gap"], rows))

    parts.append("</body></html>")
    return "".join(parts)


def write_report(path: str, **kwargs) -> str:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    doc = render_report(**kwargs)
    with open(path, "w") as f:
        f.write(doc)
    return path
