"""Training diagnostics (reference ``photon-client/.../diagnostics/``):
bootstrap coefficient CIs, Hosmer–Lemeshow calibration, feature importance,
fitting curves, and the HTML report writer."""

from photon_ml_tpu.diagnostics.bootstrap import (
    BootstrapReport,
    bootstrap_coefficients,
    bootstrap_weights,
)
from photon_ml_tpu.diagnostics.fitting import FittingReport, fitting_curve
from photon_ml_tpu.diagnostics.hl import HosmerLemeshowReport, hosmer_lemeshow
from photon_ml_tpu.diagnostics.importance import (
    FeatureImportanceReport,
    expected_magnitude_importance,
    variance_importance,
)
from photon_ml_tpu.diagnostics.reporting import render_report, write_report

__all__ = [
    "BootstrapReport",
    "bootstrap_coefficients",
    "bootstrap_weights",
    "FittingReport",
    "fitting_curve",
    "HosmerLemeshowReport",
    "hosmer_lemeshow",
    "FeatureImportanceReport",
    "expected_magnitude_importance",
    "variance_importance",
    "render_report",
    "write_report",
]
