"""Legacy single-model GLM training driver.

Re-design of the reference's original pipeline (``Driver.scala`` +
``PhotonMLCmdLineParser.scala`` + ``ModelTraining.scala``; BASELINE configs
1–3): read Avro → validate rows → optional feature summarization +
normalization → train one model per regularization weight (descending, warm
starts) → validate each → select best → write best + all models and the
summary log. The staged state machine (INIT → ... → VALIDATED) collapses to
straight-line host code; each stage is a ``timed`` section in the run log.
"""

from __future__ import annotations

import argparse
import os
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.cli.config import (
    add_resilience_flags,
    add_supervision_flags,
    add_telemetry_flags,
    install_resilience,
    install_telemetry,
    resilience_from_args,
    telemetry_from_args,
)
from photon_ml_tpu.data_validation import validate_game_data
from photon_ml_tpu.evaluation import parse_evaluators
from photon_ml_tpu.glm.problem import GLMOptimizationConfiguration
from photon_ml_tpu.glm.training import train_glm_sweep, validate_and_select
from photon_ml_tpu.io import (
    AvroDataReader,
    FeatureShardConfig,
    save_glm_model,
    save_glm_model_text,
)
from photon_ml_tpu.io.data_reader import parse_input_columns
from photon_ml_tpu.io.avro import write_avro_file
from photon_ml_tpu.io.schemas import FEATURE_SUMMARIZATION_RESULT_AVRO
from photon_ml_tpu.logging_util import RunLogger, timed
from photon_ml_tpu.ops.design import ChunkedSparseDesign, DenseDesign
from photon_ml_tpu.ops.normalization import NoNormalization, build_normalization
from photon_ml_tpu.ops.objective import GLMData
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optimize import OptimizerConfig
from photon_ml_tpu.stat import FeatureDataStatistics
from photon_ml_tpu.types import (
    DataValidationType,
    INTERCEPT_KEY,
    NormalizationType,
    OptimizerType,
    RegularizationType,
    TaskType,
)

DENSE_MAX_DIM = 4096


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_ml_tpu train_glm",
        description="Train a single GLM over a regularization sweep (TPU)")
    p.add_argument("--training-data", required=True)
    p.add_argument("--validation-data")
    p.add_argument("--output-dir", required=True)
    p.add_argument("--task", default="LOGISTIC_REGRESSION",
                   choices=[t.value for t in TaskType])
    p.add_argument("--optimizer", default="LBFGS",
                   choices=[o.value for o in OptimizerType])
    p.add_argument("--regularization-type", default="L2",
                   choices=[r.value for r in RegularizationType])
    p.add_argument("--elastic-net-alpha", type=float, default=0.5)
    p.add_argument("--regularization-weights", default="1.0",
                   help="semicolon-separated, e.g. '10;1;0.1'")
    p.add_argument("--normalization", default="NONE",
                   choices=[n.value for n in NormalizationType])
    p.add_argument("--evaluators", default="",
                   help="comma-separated evaluator specs (first selects the model)")
    p.add_argument("--max-iterations", type=int, default=80)
    p.add_argument("--tolerance", type=float, default=1e-6)
    p.add_argument("--no-intercept", action="store_true")
    p.add_argument("--variance-computation", default="NONE",
                   choices=["NONE", "SIMPLE", "FULL"])
    p.add_argument("--data-validation", default="VALIDATE_FULL",
                   choices=[v.value for v in DataValidationType])
    p.add_argument("--summarization-output", action="store_true",
                   help="write per-feature summary stats avro")
    p.add_argument("--training-diagnostics", action="store_true",
                   help="write diagnostics/report.html (bootstrap CIs, "
                        "Hosmer-Lemeshow, feature importance, fitting curve)")
    p.add_argument("--diagnostic-bootstrap-replicates", type=_positive_int,
                   default=16)
    p.add_argument("--profile", action="store_true",
                   help="write a jax.profiler trace of the training stage "
                        "to <output-dir>/profile (view with TensorBoard)")
    p.add_argument("--debug-nans", action="store_true",
                   help="enable jax_debug_nans (fail fast on NaN). Strict "
                        "debugging mode: also flags the line search's "
                        "legitimate NaN-probing on overflowing trial steps, "
                        "so use to LOCATE a NaN, not for production runs")
    p.add_argument("--input-columns", default="",
                   help="remap record fields, e.g. 'response=label' "
                        "(reference InputColumnsNames)")
    p.add_argument("--warm-start", metavar="DIR",
                   help="continuous-training warm start: seed the sweep's "
                        "FIRST solve from a previous run's best model "
                        "(DIR is a train_glm output dir containing "
                        "best/model.avro, or a model.avro's directory). "
                        "Coefficients join by feature NAME, so the prior "
                        "model aligns even if this run's feature index "
                        "orders differently; the warm-started solve "
                        "converges in strictly fewer iterations on "
                        "unchanged data. Sequential sweep mode only")
    p.add_argument("--design-dtype", default="float32",
                   choices=["float32", "bfloat16"],
                   help="storage dtype of a DENSE design matrix. bfloat16 "
                        "halves HBM traffic (the solve is bandwidth-bound; "
                        "~1.4x faster with the fused kernel) but rounds the "
                        "features to ~3 decimal digits, perturbing the "
                        "optimum — keep float32 where exact reference "
                        "parity matters")
    p.add_argument("--sweep-mode", default="sequential",
                   choices=["sequential", "batched"],
                   help="sequential (default): warm-started descending "
                        "lambda sweep, the reference's ModelTraining "
                        "semantics — fastest for DENSE designs (fused "
                        "kernel + warm starts). batched: one vmapped solve "
                        "over all lambdas — measured 1.7x faster for wide "
                        "CHUNKED-SPARSE designs (the per-iteration gather "
                        "is shared across lambda lanes), 0.6x on dense; "
                        "see glm/training.py::train_glm_sweep_batched for "
                        "the measurement table")
    p.add_argument("--multihost", action="store_true",
                   help="form a multi-controller job before touching any "
                        "device (jax.distributed.initialize from PHOTON_* "
                        "env vars or cluster auto-detection). With >1 "
                        "process: each process reads its share of the input "
                        "FILE LIST (at least one file per process), the "
                        "feature index and summary statistics are unioned "
                        "globally, every lambda solves as ONE psum'd sweep "
                        "over the global data mesh, and only process 0 "
                        "writes outputs. Not combinable with "
                        "--training-diagnostics or --design-dtype bfloat16 "
                        "yet")
    add_resilience_flags(p)
    add_supervision_flags(p)
    add_telemetry_flags(p)
    return p


def _positive_int(s: str) -> int:
    v = int(s)
    if v < 1:
        raise argparse.ArgumentTypeError(f"must be >= 1, got {v}")
    return v


def _to_glm_data(data, shard_id: str, dtype=jnp.float32) -> GLMData:
    shard = data.shards[shard_id]
    if shard.dim <= DENSE_MAX_DIM:
        design = DenseDesign(x=jnp.asarray(shard.to_dense(), dtype))
    else:
        # sparse chunked layouts keep f32 values (nnz dominates memory far
        # less than a dense design; bf16 applies to the dense path only)
        design = ChunkedSparseDesign.from_coo(
            shard.rows(), shard.cols, shard.vals,
            n_rows=shard.n_samples, n_cols=shard.dim)
    return GLMData(design=design, labels=jnp.asarray(data.labels),
                   offsets=jnp.asarray(data.offsets),
                   weights=jnp.asarray(data.weights))


def _run_diagnostics(args, task, best, glm_train, glm_val, shard, stats, imap,
                     config, normalization, reg_mask, run_logger) -> str:
    """The reference driver's DIAGNOSED stage (``--training-diagnostics``):
    bootstrap CIs, Hosmer-Lemeshow (logistic only), feature importance, and
    the fitting curve, written as ``diagnostics/report.html``."""
    from photon_ml_tpu.diagnostics import (
        bootstrap_coefficients,
        expected_magnitude_importance,
        fitting_curve,
        hosmer_lemeshow,
        variance_importance,
        write_report,
    )
    from photon_ml_tpu.glm.training import build_problem

    problem = build_problem(task, config, normalization, reg_mask)
    lam = best.regularization_weight
    w_t = best.result.w  # transformed-space solution from the sweep

    # replicate solutions live in transformed (normalized) space; report CIs
    # in original feature space to match the published model coefficients
    transform = (None if normalization.is_identity
                 else normalization.model_to_original)
    boot = bootstrap_coefficients(
        problem, glm_train, w_t, lam,
        n_replicates=args.diagnostic_bootstrap_replicates,
        transform=transform)

    hl = None
    if task == TaskType.LOGISTIC_REGRESSION:
        ev_data = glm_val if glm_val is not None else glm_train
        probs = np.asarray(best.model.predict_mean(ev_data.design,
                                                   ev_data.offsets))
        hl = hosmer_lemeshow(probs, np.asarray(ev_data.labels),
                             np.asarray(ev_data.weights))
        run_logger.metric(stage="diagnostics", hl_chi_square=hl.chi_square,
                          hl_p_value=hl.p_value)

    if stats is None:
        stats = FeatureDataStatistics.from_shard(shard)
    names = imap.names()
    coefs = np.asarray(best.model.coefficients.means)
    importance = [variance_importance(coefs, stats, names=names),
                  expected_magnitude_importance(coefs, stats, names=names)]

    fitting = None
    if glm_val is not None:
        # warm-start every portion from the trained solution (portion optima
        # are near it; solves still run to their own convergence)
        fitting = fitting_curve(problem, glm_train, glm_val, w_t, lam)

    return write_report(
        os.path.join(args.output_dir, "diagnostics", "report.html"),
        model_summary={
            "task": task.value,
            "best lambda": lam,
            "optimizer": config.optimizer.value,
            "iterations": int(best.result.iterations),
            "converged": bool(best.result.converged),
        },
        bootstrap=boot, hosmer_lemeshow=hl, importance=importance,
        fitting=fitting, feature_names=names)


def run(argv: Optional[Sequence[str]] = None) -> dict:
    import sys

    raw_argv = list(sys.argv[1:] if argv is None else argv)
    args = build_parser().parse_args(raw_argv)
    if args.supervise:
        # supervised fleet: relaunch this command N times under the
        # FleetSupervisor (before any jax/backend touch). The GLM sweep
        # has no checkpoint — a restarted fleet re-solves from scratch,
        # which the deterministic sweep makes exactly repeatable.
        import dataclasses as _dc

        from photon_ml_tpu.resilience.supervisor import supervise_from_args

        telemetry = install_telemetry(_dc.replace(
            telemetry_from_args(
                args, subdir=os.path.join("supervisor", "telemetry")),
            metrics_port=0))
        try:
            return supervise_from_args(
                "train_glm", raw_argv, args,
                worker_flags=(("--multihost",) if args.supervise > 1
                              else ()))
        finally:
            telemetry.close()
    task = TaskType(args.task)
    if args.warm_start and args.sweep_mode == "batched":
        # fail fast, before any read: batched lanes solve independently
        # from zero by design — there is nothing to warm-start
        raise SystemExit(
            "--warm-start needs --sweep-mode sequential (batched lanes "
            "solve independently from zero by design)")
    # install the retry policy BEFORE anything that might retry (multihost
    # initialization is the first candidate)
    install_resilience(resilience_from_args(args))
    if args.multihost:
        from photon_ml_tpu.parallel import multihost

        multihost.initialize(auto=True)
    import jax

    multiproc = args.multihost and jax.process_count() > 1
    chief = jax.process_index() == 0
    if multiproc:
        bad = [msg for flag, msg in (
            (args.training_diagnostics, "--training-diagnostics"),
            (args.sweep_mode == "batched", "--sweep-mode batched (vmap "
             "over the lambda axis does not compose with the multi-process "
             "mesh yet)"),
        ) if flag]
        if bad:
            raise SystemExit("multi-process --multihost training does not "
                             "support: " + ", ".join(bad))
    if args.debug_nans:
        jax.config.update("jax_debug_nans", True)
    run_logger = RunLogger(
        args.output_dir if chief else os.path.join(
            args.output_dir, "workers", f"proc-{jax.process_index()}"))
    telemetry = install_telemetry(telemetry_from_args(
        args, subdir=None if chief
        else os.path.join("workers", f"proc-{jax.process_index()}")))
    # async I/O pipeline: model/index writes run on background threads and
    # are joined before exit — "Save models" is the join wall (chief-only)
    saver = None
    if chief:
        from photon_ml_tpu.io.pipeline import BackgroundSaver

        saver = BackgroundSaver()
    from photon_ml_tpu.telemetry import emit_build_info, tracing

    emit_build_info()

    import contextlib as _contextlib

    _root_span = _contextlib.ExitStack()
    _root_span.enter_context(tracing.span("train_glm"))
    from photon_ml_tpu.events import GLOBAL_BUS

    GLOBAL_BUS.post("training_started", driver="train_glm",
                    task=task.value, output_dir=args.output_dir)
    try:
        evaluators = parse_evaluators(
            [e for e in args.evaluators.split(",") if e])
        id_columns = tuple(dict.fromkeys(
            e.id_tag for e in evaluators if e.id_tag))
        reader = AvroDataReader(
            shard_configs=(
                FeatureShardConfig("global", feature_bags=None,
                                   has_intercept=not args.no_intercept),),
            input_columns=parse_input_columns(args.input_columns))
        with timed("Read training data", run_logger):
            if multiproc:
                from photon_ml_tpu.game.multiprocess import (
                    process_file_share,
                    reconcile_global_ids,
                )

                data, index_maps, vocabs = reader.read(
                    process_file_share(reader, args.training_data),
                    id_columns=id_columns)
                # vocabs reconciled for grouped-evaluator id tags only (the
                # GLM driver has no entity models)
                data, index_maps, _ = reconcile_global_ids(
                    data, index_maps, vocabs, id_columns)
            else:
                data, index_maps, _ = reader.read(args.training_data,
                                                  id_columns=id_columns)
        imap = index_maps["global"]

        with timed("Validate data", run_logger):
            validate_game_data(data, task,
                               DataValidationType(args.data_validation))

        shard = data.shards["global"]
        norm_type = NormalizationType(args.normalization)
        normalization = NoNormalization
        stats = None
        if norm_type != NormalizationType.NONE or args.summarization_output:
            with timed("Summarize features", run_logger):
                # allreduce: global statistics when rows span processes
                # (identity single-process), so the normalization context —
                # part of the OBJECTIVE — is identical everywhere
                stats = FeatureDataStatistics.from_shard(shard).allreduce()
            if args.summarization_output and chief:
                write_avro_file(
                    os.path.join(args.output_dir, "summary.avro"),
                    stats.to_records(imap.names()),
                    FEATURE_SUMMARIZATION_RESULT_AVRO)
            if norm_type != NormalizationType.NONE:
                intercept_idx = imap.key_to_index.get(INTERCEPT_KEY)
                normalization = build_normalization(
                    norm_type, mean=stats.mean, variance=stats.variance,
                    max_magnitude=stats.max_magnitude,
                    intercept_index=intercept_idx)

        from photon_ml_tpu.types import VarianceComputationType

        lambdas = [float(x) for x in args.regularization_weights.split(";") if x]
        config = GLMOptimizationConfiguration(
            optimizer=OptimizerType(args.optimizer),
            regularization=RegularizationContext(
                RegularizationType(args.regularization_type),
                alpha=args.elastic_net_alpha),
            optimizer_config=OptimizerConfig(
                max_iterations=args.max_iterations, tolerance=args.tolerance),
            variance_type=VarianceComputationType(args.variance_computation),
        )

        reg_mask = None
        if imap.has_intercept:
            mask = np.ones(len(imap), np.float32)
            mask[imap.key_to_index[INTERCEPT_KEY]] = 0.0
            reg_mask = jnp.asarray(mask)

        design_dtype = (jnp.bfloat16 if args.design_dtype == "bfloat16"
                        else jnp.float32)
        fe_mesh = None
        if multiproc:
            # global data-axis mesh; every process feeds its own rows
            from photon_ml_tpu.game.data import host_design_for_shard
            from photon_ml_tpu.parallel.multihost import (
                global_glm_data_multihost,
                make_multihost_mesh,
            )

            fe_mesh = make_multihost_mesh()
            from photon_ml_tpu.game.data import cast_dense_design

            # the budget-reconciled feed preserves leaf dtypes, so the
            # bf16 cast here rides the wire at 2 bytes on every process
            # (same flag everywhere -> symmetric layout)
            host = GLMData(
                design=cast_dense_design(
                    host_design_for_shard(shard,
                                          dense_max_dim=DENSE_MAX_DIM),
                    design_dtype),
                labels=data.labels,
                offsets=data.offsets,
                weights=data.weights)
            glm_train = global_glm_data_multihost(host, fe_mesh)
        else:
            glm_train = _to_glm_data(data, "global", dtype=design_dtype)
        from photon_ml_tpu.logging_util import log_optimizer_trace, profiled

        # per-process profile dir: same-host processes tracing into one
        # directory overwrite each other's xplane files
        profile_dir = None
        if args.profile:
            profile_dir = os.path.join(
                args.output_dir if chief else os.path.join(
                    args.output_dir, "workers",
                    f"proc-{jax.process_index()}"),
                "profile")
        initial = None
        if args.warm_start:
            from photon_ml_tpu.io.model_io import load_glm_model

            warm_path = os.path.join(args.warm_start, "best", "model.avro")
            if not os.path.exists(warm_path):
                warm_path = os.path.join(args.warm_start, "model.avro")
            with timed("Load warm start", run_logger):
                prior = load_glm_model(warm_path, imap)
            # the sweep optimizes in TRANSFORMED space; a saved model's
            # coefficients are original-space (export back-transforms)
            w_orig = jnp.asarray(prior.coefficients.means)
            initial = (w_orig if normalization.is_identity
                       else normalization.original_to_model(w_orig))

        with timed("Train", run_logger), profiled(profile_dir):
            if args.sweep_mode == "batched":
                # multiproc + batched already rejected up front
                from photon_ml_tpu.glm.training import train_glm_sweep_batched

                trained = train_glm_sweep_batched(
                    task, glm_train, lambdas, config,
                    normalization=normalization, reg_mask=reg_mask)
            else:
                trained = train_glm_sweep(
                    task, glm_train, lambdas, config,
                    normalization=normalization, reg_mask=reg_mask,
                    initial=initial,
                    mesh=fe_mesh, dim=len(imap) if multiproc else None)
        for tm in trained:
            run_logger.metric(stage="train", regularization_weight=tm.regularization_weight,
                              value=float(tm.result.value),
                              iterations=int(tm.result.iterations),
                              converged=bool(tm.result.converged))
            # the reference's OptimizationStatesTracker iteration table
            log_optimizer_trace(
                tm.result, f"lambda={tm.regularization_weight:g}", run_logger)

        # divergence guard over the sweep (pure reads: finiteness of the
        # trained coefficients). The GLM sweep has no rollback target —
        # each lambda is an independent solve — so non-"fail" modes drop
        # the diverged lambdas from model selection and continue degraded.
        diverged = [tm for tm in trained
                    if not np.isfinite(
                        np.asarray(tm.model.coefficients.means)).all()]
        if diverged:
            from photon_ml_tpu.events import GLOBAL_BUS
            from photon_ml_tpu.resilience import DivergenceError

            bad = [tm.regularization_weight for tm in diverged]
            for w in bad:
                GLOBAL_BUS.post("divergence_detected", driver="train_glm",
                                regularization_weight=w)
            if args.on_divergence == "fail":
                raise DivergenceError(
                    f"GLM sweep diverged at lambda(s) {bad} (non-finite "
                    f"coefficients); re-run with --on-divergence=rollback "
                    f"to drop them from selection, or raise the "
                    f"regularization / lower the normalization scale")
            if len(diverged) == len(trained):
                raise DivergenceError(
                    f"every lambda in the sweep diverged ({bad}); nothing "
                    f"to select — fix the optimization configuration")
            for w in bad:
                GLOBAL_BUS.post("coordinate_frozen", driver="train_glm",
                                regularization_weight=w)
            trained = [tm for tm in trained if tm not in diverged]

        # async model publication: every lambda's model is final here —
        # submit the all/ writes NOW so they overlap the validation read,
        # scoring and selection below (evaluation is not part of the
        # written artifact, so writing before selection is byte-equivalent)
        def _save_glm(model, out_dir, model_id):
            save_glm_model(os.path.join(out_dir, "model.avro"),
                           model, imap, model_id=model_id)
            # the reference driver writes text AND Avro models
            save_glm_model_text(os.path.join(out_dir, "model.txt"),
                                model, imap)

        if chief:
            saver.submit_file_write(
                imap.save,
                os.path.join(args.output_dir, "feature-index.json"),
                label="io.save.index")
            for tm in trained:
                model_id = f"lambda-{tm.regularization_weight:g}"
                out_dir = os.path.join(args.output_dir, "all", model_id)
                saver.submit(
                    lambda tm=tm, out_dir=out_dir, model_id=model_id:
                        _save_glm(tm.model, out_dir, model_id),
                    label="io.save.model", path=out_dir)

        best_idx = 0
        glm_val = None
        # diagnostics need validation data too (fitting curve, out-of-sample
        # HL), so read it even when no evaluators are configured
        if args.validation_data and (evaluators or args.training_diagnostics):
            reader_v = AvroDataReader(shard_configs=reader.shard_configs,
                                      index_maps=index_maps,
                                      input_columns=reader.input_columns)
            with timed("Read validation data", run_logger):
                vdata, _, _ = reader_v.read(args.validation_data,
                                            id_columns=id_columns)
            glm_val = _to_glm_data(vdata, "global", dtype=design_dtype)
        if glm_val is not None and evaluators:
            with timed("Validate models", run_logger):
                best_idx, trained = validate_and_select(
                    trained, evaluators, glm_val,
                    id_tags=vdata.id_columns)
            for tm in trained:
                run_logger.metric(stage="validate",
                                  regularization_weight=tm.regularization_weight,
                                  **tm.evaluation.as_dict())

        best = trained[best_idx]
        if chief:
            # the winner is known only now; everything else has been
            # writing in the background since the sweep ended — the stage
            # is the join wall
            saver.submit(
                lambda: _save_glm(best.model,
                                  os.path.join(args.output_dir, "best"),
                                  "best"),
                label="io.save.model", path=os.path.join(args.output_dir,
                                                         "best"))
            with timed("Save models", run_logger):
                saver.join()
        report_path = None
        if args.training_diagnostics:
            # the DIAGNOSED stage of the reference driver's state machine
            with timed("Diagnostics", run_logger):
                report_path = _run_diagnostics(
                    args, task, best, glm_train, glm_val, shard, stats, imap,
                    config, normalization, reg_mask, run_logger)

        result = {
            "best_lambda": best.regularization_weight,
            "best_evaluation": (best.evaluation.as_dict()
                                if best.evaluation else None),
            "output_dir": args.output_dir,
            "diagnostics_report": report_path,
        }
        if chief:
            # supervised runs: hand the result dict back to the supervisor
            from photon_ml_tpu.resilience.supervisor import write_result_file

            write_result_file(result)
        return result
    finally:
        if saver is not None:
            # happy path already join()ed; this waits out writers a
            # failing run left in flight
            saver.close()
        _root_span.close()
        GLOBAL_BUS.post("training_finished", driver="train_glm")
        telemetry.close()
        run_logger.close()


if __name__ == "__main__":
    run()
