"""GAME training driver.

Re-design of ``photon-client/.../cli/game/training/GameTrainingDriver.scala``
(+ shared params on ``GameDriver.scala``): read train/validation Avro →
assemble feature shards + index maps → build the estimator's coordinate
datasets once → fit every hyperparameter configuration (explicit grid or
Bayesian GP search) → select best by the first validation evaluator → write
best (+ optionally all) models in the reference directory layout.
"""

from __future__ import annotations

import argparse
import os
from typing import Optional, Sequence

from photon_ml_tpu.cli.config import (
    parse_coordinate_config,
    parse_feature_shard_config,
    parse_grid,
)
from photon_ml_tpu.data_validation import validate_game_data
from photon_ml_tpu.evaluation import parse_evaluators
from photon_ml_tpu.game.estimator import (
    GameEstimator,
    GameOptimizationConfiguration,
    RandomEffectCoordinateConfig,
)
from photon_ml_tpu.io import AvroDataReader, save_game_model
from photon_ml_tpu.logging_util import RunLogger, timed
from photon_ml_tpu.types import DataValidationType, TaskType


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_ml_tpu train_game",
        description="Train a GAME mixed-effect model (TPU)")
    p.add_argument("--training-data", required=True)
    p.add_argument("--validation-data")
    p.add_argument("--output-dir", required=True)
    p.add_argument("--task", default="LOGISTIC_REGRESSION",
                   choices=[t.value for t in TaskType])
    p.add_argument("--feature-shards", required=True,
                   help="comma-separated shard specs, e.g. "
                        "'global=fixed|intercept,user=user+item|noIntercept'")
    p.add_argument("--coordinates", required=True, nargs="+",
                   help="coordinate specs, e.g. "
                        "'global=fixed,shard=global,reg=L2' "
                        "'perUser=random,entity=userId,shard=user,reg=L2'")
    p.add_argument("--update-sequence", required=True,
                   help="comma-separated coordinate ids")
    p.add_argument("--cd-iterations", type=int, default=1)
    p.add_argument("--grid", nargs="*", default=[],
                   help="per-coordinate lambda lists 'coordId=0.1;1;10'")
    p.add_argument("--tuning", choices=["NONE", "RANDOM", "BAYESIAN"],
                   default="NONE")
    p.add_argument("--tuning-iterations", type=int, default=10)
    p.add_argument("--tuning-range", default="1e-4:1e4",
                   help="lambda search range 'low:high' for tuning")
    p.add_argument("--evaluators", default="AUC",
                   help="comma-separated; first drives model selection")
    p.add_argument("--output-all-models", action="store_true")
    p.add_argument("--data-validation", default="VALIDATE_FULL",
                   choices=[v.value for v in DataValidationType])
    return p


def run(argv: Optional[Sequence[str]] = None) -> dict:
    from photon_ml_tpu.events import GLOBAL_BUS

    args = build_parser().parse_args(argv)
    task = TaskType(args.task)
    run_logger = RunLogger(args.output_dir)
    GLOBAL_BUS.post("training_started", driver="train_game",
                    task=task.value, output_dir=args.output_dir)
    try:
        shard_configs = tuple(parse_feature_shard_config(s)
                              for s in args.feature_shards.split(","))
        coordinate_configs = dict(parse_coordinate_config(s)
                                  for s in args.coordinates)
        update_sequence = [c for c in args.update_sequence.split(",") if c]
        re_types = sorted({
            c.dataset.random_effect_type
            for c in coordinate_configs.values()
            if isinstance(c, RandomEffectCoordinateConfig)})
        evaluators = parse_evaluators(
            [e for e in args.evaluators.split(",") if e])
        id_columns = tuple(dict.fromkeys(
            re_types + [e.id_tag for e in evaluators if e.id_tag]))

        reader = AvroDataReader(shard_configs=shard_configs)
        with timed("Read training data", run_logger):
            data, index_maps, vocabs = reader.read(
                args.training_data, id_columns=id_columns)
        with timed("Validate data", run_logger):
            validate_game_data(data, task,
                               DataValidationType(args.data_validation))

        validation = None
        if args.validation_data:
            reader_v = AvroDataReader(shard_configs=shard_configs,
                                      index_maps=index_maps)
            with timed("Read validation data", run_logger):
                vdata, _, _ = reader_v.read(
                    args.validation_data, id_columns=id_columns,
                    entity_vocabs=vocabs)
            validation = (vdata, evaluators)

        est = GameEstimator(task=task, coordinate_configs=coordinate_configs,
                            update_sequence=update_sequence,
                            n_cd_iterations=args.cd_iterations)

        if args.tuning == "NONE":
            grid = parse_grid(args.grid)
            unknown = {cid for g in grid for cid in g} - set(update_sequence)
            if unknown:
                raise SystemExit(
                    f"--grid names unknown coordinates {sorted(unknown)}; "
                    f"update sequence is {update_sequence}")
            configurations = [GameOptimizationConfiguration(g) for g in grid]
            with timed("Train (grid)", run_logger):
                results = est.fit(data, configurations, validation=validation)
        else:
            if validation is None:
                raise SystemExit("--tuning needs --validation-data")
            from photon_ml_tpu.hyperparameter.search import (
                GaussianProcessSearch,
                ParamRange,
                RandomSearch,
            )

            low, high = (float(x) for x in args.tuning_range.split(":"))
            space = {cid: ParamRange(low, high) for cid in update_sequence}
            results = []
            datasets = est.prepare(data)  # build once across tuning evals

            def evaluate(config: dict) -> float:
                r = est.fit(data, [GameOptimizationConfiguration(config)],
                            validation=validation, datasets=datasets)[0]
                results.append(r)
                return r.evaluation.primary[1]

            maximize = evaluators[0].maximize
            search_cls = (GaussianProcessSearch if args.tuning == "BAYESIAN"
                          else RandomSearch)
            with timed(f"Train ({args.tuning} tuning)", run_logger):
                if args.tuning == "BAYESIAN":
                    search_cls(space, maximize=maximize).find(
                        evaluate, args.tuning_iterations)
                else:
                    search_cls(space).find(evaluate, args.tuning_iterations)

        best = GameEstimator.select_best(results)
        for i, r in enumerate(results):
            GLOBAL_BUS.post(
                "configuration_evaluated", index=i,
                config=dict(r.configuration.regularization_weights),
                evaluation=r.evaluation.as_dict() if r.evaluation else None)
        if best.evaluation is not None:
            run_logger.metric(stage="best", **best.evaluation.as_dict(),
                              config=dict(best.configuration.regularization_weights))

        with timed("Save models", run_logger):
            os.makedirs(args.output_dir, exist_ok=True)
            for shard_id, imap in index_maps.items():
                imap.save(os.path.join(args.output_dir, "feature-indexes",
                                       f"{shard_id}.json"))
            save_game_model(os.path.join(args.output_dir, "best"),
                            best.model, index_maps, vocabs)
            if args.output_all_models:
                for i, r in enumerate(results):
                    save_game_model(
                        os.path.join(args.output_dir, "all", f"config-{i}"),
                        r.model, index_maps, vocabs)
        GLOBAL_BUS.post("model_saved",
                        path=os.path.join(args.output_dir, "best"))
        return {
            "best_config": dict(best.configuration.regularization_weights),
            "best_evaluation": (best.evaluation.as_dict()
                                if best.evaluation else None),
            "n_configurations": len(results),
            "output_dir": args.output_dir,
        }
    finally:
        GLOBAL_BUS.post("training_finished", driver="train_game")
        run_logger.close()


if __name__ == "__main__":
    run()
