"""GAME training driver.

Re-design of ``photon-client/.../cli/game/training/GameTrainingDriver.scala``
(+ shared params on ``GameDriver.scala``): read train/validation Avro →
assemble feature shards + index maps → build the estimator's coordinate
datasets once → fit every hyperparameter configuration (explicit grid or
Bayesian GP search) → select best by the first validation evaluator → write
best (+ optionally all) models in the reference directory layout.
"""

from __future__ import annotations

import argparse
import os
from typing import Optional, Sequence

from photon_ml_tpu.cli.config import (
    add_resilience_flags,
    add_supervision_flags,
    add_telemetry_flags,
    install_resilience,
    install_telemetry,
    parse_coordinate_config,
    parse_feature_shard_config,
    parse_grid,
    resilience_from_args,
    telemetry_from_args,
)
from photon_ml_tpu.data_validation import validate_game_data
from photon_ml_tpu.evaluation import parse_evaluators
from photon_ml_tpu.game.estimator import (
    FactoredRandomEffectCoordinateConfig,
    FixedEffectCoordinateConfig,
    GameEstimator,
    GameOptimizationConfiguration,
    RandomEffectCoordinateConfig,
)
from photon_ml_tpu.io import AvroDataReader
from photon_ml_tpu.logging_util import RunLogger, timed
from photon_ml_tpu.types import DataValidationType, TaskType


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_ml_tpu train_game",
        description="Train a GAME mixed-effect model (TPU)")
    p.add_argument("--training-data", required=True)
    p.add_argument("--validation-data")
    p.add_argument("--output-dir", required=True)
    p.add_argument("--task", default="LOGISTIC_REGRESSION",
                   choices=[t.value for t in TaskType])
    p.add_argument("--feature-shards", required=True,
                   help="comma-separated shard specs, e.g. "
                        "'global=fixed|intercept,user=user+item|noIntercept'")
    p.add_argument("--coordinates", required=True, nargs="+",
                   help="coordinate specs, e.g. "
                        "'global=fixed,shard=global,reg=L2' "
                        "'perUser=random,entity=userId,shard=user,reg=L2'")
    p.add_argument("--update-sequence", required=True,
                   help="comma-separated coordinate ids")
    p.add_argument("--cd-iterations", type=int, default=1)
    p.add_argument("--grid", nargs="*", default=[],
                   help="per-coordinate lambda lists 'coordId=0.1;1;10'")
    p.add_argument("--tuning", choices=["NONE", "RANDOM", "BAYESIAN"],
                   default="NONE")
    p.add_argument("--tuning-iterations", type=int, default=10)
    p.add_argument("--tuning-range", default="1e-4:1e4",
                   help="lambda search range 'low:high' for tuning")
    p.add_argument("--evaluators", default="AUC",
                   help="comma-separated; first drives model selection")
    p.add_argument("--output-all-models", action="store_true")
    p.add_argument("--data-validation", default="VALIDATE_FULL",
                   choices=[v.value for v in DataValidationType])
    p.add_argument("--model-input-dir",
                   help="warm-start from a previous train_game output dir "
                        "(reference partial-retrain path); its feature "
                        "indexes are reused so coefficients line up")
    p.add_argument("--locked-coordinates", default="",
                   help="comma-separated coordinate ids to FREEZE (kept "
                        "from --model-input-dir, never retrained)")
    p.add_argument("--checkpoint", action="store_true",
                   help="write coordinate-boundary checkpoints under "
                        "<output-dir>/checkpoints (single-config grids)")
    p.add_argument("--resume", action="store_true",
                   help="resume from the latest checkpoint in "
                        "<output-dir>/checkpoints")
    p.add_argument("--debug-nans", action="store_true",
                   help="enable jax_debug_nans (fail fast on NaN; §5.2 "
                        "sanitizer equivalent)")
    p.add_argument("--design-dtype", default="float32",
                   choices=["float32", "bfloat16"],
                   help="storage dtype for the dense designs (fixed-effect "
                        "AND random-effect bucket tensors), on device and "
                        "on the host-device wire: bfloat16 halves the "
                        "dominant payload (~1.4-1.5x solve, ~2x feed) for "
                        "~3-digit design rounding; labels, weights and "
                        "coefficients stay float32 and margins accumulate "
                        "in float32")
    p.add_argument("--model-sparsity-threshold", type=float, default=0.0,
                   help="drop |coefficient| <= threshold from written "
                        "models (reference model-sparsity threshold)")
    p.add_argument("--input-columns", default="",
                   help="remap record fields, e.g. 'response=label,"
                        "weight=w' (reference InputColumnsNames)")
    p.add_argument("--profile", action="store_true",
                   help="write a jax.profiler trace of the training stage "
                        "to <output-dir>/profile (view with TensorBoard)")
    p.add_argument("--multihost", action="store_true",
                   help="form a multi-controller job before touching any "
                        "device (jax.distributed.initialize from "
                        "PHOTON_COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID "
                        "env vars, or JAX cluster auto-detection on TPU "
                        "pods). Every process runs this same command; with "
                        ">1 process, training routes through the entity-"
                        "partitioned multi-process path: each process reads "
                        "its share of the input FILE LIST (provide at least "
                        "one file per process on a shared filesystem), "
                        "feature indexes and entity vocabularies are unioned "
                        "globally, the fixed effect trains on one global "
                        "data mesh (built automatically — do not pass "
                        "--mesh), random effects solve process-locally, and "
                        "only process 0 writes outputs. --checkpoint/"
                        "--resume persist per-process sweep-boundary state "
                        "(single-config grid). No --locked-coordinates/"
                        "--model-input-dir/--tuning yet")
    p.add_argument("--mesh", default="",
                   help="device mesh axes, e.g. 'data=4,entity=2': shards "
                        "fixed-effect samples over 'data' (psum'd compiled "
                        "optimizer) and random-effect entity lanes over "
                        "'entity'. Default: single device")
    add_resilience_flags(p)
    add_supervision_flags(p)
    add_telemetry_flags(p)
    return p


def parse_mesh(spec: str):
    """'data=4,entity=2' → Mesh (None when empty)."""
    if not spec:
        return None
    from photon_ml_tpu.parallel.mesh import make_mesh

    axes = {}
    for part in spec.split(","):
        name, _, size = part.partition("=")
        name = name.strip()
        if name in axes:
            raise SystemExit(f"duplicate mesh axis {name!r}")
        try:
            axes[name] = int(size)
        except ValueError:
            raise SystemExit(f"bad --mesh entry {part!r}; want axis=<int>")
        if name not in ("data", "entity", "feature"):
            raise SystemExit(
                f"unknown mesh axis {name!r}; choose from data/entity/feature")
        if axes[name] < 1:
            raise SystemExit(f"mesh axis {name!r} must be >= 1, got {axes[name]}")
    try:
        return make_mesh(axes)
    except ValueError as e:  # e.g. more devices requested than available
        raise SystemExit(f"--mesh {spec!r}: {e}")


# canonical home is the io layer, next to InputColumnsNames; re-exported
# here for backward compatibility
from photon_ml_tpu.io.data_reader import parse_input_columns  # noqa: E402,F401


def _process_index() -> int:
    import jax

    return jax.process_index()


def _resolve_model_dir(path: str) -> str:
    """Accept a run dir (containing best/) or a model dir directly."""
    path = os.path.normpath(path)
    if os.path.exists(os.path.join(path, "model-metadata.json")):
        return path
    nested = os.path.join(path, "best")
    if os.path.exists(os.path.join(nested, "model-metadata.json")):
        return nested
    raise FileNotFoundError(f"no model-metadata.json under {path!r}")


def _run_supervised(raw_argv: Sequence[str], args) -> dict:
    """The ``--supervise N`` branch: relaunch this command as an N-process
    supervised fleet (workers get ``--checkpoint --resume`` so every
    restart resumes from the latest agreed checkpoint, and ``--multihost``
    at N > 1) and return the chief's result dict + the restart count.
    Runs BEFORE any jax/backend touch — the supervisor process itself
    never trains."""
    from photon_ml_tpu.cli.config import (
        install_telemetry,
        parse_grid,
        telemetry_from_args,
    )
    from photon_ml_tpu.resilience.supervisor import supervise_from_args

    if args.tuning != "NONE" or len(parse_grid(args.grid)) != 1:
        raise SystemExit(
            "--supervise needs a single-config grid and no --tuning: "
            "restart-from-checkpoint resumes ONE training (the same "
            "constraint as --checkpoint/--resume)")
    worker_flags = ["--checkpoint", "--resume"]
    if args.supervise > 1:
        worker_flags.append("--multihost")
    # the supervisor's own telemetry (supervisor.run/attempt spans and the
    # photon_supervisor_* bridge metrics) lands under supervisor/ — the
    # worker processes own the run's telemetry dirs AND the metrics port
    # (binding it here too would collide with the chief worker's server)
    import dataclasses as _dc

    telemetry = install_telemetry(_dc.replace(
        telemetry_from_args(args,
                            subdir=os.path.join("supervisor", "telemetry")),
        metrics_port=0))
    try:
        return supervise_from_args("train_game", raw_argv, args,
                                   worker_flags=worker_flags)
    finally:
        telemetry.close()


def run(argv: Optional[Sequence[str]] = None) -> dict:
    import sys

    from photon_ml_tpu.events import GLOBAL_BUS

    raw_argv = list(sys.argv[1:] if argv is None else argv)
    args = build_parser().parse_args(raw_argv)
    if args.supervise:
        return _run_supervised(raw_argv, args)
    task = TaskType(args.task)
    # install the retry policy BEFORE anything that might retry (multihost
    # initialization is the first candidate)
    guard = install_resilience(resilience_from_args(args))
    if args.multihost:
        # must precede parse_mesh: forming the job is only possible before
        # the first backend-touching call
        from photon_ml_tpu.parallel import multihost

        multihost.initialize(auto=True)
    from photon_ml_tpu.parallel.multihost import is_chief

    chief = is_chief()
    import jax

    # >1 process: route training through the entity-partitioned
    # multi-process path (game/multiprocess.py) — per-process file reads,
    # global id agreement, dp fixed effect on the global mesh,
    # process-local random-effect solves, allgathered model
    multiproc = args.multihost and jax.process_count() > 1
    if multiproc and args.mesh:
        raise SystemExit(
            "multi-process --multihost training does not take --mesh: the "
            "global data mesh is built automatically, the entity axis is "
            "subsumed by the entity->process partition, and TP-across-"
            "processes has no photon-scale workload — see PARALLELISM.md "
            "\"Why --mesh is refused at >1 process\" for the full rationale")
    # fail fast on a bad mesh spec / device-count mismatch, BEFORE the
    # (potentially long) Avro reads
    mesh = parse_mesh(args.mesh)
    if args.debug_nans:
        jax.config.update("jax_debug_nans", True)
    # non-chief processes log under a per-process subdir: on the shared
    # filesystem --multihost mandates, N processes appending to one
    # photon.log/metrics.jsonl would interleave and duplicate every line
    log_dir = args.output_dir if chief else os.path.join(
        args.output_dir, "workers", f"proc-{_process_index()}")
    run_logger = RunLogger(log_dir)
    # telemetry before the first event post, so the bridge sees the whole
    # run; non-chief processes trace under their own workers/ subdir
    telemetry = install_telemetry(telemetry_from_args(
        args, subdir=None if chief
        else os.path.join("workers", f"proc-{_process_index()}")))
    # the async I/O pipeline's writer service: feature indexes and model
    # part-files are written on background threads and joined before exit,
    # so "Save models" shrinks to the join wall (chief-only — only the
    # chief writes outputs)
    saver = None
    if chief:
        from photon_ml_tpu.io.pipeline import BackgroundSaver

        saver = BackgroundSaver()
    from photon_ml_tpu.telemetry import emit_build_info, tracing

    # photon_build_info{version, process, jax_version}: every process
    # stamps itself so a fleet scrape exposes mixed-version fleets
    emit_build_info()
    import contextlib as _contextlib

    _root_span = _contextlib.ExitStack()
    _root_span.enter_context(tracing.span("train_game"))
    GLOBAL_BUS.post("training_started", driver="train_game",
                    task=task.value, output_dir=args.output_dir)
    try:
        shard_configs = tuple(parse_feature_shard_config(s)
                              for s in args.feature_shards.split(","))
        coordinate_configs = dict(parse_coordinate_config(s)
                                  for s in args.coordinates)
        if args.design_dtype != "float32":
            import dataclasses as _dc

            if any(isinstance(c, FactoredRandomEffectCoordinateConfig)
                   for c in coordinate_configs.values()):
                # factored coordinates solve in the RANDOM-projected space
                # and keep f32 designs; silently training them f32 under a
                # bf16 request would fake the promised speedup
                raise SystemExit(
                    "--design-dtype bfloat16 does not apply to factored "
                    "random-effect coordinates (their projected designs "
                    "are float32); drop the flag or the factored "
                    "coordinate")
            coordinate_configs = {
                cid: (_dc.replace(c, design_dtype=args.design_dtype)
                      if isinstance(c, (FixedEffectCoordinateConfig,
                                        RandomEffectCoordinateConfig))
                      else c)
                for cid, c in coordinate_configs.items()}
        update_sequence = [c for c in args.update_sequence.split(",") if c]
        locked = [c for c in args.locked_coordinates.split(",") if c]
        if locked and not args.model_input_dir:
            raise SystemExit("--locked-coordinates needs --model-input-dir")
        re_types = {
            c.dataset.random_effect_type
            for c in coordinate_configs.values()
            if isinstance(c, (RandomEffectCoordinateConfig,
                              FactoredRandomEffectCoordinateConfig))}
        if args.model_input_dir:
            # locked coordinates have no config entry, but their entity-id
            # columns must still be read so the loaded model's entity keys
            # resolve (model-metadata.json records each coordinate's type)
            import json as _json

            with open(os.path.join(_resolve_model_dir(args.model_input_dir),
                                   "model-metadata.json")) as f:
                for info in _json.load(f)["coordinates"].values():
                    if info["type"] == "random-effect":
                        re_types.add(info["randomEffectType"])
        re_types = sorted(re_types)
        evaluators = parse_evaluators(
            [e for e in args.evaluators.split(",") if e])
        id_columns = tuple(dict.fromkeys(
            re_types + [e.id_tag for e in evaluators if e.id_tag]))

        preset_maps = None
        if args.model_input_dir:
            from photon_ml_tpu.io.index import IndexMap

            model_dir = _resolve_model_dir(args.model_input_dir)
            index_dir = os.path.join(os.path.dirname(model_dir)
                                     if os.path.basename(model_dir) == "best"
                                     else model_dir, "feature-indexes")
            if not os.path.isdir(index_dir):
                index_dir = os.path.join(model_dir, "feature-indexes")
            preset_maps = {
                cfg.shard_id: IndexMap.load(
                    os.path.join(index_dir, f"{cfg.shard_id}.json"))
                for cfg in shard_configs}

        reader = AvroDataReader(shard_configs=shard_configs,
                                index_maps=preset_maps,
                                input_columns=parse_input_columns(
                                    args.input_columns))
        with timed("Read training data", run_logger):
            if multiproc:
                # each process reads its share of the file list (the
                # reference's executor-local reads), then ids are unioned
                # into one global feature index / entity vocabulary
                from photon_ml_tpu.game.multiprocess import (
                    process_file_share,
                    reconcile_global_ids,
                )

                data, index_maps, vocabs = reader.read(
                    process_file_share(reader, args.training_data),
                    id_columns=id_columns)
                data, index_maps, vocabs = reconcile_global_ids(
                    data, index_maps, vocabs, id_columns)
            else:
                data, index_maps, vocabs = reader.read(
                    args.training_data, id_columns=id_columns)
        if saver is not None:
            # the index maps are final from here on: their JSON files write
            # on the background pool, fully hidden under the stages below
            os.makedirs(args.output_dir, exist_ok=True)
            for shard_id, imap in index_maps.items():
                saver.submit_file_write(
                    imap.save,
                    os.path.join(args.output_dir, "feature-indexes",
                                 f"{shard_id}.json"),
                    label="io.save.index", shard=shard_id)

        initial_models = None
        if args.model_input_dir:
            from photon_ml_tpu.io import load_game_model

            with timed("Load initial model", run_logger):
                initial_models = dict(load_game_model(
                    model_dir, index_maps, vocabs).coordinates)
            missing = set(locked) - set(initial_models)
            if missing:
                raise SystemExit(
                    f"locked coordinates {sorted(missing)} not present in "
                    f"the input model")

        # --- continuous-training lineage + data manifest ----------------
        # every published model records where it came from (parentModel /
        # trainedAt) and a per-entity fingerprint manifest of its training
        # data, so refresh_game can warm-start from it and re-solve only
        # the entities whose data changed. Chief-only and single-process:
        # a multi-process share sees a partial row set, so its manifest
        # would mis-flag every remotely-read entity as changed.
        lineage = None
        if chief:
            import datetime as _dt

            manifest_digest = None
            if not multiproc:
                from photon_ml_tpu.continuous import delta as _delta

                re_coords = {
                    cid: (c.dataset.random_effect_type,
                          c.dataset.feature_shard_id)
                    for cid, c in coordinate_configs.items()
                    if isinstance(c, RandomEffectCoordinateConfig)}
                _manifest = _delta.build_manifest(data, re_coords, vocabs)
                manifest_digest = _delta.manifest_digest(_manifest)
                saver.submit_file_write(
                    lambda path, m=_manifest: _delta.save_manifest(path, m),
                    os.path.join(args.output_dir, _delta.MANIFEST_NAME),
                    label="io.save.manifest")
            parent_lineage = None
            if args.model_input_dir:
                from photon_ml_tpu.io.model_io import model_lineage_id

                parent_lineage = model_lineage_id(model_dir)
            lineage = {
                "parentModel": parent_lineage,
                "trainedAt": _dt.datetime.now(
                    _dt.timezone.utc).isoformat(),
                "dataManifest": manifest_digest,
            }
        with timed("Validate data", run_logger):
            validate_game_data(data, task,
                               DataValidationType(args.data_validation))

        validation = None
        if args.validation_data:
            reader_v = AvroDataReader(shard_configs=shard_configs,
                                      index_maps=index_maps,
                                      input_columns=reader.input_columns)
            if multiproc:
                # collective path: every process must hold the data before
                # the symmetric training starts — read it here
                with timed("Read validation data", run_logger):
                    vdata, _, _ = reader_v.read(
                        args.validation_data, id_columns=id_columns,
                        entity_vocabs=vocabs)
                validation = (vdata, evaluators)
            else:
                # async ingest: the read runs in the background while the
                # training data uploads and the first sweep trains; the
                # callable joins it at first use (sweep 1's evaluation),
                # and the "Read validation data" stage records the JOIN
                # wall — the visible (unhidden) part of the read
                from photon_ml_tpu.io.pipeline import read_in_background

                _v_future = read_in_background(
                    reader_v.read, args.validation_data,
                    id_columns=id_columns, entity_vocabs=vocabs,
                    label="io.read.validation")
                _v_cell: list = []

                def validation():
                    if not _v_cell:
                        with timed("Read validation data", run_logger):
                            vdata, _, _ = _v_future.result()
                        _v_cell.append((vdata, evaluators))
                    return _v_cell[0]

        est = GameEstimator(task=task, coordinate_configs=coordinate_configs,
                            update_sequence=update_sequence,
                            n_cd_iterations=args.cd_iterations, mesh=mesh)

        # async model publication: each configuration's model save is
        # submitted the moment that configuration finishes, overlapping
        # the remaining grid points and best-selection. With
        # --output-all-models every config lands under all/config-i (and
        # best/ is published later as a hardlink alias of the winner —
        # the model is serialized ONCE); a single-config grid's only
        # result IS best, so it saves straight to best/ while the driver
        # finishes bookkeeping.
        _single_config = [False]
        _best_pre_submitted = [False]

        def _note_result(i, r):
            if saver is None:
                return
            if args.output_all_models:
                saver.submit_game_save(
                    os.path.join(args.output_dir, "all", f"config-{i}"),
                    r.model, index_maps, vocabs,
                    sparsity_threshold=args.model_sparsity_threshold,
                    lineage=lineage)
            elif _single_config[0] and i == 0:
                saver.submit_game_save(
                    os.path.join(args.output_dir, "best"),
                    r.model, index_maps, vocabs,
                    sparsity_threshold=args.model_sparsity_threshold,
                    lineage=lineage)
                _best_pre_submitted[0] = True

        def _mp_fit(config, mp_ckpt=None):
            """One collective-symmetric multi-process fit, evaluated and
            wrapped as a GameResult — shared by the grid and tuning paths
            so their result assembly can never drift apart."""
            from photon_ml_tpu.evaluation import evaluate_all
            from photon_ml_tpu.game.estimator import GameResult
            from photon_ml_tpu.game.multiprocess import (
                train_game_multiprocess,
            )

            mp = train_game_multiprocess(
                data, task, coordinate_configs, update_sequence,
                config.regularization_weights,
                n_cd_iterations=args.cd_iterations,
                checkpoint_dir=mp_ckpt, resume=args.resume,
                initial_models=initial_models, locked=locked,
                validation=validation, guard=guard)
            evaluation = None
            if validation is not None:
                vdata, evs = validation
                # per-sweep history is tracked inside the run; the final
                # EvaluationResults object is re-derived for model selection
                evaluation = evaluate_all(
                    evs, mp.model.score(vdata), vdata.labels,
                    weights=vdata.weights, id_tags=vdata.id_columns)
            return GameResult(
                model=mp.model, configuration=config, evaluation=evaluation,
                validation_history=list(mp.validation_history))

        checkpoint = None
        if (args.checkpoint or args.resume) and not multiproc:
            # multiproc uses its own per-process sweep-boundary state files
            # (created in the training branch below), not this manager
            from photon_ml_tpu.io.checkpoint import CheckpointManager

            # non-chief: read-only, so --resume stays in lockstep with the
            # chief's checkpoints without racing its writes
            checkpoint = CheckpointManager(
                os.path.join(args.output_dir, "checkpoints"),
                read_only=not chief)
            if jax.process_count() > 1:
                # agree on the resume point ONCE, before training: each
                # process polling the shared filesystem independently would
                # race the chief's own saves (collective: all processes
                # must reach this broadcast)
                import numpy as _np
                from jax.experimental import multihost_utils

                step = checkpoint.latest_step() if chief else None
                agreed = int(multihost_utils.broadcast_one_to_all(
                    _np.int64(-1 if step is None else step)))
                checkpoint.pin_step(None if agreed < 0 else agreed)
        profile_dir = (os.path.join(args.output_dir, "profile")
                       if args.profile else None)

        if args.tuning == "NONE":
            grid = parse_grid(args.grid)
            unknown = {cid for g in grid for cid in g} - set(update_sequence)
            if unknown:
                raise SystemExit(
                    f"--grid names unknown coordinates {sorted(unknown)}; "
                    f"update sequence is {update_sequence}")
            configurations = [GameOptimizationConfiguration(g) for g in grid]
            if ((checkpoint is not None
                 or (multiproc and (args.checkpoint or args.resume)))
                    and len(configurations) != 1):
                raise SystemExit("--checkpoint/--resume need a single-config "
                                 "grid (got %d configs)" % len(configurations))
            from photon_ml_tpu.logging_util import profiled

            if multiproc:
                # multi-process checkpoints are per-process sweep-boundary
                # state files (game/multiprocess.py), not the single-process
                # CheckpointManager format
                mp_ckpt = None
                if args.checkpoint or args.resume:
                    mp_ckpt = os.path.join(args.output_dir,
                                           "checkpoints-mp")
                results = []
                with timed("Train (grid, multi-process)", run_logger), \
                        profiled(profile_dir):
                    # grid points run sequentially — each is one
                    # collective-symmetric training all processes join
                    for config in configurations:
                        results.append(_mp_fit(config, mp_ckpt))
                        _note_result(len(results) - 1, results[-1])
            else:
                _single_config[0] = len(configurations) == 1
                with timed("Train (grid)", run_logger), profiled(profile_dir):
                    results = est.fit(
                        data, configurations, validation=validation,
                        initial_models=initial_models, locked=locked,
                        checkpoint=checkpoint, resume=args.resume,
                        guard=guard, on_result=_note_result)
                    # drain the async solve queue inside the timed block:
                    # without this the final sweep's device programs finish
                    # during "Save models", which then reports compute as
                    # IO (stages get reference Timed semantics; the wall is
                    # unchanged — save's materialize would wait anyway)
                    results[-1].model.device_wait()
        else:
            if validation is None:
                raise SystemExit("--tuning needs --validation-data")
            if (checkpoint is not None
                    or (multiproc and (args.checkpoint or args.resume))):
                raise SystemExit("--checkpoint/--resume don't combine with "
                                 "--tuning")
            from photon_ml_tpu.hyperparameter.search import (
                GaussianProcessSearch,
                ParamRange,
                RandomSearch,
            )

            low, high = (float(x) for x in args.tuning_range.split(":"))
            # locked coordinates are frozen — tuning their lambda would
            # explore a dead axis
            space = {cid: ParamRange(low, high) for cid in update_sequence
                     if cid not in locked}
            results = []
            if multiproc:
                # every process runs the IDENTICAL search loop: the search
                # is deterministic (seeded) and each observation — the
                # validation metric of a collective-symmetric training —
                # is computed identically on every process, so the
                # candidate sequence never diverges
                def evaluate(config: dict) -> float:
                    r = _mp_fit(GameOptimizationConfiguration(config))
                    results.append(r)
                    _note_result(len(results) - 1, r)
                    return r.evaluation.primary[1]

                def release_datasets():
                    pass  # per-fit datasets are process-local temporaries
            else:
                datasets = est.prepare(data, locked=locked)  # build once

                def evaluate(config: dict) -> float:
                    r = est.fit(data, [GameOptimizationConfiguration(config)],
                                validation=validation, datasets=datasets,
                                initial_models=initial_models, locked=locked,
                                guard=guard)[0]
                    results.append(r)
                    _note_result(len(results) - 1, r)
                    return r.evaluation.primary[1]

                def release_datasets():
                    # tuning holds the datasets across fits; drop the cached
                    # device placements (HBM) once the search is done —
                    # including GameData's (dense shard image, labels/weights
                    # uploaded by device_dense_shard)
                    for ds in datasets.values():
                        if hasattr(ds, "clear_device_cache"):
                            ds.clear_device_cache()
                    data.clear_device_cache()

            maximize = evaluators[0].maximize
            search_cls = (GaussianProcessSearch if args.tuning == "BAYESIAN"
                          else RandomSearch)
            from photon_ml_tpu.logging_util import profiled

            with timed(f"Train ({args.tuning} tuning)", run_logger), \
                    profiled(profile_dir):
                if args.tuning == "BAYESIAN":
                    search_cls(space, maximize=maximize).find(
                        evaluate, args.tuning_iterations)
                else:
                    search_cls(space).find(evaluate, args.tuning_iterations)
            release_datasets()

        best = GameEstimator.select_best(results)
        for i, r in enumerate(results):
            GLOBAL_BUS.post(
                "configuration_evaluated", index=i,
                config=dict(r.configuration.regularization_weights),
                evaluation=r.evaluation.as_dict() if r.evaluation else None)
        if best.evaluation is not None:
            run_logger.metric(stage="best", **best.evaluation.as_dict(),
                              config=dict(best.configuration.regularization_weights))

        if chief:
            best_dir = os.path.join(args.output_dir, "best")
            # train-time quality baseline (quality/baseline.py): profile
            # the winner's score distribution on the validation set (the
            # training set when the run has none — still a reference
            # distribution for online drift) and publish it at the run
            # root next to best/ and data-manifest.json. The whole
            # computation rides the background writer pool: score-side
            # work never touches the training wall, and the serving
            # registry rediscovers the artifact at load time.
            from photon_ml_tpu.quality import (
                BASELINE_NAME,
                baseline_from_game,
                save_baseline,
            )

            if validation is not None:
                _b_source = (validation() if callable(validation)
                             else validation)[0]
            else:
                _b_source = data

            def _write_baseline(path, model=best.model, bdata=_b_source,
                                blineage=lineage):
                save_baseline(path, baseline_from_game(
                    model, bdata, task=task, lineage=blineage))

            saver.submit_file_write(
                _write_baseline,
                os.path.join(args.output_dir, BASELINE_NAME),
                label="quality.baseline")
            if not args.output_all_models and not _best_pre_submitted[0]:
                # multi-config grid / tuning without --output-all-models:
                # the winner is only known now — submit its (sole) save
                saver.submit_game_save(
                    best_dir, best.model, index_maps, vocabs,
                    sparsity_threshold=args.model_sparsity_threshold,
                    lineage=lineage)
            # the stage is now the JOIN wall: whatever the background
            # writers didn't finish under train/selection (plus, under
            # --output-all-models, the hardlink alias publish)
            with timed("Save models", run_logger):
                saver.join()
                if args.output_all_models:
                    from photon_ml_tpu.io.pipeline import publish_model_alias

                    best_i = next(i for i, r in enumerate(results)
                                  if r is best)
                    publish_model_alias(
                        os.path.join(args.output_dir, "all",
                                     f"config-{best_i}"), best_dir)
            GLOBAL_BUS.post("model_saved", path=best_dir)
        result = {
            "best_config": dict(best.configuration.regularization_weights),
            "best_evaluation": (best.evaluation.as_dict()
                                if best.evaluation else None),
            "n_configurations": len(results),
            "output_dir": args.output_dir,
        }
        if chief:
            # supervised runs: hand the result dict back to the supervisor
            # (no-op unsupervised)
            from photon_ml_tpu.resilience.supervisor import write_result_file

            write_result_file(result)
        return result
    finally:
        if saver is not None:
            # happy path already join()ed (errors propagated there); this
            # waits out any writer a failing run left in flight so no
            # thread outlives the driver into a dir being torn down
            saver.close()
        _root_span.close()
        GLOBAL_BUS.post("training_finished", driver="train_game")
        telemetry.close()
        run_logger.close()


if __name__ == "__main__":
    run()
