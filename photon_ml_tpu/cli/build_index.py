"""Feature-indexing driver.

Re-design of ``photon-client/.../index/FeatureIndexingDriver.scala``: scan
training data, build one feature index per shard, write them for later
training/scoring runs. The reference writes partitioned PalDB stores because
every executor mmaps them; here one JSON file per shard suffices (see
:mod:`photon_ml_tpu.io.index`).
"""

from __future__ import annotations

import argparse
import os
from typing import Optional, Sequence

from photon_ml_tpu.cli.config import parse_feature_shard_config
from photon_ml_tpu.io import AvroDataReader
from photon_ml_tpu.logging_util import RunLogger, timed


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_ml_tpu build_index",
        description="Build feature index maps from training data")
    p.add_argument("--data", required=True)
    p.add_argument("--output-dir", required=True)
    p.add_argument("--feature-shards", required=True)
    return p


def run(argv: Optional[Sequence[str]] = None) -> dict:
    args = build_parser().parse_args(argv)
    run_logger = RunLogger(args.output_dir)
    try:
        shard_configs = tuple(parse_feature_shard_config(s)
                              for s in args.feature_shards.split(","))
        reader = AvroDataReader(shard_configs=shard_configs)
        from photon_ml_tpu.io.avro import iter_avro_file

        with timed("Scan features", run_logger):
            records = (r for p in reader.paths(args.data)
                       for r in iter_avro_file(p))
            index_maps = reader.build_index_maps(records)
        sizes = {}
        with timed("Write indexes", run_logger):
            for shard_id, imap in index_maps.items():
                imap.save(os.path.join(args.output_dir, f"{shard_id}.json"))
                sizes[shard_id] = len(imap)
                run_logger.metric(stage="index", shard=shard_id,
                                  n_features=len(imap))
        return {"sizes": sizes, "output_dir": args.output_dir}
    finally:
        run_logger.close()


if __name__ == "__main__":
    run()
