"""Command-line drivers.

Re-design of the reference's client layer (``photon-client/.../cli/...`` and
the legacy ``Driver.scala``): the reference's entry points with its
vocabulary, plus the online-serving driver —

- ``python -m photon_ml_tpu train_glm``  (legacy GLM ``Driver``)
- ``python -m photon_ml_tpu train_game`` (``GameTrainingDriver``)
- ``python -m photon_ml_tpu score_game`` (``GameScoringDriver``)
- ``python -m photon_ml_tpu serve_game`` (online HTTP scoring — no
  reference counterpart; see :mod:`photon_ml_tpu.serving`)
- ``python -m photon_ml_tpu build_index`` (``FeatureIndexingDriver``)

Spark-submit/scopt is replaced by argparse; the rich inline DSLs (feature
shard configs, coordinate configs, evaluator strings) are kept — see
:mod:`photon_ml_tpu.cli.config` for the grammar.
"""

from photon_ml_tpu.cli.config import (  # noqa: F401
    parse_coordinate_config,
    parse_feature_shard_config,
)
