"""GAME batch-scoring driver.

Re-design of ``photon-client/.../cli/game/scoring/GameScoringDriver.scala``
(+ ``transformers/GameTransformer.scala``): load a saved GAME model + data →
sum coordinate scores (+ offsets) → write ``ScoringResultAvro`` records;
optional per-coordinate score breakdown and evaluation of the scored output.
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Optional, Sequence

import numpy as np

from photon_ml_tpu.evaluation import parse_evaluators
from photon_ml_tpu.game.transformer import GameTransformer
from photon_ml_tpu.io import AvroDataReader, load_game_model
from photon_ml_tpu.io.avro import write_avro_file
from photon_ml_tpu.io.index import IndexMap
from photon_ml_tpu.io.schemas import SCORING_RESULT_AVRO
from photon_ml_tpu.logging_util import RunLogger, timed


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_ml_tpu score_game",
        description="Score data with a saved GAME model")
    p.add_argument("--data", required=True)
    p.add_argument("--model-dir", required=True,
                   help="a train_game output dir (containing best/ or a "
                        "model-metadata.json directly)")
    p.add_argument("--output-dir", required=True)
    p.add_argument("--feature-shards", required=True,
                   help="same shard specs used at training time")
    p.add_argument("--evaluators", default="",
                   help="optional evaluation of the scored output")
    p.add_argument("--score-breakdown", action="store_true",
                   help="also write per-coordinate scores json")
    p.add_argument("--input-columns", default="",
                   help="remap record fields, e.g. 'response=label' "
                        "(reference InputColumnsNames)")
    p.add_argument("--multihost", action="store_true",
                   help="multi-controller scoring: every process runs this "
                        "same command, reads its share of the input FILE "
                        "LIST (at least one file per process), scores with "
                        "the shared model, and writes its own "
                        "scores-part-<pid>.avro; evaluation (if requested) "
                        "is computed on the globally gathered scores — the "
                        "reference's per-partition scoring map + shuffle-"
                        "side evaluation (GameScoringDriver.scala)")
    from photon_ml_tpu.cli.config import add_telemetry_flags

    # --telemetry-dir / --telemetry-poll-s / --metrics-port: batch scoring
    # gets the same spans, metrics.prom and compile accounting as the
    # training and serving drivers
    add_telemetry_flags(p)
    return p


def run(argv: Optional[Sequence[str]] = None) -> dict:
    from photon_ml_tpu.cli.config import parse_feature_shard_config
    from photon_ml_tpu.io.data_reader import parse_input_columns

    args = build_parser().parse_args(argv)
    if args.multihost:
        from photon_ml_tpu.parallel import multihost

        multihost.initialize(auto=True)
    import jax

    from photon_ml_tpu.cli.config import (
        install_telemetry,
        telemetry_from_args,
    )

    multiproc = args.multihost and jax.process_count() > 1
    chief = jax.process_index() == 0
    log_dir = args.output_dir if chief else os.path.join(
        args.output_dir, "workers", f"proc-{jax.process_index()}")
    run_logger = RunLogger(log_dir)
    # telemetry before the first stage, so every timed() section lands in
    # the span tree; non-chief processes trace under workers/proc-N (same
    # rule as photon.log)
    telemetry = install_telemetry(telemetry_from_args(
        args, subdir=None if chief
        else os.path.join("workers", f"proc-{jax.process_index()}")))
    from photon_ml_tpu.telemetry import emit_build_info, tracing

    emit_build_info()
    import contextlib as _contextlib

    _root_span = _contextlib.ExitStack()
    _root_span.enter_context(tracing.span("score_game"))
    try:
        from photon_ml_tpu.io import (
            find_feature_index_dir,
            resolve_game_model_dir,
        )

        model_dir = resolve_game_model_dir(args.model_dir)
        index_dir = find_feature_index_dir(model_dir)
        shard_configs = tuple(parse_feature_shard_config(s)
                              for s in args.feature_shards.split(","))
        index_maps = {
            cfg.shard_id: IndexMap.load(
                os.path.join(index_dir, f"{cfg.shard_id}.json"))
            for cfg in shard_configs}

        with open(os.path.join(model_dir, "model-metadata.json")) as f:
            metadata = json.load(f)
        re_types = sorted({info["randomEffectType"]
                           for info in metadata["coordinates"].values()
                           if info["type"] == "random-effect"})
        evaluators = parse_evaluators(
            [e for e in args.evaluators.split(",") if e])
        id_columns = tuple(dict.fromkeys(
            re_types + [e.id_tag for e in evaluators if e.id_tag]))

        reader = AvroDataReader(shard_configs=shard_configs,
                                index_maps=index_maps,
                                input_columns=parse_input_columns(
                                    args.input_columns))
        with timed("Read data", run_logger):
            # entity vocab must match training; rebuilt from data then used
            # for lookups — entities unseen at training score 0 for REs
            if multiproc:
                from photon_ml_tpu.game.multiprocess import (
                    process_file_share,
                )

                data, _, vocabs = reader.read(
                    process_file_share(reader, args.data),
                    id_columns=id_columns)
                if evaluators:
                    # grouped metrics compare id tags across processes —
                    # agree on one global id space for them. The model's
                    # RE lookups only need LOCAL consistency (each process
                    # keys its own table from its own vocab), but one
                    # id space serves both, so reconcile for all columns.
                    from photon_ml_tpu.game.multiprocess import (
                        reconcile_vocabs,
                    )

                    data, vocabs = reconcile_vocabs(data, vocabs,
                                                    id_columns)
            else:
                data, _, vocabs = reader.read(args.data,
                                              id_columns=id_columns)

        with timed("Load model", run_logger):
            model = load_game_model(model_dir, index_maps, vocabs)

        transformer = GameTransformer(
            model=model,
            evaluators=() if multiproc else evaluators,
            score_breakdown=args.score_breakdown)
        with timed("Score", run_logger):
            result = transformer.transform(data)

        with timed("Write scores", run_logger):
            os.makedirs(args.output_dir, exist_ok=True)
            # multi-process: one part file per process (the reference's
            # per-partition part-NNNNN outputs); single-process keeps the
            # plain scores.avro name
            out_path = os.path.join(
                args.output_dir,
                f"scores-part-{jax.process_index():05d}.avro"
                if multiproc else "scores.avro")
            from photon_ml_tpu import native

            # columnar native writer (~50x the record encoder); the Python
            # codec is the transparent fallback — codec pinned to null so
            # both paths emit identical container properties, not just
            # identical records
            if not native.write_scoring_results(
                    out_path, np.asarray(result.scores, np.float64),
                    np.asarray(data.labels, np.float64)):
                records = (
                    {"uid": str(i), "predictionScore": float(s),
                     "label": float(l), "metadataMap": None}
                    for i, (s, l) in enumerate(zip(result.scores, data.labels)))
                write_avro_file(out_path, records, SCORING_RESULT_AVRO,
                                codec="null")
            if result.by_coordinate is not None:
                # per-process part name under multi-process: concurrent
                # writers to one shared file would clobber each other
                bd = (f"score-breakdown-part-{jax.process_index():05d}.json"
                      if multiproc else "score-breakdown.json")
                with open(os.path.join(args.output_dir, bd), "w") as f:
                    json.dump({k: v.tolist()
                               for k, v in result.by_coordinate.items()}, f)

        evaluation = None
        n_scored = data.n_samples
        if multiproc:
            from photon_ml_tpu.parallel.multihost import (
                allgather_concat,
                allreduce_sum,
            )

            n_scored = int(allreduce_sum(
                np.array([data.n_samples], np.int64))[0])
            if evaluators:
                # global evaluation on the gathered scores (every process
                # computes the same numbers; chief logs) — the analog of
                # the reference evaluating scored RDDs with shuffles
                from photon_ml_tpu.evaluation import evaluate_all

                g_scores = allgather_concat(
                    np.asarray(result.scores, np.float32))
                g_labels = allgather_concat(
                    np.asarray(data.labels, np.float32))
                g_weights = allgather_concat(
                    np.asarray(data.weights, np.float32))
                g_tags = {c: allgather_concat(data.id_columns[c])
                          for c in sorted(data.id_columns)}
                g_eval = evaluate_all(evaluators, g_scores, g_labels,
                                      weights=g_weights, id_tags=g_tags)
                evaluation = g_eval.as_dict()
                run_logger.metric(stage="evaluate", **evaluation)
        elif result.evaluation is not None:
            evaluation = result.evaluation.as_dict()
            run_logger.metric(stage="evaluate", **evaluation)
        return {"n_scored": n_scored, "evaluation": evaluation,
                "output_dir": args.output_dir}
    finally:
        _root_span.close()
        telemetry.close()
        run_logger.close()


if __name__ == "__main__":
    run()
