"""Inline config DSLs for the drivers.

The reference's scopt parsers accept rich inline grammars
(``util/ScoptGameTrainingParametersParser.scala``); ours keep the same
semantic fields with an explicit, documented syntax:

**Feature shard** (``--feature-shards``, comma-separates multiple)::

    shardId=bag1+bag2            # bags; intercept on by default
    shardId=bag1+bag2|noIntercept
    shardId=*                    # every feature in the record

**Coordinate** (``--coordinates``, one flag per coordinate)::

    coordId=fixed,shard=global,optimizer=LBFGS,reg=L2,maxIter=80,tol=1e-6
    coordId=random,entity=userId,shard=user,reg=L2,activeUpper=1000,
           activeLower=1,maxFeatures=500
    coordId=random,entity=userId,shard=user,projector=RANDOM,projectedDim=64

**Regularization weights** (``--grid``)::

    coordId=0.1;1;10  [space-separated groups → cartesian product]

**Evaluators** (``--evaluators``): reference vocabulary — ``AUC``, ``RMSE``,
``LOGISTIC_LOSS``, ``AUC:queryId``, ``PRECISION@5:documentId``, ...
"""

from __future__ import annotations

import dataclasses
import itertools
import os
from typing import Mapping, Optional, Sequence

from photon_ml_tpu.game.data import RandomEffectDatasetConfig
from photon_ml_tpu.game.projector import ProjectorType
from photon_ml_tpu.game.estimator import (
    FactoredRandomEffectCoordinateConfig,
    FixedEffectCoordinateConfig,
    RandomEffectCoordinateConfig,
)
from photon_ml_tpu.glm.problem import GLMOptimizationConfiguration
from photon_ml_tpu.io.data_reader import FeatureShardConfig
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optimize import OptimizerConfig
from photon_ml_tpu.sampling import BinaryClassificationDownSampler, DownSampler
from photon_ml_tpu.types import OptimizerType, RegularizationType


def parse_feature_shard_config(spec: str) -> FeatureShardConfig:
    spec = spec.strip()
    if "=" not in spec:
        raise ValueError(f"feature shard spec needs shardId=bags, got {spec!r}")
    shard_id, rhs = spec.split("=", 1)
    has_intercept = True
    if "|" in rhs:
        rhs, flag = rhs.split("|", 1)
        if flag == "noIntercept":
            has_intercept = False
        elif flag != "intercept":
            raise ValueError(f"unknown shard flag {flag!r}")
    bags = None if rhs == "*" else tuple(b for b in rhs.split("+") if b)
    return FeatureShardConfig(shard_id=shard_id.strip(), feature_bags=bags,
                              has_intercept=has_intercept)


def _parse_kv(parts: Sequence[str]) -> dict[str, str]:
    out = {}
    for p in parts:
        if not p:
            continue
        if "=" not in p:
            raise ValueError(f"expected key=value, got {p!r}")
        k, v = p.split("=", 1)
        out[k.strip()] = v.strip()
    return out


def _optimization(kv: dict) -> GLMOptimizationConfiguration:
    reg_type = RegularizationType(kv.pop("reg", "NONE").upper())
    alpha = float(kv.pop("alpha", 0.5))
    optimizer = OptimizerType(kv.pop("optimizer", "LBFGS").upper())
    opt_cfg = OptimizerConfig(
        max_iterations=int(kv.pop("maxIter", 80)),
        tolerance=float(kv.pop("tol", 1e-6)),
        history=int(kv.pop("history", 10)),
    )
    from photon_ml_tpu.types import VarianceComputationType

    variance = VarianceComputationType(kv.pop("variance", "NONE").upper())
    return GLMOptimizationConfiguration(
        optimizer=optimizer,
        regularization=RegularizationContext(reg_type, alpha=alpha),
        optimizer_config=opt_cfg,
        variance_type=variance,
    )


def parse_coordinate_config(spec: str):
    """Returns (coordinateId, FixedEffect/RandomEffectCoordinateConfig)."""
    spec = spec.strip()
    if "=" not in spec:
        raise ValueError(f"coordinate spec needs coordId=kind,..., got {spec!r}")
    cid, rhs = spec.split("=", 1)
    cid = cid.strip()
    parts = rhs.split(",")
    kind = parts[0].strip()
    kv = _parse_kv(parts[1:])
    if kind == "fixed":
        shard = kv.pop("shard")
        downsampler = None
        if "downsample" in kv:
            rate = float(kv.pop("downsample"))
            mode = kv.pop("downsampleMode", "binary")
            cls = (BinaryClassificationDownSampler if mode == "binary"
                   else DownSampler)
            downsampler = cls(rate=rate)
        cfg = FixedEffectCoordinateConfig(
            feature_shard_id=shard, optimization=_optimization(kv),
            downsampler=downsampler)
    elif kind in ("random", "factored"):
        entity = kv.pop("entity")
        shard = kv.pop("shard")
        cache = kv.pop("cacheBuckets", "true").lower()
        if cache not in ("true", "false"):
            raise ValueError(
                f"cacheBuckets must be true or false, got {cache!r}")
        if kind == "factored":
            # the learned projection IS the RANDOM projector; accept a
            # redundant projector=RANDOM, reject anything else
            projector = kv.pop("projector", "RANDOM").upper()
            if projector != "RANDOM":
                raise ValueError(
                    f"factored coordinates always use the RANDOM projector "
                    f"(the projection is the trained object); got "
                    f"projector={projector!r}")
            projector_type = ProjectorType.RANDOM
        else:
            projector_type = ProjectorType(
                kv.pop("projector", "INDEX_MAP").upper())
        buckets = kv.pop("buckets", "geometric").lower()
        ds = RandomEffectDatasetConfig(
            random_effect_type=entity,
            feature_shard_id=shard,
            active_data_upper_bound=(int(kv.pop("activeUpper"))
                                     if "activeUpper" in kv else None),
            active_data_lower_bound=int(kv.pop("activeLower", 1)),
            max_active_features=(int(kv.pop("maxFeatures"))
                                 if "maxFeatures" in kv else None),
            projector_type=projector_type,
            projected_dim=(int(kv.pop("projectedDim"))
                           if "projectedDim" in kv else None),
            cache_device_buckets=cache == "true",
            bucket_strategy=buckets,
            max_sample_buckets=int(kv.pop("maxSampleBuckets", 8)),
            max_feature_buckets=int(kv.pop("maxFeatureBuckets", 4)),
        )
        if kind == "factored":
            cfg = FactoredRandomEffectCoordinateConfig(
                dataset=ds,
                lam_projection=float(kv.pop("lamProjection", 0.0)),
                n_factored_iterations=int(kv.pop("factoredIterations", 2)),
                optimization=_optimization(kv))
        else:
            cfg = RandomEffectCoordinateConfig(
                dataset=ds, optimization=_optimization(kv))
    else:
        raise ValueError(
            f"coordinate kind must be fixed|random|factored, got {kind!r}")
    if kv:
        raise ValueError(f"unknown coordinate options {sorted(kv)} in {spec!r}")
    return cid, cfg


# ---------------------------------------------------------------------------
# Resilience configuration (shared by train_game and train_glm)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ResilienceConfig:
    """The drivers' retry/divergence knobs, round-trippable through a JSON
    config file (:meth:`as_dict` / :meth:`from_dict`) so a chaos sweep or a
    production deployment can pin them alongside the rest of the run
    configuration.

    ``max_retries`` is RETRIES, not attempts (0 = try once); it budgets
    both the IO retry policy and the divergence guard's rollback-retries.
    ``on_divergence``: ``fail`` (raise with an actionable message — the
    default), ``rollback`` (roll back + regularization backoff, freeze
    after the budget), ``freeze`` (freeze immediately).
    """

    max_retries: int = 2
    retry_deadline_s: Optional[float] = None
    on_divergence: str = "fail"
    reg_backoff: float = 10.0

    def __post_init__(self):
        if self.max_retries < 0:
            raise ValueError(
                f"max_retries must be >= 0, got {self.max_retries}")
        if self.on_divergence not in ("fail", "rollback", "freeze"):
            raise ValueError(
                f"on_divergence must be fail|rollback|freeze, "
                f"got {self.on_divergence!r}")

    # --- config-file round-trip ------------------------------------------
    def as_dict(self) -> dict:
        return {
            "maxRetries": self.max_retries,
            "retryDeadlineS": self.retry_deadline_s,
            "onDivergence": self.on_divergence,
            "regBackoff": self.reg_backoff,
        }

    @classmethod
    def from_dict(cls, d: Mapping) -> "ResilienceConfig":
        return cls(
            max_retries=int(d.get("maxRetries", 2)),
            retry_deadline_s=(None if d.get("retryDeadlineS") is None
                              else float(d["retryDeadlineS"])),
            on_divergence=str(d.get("onDivergence", "fail")),
            reg_backoff=float(d.get("regBackoff", 10.0)),
        )

    # --- materialization --------------------------------------------------
    def retry_policy(self):
        from photon_ml_tpu.resilience import RetryPolicy

        return RetryPolicy(max_attempts=self.max_retries + 1,
                           deadline_s=self.retry_deadline_s)

    def guard(self, bus=None):
        from photon_ml_tpu.resilience import DivergenceGuard, DivergencePolicy

        return DivergenceGuard(
            DivergencePolicy(mode=self.on_divergence,
                             max_retries=self.max_retries,
                             reg_backoff=self.reg_backoff),
            bus=bus)


def add_resilience_flags(parser) -> None:
    """The shared driver flags (train_game + train_glm)."""
    parser.add_argument(
        "--max-retries", type=int, default=2,
        help="retries (not attempts) for transient faults: Avro reads, "
             "checkpoint save/load, multihost initialization — and the "
             "divergence guard's per-coordinate rollback budget")
    parser.add_argument(
        "--retry-deadline-s", type=float, default=None,
        help="hard wall-clock deadline across one operation's retries "
             "(the retry never sleeps into a deadline it would blow)")
    parser.add_argument(
        "--on-divergence", choices=["fail", "rollback", "freeze"],
        default="fail",
        help="when a coordinate step produces NaN/Inf: fail = raise with "
             "an actionable error (default); rollback = roll back to the "
             "last good state, bump the coordinate's regularization and "
             "retry (freeze after --max-retries failures); freeze = lock "
             "the coordinate at its last good model immediately and "
             "continue degraded")


def add_supervision_flags(parser) -> None:
    """The supervised-recovery flags (train_game + train_glm): an external
    :class:`~photon_ml_tpu.resilience.FleetSupervisor` owns the fleet's
    process lifecycle and recovers the ASYMMETRIC fault class (one process
    dead or stalled mid-collective) the in-process machinery cannot."""
    parser.add_argument(
        "--supervise", type=int, default=0, metavar="N",
        help="launch the training as an N-process supervised fleet: this "
             "command relaunches itself N times under a FleetSupervisor "
             "that watches exit codes + per-process heartbeats and, on any "
             "asymmetric failure (a process crash, or a heartbeat stale "
             "past --heartbeat-timeout-s), kills the survivors and "
             "restarts the WHOLE fleet from the latest agreed checkpoint. "
             "0 (default) = train in this process, unsupervised")
    parser.add_argument(
        "--max-restarts", type=int, default=2, metavar="K",
        help="supervised-fleet restart budget (restarts, not attempts; "
             "exponential backoff between attempts). Past the budget the "
             "supervisor raises with the failing processes' log tails")
    parser.add_argument(
        "--heartbeat-timeout-s", type=float, default=300.0,
        help="declare a supervised process stalled when its heartbeat file "
             "(touched at sweep/coordinate/collective boundaries) goes "
             "this stale — size it above the longest healthy gap between "
             "boundaries (a long healthy collective does not beat while "
             "inside it). <= 0 disables stall detection (exit codes only)")
    parser.add_argument(
        "--restart-deadline-s", type=float, default=None,
        help="hard wall-clock deadline across ALL supervised attempts "
             "including backoff sleeps; like retries, the supervisor never "
             "sleeps into a deadline it would then blow")


def resilience_from_args(args) -> ResilienceConfig:
    return ResilienceConfig(max_retries=args.max_retries,
                            retry_deadline_s=args.retry_deadline_s,
                            on_divergence=args.on_divergence)


def install_resilience(config: ResilienceConfig):
    """Install the process-wide retry policy and build the run's guard —
    the one call both drivers make after parsing flags."""
    from photon_ml_tpu.resilience import set_default_policy

    set_default_policy(config.retry_policy())
    return config.guard()


# ---------------------------------------------------------------------------
# Telemetry configuration (shared by train_game, train_glm and serve_game)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TelemetryConfig:
    """The drivers' telemetry knobs, round-trippable through a JSON config
    file like :class:`ResilienceConfig`.

    ``telemetry_dir`` (None = disabled) receives ``trace.jsonl`` (the span
    tree) while the run is live and ``metrics.prom`` (the registry
    snapshot) at close; ``poll_interval_s`` (0 = disabled) starts the
    host-RSS/device-memory gauge sampler at that period AND, when a
    telemetry dir is set, re-snapshots ``metrics.prom`` on the same cadence
    (push-gateway-style, so batch runs are observable mid-flight);
    ``metrics_port`` (0 = disabled) serves the live fleet-wide aggregate
    from ``GET /metrics`` on the chief and, at >1 process, enables the
    collective registry fold at sweep boundaries.
    """

    telemetry_dir: Optional[str] = None
    poll_interval_s: float = 0.0
    metrics_port: int = 0

    def __post_init__(self):
        if self.poll_interval_s < 0:
            raise ValueError(f"poll_interval_s must be >= 0, "
                             f"got {self.poll_interval_s}")
        if not 0 <= self.metrics_port < 65536:
            raise ValueError(f"metrics_port must be in [0, 65535], "
                             f"got {self.metrics_port}")

    # --- config-file round-trip ------------------------------------------
    def as_dict(self) -> dict:
        return {"telemetryDir": self.telemetry_dir,
                "pollIntervalS": self.poll_interval_s,
                "metricsPort": self.metrics_port}

    @classmethod
    def from_dict(cls, d: Mapping) -> "TelemetryConfig":
        return cls(telemetry_dir=d.get("telemetryDir"),
                   poll_interval_s=float(d.get("pollIntervalS", 0.0)),
                   metrics_port=int(d.get("metricsPort", 0)))


def add_telemetry_flags(parser) -> None:
    """The shared driver flags (train_game, train_glm, serve_game)."""
    parser.add_argument(
        "--telemetry-dir", default=None,
        help="enable span tracing + metric export into this directory: "
             "trace.jsonl (nested spans: stages, coordinate-descent sweeps "
             "and steps, optimizer traces) streamed during the run, "
             "metrics.prom (Prometheus text snapshot of every counter/"
             "gauge/histogram) written at exit — plus, on the chief of a "
             "--metrics-port run, metrics.aggregate.prom (the fleet fold; "
             "tools/metrics_fold.py reproduces it offline). Default: "
             "telemetry off (zero per-step device syncs)")
    parser.add_argument(
        "--telemetry-poll-s", type=float, default=0.0,
        help="poll interval for the host-RSS / device-memory gauge "
             "sampler (seconds; 0 disables — device memory_stats can "
             "synchronize with the backend, so this is strictly opt-in). "
             "With --telemetry-dir, also re-snapshots metrics.prom at the "
             "same period so batch runs are scrapeable mid-flight")
    parser.add_argument(
        "--metrics-port", type=int, default=0,
        help="serve GET /metrics on this port (chief process only; 0 "
             "disables). In a --multihost run the endpoint returns the "
             "FLEET aggregate — counters and histogram buckets summed "
             "across every process, per-host gauges fanned out under a "
             "process label — refreshed by a collective registry fold at "
             "each coordinate-descent sweep / GLM lambda boundary")


def telemetry_from_args(args, *, subdir: Optional[str] = None,
                        ) -> TelemetryConfig:
    """``subdir`` relocates a non-chief process's telemetry under
    ``workers/proc-N`` — N processes appending to one trace.jsonl would
    interleave records from different runs of the id counter."""
    tdir = args.telemetry_dir
    if tdir and subdir:
        tdir = os.path.join(tdir, subdir)
    return TelemetryConfig(telemetry_dir=tdir,
                           poll_interval_s=args.telemetry_poll_s,
                           metrics_port=args.metrics_port)


def install_telemetry(config: TelemetryConfig):
    """Start the run's telemetry session (a no-op session when everything
    is disabled) — the one call every driver makes after parsing flags.
    Callers own ``session.close()``."""
    from photon_ml_tpu.telemetry import start_telemetry

    return start_telemetry(telemetry_dir=config.telemetry_dir,
                           poll_interval_s=config.poll_interval_s,
                           metrics_port=config.metrics_port)


# ---------------------------------------------------------------------------
# Retained-telemetry configuration (serve_game and serve_fleet)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RetainedConfig:
    """The serving mains' retained-telemetry knobs (history ring +
    black-box flight recorder), round-trippable through a JSON config
    file like :class:`TelemetryConfig`.

    The history sampler is ALWAYS armed on a serving host (a ring of
    ``history_capacity`` snapshots behind ``GET /history``);
    ``history_period_s`` (0 = manual ticks only, what tests drive)
    starts the periodic sampler thread. ``flight_dir`` (None = disabled)
    arms the flight recorder: the last ``flight_capacity`` spans/events/
    logs/history snapshots, dumped atomically to ``flight-<ts>.jsonl``
    on fault-site trip, unhandled exception, SIGTERM and watchdog stall
    (``watchdog_timeout_s`` > 0 arms the in-process stall watchdog,
    petted by history samples).
    """

    history_capacity: int = 240
    history_period_s: float = 0.0
    flight_dir: Optional[str] = None
    flight_capacity: int = 512
    watchdog_timeout_s: float = 0.0

    def __post_init__(self):
        if self.history_capacity <= 0:
            raise ValueError(f"history_capacity must be > 0, "
                             f"got {self.history_capacity}")
        if self.history_period_s < 0:
            raise ValueError(f"history_period_s must be >= 0, "
                             f"got {self.history_period_s}")
        if self.flight_capacity <= 0:
            raise ValueError(f"flight_capacity must be > 0, "
                             f"got {self.flight_capacity}")
        if self.watchdog_timeout_s < 0:
            raise ValueError(f"watchdog_timeout_s must be >= 0, "
                             f"got {self.watchdog_timeout_s}")

    # --- config-file round-trip ------------------------------------------
    def as_dict(self) -> dict:
        return {"historyCapacity": self.history_capacity,
                "historyPeriodS": self.history_period_s,
                "flightDir": self.flight_dir,
                "flightCapacity": self.flight_capacity,
                "watchdogTimeoutS": self.watchdog_timeout_s}

    @classmethod
    def from_dict(cls, d: Mapping) -> "RetainedConfig":
        return cls(
            history_capacity=int(d.get("historyCapacity", 240)),
            history_period_s=float(d.get("historyPeriodS", 0.0)),
            flight_dir=d.get("flightDir"),
            flight_capacity=int(d.get("flightCapacity", 512)),
            watchdog_timeout_s=float(d.get("watchdogTimeoutS", 0.0)))


def add_retained_flags(parser) -> None:
    """The retained-telemetry flags (serve_game, serve_fleet)."""
    parser.add_argument(
        "--history-capacity", type=int, default=240,
        help="snapshots retained by the on-host telemetry history ring "
             "served from GET /history (closed series vocabulary: "
             "requests, shed_rate, hedge_rate, shard p50/p99, compiles, "
             "...). The ring is always armed; this bounds its memory")
    parser.add_argument(
        "--history-period-s", type=float, default=0.0,
        help="period of the history sampler thread (seconds; 0 = no "
             "thread, snapshots only on demand — tests drive the "
             "injectable tick directly). Each snapshot derives the "
             "interval's series from the watched registry subset")
    parser.add_argument(
        "--flight-dir", default=None,
        help="arm the black-box flight recorder: keep the last "
             "--flight-capacity span/event/log/history records in a "
             "preallocated ring and dump them ATOMICALLY to "
             "flight-<ts>.jsonl in this directory on fault-site trip, "
             "unhandled exception, SIGTERM, or watchdog stall "
             "(tools/postmortem.py renders the incident report). "
             "Default: off")
    parser.add_argument(
        "--flight-capacity", type=int, default=512,
        help="flight-recorder ring capacity (records)")
    parser.add_argument(
        "--watchdog-timeout-s", type=float, default=0.0,
        help="with --flight-dir and --history-period-s > 0: dump a "
             "watchdog_stall flight record when history sampling stops "
             "making progress for this long (seconds; 0 disables). The "
             "fleet supervisor's heartbeat-stall detection triggers the "
             "same dump class out-of-process")


def retained_from_args(args) -> RetainedConfig:
    return RetainedConfig(
        history_capacity=args.history_capacity,
        history_period_s=args.history_period_s,
        flight_dir=args.flight_dir,
        flight_capacity=args.flight_capacity,
        watchdog_timeout_s=args.watchdog_timeout_s)


# ---------------------------------------------------------------------------
# Capacity configuration (serve_game and serve_fleet)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CapacityConfig:
    """The serving mains' capacity-plane knobs (OBSERVABILITY.md
    "Saturation & capacity"), round-trippable through a JSON config file
    like :class:`RetainedConfig`.

    ``max_connections`` (0 = unlimited) is the connection budget: past
    it, a new socket is answered with ONE typed 503
    ``reason=connections`` + ``Connection: close`` and refused — the
    accounting (and the refusal contract) the future event-loop front
    end must preserve. The saturation sampler itself is always armed on
    a serving host (USE gauges ride the history ring's tick; there is
    nothing to configure).
    """

    max_connections: int = 0

    def __post_init__(self):
        if self.max_connections < 0:
            raise ValueError(f"max_connections must be >= 0, "
                             f"got {self.max_connections}")

    # --- config-file round-trip ------------------------------------------
    def as_dict(self) -> dict:
        return {"maxConnections": self.max_connections}

    @classmethod
    def from_dict(cls, d: Mapping) -> "CapacityConfig":
        return cls(max_connections=int(d.get("maxConnections", 0)))


def add_capacity_flags(parser) -> None:
    """The capacity-plane flags (serve_game, serve_fleet)."""
    parser.add_argument(
        "--max-connections", type=int, default=0, metavar="N",
        help="connection budget per serving host (0 = unlimited): a "
             "socket past the ceiling gets one typed 503 "
             "reason=connections with Connection: close — counted in "
             "photon_connections_refused_total, surfaced by /readyz as "
             "connections_exhausted, feeding the brownout ladder — "
             "never a hang (SERVING.md 'Connection budget')")


def capacity_from_args(args) -> CapacityConfig:
    return CapacityConfig(max_connections=args.max_connections)


# ---------------------------------------------------------------------------
# Model-quality configuration (serve_game; baseline knobs on the trainers)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class QualityConfig:
    """serve_game's model-quality knobs, round-trippable through a JSON
    config file like :class:`ResilienceConfig`.

    ``canary_gate`` refuses divergent candidates at activation
    (``canary_bound`` None = the table dtype's documented score
    tolerance, see quality/canary.py); ``quality_poll_s`` (0 = disabled)
    runs the background drift evaluator at that period, raising
    ``quality_drift_detected`` past ``drift_threshold`` (PSI).
    """

    canary_gate: bool = False
    canary_bound: Optional[float] = None
    quality_poll_s: float = 0.0
    drift_threshold: float = 0.25

    def __post_init__(self):
        if self.quality_poll_s < 0:
            raise ValueError(f"quality_poll_s must be >= 0, "
                             f"got {self.quality_poll_s}")
        if self.canary_bound is not None and self.canary_bound < 0:
            raise ValueError(f"canary_bound must be >= 0, "
                             f"got {self.canary_bound}")

    # --- config-file round-trip ------------------------------------------
    def as_dict(self) -> dict:
        return {"canaryGate": self.canary_gate,
                "canaryBound": self.canary_bound,
                "qualityPollS": self.quality_poll_s,
                "driftThreshold": self.drift_threshold}

    @classmethod
    def from_dict(cls, d: Mapping) -> "QualityConfig":
        return cls(
            canary_gate=bool(d.get("canaryGate", False)),
            canary_bound=(None if d.get("canaryBound") is None
                          else float(d["canaryBound"])),
            quality_poll_s=float(d.get("qualityPollS", 0.0)),
            drift_threshold=float(d.get("driftThreshold", 0.25)))

    # --- materialization --------------------------------------------------
    def canary(self):
        from photon_ml_tpu.quality import CanaryConfig

        return CanaryConfig(gate=self.canary_gate, bound=self.canary_bound)


def add_quality_flags(parser) -> None:
    """The serve_game model-quality flags (drift monitoring + canary)."""
    parser.add_argument(
        "--canary-gate", action="store_true",
        help="REFUSE a /reload or watch-dir candidate — exactly like a "
             "validation failure, the incumbent keeps serving — when its "
             "shadow scores over a reservoir of recent live requests "
             "diverge from the incumbent's past the bound. Without the "
             "flag the divergence is still measured and annotated onto "
             "the activation")
    parser.add_argument(
        "--canary-bound", type=float, default=None,
        help="max relative score divergence the canary accepts; default "
             "= the configured --table-dtype's documented score "
             "tolerance (bf16 1e-2, int8 5e-2; float32 takes 5e-2). "
             "Widen it for intended large model changes")
    parser.add_argument(
        "--quality-poll-s", type=float, default=0.0,
        help="period of the background drift evaluator: fold the live "
             "score distribution against the active model's train-time "
             "quality-baseline.json into photon_quality_drift_score "
             "gauges, posting quality_drift_detected past "
             "--drift-threshold (0 disables; evaluation is host-side "
             "accumulator reads — never touches the score path)")
    parser.add_argument(
        "--drift-threshold", type=float, default=0.25,
        help="total-score PSI above which quality_drift_detected fires "
             "(rule of thumb: >0.25 = significant population shift)")


def quality_from_args(args) -> QualityConfig:
    return QualityConfig(canary_gate=args.canary_gate,
                         canary_bound=args.canary_bound,
                         quality_poll_s=args.quality_poll_s,
                         drift_threshold=args.drift_threshold)


# ---------------------------------------------------------------------------
# Ranked-retrieval configuration (serve_game)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RankConfig:
    """serve_game's ``/rank`` knobs, round-trippable through a JSON config
    file like :class:`ResilienceConfig`.

    ``item_coordinate`` names the random-effect coordinate whose entity
    axis ``/rank`` retrieves over (None = ranking disabled — ``/rank``
    answers 400); ``max_k`` bounds the requestable k and sizes the
    power-of-two k buckets the ranking engine pre-traces.
    """

    item_coordinate: Optional[str] = None
    max_k: int = 128

    def __post_init__(self):
        if self.max_k < 1:
            raise ValueError(f"max_k must be >= 1, got {self.max_k}")

    # --- config-file round-trip ------------------------------------------
    def as_dict(self) -> dict:
        return {"itemCoordinate": self.item_coordinate,
                "maxK": self.max_k}

    @classmethod
    def from_dict(cls, d: Mapping) -> "RankConfig":
        return cls(item_coordinate=d.get("itemCoordinate"),
                   max_k=int(d.get("maxK", 128)))


def add_rank_flags(parser) -> None:
    """The serve_game ranked-retrieval flags (SERVING.md "Ranked
    retrieval")."""
    parser.add_argument(
        "--rank-item-coordinate", default=None, metavar="COORD",
        help="enable GET /rank?user=...&k=...: the random-effect "
             "coordinate whose entity axis is the ITEM vocabulary — its "
             "dense serving table is re-packed item-major (same "
             "--table-dtype, dequantized in-trace) and each request "
             "becomes one device matmul + top_k over every item. "
             "Default: ranking disabled")
    parser.add_argument(
        "--rank-max-k", type=int, default=128,
        help="largest requestable k (/rank k past it is a 400); also "
             "sizes the power-of-two k buckets the ranking engine "
             "pre-traces at warmup — the zero-recompile contract's "
             "k half")


def rank_from_args(args) -> RankConfig:
    return RankConfig(item_coordinate=args.rank_item_coordinate,
                      max_k=args.rank_max_k)


# ---------------------------------------------------------------------------
# Fleet-routing configuration (serve_fleet; shard flags on serve_game)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """The fleet router's knobs (``serve_fleet``), round-trippable through
    a JSON config file like :class:`ResilienceConfig`.

    ``fleet_shards`` is N — how many entity-sharded shard groups the
    router fronts; ``replicas`` is R — how many serving hosts per shard
    group (each serving the SAME ``--fleet-shard I --fleet-shard-count
    N`` view; R ≥ 2 turns a dead host into a replica retry instead of a
    503, and lets the router hedge slow legs); ``hedge_delay_ms`` fixes
    when the backup replica fires against a still-pending primary (0 =
    adaptive: the p99 of the shard's recent leg latencies);
    ``fanout_timeout_s`` bounds each per-host leg (a slower host becomes
    a typed 503 ``reason=upstream``, never a hang);
    ``request_timeout_ms`` is the router-side default deadline for
    requests carrying no ``X-Photon-Deadline-Ms`` of their own (0 =
    none), propagated to hosts as the REMAINING budget.

    ``slo_objective_ms`` arms the fleet SLO burn-rate tracker
    (``fleet/observe.py``): a routed request slower than the objective
    (or failed) spends error budget against ``slo_target``; the tracker
    ticks every ``slo_tick_s`` and posts edge-triggered
    ``slo_burn_alert`` events (→ ``photon_slo_burn_total{window}``).
    0 = no tracker.
    """

    fleet_shards: int = 2
    replicas: int = 1
    hedge_delay_ms: float = 0.0
    fanout_timeout_s: float = 30.0
    request_timeout_ms: float = 0.0
    slo_objective_ms: float = 0.0
    slo_target: float = 0.999
    slo_tick_s: float = 10.0

    def __post_init__(self):
        if self.fleet_shards < 1:
            raise ValueError(f"fleet_shards must be >= 1, "
                             f"got {self.fleet_shards}")
        if self.replicas < 1:
            raise ValueError(f"replicas must be >= 1, "
                             f"got {self.replicas}")
        if self.hedge_delay_ms < 0:
            raise ValueError(f"hedge_delay_ms must be >= 0, "
                             f"got {self.hedge_delay_ms}")
        if self.fanout_timeout_s <= 0:
            raise ValueError(f"fanout_timeout_s must be > 0, "
                             f"got {self.fanout_timeout_s}")
        if self.slo_objective_ms < 0:
            raise ValueError(f"slo_objective_ms must be >= 0, "
                             f"got {self.slo_objective_ms}")
        if not 0.0 < self.slo_target < 1.0:
            raise ValueError(f"slo_target must be in (0, 1), "
                             f"got {self.slo_target}")
        if self.slo_tick_s <= 0:
            raise ValueError(f"slo_tick_s must be > 0, "
                             f"got {self.slo_tick_s}")

    # --- config-file round-trip ------------------------------------------
    def as_dict(self) -> dict:
        return {"fleetShards": self.fleet_shards,
                "replicas": self.replicas,
                "hedgeDelayMs": self.hedge_delay_ms,
                "fanoutTimeoutS": self.fanout_timeout_s,
                "requestTimeoutMs": self.request_timeout_ms,
                "sloObjectiveMs": self.slo_objective_ms,
                "sloTarget": self.slo_target,
                "sloTickS": self.slo_tick_s}

    @classmethod
    def from_dict(cls, d: Mapping) -> "RouterConfig":
        return cls(fleet_shards=int(d.get("fleetShards", 2)),
                   replicas=int(d.get("replicas", 1)),
                   hedge_delay_ms=float(d.get("hedgeDelayMs", 0.0)),
                   fanout_timeout_s=float(d.get("fanoutTimeoutS", 30.0)),
                   request_timeout_ms=float(d.get("requestTimeoutMs", 0.0)),
                   slo_objective_ms=float(d.get("sloObjectiveMs", 0.0)),
                   slo_target=float(d.get("sloTarget", 0.999)),
                   slo_tick_s=float(d.get("sloTickS", 10.0)))


def add_router_flags(parser) -> None:
    """The serve_fleet routing-tier flags (SERVING.md "Fleet serving")."""
    parser.add_argument(
        "--fleet-shards", type=int, default=2, metavar="N",
        help="how many entity-sharded serving hosts to launch behind the "
             "router: raw entity ids hash to shards via "
             "fleet/sharding.py, each host packs only its ~1/N slice of "
             "every dense coefficient table")
    parser.add_argument(
        "--replicas", type=int, default=1, metavar="R",
        help="serving hosts PER SHARD (R×N hosts total): at R >= 2 a "
             "dead host becomes a replica retry instead of a 503 "
             "reason=upstream, and slow legs are hedged (backup fired "
             "after the p99-derived hedge delay, first answer wins)")
    parser.add_argument(
        "--hedge-delay-ms", type=float, default=0.0,
        help="fixed hedge delay for slow-leg backups (0 = adaptive: the "
             "p99 of the shard's recent leg latencies; only meaningful "
             "with --replicas >= 2)")
    parser.add_argument(
        "--fanout-timeout-s", type=float, default=30.0,
        help="per-host fan-out leg timeout; a slower or dead host maps "
             "to a typed 503 (reason=upstream) instead of a hang, and a "
             "request's remaining deadline budget caps each leg below "
             "this")
    parser.add_argument(
        "--slo-objective-ms", type=float, default=0.0,
        help="latency objective arming the fleet SLO burn-rate tracker: "
             "a routed request slower than this (or failed) spends error "
             "budget; crossing a burn-rate threshold posts slo_burn_alert "
             "(photon_slo_burn_total). 0 = no tracker")
    parser.add_argument(
        "--slo-target", type=float, default=0.999,
        help="SLO success-rate target (the error budget is 1 - target); "
             "burn rate 1.0 spends the budget exactly at the sustainable "
             "rate")
    parser.add_argument(
        "--slo-tick-s", type=float, default=10.0,
        help="how often the burn-rate tracker closes a bucket and "
             "evaluates its alert windows")


def router_from_args(args) -> RouterConfig:
    return RouterConfig(fleet_shards=args.fleet_shards,
                        replicas=args.replicas,
                        hedge_delay_ms=args.hedge_delay_ms,
                        fanout_timeout_s=args.fanout_timeout_s,
                        request_timeout_ms=args.request_timeout_ms,
                        slo_objective_ms=args.slo_objective_ms,
                        slo_target=args.slo_target,
                        slo_tick_s=args.slo_tick_s)


def parse_grid(specs: Sequence[str]) -> list[Mapping[str, float]]:
    """``coordId=0.1;1;10`` groups → cartesian product of per-coordinate
    lambda lists (the reference's hyperparameter grid)."""
    axes: list[tuple[str, list[float]]] = []
    for spec in specs:
        cid, rhs = spec.split("=", 1)
        axes.append((cid.strip(), [float(x) for x in rhs.split(";") if x]))
    out = []
    for combo in itertools.product(*(vals for _, vals in axes)):
        out.append({cid: v for (cid, _), v in zip(axes, combo)})
    return out or [{}]
