"""Feedback join driver: ``python -m photon_ml_tpu join_feedback``.

The operator-facing (and cron-able) wrapper around
:func:`photon_ml_tpu.feedback.joiner.join_feedback`: join one or more
request-log directories to a label source, write the joined rows as
``TrainingExampleAvro`` incremental training data, and print the full
accounting — joined / unjoined / late / duplicates — as JSON (nothing is
dropped silently; the same numbers land in the
``photon_feedback_*_total`` counters).

With ``--prior-dir`` (plus the training-time ``--feature-shards`` /
``--coordinates`` specs) the report additionally carries a
``data-manifest.json`` DELTA against the serving model's lineage: per
coordinate, how many entities the joined data would touch vs carry in a
refresh — the dry-run answer to "what would this feedback actually
retrain?". The autopilot (``feedback/autopilot.py``) runs the same join
in-process; this CLI is for offline/batch operation of the loop's first
leg.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

from photon_ml_tpu.cli.config import (
    add_resilience_flags,
    add_telemetry_flags,
    install_resilience,
    install_telemetry,
    parse_coordinate_config,
    parse_feature_shard_config,
    resilience_from_args,
    telemetry_from_args,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_ml_tpu join_feedback",
        description="Join request-log score records to labels and emit "
                    "incremental training data (+ optional manifest "
                    "delta vs a prior model)")
    p.add_argument("--reqlog-dir", required=True, action="append",
                   help="request-log directory (repeatable — a fleet "
                        "contributes one per host); segments scan in "
                        "sorted order so the join is deterministic")
    p.add_argument("--labels",
                   help="external label source: .avro (FeedbackLabelAvro) "
                        "or CSV 'request_id[,record_index],label'. "
                        "Omitted = inline labels only (the log schema's "
                        "nullable label field)")
    p.add_argument("--output", required=True,
                   help="joined TrainingExampleAvro path (written even "
                        "when zero rows join, so downstream min-rows "
                        "policy fails loudly instead of on a missing "
                        "file)")
    p.add_argument("--codec", default="deflate",
                   choices=["null", "deflate"])
    p.add_argument("--prior-dir",
                   help="prior run dir (train_game/refresh_game): report "
                        "a data-manifest delta of the joined data "
                        "against it (requires --feature-shards and "
                        "--coordinates)")
    p.add_argument("--feature-shards",
                   help="training-time shard specs (with --prior-dir)")
    p.add_argument("--coordinates", nargs="+",
                   help="training-time coordinate specs (with "
                        "--prior-dir)")
    p.add_argument("--report",
                   help="also write the JSON report here")
    add_resilience_flags(p)
    add_telemetry_flags(p)
    return p


def _manifest_delta(args, output_path: str) -> dict:
    """Per-coordinate touched/carried counts of the JOINED data vs the
    prior run's manifest — the refresh this feedback would drive."""
    from photon_ml_tpu.continuous import delta as delta_mod
    from photon_ml_tpu.game.estimator import RandomEffectCoordinateConfig
    from photon_ml_tpu.io import AvroDataReader
    from photon_ml_tpu.io.index import IndexMap
    from photon_ml_tpu.io.model_io import (
        find_feature_index_dir,
        game_model_entity_vocabs,
        resolve_game_model_dir,
    )

    shard_configs = tuple(parse_feature_shard_config(s)
                          for s in args.feature_shards.split(","))
    coordinate_configs = dict(parse_coordinate_config(s)
                              for s in args.coordinates)
    re_coords = {
        cid: (c.dataset.random_effect_type, c.dataset.feature_shard_id)
        for cid, c in coordinate_configs.items()
        if isinstance(c, RandomEffectCoordinateConfig)}

    prior_model_dir = resolve_game_model_dir(args.prior_dir)
    index_dir = find_feature_index_dir(prior_model_dir)
    preset_maps = {
        cfg.shard_id: IndexMap.load(
            os.path.join(index_dir, f"{cfg.shard_id}.json"))
        for cfg in shard_configs}
    reader = AvroDataReader(shard_configs=shard_configs,
                            index_maps=preset_maps)
    id_columns = tuple(sorted({t for t, _ in re_coords.values()}))
    data, _, vocabs = reader.read(output_path, id_columns=id_columns)
    # same union-vocabulary rule as refresh_game: prior entities survive
    # with zero joined rows (they would carry, not vanish)
    for re_type, pv in game_model_entity_vocabs(prior_model_dir).items():
        tgt = vocabs.setdefault(re_type, {})
        for raw in pv:
            tgt.setdefault(raw, len(tgt))
    manifest = delta_mod.build_manifest(data, re_coords, vocabs)
    prior_manifest = delta_mod.load_manifest(
        delta_mod.manifest_path_for(prior_model_dir))
    deltas = delta_mod.coordinate_deltas(prior_manifest, manifest)
    return {
        cid: {"touched": len(d.touched), "carried": len(d.carried)}
        for cid, d in sorted(deltas.items())}


def run(argv: Optional[Sequence[str]] = None) -> dict:
    args = build_parser().parse_args(
        list(sys.argv[1:] if argv is None else argv))
    if args.prior_dir and not (args.feature_shards and args.coordinates):
        raise SystemExit("--prior-dir needs --feature-shards and "
                         "--coordinates (the training-time specs) to "
                         "compute the manifest delta")
    install_resilience(resilience_from_args(args))
    telemetry = install_telemetry(telemetry_from_args(args))
    try:
        from photon_ml_tpu.feedback.joiner import join_feedback

        result = join_feedback(args.reqlog_dir, args.labels, args.output,
                               codec=args.codec)
        report = result.as_dict()
        if args.prior_dir:
            report["delta"] = _manifest_delta(args, args.output)
        if args.report:
            with open(args.report, "w") as f:
                json.dump(report, f, indent=2, sort_keys=True)
        return report
    finally:
        telemetry.close()


if __name__ == "__main__":
    run()
