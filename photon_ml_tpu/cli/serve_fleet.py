"""Fleet serving driver: ``python -m photon_ml_tpu serve_fleet``.

Launches a local N-host serving fleet in ONE process — N entity-sharded
``serve_game`` servers (each packing its 1/N slice of every dense
coefficient table) behind a :class:`~photon_ml_tpu.fleet.router.
FleetRouter` — and serves the router's endpoints (``/score`` ``/rank``
``/healthz`` ``/readyz`` ``/metrics`` ``/statusz`` ``/reload``). This is
the test and
bench topology (and the "does sharding change my scores?" audit tool: it
must not — f32 responses are bit-identical to an unsharded server). A
production fleet runs the same pieces across machines: one ``serve_game
--fleet-shard I --fleet-shard-count N`` per host, one router pointed at
their URLs; nothing in the protocol assumes shared memory.

In-process hosts share the process-global telemetry registry and
brownout state, so the per-host brownout controllers stay OFF here (a
distributed fleet keeps them: each machine degrades on its own
pressure); the router's ``/metrics`` still folds every host's snapshot
with host-owned gauges fanned out per shard.
"""

from __future__ import annotations

import argparse
from typing import Optional, Sequence


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_ml_tpu serve_fleet",
        description="Serve a saved GAME model from an entity-sharded "
                    "N-host fleet behind one router")
    p.add_argument("--model-dir", required=True,
                   help="a train_game output dir; every host loads it, "
                        "packing only its shard's entity rows")
    p.add_argument("--feature-shards", required=True,
                   help="same shard specs used at training time")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080,
                   help="router port; 0 = ephemeral (the test/bench "
                        "mode). Hosts always bind ephemeral ports")
    p.add_argument("--max-batch", type=int, default=1024)
    p.add_argument("--table-dtype",
                   choices=["float32", "bfloat16", "int8"],
                   default="float32",
                   help="per-host table storage dtype (serve_game "
                        "--table-dtype); composes with sharding — int8 "
                        "at N hosts is ~N×4 less resident bytes than one "
                        "f32 host")
    p.add_argument("--microbatch", type=int, default=64)
    p.add_argument("--max-wait-ms", type=float, default=2.0)
    p.add_argument("--max-queue", type=int, default=1024)
    p.add_argument("--request-timeout-ms", type=float, default=0.0,
                   help="router-side default deadline for requests with "
                        "no X-Photon-Deadline-Ms; the REMAINING budget "
                        "is propagated to every fan-out leg")
    p.add_argument("--no-warmup", action="store_true")
    p.add_argument("--rank-item-coordinate", default=None, metavar="COORD",
                   help="enable fleet /rank: every host indexes its item "
                        "shard, the router merges per-shard top-k "
                        "(requires the item coordinate to be the only "
                        "random effect)")
    p.add_argument("--rank-max-k", type=int, default=128)
    p.add_argument("--reqlog-dir", metavar="DIR", default=None,
                   help="enable per-host request logs: host I writes its "
                        "segments under DIR/host-I (serve_game "
                        "--reqlog-dir); the feedback joiner consumes all "
                        "of them")
    p.add_argument("--reqlog-sample", type=float, default=1.0)
    p.add_argument("--reqlog-segment-records", type=int, default=256)
    p.add_argument("--quality-poll-s", type=float, default=0.0,
                   help="per-host drift evaluator period (serve_game "
                        "--quality-poll-s); in-process hosts share one "
                        "event bus, so any host's drift event reaches "
                        "the fleet autopilot")
    p.add_argument("--drift-threshold", type=float, default=0.25)
    p.add_argument("--canary-gate", action="store_true",
                   help="per-host canary gate on reload candidates "
                        "(serve_game --canary-gate); under the router's "
                        "two-phase epoch ONE host's refusal aborts the "
                        "activation fleet-wide")
    p.add_argument("--canary-bound", type=float, default=None)
    p.add_argument("--autopilot-config", metavar="JSON",
                   help="close the freshness loop fleet-wide: a "
                        "feedback.AutopilotConfig JSON file. One "
                        "autopilot (subscribed to the shared bus) joins "
                        "EVERY host's request log (--reqlog-dir "
                        "required), refreshes the drifted coordinate "
                        "with --fleet-shards = this fleet's shard count, "
                        "and publishes the per-shard patch set where "
                        "--router-watch-dir discovers it")
    p.add_argument("--router-watch-dir", metavar="DIR",
                   help="poll DIR on the ROUTER for published per-shard "
                        "patch sets (patch-shard-0..N-1, stamps "
                        "verified) or full model dirs, and drive each "
                        "through the two-phase prepare→activate fleet "
                        "epoch (fleet/watcher.py) — any host's refusal "
                        "aborts with the incumbent serving fleet-wide")
    p.add_argument("--router-watch-poll-s", type=float, default=10.0)
    from photon_ml_tpu.cli.config import (
        add_capacity_flags,
        add_retained_flags,
        add_router_flags,
        add_telemetry_flags,
    )

    add_capacity_flags(p)
    add_retained_flags(p)
    add_router_flags(p)
    add_telemetry_flags(p)
    return p


class FleetHandle:
    """The started fleet: router server + N host servers (plus the
    optional loop pieces — fleet watcher, autopilot), one stop()."""

    def __init__(self, router_server, hosts, telemetry):
        self.router_server = router_server
        self.hosts = hosts
        self.telemetry = telemetry
        self.watcher = None  # FleetPatchWatcher (--router-watch-dir)
        self.autopilot = None  # FeedbackAutopilot (--autopilot-config)
        self.history = None  # router-side HistorySampler
        self.saturation = None  # router-side SaturationSampler
        self.advisor = None  # HotShardAdvisor (GET /advisor)
        self.flight = None  # FlightRecorder (--flight-dir)
        self.watchdog = None  # flight Watchdog (--watchdog-timeout-s)

    @property
    def url(self) -> str:
        return self.router_server.url

    @property
    def router(self):
        return self.router_server.router

    def host_urls(self) -> list:
        return [h.url for h in self.hosts]

    def serve_forever(self) -> None:
        self.router_server.serve_forever()

    def stop(self) -> None:
        # loop pieces first: no refresh launches or epochs against a
        # fleet that is tearing down
        if self.autopilot is not None:
            self.autopilot.stop()
        if self.watcher is not None:
            self.watcher.stop()
        if self.watchdog is not None:
            self.watchdog.close()
        if self.history is not None:
            self.history.close()
        if self.flight is not None:
            self.flight.close()
        self.router_server.stop()
        for host in self.hosts:
            if getattr(host, "drift_evaluator", None) is not None:
                host.drift_evaluator.stop()
            host.stop()
        self.telemetry.close()


def build_fleet(argv: Optional[Sequence[str]] = None) -> FleetHandle:
    """Parse flags → started (router + N hosts) fleet, router not yet
    serving-forever (the programmatic/test entry)."""
    args = build_parser().parse_args(argv)
    from photon_ml_tpu.cli.config import (
        install_telemetry,
        router_from_args,
        telemetry_from_args,
    )

    telemetry = install_telemetry(telemetry_from_args(args))
    config = router_from_args(args)

    from photon_ml_tpu.cli import serve_game
    from photon_ml_tpu.fleet.router import FleetRouter, RouterServer
    from photon_ml_tpu.fleet.sharding import shard_counts

    n = config.fleet_shards
    host_argv_common = [
        "--model-dir", args.model_dir,
        "--feature-shards", args.feature_shards,
        "--host", args.host, "--port", "0",
        "--max-batch", str(args.max_batch),
        "--table-dtype", args.table_dtype,
        "--microbatch", str(args.microbatch),
        "--max-wait-ms", str(args.max_wait_ms),
        "--max-queue", str(args.max_queue),
        # the connection budget is per-host (each host guards its own
        # socket table); the router's refusal handling maps the typed
        # 503 reason=connections into its upstream error accounting
        "--max-connections", str(args.max_connections),
        # brownout state is process-global; N in-process hosts sharing it
        # would shed each other's work — controllers stay off in the
        # single-process topology (a distributed fleet keeps them on)
        "--brownout-poll-s", "0",
        "--fleet-shard-count", str(n),
        # every host retains its own /history ring (the router's fleet
        # timeline folds them); the flight recorder stays fleet-level —
        # one black box per process (a distributed fleet passes
        # --flight-dir to each serve_game instead)
        "--history-capacity", str(args.history_capacity),
        "--history-period-s", str(args.history_period_s),
    ]
    if args.no_warmup:
        host_argv_common.append("--no-warmup")
    if args.rank_item_coordinate:
        host_argv_common += ["--rank-item-coordinate",
                             args.rank_item_coordinate,
                             "--rank-max-k", str(args.rank_max_k)]
    if args.quality_poll_s > 0:
        host_argv_common += ["--quality-poll-s", str(args.quality_poll_s),
                             "--drift-threshold",
                             str(args.drift_threshold)]
    if args.canary_gate:
        host_argv_common.append("--canary-gate")
    if args.canary_bound is not None:
        host_argv_common += ["--canary-bound", str(args.canary_bound)]
    import os as _os

    hosts = []
    reqlog_dirs = []
    try:
        # shard-major host order ([s0r0, s0r1, s1r0, ...]): every replica
        # of a group serves the SAME shard view of the same model
        for i in range(n):
            for _r in range(config.replicas):
                host_argv = host_argv_common + ["--fleet-shard", str(i)]
                if args.reqlog_dir:
                    # one log per host (a real fleet has one per machine)
                    d = _os.path.join(args.reqlog_dir,
                                      f"host-{len(hosts)}")
                    reqlog_dirs.append(d)
                    host_argv += [
                        "--reqlog-dir", d,
                        "--reqlog-sample", str(args.reqlog_sample),
                        "--reqlog-segment-records",
                        str(args.reqlog_segment_records)]
                hosts.append(serve_game.build_server(host_argv).start())
        router = FleetRouter(
            [h.url for h in hosts],
            replicas=config.replicas,
            hedge_delay_ms=config.hedge_delay_ms,
            fanout_timeout_s=config.fanout_timeout_s,
            default_timeout_ms=config.request_timeout_ms)
        if config.slo_objective_ms > 0:
            from photon_ml_tpu.events import GLOBAL_BUS
            from photon_ml_tpu.fleet.observe import SloBurnTracker

            # alerts land on the shared bus; the telemetry bridge turns
            # them into photon_slo_burn_total{window}
            router.observer.attach_slo(
                SloBurnTracker(GLOBAL_BUS,
                               objective_s=config.slo_objective_ms / 1e3,
                               target=config.slo_target),
                tick_s=config.slo_tick_s)
        # the router's retained-telemetry plane: a history ring whose
        # every snapshot carries fresh shard heat (pre_sample), the
        # read-only hot-shard advisor ticking off each snapshot, and —
        # with --flight-dir — the fleet's black box
        from photon_ml_tpu.cli.config import retained_from_args
        from photon_ml_tpu.events import GLOBAL_BUS
        from photon_ml_tpu.fleet.advisor import HotShardAdvisor
        from photon_ml_tpu.telemetry.history import HistorySampler
        from photon_ml_tpu.telemetry.tracing import GLOBAL_TRACER

        retained = retained_from_args(args)
        # router-tier capacity plane: the two fan-out executors are the
        # router's own saturable resources (the hosts probe their own)
        from photon_ml_tpu.telemetry.saturation import (
            SaturationSampler,
            executor_probe,
        )

        router_saturation = SaturationSampler()
        router_saturation.add_probe(
            "router_pool", executor_probe(router.fanout_pool))
        router_saturation.add_probe(
            "hedge_pool", executor_probe(router.hedge_pool))

        def _router_pre_sample() -> None:
            # heat first so the snapshot's shard series and the USE
            # gauges describe the same instant
            router.observer.refresh_heat()
            router_saturation.sample()

        router_sampler = HistorySampler(
            capacity=retained.history_capacity, source="router",
            pre_sample=_router_pre_sample)
        router.observer.attach_history(router_sampler)
        advisor = HotShardAdvisor(history=router_sampler,
                                  shard_map_fn=lambda: router.shard_map,
                                  bus=GLOBAL_BUS)
        router.advisor = advisor
        router_sampler.add_listener(lambda _snap: advisor.tick())
        flight = None
        watchdog = None
        if retained.flight_dir:
            import logging as _logging

            from photon_ml_tpu.telemetry.flightrec import (
                FlightRecorder,
                Watchdog,
            )

            # the dump's context header is the fleet statusz — shard-map
            # version/hash, per-host lineage, SLO burn state — what the
            # postmortem reconstructs the final epoch from
            flight = FlightRecorder(
                retained.flight_dir, capacity=retained.flight_capacity,
                source="fleet", context_fn=router.observer.statusz,
                tracer=GLOBAL_TRACER)
            flight.install(bus=GLOBAL_BUS, tracer=GLOBAL_TRACER,
                           sampler=router_sampler,
                           logger=_logging.getLogger("photon_ml_tpu"))
            if (retained.watchdog_timeout_s > 0
                    and retained.history_period_s > 0):
                watchdog = Watchdog(
                    flight, timeout_s=retained.watchdog_timeout_s)
                router_sampler.add_listener(lambda _snap: watchdog.pet())
                watchdog.start(retained.history_period_s)
        router_sampler.start(retained.history_period_s)
        server = RouterServer(router, host=args.host, port=args.port)
    except BaseException:
        for h in hosts:
            h.stop()
        telemetry.close()
        raise
    # startup balance check: heavy skew means constant/duplicated ids,
    # not bad luck — surface it in the driver log, never fail serving
    sample_store = next(iter(
        hosts[0].service.registry.active().stores.values()), None)
    handle = FleetHandle(server.start(), hosts, telemetry)
    handle.history = router_sampler
    handle.saturation = router_saturation
    handle.advisor = advisor
    handle.flight = flight
    handle.watchdog = watchdog
    if args.router_watch_dir:
        from photon_ml_tpu.fleet.watcher import FleetPatchWatcher

        handle.watcher = FleetPatchWatcher(
            router, args.router_watch_dir,
            poll_s=args.router_watch_poll_s).start()
    if args.autopilot_config:
        if not args.reqlog_dir:
            handle.stop()
            raise SystemExit("--autopilot-config needs --reqlog-dir "
                             "(the autopilot joins the hosts' request "
                             "logs)")
        from photon_ml_tpu.events import GLOBAL_BUS
        from photon_ml_tpu.feedback import (
            AutopilotConfig,
            FeedbackAutopilot,
        )

        # in-process hosts share GLOBAL_BUS (each ModelRegistry's default
        # bus), so ONE subscription hears every host's drift evaluator;
        # the autopilot joins all N logs and cuts per-shard patches
        ap_config = AutopilotConfig.load(args.autopilot_config)
        if ap_config.fleet_shards == 0:
            ap_config.fleet_shards = n
        handle.autopilot = FeedbackAutopilot(
            GLOBAL_BUS, ap_config, reqlog_dirs=reqlog_dirs,
            reqlogs=[h.service.reqlog for h in hosts
                     if h.service.reqlog is not None]).start()
    if sample_store is not None:
        import logging

        all_ids = set()
        for h in hosts:
            for store in h.service.registry.active().stores.values():
                all_ids.update(store.row_of_id)
        logging.getLogger(__name__).info(
            "fleet shard balance (entities/host): %s",
            shard_counts(sorted(all_ids), n))
    return handle


def run(argv: Optional[Sequence[str]] = None) -> dict:
    fleet = build_fleet(argv)
    if fleet.flight is not None:
        # process-level dump triggers belong to the main (signal
        # handlers only install on the main thread)
        fleet.flight.install_sigterm()
        fleet.flight.install_excepthook()
    rank_on = bool(fleet.hosts[0].service.registry.rank_coordinate)
    endpoints = ("/score" + (" /rank" if rank_on else "")
                 + " /healthz /readyz /metrics /statusz /reload /reshard"
                 + " /history /advisor")
    router = fleet.router
    print(f"serving GAME fleet ({router.n_shards} shards x "
          f"{router.replicas} replicas) on "
          f"{fleet.url} ({endpoints}); hosts: "
          f"{', '.join(fleet.host_urls())}", flush=True)
    try:
        fleet.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        fleet.stop()
    return {"url": fleet.url, "hosts": fleet.host_urls()}


if __name__ == "__main__":
    run()
