"""Fleet serving driver: ``python -m photon_ml_tpu serve_fleet``.

Launches a local N-host serving fleet in ONE process — N entity-sharded
``serve_game`` servers (each packing its 1/N slice of every dense
coefficient table) behind a :class:`~photon_ml_tpu.fleet.router.
FleetRouter` — and serves the router's endpoints (``/score`` ``/rank``
``/healthz`` ``/readyz`` ``/metrics`` ``/reload``). This is the test and
bench topology (and the "does sharding change my scores?" audit tool: it
must not — f32 responses are bit-identical to an unsharded server). A
production fleet runs the same pieces across machines: one ``serve_game
--fleet-shard I --fleet-shard-count N`` per host, one router pointed at
their URLs; nothing in the protocol assumes shared memory.

In-process hosts share the process-global telemetry registry and
brownout state, so the per-host brownout controllers stay OFF here (a
distributed fleet keeps them: each machine degrades on its own
pressure); the router's ``/metrics`` still folds every host's snapshot
with host-owned gauges fanned out per shard.
"""

from __future__ import annotations

import argparse
from typing import Optional, Sequence


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_ml_tpu serve_fleet",
        description="Serve a saved GAME model from an entity-sharded "
                    "N-host fleet behind one router")
    p.add_argument("--model-dir", required=True,
                   help="a train_game output dir; every host loads it, "
                        "packing only its shard's entity rows")
    p.add_argument("--feature-shards", required=True,
                   help="same shard specs used at training time")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080,
                   help="router port; 0 = ephemeral (the test/bench "
                        "mode). Hosts always bind ephemeral ports")
    p.add_argument("--max-batch", type=int, default=1024)
    p.add_argument("--table-dtype",
                   choices=["float32", "bfloat16", "int8"],
                   default="float32",
                   help="per-host table storage dtype (serve_game "
                        "--table-dtype); composes with sharding — int8 "
                        "at N hosts is ~N×4 less resident bytes than one "
                        "f32 host")
    p.add_argument("--microbatch", type=int, default=64)
    p.add_argument("--max-wait-ms", type=float, default=2.0)
    p.add_argument("--max-queue", type=int, default=1024)
    p.add_argument("--request-timeout-ms", type=float, default=0.0,
                   help="router-side default deadline for requests with "
                        "no X-Photon-Deadline-Ms; the REMAINING budget "
                        "is propagated to every fan-out leg")
    p.add_argument("--no-warmup", action="store_true")
    p.add_argument("--rank-item-coordinate", default=None, metavar="COORD",
                   help="enable fleet /rank: every host indexes its item "
                        "shard, the router merges per-shard top-k "
                        "(requires the item coordinate to be the only "
                        "random effect)")
    p.add_argument("--rank-max-k", type=int, default=128)
    from photon_ml_tpu.cli.config import (
        add_router_flags,
        add_telemetry_flags,
    )

    add_router_flags(p)
    add_telemetry_flags(p)
    return p


class FleetHandle:
    """The started fleet: router server + N host servers, one stop()."""

    def __init__(self, router_server, hosts, telemetry):
        self.router_server = router_server
        self.hosts = hosts
        self.telemetry = telemetry

    @property
    def url(self) -> str:
        return self.router_server.url

    @property
    def router(self):
        return self.router_server.router

    def host_urls(self) -> list:
        return [h.url for h in self.hosts]

    def serve_forever(self) -> None:
        self.router_server.serve_forever()

    def stop(self) -> None:
        self.router_server.stop()
        for host in self.hosts:
            host.stop()
        self.telemetry.close()


def build_fleet(argv: Optional[Sequence[str]] = None) -> FleetHandle:
    """Parse flags → started (router + N hosts) fleet, router not yet
    serving-forever (the programmatic/test entry)."""
    args = build_parser().parse_args(argv)
    from photon_ml_tpu.cli.config import (
        install_telemetry,
        router_from_args,
        telemetry_from_args,
    )

    telemetry = install_telemetry(telemetry_from_args(args))
    config = router_from_args(args)

    from photon_ml_tpu.cli import serve_game
    from photon_ml_tpu.fleet.router import FleetRouter, RouterServer
    from photon_ml_tpu.fleet.sharding import shard_counts

    n = config.fleet_shards
    host_argv_common = [
        "--model-dir", args.model_dir,
        "--feature-shards", args.feature_shards,
        "--host", args.host, "--port", "0",
        "--max-batch", str(args.max_batch),
        "--table-dtype", args.table_dtype,
        "--microbatch", str(args.microbatch),
        "--max-wait-ms", str(args.max_wait_ms),
        "--max-queue", str(args.max_queue),
        # brownout state is process-global; N in-process hosts sharing it
        # would shed each other's work — controllers stay off in the
        # single-process topology (a distributed fleet keeps them on)
        "--brownout-poll-s", "0",
        "--fleet-shard-count", str(n),
    ]
    if args.no_warmup:
        host_argv_common.append("--no-warmup")
    if args.rank_item_coordinate:
        host_argv_common += ["--rank-item-coordinate",
                             args.rank_item_coordinate,
                             "--rank-max-k", str(args.rank_max_k)]
    hosts = []
    try:
        # shard-major host order ([s0r0, s0r1, s1r0, ...]): every replica
        # of a group serves the SAME shard view of the same model
        for i in range(n):
            for _r in range(config.replicas):
                hosts.append(serve_game.build_server(
                    host_argv_common + ["--fleet-shard", str(i)]).start())
        router = FleetRouter(
            [h.url for h in hosts],
            replicas=config.replicas,
            hedge_delay_ms=config.hedge_delay_ms,
            fanout_timeout_s=config.fanout_timeout_s,
            default_timeout_ms=config.request_timeout_ms)
        server = RouterServer(router, host=args.host, port=args.port)
    except BaseException:
        for h in hosts:
            h.stop()
        telemetry.close()
        raise
    # startup balance check: heavy skew means constant/duplicated ids,
    # not bad luck — surface it in the driver log, never fail serving
    sample_store = next(iter(
        hosts[0].service.registry.active().stores.values()), None)
    handle = FleetHandle(server.start(), hosts, telemetry)
    if sample_store is not None:
        import logging

        all_ids = set()
        for h in hosts:
            for store in h.service.registry.active().stores.values():
                all_ids.update(store.row_of_id)
        logging.getLogger(__name__).info(
            "fleet shard balance (entities/host): %s",
            shard_counts(sorted(all_ids), n))
    return handle


def run(argv: Optional[Sequence[str]] = None) -> dict:
    fleet = build_fleet(argv)
    rank_on = bool(fleet.hosts[0].service.registry.rank_coordinate)
    endpoints = ("/score" + (" /rank" if rank_on else "")
                 + " /healthz /readyz /metrics /reload /reshard")
    router = fleet.router
    print(f"serving GAME fleet ({router.n_shards} shards x "
          f"{router.replicas} replicas) on "
          f"{fleet.url} ({endpoints}); hosts: "
          f"{', '.join(fleet.host_urls())}", flush=True)
    try:
        fleet.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        fleet.stop()
    return {"url": fleet.url, "hosts": fleet.host_urls()}


if __name__ == "__main__":
    run()
