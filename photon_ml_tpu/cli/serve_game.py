"""GAME online-serving driver: ``python -m photon_ml_tpu serve_game``.

The online counterpart of ``score_game``: load a trained GAME model once,
answer ``/score`` requests at low latency, hot-swap new versions via
``/reload`` without dropping traffic. The subsystem lives in
:mod:`photon_ml_tpu.serving`; this driver is flag parsing + process setup.

Numerics: on CPU backends the driver enables ``jax_enable_x64`` BEFORE any
scoring trace so the engine accumulates margins in float64 — the batch-path
bit-parity contract (see serving/engine.py). TPU backends have no f64 path;
serving there runs f32 accumulation (approximate parity) and this flag is
left alone.
"""

from __future__ import annotations

import argparse
from typing import Optional, Sequence


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_ml_tpu serve_game",
        description="Serve a saved GAME model over HTTP")
    p.add_argument("--model-dir", required=True,
                   help="a train_game output dir (containing best/ or a "
                        "model-metadata.json directly); also the default "
                        "for /reload")
    p.add_argument("--feature-shards", required=True,
                   help="same shard specs used at training time")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080,
                   help="0 = ephemeral (the test/bench mode)")
    p.add_argument("--max-batch", type=int, default=1024,
                   help="largest padded batch bucket; bigger requests are "
                        "chunked")
    p.add_argument("--table-dtype",
                   choices=["float32", "bfloat16", "int8"],
                   default="float32",
                   help="storage dtype of the dense per-entity coefficient "
                        "tables: bfloat16 halves and int8 (per-row scales) "
                        "quarters the resident bytes per entity — the "
                        "entities-per-host lever — at the documented "
                        "score-parity tolerances (bf16 ~1e-2 rel, int8 "
                        "~5e-2 rel; float32 keeps batch bit-parity). "
                        "Patches activated on a quantized store requantize "
                        "only the touched rows")
    p.add_argument("--microbatch", type=int, default=64,
                   help="microbatcher max coalesced batch; 0 disables the "
                        "batcher (single requests hit the engine directly)")
    p.add_argument("--max-wait-ms", type=float, default=2.0,
                   help="microbatcher linger after the first queued request")
    p.add_argument("--max-queue", type=int, default=1024,
                   help="admission-control bound on the microbatcher "
                        "queue: a submit against a full queue is shed "
                        "with a typed 429 + Retry-After (counted in "
                        "photon_shed_total{reason=queue_full}) instead "
                        "of queueing forever; 0 = unbounded (NOT "
                        "recommended under real traffic)")
    p.add_argument("--request-timeout-ms", type=float, default=0.0,
                   help="server-side deadline for requests that carry no "
                        "X-Photon-Deadline-Ms header: the budget is "
                        "stamped at parse and checked at queue drain — "
                        "an expired request is shed (429, reason="
                        "deadline) BEFORE it reaches the engine. 0 = no "
                        "server default")
    p.add_argument("--brownout-poll-s", type=float, default=1.0,
                   help="poll interval of the brownout controller "
                        "(serving/overload.py) watching queue pressure "
                        "and shedding optional work — reqlog sampling, "
                        "quality accumulation, span tracing, then "
                        "traffic — one level per tick, restoring in "
                        "reverse on recovery. 0 disables the controller")
    p.add_argument("--no-warmup", action="store_true",
                   help="skip pre-compiling the bucket executables at "
                        "startup (first requests then pay the compiles)")
    p.add_argument("--fleet-shard", type=int, default=None, metavar="I",
                   help="serve fleet shard I of --fleet-shard-count N: "
                        "the dense per-entity tables pack ONLY the raw "
                        "ids hashing to this shard "
                        "(fleet/sharding.py::shard_of_id) — ~1/N of the "
                        "device bytes per host — and per-host patches "
                        "from refresh_game --fleet-shards are refused "
                        "unless their fleetShard matches. Put a "
                        "serve_fleet router in front (SERVING.md 'Fleet "
                        "serving'). Default: unsharded")
    p.add_argument("--fleet-shard-count", type=int, default=None,
                   metavar="N",
                   help="the fleet's shard count (required with "
                        "--fleet-shard)")
    p.add_argument("--watch-dir", metavar="DIR",
                   help="poll DIR for new model versions — full "
                        "train_game/refresh_game output dirs OR "
                        "coefficient-patch dirs — and apply each through "
                        "the validate-then-activate path (registry-driven "
                        "discovery; no /reload call needed). Entries "
                        "apply in sorted name order; rejected candidates "
                        "never disturb the active version")
    p.add_argument("--watch-poll-s", type=float, default=10.0,
                   help="poll interval for --watch-dir (seconds)")
    p.add_argument("--reqlog-dir", metavar="DIR", default=None,
                   help="enable the durable request/score log: sampled "
                        "requests land in rotated Avro segments under DIR "
                        "(request id, entity ids, scores, model lineage, "
                        "stage timings — serving/reqlog.py), written off "
                        "the request path on a background writer pool. "
                        "tools/reqlog_replay.py re-scores the log "
                        "bit-identically against the named lineage. "
                        "Default: no request log")
    p.add_argument("--reqlog-sample", type=float, default=1.0,
                   help="request-log sampling rate in [0,1], decided "
                        "deterministically per request id (default 1.0 = "
                        "log everything that fits the budget)")
    p.add_argument("--reqlog-segment-records", type=int, default=256,
                   help="requests per log segment file (smaller = fresher "
                        "on disk, more files)")
    p.add_argument("--reqlog-max-mb", type=float, default=64.0,
                   help="total on-disk request-log budget; oldest segments "
                        "rotate out past it")
    p.add_argument("--autopilot-config", metavar="JSON",
                   help="close the freshness loop in-process: a "
                        "feedback.AutopilotConfig JSON file (prior_dir, "
                        "publish_dir, labels, the training-time specs, "
                        "debounce/min-interval guards). On "
                        "quality_drift_detected the autopilot joins this "
                        "host's request log (--reqlog-dir required) to "
                        "the labels, refreshes ONLY the drifted "
                        "coordinate, and publishes into publish_dir — "
                        "point --watch-dir there and the loop closes "
                        "(CONTINUOUS.md 'The closed loop')")
    from photon_ml_tpu.cli.config import (
        add_capacity_flags,
        add_quality_flags,
        add_rank_flags,
        add_retained_flags,
        add_telemetry_flags,
    )

    add_capacity_flags(p)
    add_quality_flags(p)
    add_rank_flags(p)
    add_retained_flags(p)
    add_telemetry_flags(p)
    return p


def build_server(argv: Optional[Sequence[str]] = None):
    """Parse flags → started-but-not-serving :class:`GameServer` (the
    programmatic/test entry; :func:`run` wraps it in serve-forever)."""
    from photon_ml_tpu.cli.config import parse_feature_shard_config

    args = build_parser().parse_args(argv)
    from photon_ml_tpu.cli.config import (
        install_telemetry,
        telemetry_from_args,
    )

    # /metrics is always live (the registry is process-global); the session
    # adds the trace file and device sampler when the flags ask for them
    telemetry = install_telemetry(telemetry_from_args(args))
    import jax

    from photon_ml_tpu.telemetry import emit_build_info

    emit_build_info()

    if jax.default_backend() == "cpu" and not jax.config.jax_enable_x64:
        # float64 margin accumulation = bit-parity with the batch scorer;
        # must be set before the first trace (serving owns this process)
        jax.config.update("jax_enable_x64", True)

    from photon_ml_tpu.serving import (
        GameServer,
        MicroBatcher,
        ModelRegistry,
        ServingService,
    )

    from photon_ml_tpu.cli.config import quality_from_args, rank_from_args

    quality = quality_from_args(args)
    rank = rank_from_args(args)
    shard_configs = tuple(parse_feature_shard_config(s)
                          for s in args.feature_shards.split(","))
    fleet_shard = None
    if args.fleet_shard is not None or args.fleet_shard_count is not None:
        if args.fleet_shard is None or args.fleet_shard_count is None:
            raise SystemExit("--fleet-shard and --fleet-shard-count go "
                             "together (I of N)")
        fleet_shard = (args.fleet_shard, args.fleet_shard_count)
    registry = ModelRegistry(shard_configs, max_batch=args.max_batch,
                             warmup=not args.no_warmup,
                             table_dtype=args.table_dtype,
                             canary=quality.canary(),
                             rank_coordinate=rank.item_coordinate,
                             rank_max_k=rank.max_k,
                             fleet_shard=fleet_shard)
    registry.load(args.model_dir)
    batcher = None
    if args.microbatch > 0:
        batcher = MicroBatcher(
            lambda records: registry.active().score(records),
            max_batch=args.microbatch, max_wait_ms=args.max_wait_ms,
            max_queue=args.max_queue if args.max_queue > 0 else None)
    rank_batcher = None
    if rank.item_coordinate and args.microbatch > 0:
        import numpy as np

        def _rank_fn(entries):
            # entries are opaque (record, k) tuples; results ride a 1-D
            # object array so the batcher's shape contract still holds
            results = registry.active().rank([r for r, _ in entries],
                                             [k for _, k in entries])
            out = np.empty(len(results), dtype=object)
            for i, res in enumerate(results):
                out[i] = res
            return out

        rank_batcher = MicroBatcher(
            _rank_fn, coerce=lambda s: s,
            max_batch=8, max_wait_ms=args.max_wait_ms,
            max_queue=args.max_queue if args.max_queue > 0 else None)
    # the connection plane: accounting is always on; --max-connections
    # arms the budget (typed 503 refusals past the ceiling)
    from photon_ml_tpu.cli.config import capacity_from_args
    from photon_ml_tpu.serving.http import ConnectionTracker

    capacity = capacity_from_args(args)
    connections = ConnectionTracker(
        max_connections=capacity.max_connections)
    overload = None
    if batcher is not None and args.brownout_poll_s > 0:
        from photon_ml_tpu.serving import OverloadController

        overload = OverloadController(
            batcher, poll_s=args.brownout_poll_s,
            connections=connections).start()
    reqlog = None
    if args.reqlog_dir:
        from photon_ml_tpu.serving import RequestLog

        reqlog = RequestLog(
            args.reqlog_dir, sample_rate=args.reqlog_sample,
            segment_records=args.reqlog_segment_records,
            max_bytes=int(args.reqlog_max_mb * (1 << 20)))
    service = ServingService(registry, default_model_dir=args.model_dir,
                             batcher=batcher, rank_batcher=rank_batcher,
                             reqlog=reqlog,
                             default_timeout_ms=args.request_timeout_ms,
                             overload=overload,
                             connections=connections)
    server = GameServer(service, host=args.host, port=args.port)
    server.telemetry = telemetry  # closed by run()'s finally
    server.watcher = None
    if args.watch_dir:
        from photon_ml_tpu.serving import ModelDirectoryWatcher

        server.watcher = ModelDirectoryWatcher(
            registry, args.watch_dir, poll_s=args.watch_poll_s).start()
    server.drift_evaluator = None
    if quality.quality_poll_s > 0:
        # background model-quality evaluator: live score distribution vs
        # the active version's train-time baseline (quality/monitor.py)
        from photon_ml_tpu.quality import DriftEvaluator

        server.drift_evaluator = DriftEvaluator(
            registry, threshold=quality.drift_threshold,
            poll_s=quality.quality_poll_s).start()
    server.autopilot = None
    if args.autopilot_config:
        if reqlog is None:
            raise SystemExit("--autopilot-config needs --reqlog-dir "
                             "(the autopilot joins the request log)")
        from photon_ml_tpu.feedback import (
            AutopilotConfig,
            FeedbackAutopilot,
        )

        server.autopilot = FeedbackAutopilot(
            registry.bus, AutopilotConfig.load(args.autopilot_config),
            reqlog_dirs=[args.reqlog_dir], reqlogs=[reqlog]).start()
    # retained telemetry: the history ring is always armed (GET /history
    # costs one bounded ring); the flight recorder and its stall
    # watchdog only when --flight-dir asks for the black box
    import logging

    from photon_ml_tpu.cli.config import retained_from_args
    from photon_ml_tpu.events import GLOBAL_BUS
    from photon_ml_tpu.telemetry.history import HistorySampler
    from photon_ml_tpu.telemetry.tracing import GLOBAL_TRACER

    retained = retained_from_args(args)
    # the capacity plane (OBSERVABILITY.md "Saturation & capacity"):
    # USE gauges per serving-path resource, refreshed as the history
    # ring's pre-sample so every retained snapshot carries them — the
    # probes are built HERE, at the wiring site, so telemetry never
    # imports serving
    from photon_ml_tpu.serving import overload as serving_overload
    from photon_ml_tpu.telemetry.saturation import (
        SaturationSampler,
        busy_probe,
        executor_probe,
        device_busy_seconds,
        queue_probe,
    )

    saturation = SaturationSampler()
    saturation.add_probe("device", busy_probe(device_busy_seconds))
    if batcher is not None:
        saturation.add_probe("batcher_queue", queue_probe(
            batcher.queue_depth, lambda: batcher.max_queue,
            lambda: serving_overload.shed_counts()["queue_full"]))
    if rank_batcher is not None:
        saturation.add_probe("rank_batcher_queue", queue_probe(
            rank_batcher.queue_depth, lambda: rank_batcher.max_queue))

    def _connections_probe() -> dict:
        stats = connections.stats()
        return {"utilization": connections.utilization(),
                "saturation": float(stats["open"]),
                "errors": float(stats["refused"])}

    def _handler_threads_probe() -> dict:
        # ThreadingHTTPServer spawns a thread per connection (no fixed
        # pool): active request threads against the connection budget
        stats = connections.stats()
        budget = connections.max_connections
        return {"utilization": (stats["active"] / budget if budget
                                else 0.0),
                "saturation": float(stats["active"])}

    saturation.add_probe("http_connections", _connections_probe)
    saturation.add_probe("handler_threads", _handler_threads_probe)
    if reqlog is not None:
        def _reqlog_probe() -> dict:
            stats = reqlog.stats()
            return {"utilization": (min(1.0, stats["bytes"]
                                        / reqlog.max_bytes)
                                    if reqlog.max_bytes else 0.0),
                    "saturation": float(stats["buffered"]),
                    "errors": float(stats["dropped"])}

        saturation.add_probe("reqlog", _reqlog_probe)
        saturation.add_probe("saver_pool",
                             executor_probe(reqlog.saver.save_executor))
    service.saturation = saturation
    server.saturation = saturation
    sampler = HistorySampler(capacity=retained.history_capacity,
                             source="host",
                             pre_sample=saturation.sample)
    service.history = sampler
    server.history = sampler
    server.flight = None
    server.watchdog = None
    if retained.flight_dir:
        from photon_ml_tpu.telemetry.flightrec import (
            FlightRecorder,
            Watchdog,
        )

        # the dump's context header is the host's live healthz (active
        # version/lineage, compiles) — what the postmortem reconstructs
        # the final epoch from
        recorder = FlightRecorder(
            retained.flight_dir, capacity=retained.flight_capacity,
            source="host", context_fn=service.healthz,
            tracer=GLOBAL_TRACER)
        recorder.install(bus=GLOBAL_BUS, tracer=GLOBAL_TRACER,
                         sampler=sampler,
                         logger=logging.getLogger("photon_ml_tpu"))
        server.flight = recorder
        if retained.watchdog_timeout_s > 0 and retained.history_period_s > 0:
            watchdog = Watchdog(recorder,
                                timeout_s=retained.watchdog_timeout_s)
            sampler.add_listener(lambda _snap: watchdog.pet())
            watchdog.start(retained.history_period_s)
            server.watchdog = watchdog
    sampler.start(retained.history_period_s)
    return server


def run(argv: Optional[Sequence[str]] = None) -> dict:
    server = build_server(argv)
    if server.flight is not None:
        # the main owns the process-level triggers: a signal handler
        # only installs on the main thread, so build_server (callable
        # from anywhere) cannot arm these
        server.flight.install_sigterm()
        server.flight.install_excepthook()
    version = server.service.registry.active_version
    rank_on = server.service.registry.rank_coordinate is not None
    endpoints = ("/score" + (" /rank" if rank_on else "")
                 + " /healthz /readyz /metrics /reload /history")
    print(f"serving GAME model version {version} on {server.url} "
          f"({endpoints})", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        if server.autopilot is not None:
            server.autopilot.stop()
        if server.drift_evaluator is not None:
            server.drift_evaluator.stop()
        if server.watcher is not None:
            server.watcher.stop()
        if server.watchdog is not None:
            server.watchdog.close()
        server.history.close()
        if server.flight is not None:
            server.flight.close()
        server.stop()
        server.telemetry.close()
    return {"url": server.url, "version": version}


if __name__ == "__main__":
    run()
