"""Continuous-training refresh driver: ``python -m photon_ml_tpu refresh_game``.

The periodic retrain of a continuously refreshing GLMix deployment
(PAPER.md §0): warm-start every optimizer from a previously published
model, re-solve ONLY the random-effect entities whose training data
changed since that model's run (the ``data-manifest.json`` diff), carry
every untouched entity's coefficients forward bit-identically, and
publish BOTH a full merged model directory (the next refresh's parent)
and an entity-level coefficient patch serving can activate by overwriting
only the touched rows of its device tables (``serve_game --watch-dir`` or
``/reload``).

Feature indexes are PRESET from the prior run — a refresh lives in its
parent's feature space by contract (that is what makes warm starts,
carried coefficients, and patch rows line up) — while entity vocabularies
extend freely: new entities train and patch in as fresh rows.
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Optional, Sequence

import numpy as np

from photon_ml_tpu.cli.config import (
    add_resilience_flags,
    add_telemetry_flags,
    install_resilience,
    install_telemetry,
    parse_coordinate_config,
    parse_feature_shard_config,
    parse_grid,
    resilience_from_args,
    telemetry_from_args,
)
from photon_ml_tpu.data_validation import validate_game_data
from photon_ml_tpu.evaluation import parse_evaluators
from photon_ml_tpu.game.estimator import (
    GameOptimizationConfiguration,
    RandomEffectCoordinateConfig,
)
from photon_ml_tpu.io import AvroDataReader
from photon_ml_tpu.io.data_reader import parse_input_columns
from photon_ml_tpu.logging_util import RunLogger, timed
from photon_ml_tpu.types import DataValidationType, TaskType


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_ml_tpu refresh_game",
        description="Incrementally refresh a published GAME model "
                    "(warm-start + touched-entity refit + delta publish)")
    p.add_argument("--prior-dir", required=True,
                   help="the previous run's output dir (train_game or "
                        "refresh_game; contains best/ or a "
                        "model-metadata.json directly) — the refresh "
                        "warm-starts from it, reuses its feature indexes, "
                        "and diffs against its data-manifest.json")
    p.add_argument("--training-data", required=True)
    p.add_argument("--validation-data")
    p.add_argument("--output-dir", required=True)
    p.add_argument("--task", default="LOGISTIC_REGRESSION",
                   choices=[t.value for t in TaskType])
    p.add_argument("--feature-shards", required=True,
                   help="same shard specs used at training time")
    p.add_argument("--coordinates", required=True, nargs="+",
                   help="same coordinate specs used at training time")
    p.add_argument("--update-sequence", required=True)
    p.add_argument("--grid", nargs="*", default=[],
                   help="ONE per-coordinate lambda config "
                        "'coordId=lambda' (a refresh fits a single "
                        "configuration — tuning belongs to full retrains)")
    p.add_argument("--refresh-coordinates", nargs="+", default=None,
                   metavar="COORD",
                   help="restrict the touched-entity refit to these "
                        "random-effect coordinates: every OTHER "
                        "coordinate carries its coefficients forward "
                        "bit-identically with zero solves even when its "
                        "data changed (the feedback autopilot's "
                        "drifted-coordinate refresh). Fixed effects "
                        "always retrain. Default: refit wherever the "
                        "manifest diff finds touched entities")
    p.add_argument("--refresh-sweeps", type=int, default=1,
                   help="refresh sweeps over the update sequence "
                        "(1 = production refresh: one warm pass)")
    p.add_argument("--evaluators", default="AUC")
    p.add_argument("--data-validation", default="VALIDATE_FULL",
                   choices=[v.value for v in DataValidationType])
    p.add_argument("--model-sparsity-threshold", type=float, default=0.0)
    p.add_argument("--input-columns", default="")
    p.add_argument("--no-patch", action="store_true",
                   help="skip the coefficient-patch artifact (full model "
                        "dir only)")
    p.add_argument("--fleet-shards", type=int, default=0, metavar="N",
                   help="ALSO publish N per-host patches (patch-shard-I/ "
                        "next to patch/): the touched entity set is "
                        "partitioned by the same raw-id hash serving "
                        "shards by (fleet/sharding.py), each patch "
                        "carries ONLY that shard's rows plus the "
                        "always-retrained fixed effect, and its metadata "
                        "names the shard (fleetShard/fleetShardCount) so "
                        "a host refuses a foreign shard's patch. 0 "
                        "(default) = global patch only")
    add_resilience_flags(p)
    add_telemetry_flags(p)
    return p


def run(argv: Optional[Sequence[str]] = None) -> dict:
    import sys

    from photon_ml_tpu.events import GLOBAL_BUS

    args = build_parser().parse_args(
        list(sys.argv[1:] if argv is None else argv))
    task = TaskType(args.task)
    install_resilience(resilience_from_args(args))
    run_logger = RunLogger(args.output_dir)
    telemetry = install_telemetry(telemetry_from_args(args))
    from photon_ml_tpu.telemetry import emit_build_info, tracing

    emit_build_info()
    import contextlib as _contextlib

    _root_span = _contextlib.ExitStack()
    _root_span.enter_context(tracing.span("refresh_game"))
    GLOBAL_BUS.post("training_started", driver="refresh_game",
                    task=task.value, output_dir=args.output_dir)
    saver = None
    try:
        from photon_ml_tpu.continuous import delta as delta_mod
        from photon_ml_tpu.continuous.refresh import (
            patch_bytes_counter,
            refresh_game_model,
        )
        from photon_ml_tpu.io.index import IndexMap
        from photon_ml_tpu.io.model_io import (
            find_feature_index_dir,
            game_model_entity_vocabs,
            load_game_model,
            model_lineage_id,
            resolve_game_model_dir,
        )
        from photon_ml_tpu.io.pipeline import (
            BackgroundSaver,
            save_model_patch_atomic,
        )

        shard_configs = tuple(parse_feature_shard_config(s)
                              for s in args.feature_shards.split(","))
        coordinate_configs = dict(parse_coordinate_config(s)
                                  for s in args.coordinates)
        update_sequence = [c for c in args.update_sequence.split(",") if c]
        grid = parse_grid(args.grid)
        if len(grid) != 1:
            raise SystemExit(
                f"refresh_game fits exactly one configuration "
                f"(got {len(grid)} --grid configs)")
        configuration = GameOptimizationConfiguration(grid[0])
        evaluators = parse_evaluators(
            [e for e in args.evaluators.split(",") if e])

        prior_model_dir = resolve_game_model_dir(args.prior_dir)
        index_dir = find_feature_index_dir(prior_model_dir)
        preset_maps = {
            cfg.shard_id: IndexMap.load(
                os.path.join(index_dir, f"{cfg.shard_id}.json"))
            for cfg in shard_configs}

        re_types = sorted({
            c.dataset.random_effect_type
            for c in coordinate_configs.values()
            if isinstance(c, RandomEffectCoordinateConfig)})
        id_columns = tuple(dict.fromkeys(
            re_types + [e.id_tag for e in evaluators if e.id_tag]))

        reader = AvroDataReader(
            shard_configs=shard_configs, index_maps=preset_maps,
            input_columns=parse_input_columns(args.input_columns))
        with timed("Read training data", run_logger):
            data, index_maps, vocabs = reader.read(args.training_data,
                                                   id_columns=id_columns)
        # union id universe: entities of the prior MODEL extend the data's
        # vocabulary, so carried entities survive even with zero rows this
        # run (the GLMix refresh premise: most entities see no new data)
        prior_vocabs = game_model_entity_vocabs(prior_model_dir)
        for re_type, pv in prior_vocabs.items():
            tgt = vocabs.setdefault(re_type, {})
            for raw in pv:
                tgt.setdefault(raw, len(tgt))

        with timed("Load prior model", run_logger):
            initial_models = dict(load_game_model(
                prior_model_dir, index_maps, vocabs).coordinates)
            prior_lineage = model_lineage_id(prior_model_dir)

        with timed("Validate data", run_logger):
            validate_game_data(data, task,
                               DataValidationType(args.data_validation))

        # --- change detection ------------------------------------------
        re_coords = {
            cid: (c.dataset.random_effect_type, c.dataset.feature_shard_id)
            for cid, c in coordinate_configs.items()
            if isinstance(c, RandomEffectCoordinateConfig)}
        with timed("Compute delta", run_logger), \
                tracing.span("refresh.delta"):
            manifest = delta_mod.build_manifest(data, re_coords, vocabs)
            prior_manifest = delta_mod.load_manifest(
                delta_mod.manifest_path_for(prior_model_dir))
            deltas = delta_mod.coordinate_deltas(prior_manifest, manifest)
        touched_entities = {
            cid: np.asarray(
                sorted(vocabs[re_coords[cid][0]][raw]
                       for raw in d.touched), np.int64)
            for cid, d in deltas.items()}
        if args.refresh_coordinates:
            allowed = set(args.refresh_coordinates)
            unknown = sorted(allowed - set(re_coords))
            if unknown:
                raise SystemExit(
                    f"--refresh-coordinates names unknown random-effect "
                    f"coordinate(s) {unknown}; this model has "
                    f"{sorted(re_coords)}")
            # the drifted-coordinate restriction: an empty touched array
            # (NOT a missing entry) pins the coordinate to a full carry
            touched_entities = {
                cid: (ids if cid in allowed
                      else np.asarray([], np.int64))
                for cid, ids in touched_entities.items()}
        if prior_manifest is None:
            import logging

            logging.getLogger(__name__).warning(
                "prior run has no data-manifest.json — treating every "
                "entity as touched (cold-cost refresh; the output records "
                "a manifest, so the NEXT refresh is incremental)")

        validation = None
        if args.validation_data:
            reader_v = AvroDataReader(shard_configs=shard_configs,
                                      index_maps=index_maps,
                                      input_columns=reader.input_columns)
            with timed("Read validation data", run_logger):
                vdata, _, _ = reader_v.read(args.validation_data,
                                            id_columns=id_columns,
                                            entity_vocabs=vocabs)
            validation = (vdata, evaluators)

        with timed("Refresh", run_logger):
            result = refresh_game_model(
                task, coordinate_configs, update_sequence, data,
                configuration, initial_models, touched_entities,
                n_sweeps=args.refresh_sweeps, validation=validation)
        for cid, st in result.stats.items():
            run_logger.metric(stage="refresh", coordinate=cid,
                              touched=st.touched, carried=st.carried,
                              solved=st.solved)

        # --- publish: full model (next parent) + manifest + indexes ------
        import datetime as _dt

        trained_at = _dt.datetime.now(_dt.timezone.utc).isoformat()
        manifest_dig = delta_mod.manifest_digest(manifest)
        lineage = {"parentModel": prior_lineage, "trainedAt": trained_at,
                   "dataManifest": manifest_dig}
        saver = BackgroundSaver()
        best_dir = os.path.join(args.output_dir, "best")
        saver.submit_game_save(
            best_dir, result.model, index_maps, vocabs,
            sparsity_threshold=args.model_sparsity_threshold,
            lineage=lineage)
        for shard_id, imap in index_maps.items():
            saver.submit_file_write(
                imap.save,
                os.path.join(args.output_dir, "feature-indexes",
                             f"{shard_id}.json"),
                label="io.save.index", shard=shard_id)
        saver.submit_file_write(
            lambda path: delta_mod.save_manifest(path, manifest),
            os.path.join(args.output_dir, delta_mod.MANIFEST_NAME),
            label="io.save.manifest")
        # quality baseline of the REFRESHED model, carrying the refresh's
        # lineage (parentModel/trainedAt/dataManifest) — published at the
        # run root, where serving's find_baseline discovers it for both
        # the full dir and the sibling patch/ activation
        from photon_ml_tpu.quality import (
            BASELINE_NAME,
            baseline_from_game,
            save_baseline,
        )

        _b_source = validation[0] if validation is not None else data

        def _write_baseline(path, model=result.model, bdata=_b_source,
                            blineage=lineage):
            save_baseline(path, baseline_from_game(
                model, bdata, task=task, lineage=blineage))

        saver.submit_file_write(
            _write_baseline,
            os.path.join(args.output_dir, BASELINE_NAME),
            label="quality.baseline")
        with timed("Save models", run_logger):
            saver.join()
        GLOBAL_BUS.post("model_saved", path=best_dir)

        # --- publish: the entity-level coefficient patch ----------------
        patch_dir = None
        shard_patch_dirs: list = []
        if not args.no_patch:
            patch_dir = os.path.join(args.output_dir, "patch")
            reverse = {t: {v: k for k, v in vocabs[t].items()}
                       for t in vocabs}
            removed_raw = {}
            for cid, dense_ids in result.removed.items():
                t = re_coords[cid][0]
                removed_raw[cid] = [reverse[t][int(e)] for e in dense_ids]
            model_id = model_lineage_id(best_dir)
            patch_lineage = {"trainedAt": trained_at,
                             "dataManifest": manifest_dig}
            with timed("Publish patch", run_logger):
                patch_bytes = save_model_patch_atomic(
                    patch_dir, result.patch, index_maps, vocabs,
                    task=task, parent_model=prior_lineage,
                    model_id=model_id,
                    removed=removed_raw,
                    lineage=patch_lineage,
                    sparsity_threshold=args.model_sparsity_threshold)
            patch_bytes_counter().inc(patch_bytes)
            run_logger.metric(stage="patch", bytes=patch_bytes,
                              coordinates=sorted(result.patch))
            if args.fleet_shards > 0:
                # per-host patches for an entity-sharded serving fleet:
                # the SAME hash serving packed by partitions the touched
                # set, every shard's patch names itself (fleetShard) and
                # chains to the SAME merged model id — after each host
                # applies its own patch, the fleet's lineage is uniform
                from photon_ml_tpu.continuous.refresh import (
                    partition_patch_by_shard,
                )

                parts = partition_patch_by_shard(
                    result.patch, removed_raw, vocabs, args.fleet_shards)
                with timed("Publish fleet patches", run_logger):
                    for shard, (models, rm) in enumerate(parts):
                        sdir = os.path.join(args.output_dir,
                                            f"patch-shard-{shard}")
                        sbytes = save_model_patch_atomic(
                            sdir, models, index_maps, vocabs,
                            task=task, parent_model=prior_lineage,
                            model_id=model_id, removed=rm,
                            lineage=patch_lineage,
                            sparsity_threshold=(
                                args.model_sparsity_threshold),
                            fleet_shard=(shard, args.fleet_shards))
                        patch_bytes_counter().inc(sbytes)
                        shard_patch_dirs.append(sdir)
                        run_logger.metric(
                            stage="patch", shard=shard,
                            of=args.fleet_shards, bytes=sbytes)

        out = {
            "output_dir": args.output_dir,
            "patch_dir": patch_dir,
            "shard_patch_dirs": shard_patch_dirs,
            "parent_model": prior_lineage,
            "touched": {cid: st.touched
                        for cid, st in result.stats.items()},
            "carried": {cid: st.carried
                        for cid, st in result.stats.items()},
            "solved": {cid: st.solved
                       for cid, st in result.stats.items()},
            "evaluation": (result.final_evaluation.as_dict()
                           if result.final_evaluation is not None
                           else None),
        }
        return out
    finally:
        if saver is not None:
            saver.close()
        _root_span.close()
        GLOBAL_BUS.post("training_finished", driver="refresh_game")
        telemetry.close()
        run_logger.close()


if __name__ == "__main__":
    run()
