"""Feature normalization applied as an objective transform, never materialized.

Re-design of ``photon-api/.../normalization/NormalizationContext.scala`` (+
``NormalizationType.scala`` and the summary-driven factory fed by
``stat/FeatureDataStatistics.scala``).

The reference's key trick — kept here — is that normalized features are never
materialized: aggregators compute margins in the *transformed* coordinate
system on the fly. In JAX this becomes a pure reparameterization inside the
jitted objective:

    margin(w, x) in transformed space
        = sum_j w_j * (x_j - shift_j) * factor_j
        = (w * factor) . x - w . (factor * shift)

so a single element-wise product on the coefficient vector plus one scalar
correction per sample reproduces normalization at zero bandwidth cost — ideal
for TPU, where re-scaling the design matrix would double HBM traffic.

Coefficients learned in transformed space are mapped back to the original
space for model output via :meth:`NormalizationContext.model_to_original`,
mirroring the reference's model back-transformation.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.types import NormalizationType

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class NormalizationContext:
    """Per-feature affine transform ``x' = (x - shift) * factor``.

    ``factors``/``shifts`` are dense ``(d,)`` vectors (``shifts`` may be None
    for scale-only types). The intercept column, when present, must have
    ``factor=1, shift=0`` — shifts require an intercept to absorb them, as in
    the reference's ``NormalizationContext`` require-intercept check.
    """

    factors: Optional[Array] = None
    shifts: Optional[Array] = None
    intercept_index: Optional[int] = dataclasses.field(
        default=None, metadata=dict(static=True)
    )

    @property
    def is_identity(self) -> bool:
        return self.factors is None and self.shifts is None

    # --- coefficient-space transforms -------------------------------------
    def transform_coefficients(self, w: Array) -> tuple[Array, Array]:
        """Return ``(w_eff, margin_shift)`` such that the transformed-space
        margin for a raw sample x is ``w_eff . x + margin_shift``."""
        w_eff = w if self.factors is None else w * self.factors
        if self.shifts is None:
            margin_shift = jnp.zeros((), dtype=w.dtype)
        else:
            margin_shift = -jnp.sum(w_eff * self.shifts)
        return w_eff, margin_shift

    def model_to_original(self, w: Array) -> Array:
        """Map coefficients learned in transformed space back to original
        feature space (so saved models score raw features directly)."""
        w_orig = w if self.factors is None else w * self.factors
        if self.shifts is not None:
            if self.intercept_index is None:
                raise ValueError("shifts require an intercept column")
            correction = jnp.sum(jnp.delete(w_orig, self.intercept_index, assume_unique_indices=True)
                                 * jnp.delete(self.shifts, self.intercept_index, assume_unique_indices=True))
            w_orig = w_orig.at[self.intercept_index].add(-correction)
        return w_orig

    def original_to_model(self, w_orig: Array) -> Array:
        """Inverse of :meth:`model_to_original` (for warm starts from saved
        models when training with normalization)."""
        if self.shifts is not None:
            if self.intercept_index is None:
                raise ValueError("shifts require an intercept column")
            correction = jnp.sum(
                jnp.delete(w_orig, self.intercept_index, assume_unique_indices=True)
                * jnp.delete(self.shifts, self.intercept_index, assume_unique_indices=True))
            w_orig = w_orig.at[self.intercept_index].add(correction)
        return w_orig if self.factors is None else w_orig / self.factors


NoNormalization = NormalizationContext()


def build_normalization(
    norm_type: NormalizationType,
    *,
    mean: np.ndarray,
    variance: np.ndarray,
    max_magnitude: np.ndarray,
    intercept_index: Optional[int],
    dtype=jnp.float32,
) -> NormalizationContext:
    """Build a context from feature summary statistics.

    Mirrors the reference's ``NormalizationContext`` factory driven by
    ``FeatureDataStatistics`` (a.k.a. ``BasicStatisticalSummary``):

    - ``SCALE_WITH_STANDARD_DEVIATION``: factor = 1/std (std==0 -> 1)
    - ``SCALE_WITH_MAX_MAGNITUDE``: factor = 1/max|x| (0 -> 1)
    - ``STANDARDIZATION``: factor = 1/std, shift = mean (needs intercept)
    """
    d = len(mean)
    std = np.sqrt(np.maximum(variance, 0.0))
    inv_std = np.where(std > 0, 1.0 / np.where(std > 0, std, 1.0), 1.0)
    inv_mag = np.where(max_magnitude > 0, 1.0 / np.where(max_magnitude > 0, max_magnitude, 1.0), 1.0)

    if norm_type == NormalizationType.NONE:
        return NoNormalization
    if norm_type == NormalizationType.SCALE_WITH_STANDARD_DEVIATION:
        factors, shifts = inv_std, None
    elif norm_type == NormalizationType.SCALE_WITH_MAX_MAGNITUDE:
        factors, shifts = inv_mag, None
    elif norm_type == NormalizationType.STANDARDIZATION:
        if intercept_index is None:
            raise ValueError("STANDARDIZATION requires an intercept column")
        factors, shifts = inv_std, mean.astype(np.float64).copy()
    else:
        raise ValueError(f"unknown normalization type {norm_type}")

    factors = np.asarray(factors, dtype=np.float64).copy()
    if intercept_index is not None:
        factors[intercept_index] = 1.0
        if shifts is not None:
            shifts[intercept_index] = 0.0
    assert len(factors) == d
    return NormalizationContext(
        factors=jnp.asarray(factors, dtype=dtype),
        shifts=None if shifts is None else jnp.asarray(shifts, dtype=dtype),
        intercept_index=intercept_index,
    )
