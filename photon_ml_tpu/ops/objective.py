"""GLM objective: value / gradient / Hessian-vector product by autodiff.

TPU-first replacement for the reference's objective-function hierarchy
(``photon-api/.../function/ObjectiveFunction.scala``, ``DiffFunction.scala``,
``TwiceDiffFunction.scala``, ``function/glm/DistributedGLMLossFunction.scala``,
``function/glm/SingleNodeGLMLossFunction.scala`` and the four aggregator
classes ``ValueAndGradientAggregator`` / ``HessianVectorAggregator`` /
``HessianDiagonalAggregator`` / ``HessianMatrixAggregator``).

Design stance (SURVEY.md §7): define only the per-sample pointwise loss and the
(linear) margin model; derive everything else:

- value: ``sum_i weight_i * l(margin_i, label_i) + 0.5 * l2 * ||w_reg||^2``
- gradient: ``jax.grad`` of that pure function,
- Hessian-vector product: ``jax.jvp`` of the gradient — exact for GLMs
  (the margin is linear in ``w``, so forward-over-reverse equals
  ``X^T diag(d2) X v + l2 v``, the quantity TRON needs),
- Hessian diagonal / full matrix (for variance computation): closed-form
  contractions using the loss's ``d2``.

Everything here is a pure function of ``(w, data, l2)`` and safe under
``jit`` / ``vmap`` / ``shard_map``; the distributed ("DistributedGLMLossFunction")
variant is these same functions wrapped in a ``psum`` by
:mod:`photon_ml_tpu.parallel.distributed` — one code path from a single chip
to a pod, replacing the RDD ``treeAggregate`` tree.

Normalization is applied as a coefficient-space reparameterization
(:mod:`photon_ml_tpu.ops.normalization`) — transformed-space margins are
computed on raw features on the fly, never materializing scaled data, matching
the reference's normalization-aware aggregators.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.ops.design import (
    ChunkedSparseDesign,
    CsrDesign,
    DenseDesign,
    Design,
)
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.ops.normalization import NormalizationContext, NoNormalization

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GLMData:
    """One batch/shard of labeled GLM data.

    Counterpart of the reference's ``data/LabeledPoint.scala`` collection:
    ``labels`` ``(n,)``, per-sample additive ``offsets`` ``(n,)`` (the residual
    scores that make GAME coordinate descent work), non-negative ``weights``
    ``(n,)``. ``weights`` may also encode padding: a padded row has weight 0
    and contributes exactly nothing to value/grad/Hvp, which is what makes
    fixed-shape bucketing of ragged entity data correct.
    """

    design: Design
    labels: Array
    offsets: Array
    weights: Array

    @property
    def n_samples(self) -> int:
        return self.design.n_samples

    @property
    def dim(self) -> int:
        return self.design.dim

    def with_offsets(self, offsets: Array) -> "GLMData":
        return dataclasses.replace(self, offsets=offsets)


@dataclasses.dataclass(frozen=True)
class GLMObjective:
    """Pure-functional twice-differentiable GLM objective.

    Static configuration only (the pointwise loss, the normalization context,
    and an optional L2 mask); all numeric state flows through arguments so a
    single compilation serves every lambda in a regularization sweep (the
    reference's warm-start sweep in ``ModelTraining.scala``).

    ``reg_mask`` is an optional ``(d,)`` 0/1 vector selecting which
    coefficients the L2 term touches (e.g. to exempt the intercept).
    """

    loss: PointwiseLoss
    normalization: NormalizationContext = NoNormalization
    reg_mask: Optional[Array] = None
    #: use the Pallas fused one-pass value+grad kernel (TPU only; dense
    #: designs with identity normalization — other cases fall back to
    #: autodiff transparently). See photon_ml_tpu/ops/pallas_glm.py.
    fused: bool = False
    #: entity-batched variant of ``fused`` (the random-effect bucket solve):
    #: under a vmap carrying the batch axis on every operand, dispatch the
    #: single-pass (E, S, D) Pallas kernel (ops/pallas_re.py). A separate
    #: switch because eligibility differs — per-entity designs are small, so
    #: the gate is the ENTITY block plan (lane_fits_vmem), not
    #: auto_block_rows over the sample dim. Set by RandomEffectSolver; the
    #: two flags are not meant to be combined.
    fused_entity: bool = False
    #: testing only: run the fused kernel through the Pallas interpreter on
    #: non-TPU backends instead of falling back to the closed form. The
    #: interpreter is orders of magnitude slower than XLA — never in prod.
    fused_interpret: bool = False

    def __post_init__(self):
        # The closed-form paths (reg_curvature, _closed_value_and_grad) and
        # the autodiff of value() agree only for a 0/1 mask: the L2 term is
        # 0.5*l2*||w*mask||², whose true curvature is l2*mask² — equal to
        # the l2*mask the closed forms use iff mask ∈ {0, 1}.
        if self.reg_mask is not None and not isinstance(
                self.reg_mask, jax.core.Tracer):
            import numpy as np

            vals = np.asarray(self.reg_mask)
            if not np.all((vals == 0) | (vals == 1)):
                raise ValueError(
                    "reg_mask must be a 0/1 selector vector; got values "
                    f"outside {{0, 1}}: {vals[(vals != 0) & (vals != 1)][:5]}")

    # --- margins ----------------------------------------------------------
    def margins(self, w: Array, data: GLMData) -> Array:
        w_eff, margin_shift = self.normalization.transform_coefficients(w)
        return data.design.matvec(w_eff) + margin_shift + data.offsets

    # --- objective value --------------------------------------------------
    def _reg_w(self, w: Array) -> Array:
        """Coefficients as seen by the L2 term (reg_mask selects, e.g. to
        exempt the intercept) — single home of the mask semantics."""
        return w if self.reg_mask is None else w * self.reg_mask

    def _l2_term(self, w: Array, l2) -> Array:
        wr = self._reg_w(w)
        return 0.5 * l2 * jnp.vdot(wr, wr)

    def reg_curvature(self, l2):
        """The L2 term's Hessian diagonal — single home of the 0/1-mask
        curvature convention (d²/dw² of 0.5·l2·||w·mask||² = l2·mask for a
        0/1 mask; shared by the distributed wrappers)."""
        return l2 if self.reg_mask is None else l2 * self.reg_mask

    def value(self, w: Array, data: GLMData, l2=0.0) -> Array:
        live = data.weights > 0
        m = self.margins(w, data)
        # Double-where masking: weight-0 padding rows are evaluated at margin
        # 0 (finite) AND zero-weighted. Masking only the output would leave
        # 0 * inf = NaN in the value and — because backprop differentiates the
        # overflowing primal — NaN in the gradient; this is the invariant that
        # makes fixed-shape bucketing of ragged entity data safe.
        m_safe = jnp.where(live, m, 0.0)
        per_sample = self.loss.loss(m_safe, data.labels)
        contrib = jnp.where(live, data.weights * per_sample, 0.0)
        return jnp.sum(contrib) + self._l2_term(w, l2)

    # --- derivatives ------------------------------------------------------
    def _fused_eligible(self, data: GLMData) -> bool:
        """Single home of the fused-kernel gate (shared by value_and_grad,
        hvp_prefers_operator, hvp_operator — they must not drift): Mosaic
        lowering needs a TPU (tests opt into the interpreter via
        fused_interpret), dense design, identity normalization, and a
        no-copy auto block (shapes with no tile-aligned dividing block
        would force the kernel to re-pad the full design per evaluation —
        a net loss vs the closed form)."""
        on_tpu = jax.default_backend() == "tpu"
        if not (self.fused and (on_tpu or self.fused_interpret)
                and isinstance(data.design, DenseDesign)
                and self.normalization.is_identity):
            return False
        from photon_ml_tpu.ops.pallas_glm import auto_block_rows

        return auto_block_rows(data.n_samples, data.design.x.dtype) is not None

    def _entity_fused_eligible(self, data: GLMData) -> bool:
        """Gate for the entity-batched kernel (``fused_entity``) — same
        backend/design/normalization conditions as :meth:`_fused_eligible`,
        but the shape test is the per-entity VMEM plan: under the bucket
        vmap this objective sees ONE (S, D) lane, and the kernel blocks
        over entities, so ``auto_block_rows`` over samples is the wrong
        question."""
        on_tpu = jax.default_backend() == "tpu"
        if not (self.fused_entity and (on_tpu or self.fused_interpret)
                and isinstance(data.design, DenseDesign)
                and self.normalization.is_identity):
            return False
        from photon_ml_tpu.ops.pallas_re import lane_fits_vmem

        return lane_fits_vmem(data.n_samples, data.dim, data.design.x.dtype)

    def value_and_grad(self, w: Array, data: GLMData, l2=0.0) -> tuple[Array, Array]:
        if self._entity_fused_eligible(data):
            from photon_ml_tpu.ops.pallas_re import (
                vmappable_entity_value_and_grad,
            )

            # custom-vmap wrapper: the bucket solve's all-operands vmap
            # dispatches the single-pass entity kernel; called unbatched it
            # is the closed form (identical math, one lane)
            vag = vmappable_entity_value_and_grad(
                self.loss, jax.default_backend() != "tpu")
            value, grad = vag(data.design.x, w, data.labels, data.offsets,
                              data.weights)
            l2 = jnp.asarray(l2, value.dtype)
            return (value + self._l2_term(w, l2),
                    grad + l2 * self._reg_w(w))
        if self._fused_eligible(data):
            from photon_ml_tpu.ops.pallas_glm import vmappable_value_and_grad

            # custom-vmap wrapper: a vmap over w alone (the batched lambda
            # sweep) runs the multi-row kernel — one pass over X for all
            # lanes; unbatched calls behave exactly like the plain kernel
            vag = vmappable_value_and_grad(
                self.loss, jax.default_backend() != "tpu")
            value, grad = vag(data.design.x, w, data.labels, data.offsets,
                              data.weights)
            l2 = jnp.asarray(l2, value.dtype)
            return (value + self._l2_term(w, l2),
                    grad + l2 * self._reg_w(w))
        return self._closed_value_and_grad(w, data, l2)

    def _closed_value_and_grad(self, w, data, l2) -> tuple[Array, Array]:
        """Closed-form (value, grad): margins computed ONCE, two passes over
        the design total. ``jax.value_and_grad`` rematerializes the margins
        in the backward pass — a third full pass over X — which costs ~1.5x
        wall-clock in the HBM-bound regime (measured on TPU v5e); GLM
        gradients are simple enough (``g = X'(weight·dl)``) that autodiff
        buys nothing here. Same double-where padding guards as :meth:`value`.

        Normalization enters by chain rule: the transformed column is
        ``f_j·(x_ij − s_j)``, so ``g = f ∘ (Xᵀdl − s·Σdl)`` — no scaled
        design is ever materialized (reference: normalization-aware
        ``ValueAndGradientAggregator.scala``).
        """
        live = data.weights > 0
        m = self.margins(w, data)
        m_safe = jnp.where(live, m, 0.0)
        lvec = self.loss.loss(m_safe, data.labels)
        value = (jnp.sum(jnp.where(live, data.weights * lvec, 0.0))
                 + self._l2_term(w, l2))
        dl = jnp.where(live, data.weights * self.loss.d1(m_safe, data.labels),
                       0.0)
        g = data.design.rmatvec(dl)
        norm = self.normalization
        if norm.shifts is not None:
            g = g - norm.shifts * jnp.sum(dl)
        if norm.factors is not None:
            g = g * norm.factors
        g = g.astype(w.dtype)
        return value, g + jnp.asarray(l2, w.dtype) * self._reg_w(w)

    def grad(self, w: Array, data: GLMData, l2=0.0) -> Array:
        return jax.grad(self.value)(w, data, l2)

    def hvp(self, w: Array, v: Array, data: GLMData, l2=0.0) -> Array:
        """Exact Hessian-vector product. Replaces
        ``HessianVectorAggregator.scala``; feeds TRON's inner CG.
        One-shot form of :meth:`hvp_operator`.
        """
        return self.hvp_operator(w, data, l2)(v)

    def hvp_prefers_operator(self, data: GLMData) -> bool:
        """True when :meth:`hvp_operator` actually buys wall-clock — i.e.
        the fused one-pass Hvp kernel will engage. Forcing the hoisted
        operator form onto the plain closed form measured SLOWER than
        letting XLA's loop-invariant code motion handle the d2 pass
        (1280 ms vs 987 ms on the TRON bench shape), so TRON only asks for
        the operator when the kernel is available."""
        return self._fused_eligible(data)

    def hvp_operator(self, w: Array, data: GLMData, l2=0.0):
        """``v ↦ Hv`` at fixed ``w`` — the shape TRON's inner CG wants.

        The margin-dependent ``d2`` weights are computed ONCE here (one
        pass over the design); each returned product is then a single
        further design traversal: the fused Pallas one-pass kernel on TPU
        for dense identity-normalization objectives, else the closed form
        ``X'ᵀ(d2·(X'v)) + l2·v`` with the normalized column
        ``x'_ij = f_j·(x_ij − s_j)`` expanded by chain rule (autodiff would
        differentiate through ``matvec``, and the backward of a sparse
        gather is the giant scatter the chunked design exists to avoid).
        """
        norm = self.normalization
        d2w = self._d2_weights(w, data)
        reg = jnp.asarray(self.reg_curvature(l2), w.dtype)

        if self._fused_eligible(data):
            from photon_ml_tpu.ops.pallas_glm import fused_hvp

            x = data.design.x
            interpret = jax.default_backend() != "tpu"

            def apply_fused(v: Array) -> Array:
                hv = fused_hvp(x, v, d2w, interpret=interpret)
                return hv.astype(w.dtype) + reg * v

            return apply_fused

        def apply(v: Array) -> Array:
            u = v if norm.factors is None else v * norm.factors
            t = data.design.matvec(u)
            if norm.shifts is not None:
                t = t - jnp.vdot(u, norm.shifts)
            d2t = d2w * t
            hv = data.design.rmatvec(d2t)
            if norm.shifts is not None:
                hv = hv - norm.shifts * jnp.sum(d2t)
            if norm.factors is not None:
                hv = hv * norm.factors
            return hv.astype(w.dtype) + reg * v

        return apply

    # --- closed-form second-order contractions (for variance) -------------
    def _d2_weights(self, w: Array, data: GLMData) -> Array:
        live = data.weights > 0
        m = jnp.where(live, self.margins(w, data), 0.0)
        d2 = self.loss.d2(m, data.labels)
        return jnp.where(live, data.weights * d2, 0.0)

    def hessian_diagonal(self, w: Array, data: GLMData, l2=0.0) -> Array:
        """Diagonal of the Hessian in *transformed* feature space.

        Replaces ``HessianDiagonalAggregator.scala`` (VarianceComputationType
        SIMPLE). Computed as ``sum_i d2_i * x'_ij^2`` via one Hvp-free pass:
        for the dense design it is an einsum; for sparse, a scatter-add of
        squared values.
        """
        d2 = self._d2_weights(w, data)
        design = data.design
        factors = self.normalization.factors

        if isinstance(design, DenseDesign):
            x = design.x
            if self.normalization.shifts is not None:
                x = x - self.normalization.shifts
            if factors is not None:
                x = x * factors
            diag = jnp.einsum("nd,n->d", jnp.square(x), d2,
                              preferred_element_type=jnp.promote_types(x.dtype, jnp.float32))
        elif isinstance(design, (ChunkedSparseDesign, CsrDesign)):
            # Σ_i d2_i (x_ij − s_j)² expands analytically over the sparse
            # pattern: Σ d2 x² − 2 s_j Σ d2 x + s_j² Σ d2, where the first
            # two column sums draw only on stored entries and the last term
            # covers the implicit zeros ((0 − s_j)² = s_j²) for free.
            if isinstance(design, ChunkedSparseDesign):
                sq = design.rmatvec_squared(d2)
            else:
                contrib = jnp.square(design.values) * jnp.take(d2, design.rows)
                sq = jnp.zeros((design.dim,), contrib.dtype).at[design.cols].add(contrib)
            shifts = self.normalization.shifts
            if shifts is None:
                diag = sq
            else:
                lin = design.rmatvec(d2)
                diag = sq - 2.0 * shifts * lin + jnp.square(shifts) * jnp.sum(d2)
            if factors is not None:
                # transformed column is f_j·(x_ij − s_j): factor² scales out
                diag = diag * jnp.square(factors)
        else:
            raise TypeError(type(design))
        return diag + self.reg_curvature(l2)

    def hessian_matrix(self, w: Array, data: GLMData, l2=0.0) -> Array:
        """Full ``(d, d)`` Hessian (VarianceComputationType FULL; replaces
        ``HessianMatrixAggregator.scala``). Only for small ``d`` — the
        reference has the same restriction."""
        if not isinstance(data.design, DenseDesign):
            # Materialize through Hvp columns for sparse designs; the
            # operator form computes the d2 weights once for all columns.
            eye = jnp.eye(data.dim, dtype=w.dtype)
            return jax.vmap(self.hvp_operator(w, data, l2))(eye).T
        d2 = self._d2_weights(w, data)
        x = data.design.x
        if self.normalization.shifts is not None:
            x = x - self.normalization.shifts
        if self.normalization.factors is not None:
            x = x * self.normalization.factors
        h = jnp.einsum("nd,n,ne->de", x, d2, x,
                       preferred_element_type=jnp.promote_types(x.dtype, jnp.float32))
        return h + jnp.diag(jnp.broadcast_to(self.reg_curvature(l2),
                                             (data.dim,)))
