from photon_ml_tpu.ops.losses import (  # noqa: F401
    LogisticLoss,
    PointwiseLoss,
    PoissonLoss,
    SmoothedHingeLoss,
    SquaredLoss,
    loss_for_task,
)
from photon_ml_tpu.ops.regularization import RegularizationContext  # noqa: F401
from photon_ml_tpu.ops.normalization import NormalizationContext  # noqa: F401
from photon_ml_tpu.ops.design import (  # noqa: F401
    ChunkedSparseDesign,
    CsrDesign,
    DenseDesign,
)
from photon_ml_tpu.ops.objective import GLMData, GLMObjective  # noqa: F401
