"""Pallas TPU kernel: fused GLM objective value + gradient in ONE pass over X.

Why: XLA computes ``value_and_grad`` of the GLM objective as two passes over
the design matrix — forward margins (``X @ w``) and transposed gradient
(``X^T @ dl``) — so the HBM-bound solve reads X twice per L-BFGS iteration.
This kernel streams each row-block of X through VMEM once and computes BOTH
contractions while the block is resident (the counterpart of the
reference's single-pass per-partition ``ValueAndGradientAggregator.scala``,
which also fuses margin/loss/gradient in one sweep per sample):

    per block i:   m   = X_i @ w + offsets_i          (MXU)
                   l  += Σ weights_i * loss(m, y_i)   (VPU)
                   g  += X_i^T @ (weights_i * dl(m))  (MXU)

Status (measured on the axon TPU v5e, (200k, 1024) f32): the closed-form
two-pass XLA path (``GLMObjective._closed_value_and_grad``) currently WINS —
~3.7 ms/iteration vs ~6.9 ms for this kernel — because the kernel's
per-block matvec/outer-product shapes under-utilize the MXU while XLA's
fused matvec pipeline streams near memory bandwidth. The kernel is kept
behind ``GLMObjective(fused=True)`` as the starting point for a blocked
multi-row formulation; do not enable it by default without re-measuring.
It is jit/shard_map-safe (the distributed layer's psum wraps around it);
L2 and normalization stay outside (coefficient-space reparameterization,
SURVEY.md §7).

Grid iteration on TPU is sequential, so accumulating into the outputs across
grid steps (init at block 0) is the standard reduction pattern.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from photon_ml_tpu.ops.losses import PointwiseLoss

#: rows streamed per grid step; multiple of every dtype's sublane tile
DEFAULT_BLOCK_ROWS = 1024


def _kernel(loss: PointwiseLoss, x_ref, y_ref, off_ref, wt_ref, w_ref,
            loss_ref, grad_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        loss_ref[:] = jnp.zeros_like(loss_ref)
        grad_ref[:] = jnp.zeros_like(grad_ref)

    x = x_ref[:]  # (B, D) — read once, used by both contractions
    w = w_ref[:]  # (D, 1)
    y = y_ref[:]  # (1, B)
    off = off_ref[:]
    wt = wt_ref[:]

    margins = jnp.dot(x, w, preferred_element_type=jnp.float32)  # (B, 1)
    m = margins.reshape(1, -1) + off
    lvec = loss.loss(m, y)
    dvec = loss.d1(m, y) * wt
    # padded rows carry weight 0; the where guards 0 * inf = nan
    lsum = jnp.sum(jnp.where(wt > 0, wt * lvec, 0.0))
    # full-slice (1,1) store: Mosaic rejects scalar stores to VMEM
    loss_ref[:] += lsum.reshape(1, 1)
    grad_ref[:] += jnp.dot(x.T, dvec.reshape(-1, 1).astype(x.dtype),
                           preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("loss", "block_rows", "interpret"))
def fused_value_and_grad(loss: PointwiseLoss, x, w, labels, offsets, weights,
                         *, block_rows: int = DEFAULT_BLOCK_ROWS,
                         interpret: bool = False):
    """(value, grad) of ``Σ_i weights_i * loss(x_i·w + offsets_i, y_i)``.

    ``x`` is ``(n, d)`` (any float dtype; bf16 recommended), ``w`` ``(d,)``
    f32. Rows are processed in ``block_rows`` chunks; the tail block is
    padded with weight-0 rows, which contribute exactly nothing.
    """
    n, d = x.shape
    b = min(block_rows, max(n, 8))
    n_blocks = pl.cdiv(n, b)
    n_pad = n_blocks * b
    if n_pad != n:
        pad = n_pad - n
        x = jnp.pad(x, ((0, pad), (0, 0)))
        labels = jnp.pad(labels, (0, pad))
        offsets = jnp.pad(offsets, (0, pad))
        weights = jnp.pad(weights, (0, pad))

    f32 = jnp.float32
    out = pl.pallas_call(
        functools.partial(_kernel, loss),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((b, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, b), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, b), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, b), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((d, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((d, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), f32),
            jax.ShapeDtypeStruct((d, 1), f32),
        ],
        interpret=interpret,
    )(
        x,
        labels.astype(f32).reshape(1, -1),
        offsets.astype(f32).reshape(1, -1),
        weights.astype(f32).reshape(1, -1),
        w.astype(f32).reshape(-1, 1),
    )
    value, grad = out
    return value[0, 0], grad[:, 0]
