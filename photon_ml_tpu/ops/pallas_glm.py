"""Pallas TPU kernel: fused GLM objective value + gradient in ONE pass over X.

Why: XLA computes ``value_and_grad`` of the GLM objective as two passes over
the design matrix — forward margins (``X @ w``) and transposed gradient
(``X^T @ dl``) — so the HBM-bound solve reads X twice per L-BFGS iteration.
This kernel streams each row-block of X through VMEM once and computes BOTH
contractions while the block is resident (the counterpart of the
reference's single-pass per-partition ``ValueAndGradientAggregator.scala``,
which also fuses margin/loss/gradient in one sweep per sample):

    per block i:   m   = w·X_iᵀ + offsets_i           (MXU, 1-row matmul)
                   l  += Σ weights_i * loss(m, y_i)   (VPU)
                   g  += (weights_i * dl(m)) · X_i    (MXU, 1-row matmul)

Layout is the whole game (this is round 2 of this kernel; round 1 lost to
XLA): every vector lives LANE-MAJOR — labels/offsets/weights/margins as
``(1, B)`` rows, the gradient accumulator as ``(1, D)`` — so there are no
``(N, 1)`` layouts (which waste 127/128 lanes per VMEM tile) and no
``(B, 1) → (1, B)`` relayouts inside the loop. Both contractions are 1-row
matmuls against the SAME resident x block:

    margins  (1,B) = dot_general(w (1,D), x (B,D), contract D with D)
    grad    +(1,D) = dot_general(dvec (1,B), x (B,D), contract B with rows)

Measured on the axon TPU v5e at (200k, 1024), 50-iteration compiled loop
(objective evaluation only):

    XLA two-pass closed form       3.61 ms/iter   (453 GB/s effective)
    this kernel, f32 (HIGHEST)     2.65 ms/iter   (1.36x)
    this kernel, f32, fast-matmul  2.44 ms/iter   (but ~1e-3 gradients — see
                                                   precision note in _kernel)
    this kernel, bf16, B=1024      1.85 ms/iter   (1.95x; design stored bf16)

Round-2 block-size sweep (same shape, 50-iter fori_loop, best of 3):

    f32  B=400 (auto)   2.658 ms/iter   308 GB/s effective — 91% of the
                        ~340 GB/s practical single-op ceiling measured on
                        this box; the f32 kernel is AT the bandwidth wall.
    f32  B=800          VMEM OOM (19.7 MB scoped > 16 MB limit)
    bf16 B=800 (auto)   1.947 ms/iter   210 GB/s eff
    bf16 B=1000         3.890 ms/iter   (sublane-hostile: 1000 % 16 != 0
                        after rounding → padding path)
    bf16 B=1600         2.630 ms/iter
    bf16 B=2000         1.908 ms/iter   215 GB/s eff

bf16 is NOT bandwidth-bound: halving the bytes recovered only 1.37x over
fused f32, flat across block sizes — the M=1 matvec shape leaves 127/128
MXU rows idle, so at bf16's byte rate the kernel hits the issue/compute
wall (~210 GB/s effective) before the HBM wall (~340). End-to-end the
bf16-design solve still measures ~1.4–1.5x over the f32 fused solve
(101 ms vs 150 ms, 50 iterations) because line-search evaluations share
the same kernel. Auto block sizes (f32 400, bf16 800) are within 2% of
the best measured; no retune needed.

Round-4 multi-row-margin variant (``fused_value_and_grad_multi`` + the
``vmappable_value_and_grad`` custom-vmap wrapper — the batched
lambda-sweep consumer VERDICT r3 item 5 asked about): M coefficient rows
share one pass over X; margins are M rows of one MXU matmul. Measured on
the axon TPU v5e, dense 200k x 1024, 5 lambdas, 50-iteration solves,
D2H-sync, min of 3:

    batched sweep, unfused under vmap (round 3)   1.27 s
    batched sweep + multi-row kernel              0.95 s   (1.33x better)
    sequential sweep (M=1 kernel + warm starts)   0.74 s   (still the
                                                  dense winner)

Verdict: the idle MXU rows are real and the multi-row kernel recovers a
1.33x on the batched path, but warm starts (late lanes converge in a few
iterations) still beat lockstep lanes on dense problems — the sweep
default (sequential for dense, batched for chunked-sparse at its 1.74x)
stands. The kernel pays off when lanes genuinely must run without warm
starts (the vmapped batched mode users opt into). Standalone per-call
timings through the axon tunnel are floored at ~80 ms by the D2H round
trip — only chained/in-solve measurements are meaningful here.

In auto mode the block size prefers the largest ≤-cap divisor of n (see
``_dividing_block_rows``; at n=200k f32 that's B=400) so X streams in
place — padding the row dim means `jnp.pad` copying the FULL design inside
the traced objective on every evaluation, which more than erased the
kernel's win inside the L-BFGS loop when first measured. End to end: the
bench solve (50 iterations) runs 0.145 s fused vs 0.196 s closed-form
(1.35x), converging to the same objective value.

Alternatives measured and rejected: the round-1 sublane-major formulation
(2.6–6.9 ms); per-block output slots with a ``parallel`` grid + outside
reduction (2.68 ms f32 — the revisited accumulator is NOT the bottleneck);
larger f32 blocks (B=2048 exceeds the 16 MB VMEM scoped limit).

Enabled via ``GLMObjective(fused=True)`` for dense designs with identity
normalization; other cases fall back to autodiff transparently. L2 stays
outside (coefficient-space term). The bf16 path is opt-in by storing the
design bf16 — margins/loss/gradient still accumulate f32 on the MXU, but
the design itself is rounded (~3 decimal digits), which perturbs the
optimum; keep f32 where reference-parity matters.

Grid iteration on TPU is sequential, so accumulating into the outputs across
grid steps (init at block 0) is the standard reduction pattern.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from photon_ml_tpu.ops.losses import PointwiseLoss

#: rows streamed per grid step, by design dtype: the f32 sweet spot is the
#: largest block whose double-buffered DMA fits scoped VMEM; bf16 blocks are
#: half the bytes so twice the rows.
DEFAULT_BLOCK_ROWS_F32 = 512
DEFAULT_BLOCK_ROWS_BF16 = 1024


def _kernel(loss: PointwiseLoss, x_ref, y_ref, off_ref, wt_ref, w_ref,
            loss_ref, grad_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        loss_ref[:] = jnp.zeros_like(loss_ref)
        grad_ref[:] = jnp.zeros_like(grad_ref)

    x = x_ref[:]  # (B, D) — read once, used by both contractions
    w = w_ref[:]  # (1, D) f32
    y = y_ref[0]  # (1, B) — block i of the (n_blocks, 1, B) reshaped vector
    off = off_ref[0]
    wt = wt_ref[0]

    # precision=HIGHEST for f32 designs: the MXU's default f32 handling is
    # a single bf16 pass (~1e-3 relative — measured 40x worse gradients
    # than the XLA closed form, enough to disturb L-BFGS paths); HIGHEST
    # selects the multi-pass f32 emulation at no wall-clock cost (the
    # kernel is HBM-bound). bf16 designs keep DEFAULT — requesting an
    # fp32-contract on bf16 operands is rejected by Mosaic ("Bad lhs
    # type"), and bf16 storage has already rounded the data anyway.
    precision = (jax.lax.Precision.HIGHEST if x.dtype == jnp.float32
                 else jax.lax.Precision.DEFAULT)
    m = jax.lax.dot_general(
        w.astype(x.dtype), x,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=precision) + off  # (1, B)
    # padded rows carry weight 0: evaluate them at margin 0 (finite) AND
    # zero-weight the output — the double-where guard of GLMObjective.value
    live = wt > 0
    m_safe = jnp.where(live, m, 0.0)
    lvec = loss.loss(m_safe, y)
    dvec = jnp.where(live, loss.d1(m_safe, y) * wt, 0.0)
    loss_ref[:] += jnp.sum(jnp.where(live, wt * lvec, 0.0)).reshape(1, 1)
    grad_ref[:] += jax.lax.dot_general(
        dvec.astype(x.dtype), x,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=precision)  # (1, D)


def _out_struct(x, shape, dtype):
    """ShapeDtypeStruct for a kernel output, carrying the input's varying
    manual axes: under shard_map, outputs vary over the same mesh axes as
    the design block — without the vma the checker rejects the
    pallas_call. One home for both kernels so the plumbing cannot drift."""
    from photon_ml_tpu.compat import typeof

    vma = getattr(typeof(x), "vma", frozenset()) or None
    return (jax.ShapeDtypeStruct(shape, dtype) if vma is None
            else jax.ShapeDtypeStruct(shape, dtype, vma=vma))


def _default_block_rows(dtype) -> int:
    if dtype == jnp.bfloat16:
        return DEFAULT_BLOCK_ROWS_BF16
    return DEFAULT_BLOCK_ROWS_F32


def _sublane_tile(dtype) -> int:
    """Minimum second-to-last block dim for this dtype (Mosaic tiling)."""
    return 16 if dtype == jnp.bfloat16 else 8


def _dividing_block_rows(n: int, cap: int, tile: int) -> int | None:
    """Largest tile-aligned divisor of ``n`` that is ≤ cap and ≥ 128.

    A block size that divides ``n`` lets the kernel stream X in place. The
    alternative — padding the row dim — is `jnp.pad` of the FULL design
    inside the traced objective, a copy of the dominant payload on every
    evaluation (measured: it more than erased the kernel's win inside the
    L-BFGS loop). Below 128 rows the grid gets long and per-block overhead
    wins; fall back to the padding path instead. ``tile`` is the dtype's
    sublane tile (8 for f32, 16 for bf16) — a block that is a multiple of 8
    but not 16 fails Mosaic lowering for a bf16 design.
    """
    for b in range(min(cap, n) // tile * tile, 127, -tile):
        if n % b == 0:
            return b
    return None


def _rounded_block(n: int, cap: int, tile: int) -> int:
    """Tile-align ``cap`` against ``n`` rows — a block covering the whole
    (unpadded) array is accepted as-is by Mosaic, anything smaller must be a
    multiple of the dtype's sublane tile."""
    b = min(cap, max(n, tile))
    if b < n:
        b = max(tile, b // tile * tile)
    return b


def auto_block_rows(n: int, dtype) -> int | None:
    """The block size auto mode will stream with NO per-call copy, or None.

    ``None`` means :func:`fused_value_and_grad` in auto mode would have to
    ``jnp.pad`` the full design inside the traced objective on every
    evaluation — the regression documented in :func:`_dividing_block_rows`.
    Callers (``GLMObjective.value_and_grad``) use this to fall back to the
    XLA closed form for such shapes instead of paying the copy. This IS the
    kernel's auto-mode selection (``fused_value_and_grad`` calls it), so the
    predicate cannot drift from the executor.
    """
    tile = _sublane_tile(dtype)
    b = _rounded_block(n, _default_block_rows(dtype), tile)
    if n % b == 0:
        return b
    return _dividing_block_rows(n, _default_block_rows(dtype), tile)


@functools.partial(jax.jit, static_argnames=("loss", "block_rows", "interpret"))
def fused_value_and_grad(loss: PointwiseLoss, x, w, labels, offsets, weights,
                         *, block_rows: int | None = None,
                         interpret: bool = False):
    """(value, grad) of ``Σ_i weights_i * loss(x_i·w + offsets_i, y_i)``.

    ``x`` is ``(n, d)`` (f32, or bf16 for the half-bandwidth path), ``w``
    ``(d,)`` f32. Rows are processed in ``block_rows`` chunks; the tail
    block is padded with weight-0 rows, which contribute exactly nothing.
    """
    n, d = x.shape
    tile = _sublane_tile(x.dtype)
    if block_rows is None:
        # auto mode prefers a dividing block (no-copy); one shared selector
        # (auto_block_rows) so the objective's skip-predicate cannot drift
        b = auto_block_rows(n, x.dtype)
        if b is None:  # no dividing block: padding path
            b = _rounded_block(n, _default_block_rows(x.dtype), tile)
    else:
        # an explicit block_rows is honored (tile-rounded), padding if needed
        b = _rounded_block(n, block_rows, tile)
    n_blocks = pl.cdiv(n, b)
    n_pad = n_blocks * b
    if n_pad != n:
        pad = n_pad - n
        x = jnp.pad(x, ((0, pad), (0, 0)))
        labels = jnp.pad(labels, (0, pad))
        offsets = jnp.pad(offsets, (0, pad))
        weights = jnp.pad(weights, (0, pad))

    f32 = jnp.float32
    itemsize = jnp.dtype(x.dtype).itemsize
    # vectors ride as (n_blocks, 1, b) — a free reshape — so the per-step
    # block (1, 1, b) has its last two dims equal to the array's own; Mosaic
    # otherwise requires (8k, 128k) block dims, which would force b to be a
    # multiple of 128 and usually rule out the no-copy dividing block size
    out = pl.pallas_call(
        functools.partial(_kernel, loss),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((b, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, b), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, b), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, b), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _out_struct(x, (1, 1), f32),
            _out_struct(x, (1, d), f32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * n_pad * d,
            transcendentals=2 * n_pad,
            bytes_accessed=n_pad * d * itemsize,
        ),
        interpret=interpret,
    )(
        x,
        labels.astype(f32).reshape(n_blocks, 1, b),
        offsets.astype(f32).reshape(n_blocks, 1, b),
        weights.astype(f32).reshape(n_blocks, 1, b),
        w.astype(f32).reshape(1, -1),
    )
    value, grad = out
    return value[0, 0], grad[0, :]


def _kernel_multi(loss: PointwiseLoss, x_ref, y_ref, off_ref, wt_ref, w_ref,
                  loss_ref, grad_ref):
    """Multi-row-margin variant: M coefficient rows share ONE pass over the
    design block. The M=1 kernel leaves 127/128 MXU rows idle (the issue
    wall the measurement table documents); here margins are the (M, B) rows
    of a single matmul and the gradient a real (M, B)x(B, D) matmul — the
    batched lambda-sweep's lanes ride the idle rows for free."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        loss_ref[:] = jnp.zeros_like(loss_ref)
        grad_ref[:] = jnp.zeros_like(grad_ref)

    x = x_ref[:]  # (B, D) — read once, shared by every lane
    w = w_ref[:]  # (M, D) f32
    y = y_ref[0]  # (1, B)
    off = off_ref[0]
    wt = wt_ref[0]
    precision = (jax.lax.Precision.HIGHEST if x.dtype == jnp.float32
                 else jax.lax.Precision.DEFAULT)
    m = jax.lax.dot_general(
        w.astype(x.dtype), x,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=precision) + off  # (M, B); off broadcasts over lanes
    live = wt > 0  # (1, B) — broadcasts
    m_safe = jnp.where(live, m, 0.0)
    lvec = loss.loss(m_safe, y)
    dvec = jnp.where(live, loss.d1(m_safe, y) * wt, 0.0)
    loss_ref[:] += jnp.sum(jnp.where(live, wt * lvec, 0.0),
                           axis=1).reshape(1, -1)  # (1, M)
    grad_ref[:] += jax.lax.dot_general(
        dvec.astype(x.dtype), x,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=precision)  # (M, D)


@functools.partial(jax.jit, static_argnames=("loss", "block_rows", "interpret"))
def fused_value_and_grad_multi(loss: PointwiseLoss, x, ws, labels, offsets,
                               weights, *, block_rows: int | None = None,
                               interpret: bool = False):
    """(values (M,), grads (M, D)) for M coefficient vectors over ONE pass
    of the design — the batched lambda-sweep consumer (every lane shares
    the same data; only w differs per lane). Block selection and padding
    semantics are identical to :func:`fused_value_and_grad`.

    KEPT SEPARATE from the M=1 kernel deliberately: the single-row kernel's
    (1, B)/(1, D) lane-major layouts are the measured-fastest formulation
    for the headline solve (see the module table — round 1's alternative
    layouts lost 1.0-2.6x), and routing M=1 through this kernel's (M, ·)
    shapes was not measured equal. Any change to the block-selection /
    padding / BlockSpec plumbing here must be mirrored in
    :func:`fused_value_and_grad` (and vice versa)."""
    n, d = x.shape
    n_lanes = ws.shape[0]
    tile = _sublane_tile(x.dtype)
    if block_rows is None:
        b = auto_block_rows(n, x.dtype)
        if b is None:
            b = _rounded_block(n, _default_block_rows(x.dtype), tile)
    else:
        b = _rounded_block(n, block_rows, tile)
    n_blocks = pl.cdiv(n, b)
    n_pad = n_blocks * b
    if n_pad != n:
        pad = n_pad - n
        x = jnp.pad(x, ((0, pad), (0, 0)))
        labels = jnp.pad(labels, (0, pad))
        offsets = jnp.pad(offsets, (0, pad))
        weights = jnp.pad(weights, (0, pad))

    f32 = jnp.float32
    itemsize = jnp.dtype(x.dtype).itemsize
    out = pl.pallas_call(
        functools.partial(_kernel_multi, loss),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((b, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, b), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, b), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, b), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((n_lanes, d), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, n_lanes), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((n_lanes, d), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _out_struct(x, (1, n_lanes), f32),
            _out_struct(x, (n_lanes, d), f32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * n_pad * d * n_lanes,
            transcendentals=2 * n_pad * n_lanes,
            bytes_accessed=n_pad * d * itemsize,
        ),
        interpret=interpret,
    )(
        x,
        labels.astype(f32).reshape(n_blocks, 1, b),
        offsets.astype(f32).reshape(n_blocks, 1, b),
        weights.astype(f32).reshape(n_blocks, 1, b),
        ws.astype(f32),
    )
    value, grad = out
    return value[0, :], grad


@functools.lru_cache(maxsize=None)
def vmappable_value_and_grad(loss: PointwiseLoss, interpret: bool = False):
    """The fused (value, grad) with a custom vmap rule: a vmap over the
    coefficient vector alone (the batched lambda sweep) dispatches to the
    multi-row kernel — one pass over X shared by all lanes, M margins as M
    rows of one MXU matmul — instead of M independent kernel passes. Any
    other batching combination falls back to a sequential lane map."""

    @jax.custom_batching.custom_vmap
    def vag(x, w, labels, offsets, weights):
        return fused_value_and_grad(loss, x, w, labels, offsets, weights,
                                    interpret=interpret)

    @vag.def_vmap
    def _rule(axis_size, in_batched, x, w, labels, offsets, weights):
        xb, wb, lb, ob, wtb = in_batched
        if wb and not (xb or lb or ob or wtb):
            values, grads = fused_value_and_grad_multi(
                loss, x, w, labels, offsets, weights, interpret=interpret)
            return (values, grads), (True, True)

        def body(i):
            return fused_value_and_grad(
                loss, x[i] if xb else x, w[i] if wb else w,
                labels[i] if lb else labels, offsets[i] if ob else offsets,
                weights[i] if wtb else weights, interpret=interpret)

        values, grads = jax.lax.map(body, jnp.arange(axis_size))
        return (values, grads), (True, True)

    return vag


def _hvp_kernel(x_ref, d2_ref, v_ref, out_ref):
    """One-pass GLM Hessian-vector product: out = Xᵀ(d2 ∘ (Xv)).

    Same lane-major shape discipline as :func:`_kernel` — both
    contractions are 1-row matmuls against the SAME resident x block, so
    the design streams through VMEM exactly once per product (the XLA
    closed form reads it twice: matvec then rmatvec). ``d2`` is the
    precomputed per-sample weight·d2loss vector — margin-dependent only
    through ``w``, so TRON's inner CG (many products at fixed ``w``)
    amortizes its computation to zero.
    """
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = x_ref[:]  # (B, D)
    v = v_ref[:]  # (1, D) f32
    d2 = d2_ref[0]  # (1, B)
    precision = (jax.lax.Precision.HIGHEST if x.dtype == jnp.float32
                 else jax.lax.Precision.DEFAULT)
    t = jax.lax.dot_general(
        v.astype(x.dtype), x,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=precision)  # (1, B) = (Xv)ᵀ for this block
    out_ref[:] += jax.lax.dot_general(
        (d2 * t).astype(x.dtype), x,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=precision)  # (1, D)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def fused_hvp(x, v, d2w, *, block_rows: int | None = None,
              interpret: bool = False):
    """``Xᵀ(d2w ∘ (Xv))`` in ONE pass over ``x`` (no L2 term — caller adds).

    ``x`` is ``(n, d)``; ``v`` ``(d,)`` f32; ``d2w`` ``(n,)`` the
    weight-and-padding-masked second derivatives (0 on padded rows, which
    then contribute exactly nothing). Block selection mirrors
    :func:`fused_value_and_grad` via the shared :func:`auto_block_rows`.
    """
    n, d = x.shape
    tile = _sublane_tile(x.dtype)
    if block_rows is None:
        b = auto_block_rows(n, x.dtype)
        if b is None:  # no dividing block: padding path
            b = _rounded_block(n, _default_block_rows(x.dtype), tile)
    else:
        b = _rounded_block(n, block_rows, tile)
    n_blocks = pl.cdiv(n, b)
    n_pad = n_blocks * b
    if n_pad != n:
        x = jnp.pad(x, ((0, n_pad - n), (0, 0)))
        d2w = jnp.pad(d2w, (0, n_pad - n))

    f32 = jnp.float32
    itemsize = jnp.dtype(x.dtype).itemsize
    out = pl.pallas_call(
        _hvp_kernel,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((b, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, b), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, d), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=_out_struct(x, (1, d), f32),
        cost_estimate=pl.CostEstimate(
            flops=4 * n_pad * d,
            transcendentals=0,
            bytes_accessed=n_pad * d * itemsize,
        ),
        interpret=interpret,
    )(
        x,
        d2w.astype(f32).reshape(n_blocks, 1, b),
        v.astype(f32).reshape(1, -1),
    )
    return out[0, :]
