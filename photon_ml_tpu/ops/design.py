"""Design-matrix abstractions: dense tiles and padded-COO sparse batches.

The reference stores every sample as a breeze ``SparseVector`` and computes
per-sample dot products in JVM loops
(``photon-api/.../data/LabeledPoint.scala`` +
``function/glm/ValueAndGradientAggregator.scala``). TPUs want the opposite:
large, fixed-shape, batched contractions that XLA can tile onto the MXU.

Two representations, both jit/vmap-safe pytrees:

- :class:`DenseDesign` — an ``(n, d)`` matrix; margins are one matmul. Right
  choice whenever ``d`` is modest (a1a's 123 features) or data is dense after
  bucketing. The matmul rides the MXU; optionally stored bfloat16.
- :class:`CsrDesign` — padded COO triplets ``(rows, cols, values)`` of a fixed
  nnz budget; margins via ``segment_sum`` and the gradient transpose via a
  scatter-add, both XLA-native. Padding entries carry ``value = 0`` so they
  contribute nothing to either pass. Right choice for the reference's
  sparse-feature regime (millions of features, ~hundreds of nnz/row).

Autodiff through ``matvec`` gives the gradient/Hvp aggregation for free —
XLA transposes a matmul into a matmul and a gather into a scatter — which is
what deletes the reference's four hand-written aggregator classes per loss.
"""

from __future__ import annotations

import dataclasses
from typing import Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class DenseDesign:
    """Dense ``(n, d)`` design matrix."""

    x: Array

    @property
    def n_samples(self) -> int:
        return self.x.shape[-2]

    @property
    def dim(self) -> int:
        return self.x.shape[-1]

    def matvec(self, w: Array) -> Array:
        """Margins ``X @ w``, accumulated in at least f32 (bf16 storage still
        gets f32 accumulation on the MXU; f64 inputs keep f64)."""
        acc = jnp.promote_types(self.x.dtype, jnp.float32)
        return jnp.einsum("...nd,...d->...n", self.x, w,
                          preferred_element_type=acc)

    def rmatvec(self, g: Array) -> Array:
        acc = jnp.promote_types(self.x.dtype, jnp.float32)
        return jnp.einsum("...nd,...n->...d", self.x, g,
                          preferred_element_type=acc)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class CsrDesign:
    """Fixed-nnz padded COO sparse design (TPU-friendly CSR replacement).

    ``rows``/``cols`` are int32 ``(nnz,)``; ``values`` float ``(nnz,)``.
    Padding entries must have ``values == 0`` (rows/cols may point anywhere
    in-range). ``n_samples``/``dim`` are static ints so shapes stay fixed
    under jit.
    """

    rows: Array
    cols: Array
    values: Array
    n_rows: int = dataclasses.field(metadata=dict(static=True))
    n_cols: int = dataclasses.field(metadata=dict(static=True))

    @property
    def n_samples(self) -> int:
        return self.n_rows

    @property
    def dim(self) -> int:
        return self.n_cols

    def matvec(self, w: Array) -> Array:
        # Accumulate in at least f32 (bf16 values would otherwise accumulate
        # hundreds of nnz/row in 8-bit mantissa); f64 inputs keep f64.
        acc = jnp.promote_types(jnp.promote_types(self.values.dtype, w.dtype),
                                jnp.float32)
        contrib = (self.values * jnp.take(w, self.cols, axis=0)).astype(acc)
        return jax.ops.segment_sum(contrib, self.rows, num_segments=self.n_rows)

    def rmatvec(self, g: Array) -> Array:
        acc = jnp.promote_types(jnp.promote_types(self.values.dtype, g.dtype),
                                jnp.float32)
        contrib = (self.values * jnp.take(g, self.rows, axis=0)).astype(acc)
        return jnp.zeros((self.n_cols,), dtype=acc).at[self.cols].add(contrib)

    @staticmethod
    def from_scipy(sp_matrix, *, nnz_pad: int | None = None, dtype=np.float32) -> "CsrDesign":
        """Build from a scipy.sparse matrix, padding nnz up to ``nnz_pad``."""
        coo = sp_matrix.tocoo()
        nnz = coo.nnz
        pad = (nnz if nnz_pad is None else nnz_pad) - nnz
        if pad < 0:
            raise ValueError(f"nnz_pad {nnz_pad} < actual nnz {nnz}")
        rows = np.concatenate([coo.row.astype(np.int32), np.zeros(pad, np.int32)])
        cols = np.concatenate([coo.col.astype(np.int32), np.zeros(pad, np.int32)])
        vals = np.concatenate([coo.data.astype(dtype), np.zeros(pad, dtype)])
        return CsrDesign(
            rows=jnp.asarray(rows), cols=jnp.asarray(cols), values=jnp.asarray(vals),
            n_rows=int(sp_matrix.shape[0]), n_cols=int(sp_matrix.shape[1]),
        )


Design = Union[DenseDesign, CsrDesign]
