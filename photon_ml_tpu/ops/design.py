"""Design-matrix abstractions: dense tiles and padded-COO sparse batches.

The reference stores every sample as a breeze ``SparseVector`` and computes
per-sample dot products in JVM loops
(``photon-api/.../data/LabeledPoint.scala`` +
``function/glm/ValueAndGradientAggregator.scala``). TPUs want the opposite:
large, fixed-shape, batched contractions that XLA can tile onto the MXU.

Three representations, all jit/vmap-safe pytrees:

- :class:`DenseDesign` — an ``(n, d)`` matrix; margins are one matmul. Right
  choice whenever ``d`` is modest (a1a's 123 features) or data is dense after
  bucketing. The matmul rides the MXU; optionally stored bfloat16.
- :class:`CsrDesign` — padded COO triplets ``(rows, cols, values)`` of a fixed
  nnz budget; margins via ``segment_sum`` and the gradient transpose via a
  scatter-add, both XLA-native. Padding entries carry ``value = 0`` so they
  contribute nothing to either pass. Right choice for the reference's
  sparse-feature regime (millions of features, ~hundreds of nnz/row) —
  superseded on TPU by :class:`ChunkedSparseDesign` (below), which replaces
  both per-nnz ops with gathers + chunk partial sums; CsrDesign remains the
  COO container/reference implementation.

Autodiff through ``matvec`` gives the gradient/Hvp aggregation for free —
XLA transposes a matmul into a matmul and a gather into a scatter — which is
what deletes the reference's four hand-written aggregator classes per loss.
"""

from __future__ import annotations

import dataclasses
from typing import Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class DenseDesign:
    """Dense ``(n, d)`` design matrix."""

    x: Array

    @property
    def n_samples(self) -> int:
        return self.x.shape[-2]

    @property
    def dim(self) -> int:
        return self.x.shape[-1]

    def matvec(self, w: Array) -> Array:
        """Margins ``X @ w``, accumulated in at least f32 (bf16 storage still
        gets f32 accumulation on the MXU; f64 inputs keep f64)."""
        acc = jnp.promote_types(self.x.dtype, jnp.float32)
        return jnp.einsum("...nd,...d->...n", self.x, w,
                          preferred_element_type=acc)

    def rmatvec(self, g: Array) -> Array:
        acc = jnp.promote_types(self.x.dtype, jnp.float32)
        return jnp.einsum("...nd,...n->...d", self.x, g,
                          preferred_element_type=acc)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class CsrDesign:
    """Fixed-nnz padded COO sparse design (TPU-friendly CSR replacement).

    ``rows``/``cols`` are int32 ``(nnz,)``; ``values`` float ``(nnz,)``.
    Padding entries must have ``values == 0`` (rows/cols may point anywhere
    in-range). ``n_samples``/``dim`` are static ints so shapes stay fixed
    under jit.
    """

    rows: Array
    cols: Array
    values: Array
    n_rows: int = dataclasses.field(metadata=dict(static=True))
    n_cols: int = dataclasses.field(metadata=dict(static=True))

    @property
    def n_samples(self) -> int:
        return self.n_rows

    @property
    def dim(self) -> int:
        return self.n_cols

    def matvec(self, w: Array) -> Array:
        # Accumulate in at least f32 (bf16 values would otherwise accumulate
        # hundreds of nnz/row in 8-bit mantissa); f64 inputs keep f64.
        acc = jnp.promote_types(jnp.promote_types(self.values.dtype, w.dtype),
                                jnp.float32)
        contrib = (self.values * jnp.take(w, self.cols, axis=0)).astype(acc)
        return jax.ops.segment_sum(contrib, self.rows, num_segments=self.n_rows)

    def rmatvec(self, g: Array) -> Array:
        acc = jnp.promote_types(jnp.promote_types(self.values.dtype, g.dtype),
                                jnp.float32)
        contrib = (self.values * jnp.take(g, self.rows, axis=0)).astype(acc)
        return jnp.zeros((self.n_cols,), dtype=acc).at[self.cols].add(contrib)

    @staticmethod
    def from_scipy(sp_matrix, *, nnz_pad: int | None = None, dtype=np.float32) -> "CsrDesign":
        """Build from a scipy.sparse matrix, padding nnz up to ``nnz_pad``."""
        coo = sp_matrix.tocoo()
        nnz = coo.nnz
        pad = (nnz if nnz_pad is None else nnz_pad) - nnz
        if pad < 0:
            raise ValueError(f"nnz_pad {nnz_pad} < actual nnz {nnz}")
        rows = np.concatenate([coo.row.astype(np.int32), np.zeros(pad, np.int32)])
        cols = np.concatenate([coo.col.astype(np.int32), np.zeros(pad, np.int32)])
        vals = np.concatenate([coo.data.astype(dtype), np.zeros(pad, dtype)])
        return CsrDesign(
            rows=jnp.asarray(rows), cols=jnp.asarray(cols), values=jnp.asarray(vals),
            n_rows=int(sp_matrix.shape[0]), n_cols=int(sp_matrix.shape[1]),
        )


def _chunk_sorted(keys: np.ndarray, payload_idx: np.ndarray, n_keys: int,
                  chunk: int) -> tuple[np.ndarray, np.ndarray]:
    """Chunk entries sorted by ``keys`` into fixed-width groups per key.

    Returns ``(gather, chunk_key)``: ``gather`` is an ``(M, chunk)`` int64 index into
    the payload (−1 = padding slot), ``chunk_key`` ``(M,)`` the key id of
    each chunk. A key with k entries occupies ceil(k/chunk) chunks.
    """
    counts = np.bincount(keys, minlength=n_keys)
    present = np.flatnonzero(counts)
    n_chunks_per = -(-counts[present] // chunk)
    total = int(n_chunks_per.sum())
    chunk_key = np.repeat(present, n_chunks_per).astype(np.int32)
    # entry positions: within-key offset → (chunk row, slot)
    starts = np.zeros(len(present) + 1, np.int64)
    np.cumsum(counts[present], out=starts[1:])
    chunk_starts = np.zeros(len(present) + 1, np.int64)
    np.cumsum(n_chunks_per, out=chunk_starts[1:])
    within = np.arange(len(keys)) - np.repeat(starts[:-1], counts[present])
    chunk_row = np.repeat(chunk_starts[:-1], counts[present]) + within // chunk
    slot = within % chunk
    gather = np.full((total, chunk), -1, np.int64)
    gather[chunk_row, slot] = payload_idx
    return gather, chunk_key


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ChunkedSparseDesign:
    """Dual chunked-COO sparse design: scatters shrunk by chunk partial sums.

    Motivation (measured on the axon TPU v5e, 12.8M nnz, d=100k):
    ``CsrDesign``'s per-nnz ``segment_sum`` margins cost ~116 ms and its
    scatter-add transpose ~89 ms, while a gather + fixed-width row-sum of
    the same entries costs ~5 ms — XLA lowers large scatters serially on
    TPU, but gathers and lane reductions stream. So this layout stores the
    entries TWICE, pre-sorted on host at build time:

    - row-major: ``(Mr, C)`` values/col-ids with one row id per chunk —
      margins = per-chunk ``Σ v·w[col]`` then a segment-sum of ONLY
      ``Mr ≈ nnz/C + n`` partials;
    - col-major: ``(Mc, C)`` values/row-ids with one col id per chunk —
      the gradient transpose the same way into ``d`` bins.

    Chunk padding carries ``value = 0`` (contributes nothing). The chunk
    width trades padding (small C) against scatter length (large C); the
    builder defaults to the per-key median rounded to a multiple of 8,
    clamped to [8, 128]. 2x memory vs CsrDesign — the price of replacing
    both big scatters. This is the counterpart of the reference's executor-
    local hash-map gradient accumulation in
    ``function/glm/ValueAndGradientAggregator.scala``, re-shaped for a
    machine that hates random writes and loves wide reads.
    """

    rvals: Array  # (Mr, C) f32
    rcols: Array  # (Mr, C) int32
    rrow: Array  # (Mr,) int32 — row id per chunk (non-decreasing)
    cvals: Array  # (Mc, C) f32
    crows: Array  # (Mc, C) int32
    ccol: Array  # (Mc,) int32 — col id per chunk (non-decreasing)
    n_rows: int = dataclasses.field(metadata=dict(static=True))
    n_cols: int = dataclasses.field(metadata=dict(static=True))

    @property
    def n_samples(self) -> int:
        return self.n_rows

    @property
    def dim(self) -> int:
        return self.n_cols

    @staticmethod
    def _gather2d(table: Array, idx: Array) -> Array:
        """``table[idx]`` for a 2D index array via a FLAT gather + reshape —
        XLA lowers a gather with a 2D start-index array ~30x slower on TPU
        (measured 129 ms vs 4.3 ms for 13M indices)."""
        return jnp.take(table, idx.reshape(-1), axis=0).reshape(idx.shape)

    def matvec(self, w: Array) -> Array:
        acc = jnp.promote_types(jnp.promote_types(self.rvals.dtype, w.dtype),
                                jnp.float32)
        part = jnp.sum((self.rvals * self._gather2d(w, self.rcols)
                        ).astype(acc), axis=-1)
        return jax.ops.segment_sum(part, self.rrow, num_segments=self.n_rows,
                                   indices_are_sorted=True)

    def rmatvec(self, g: Array) -> Array:
        acc = jnp.promote_types(jnp.promote_types(self.cvals.dtype, g.dtype),
                                jnp.float32)
        part = jnp.sum((self.cvals * self._gather2d(g, self.crows)
                        ).astype(acc), axis=-1)
        return jax.ops.segment_sum(part, self.ccol, num_segments=self.n_cols,
                                   indices_are_sorted=True)

    def rmatvec_squared(self, g: Array) -> Array:
        """``(X²)ᵀ g`` — the Hessian-diagonal contraction (values squared)."""
        acc = jnp.promote_types(jnp.promote_types(self.cvals.dtype, g.dtype),
                                jnp.float32)
        part = jnp.sum((jnp.square(self.cvals)
                        * self._gather2d(g, self.crows)).astype(acc),
                       axis=-1)
        return jax.ops.segment_sum(part, self.ccol, num_segments=self.n_cols,
                                   indices_are_sorted=True)

    @staticmethod
    def default_chunk(counts: np.ndarray) -> int:
        """Median nnz of the non-empty keys, rounded to 8 in [8, 128]."""
        nz = counts[counts > 0]
        if not len(nz):
            return 8
        med = int(np.median(nz))
        return int(np.clip(-(-med // 8) * 8, 8, 128))

    @staticmethod
    def layout_numpy(rows, cols, vals, *, row_chunk: int | None = None,
                     col_chunk: int | None = None) -> dict:
        """Host-side chunk layouts as numpy arrays (for stacking/sharding)."""
        rows = np.asarray(rows, np.int64)
        cols = np.asarray(cols, np.int64)
        vals = np.asarray(vals, np.float32)
        live = vals != 0  # drop explicit zero padding from CSR-style inputs
        rows, cols, vals = rows[live], cols[live], vals[live]
        if row_chunk is None:
            row_chunk = ChunkedSparseDesign.default_chunk(
                np.bincount(rows) if len(rows) else np.zeros(1, np.int64))
        if col_chunk is None:
            col_chunk = ChunkedSparseDesign.default_chunk(
                np.bincount(cols) if len(cols) else np.zeros(1, np.int64))

        def layout(keys, chunk):
            order = np.argsort(keys, kind="stable")
            gather, chunk_key = _chunk_sorted(
                keys[order], order,
                max(int(keys.max()) + 1 if len(keys) else 1, 1), chunk)
            pad = gather < 0
            safe = np.where(pad, 0, gather)
            v = np.where(pad, 0.0, vals[safe] if len(vals) else 0.0
                         ).astype(np.float32)
            return v, safe, chunk_key

        rvals, r_src, rrow = layout(rows, row_chunk)
        cvals, c_src, ccol = layout(cols, col_chunk)
        safe_cols = cols[r_src] if len(cols) else np.zeros_like(r_src)
        safe_rows = rows[c_src] if len(rows) else np.zeros_like(c_src)
        return dict(
            rvals=rvals, rcols=safe_cols.astype(np.int32), rrow=rrow,
            cvals=cvals, crows=safe_rows.astype(np.int32), ccol=ccol,
            row_chunk=row_chunk, col_chunk=col_chunk)

    @staticmethod
    def from_coo(rows, cols, vals, n_rows: int, n_cols: int,
                 row_chunk: int | None = None, col_chunk: int | None = None,
                 ) -> "ChunkedSparseDesign":
        """Build both layouts from host COO triplets. Duplicate (row, col)
        entries occupy separate slots and accumulate in every contraction,
        the same semantics as CsrDesign."""
        lay = ChunkedSparseDesign.layout_numpy(
            rows, cols, vals, row_chunk=row_chunk, col_chunk=col_chunk)
        return ChunkedSparseDesign(
            rvals=jnp.asarray(lay["rvals"]), rcols=jnp.asarray(lay["rcols"]),
            rrow=jnp.asarray(lay["rrow"]),
            cvals=jnp.asarray(lay["cvals"]), crows=jnp.asarray(lay["crows"]),
            ccol=jnp.asarray(lay["ccol"]),
            n_rows=int(n_rows), n_cols=int(n_cols))


Design = Union[DenseDesign, CsrDesign, ChunkedSparseDesign]
