"""Pallas TPU kernel: entity-batched GLM value + gradient in ONE pass over X.

The random-effect bucket solve is ``vmap(solve_one)`` over entity lanes of
an ``(E, S, D)`` design block (game/random_effect.py). Under vmap, XLA
computes each L-BFGS evaluation's value and gradient as two passes over the
block — batched margins (``einsum esd,ed->es``) then the transposed batched
gradient (``einsum es,esd->ed``) — so the HBM-dominant payload is read
twice per optimizer evaluation, exactly the double-read
:mod:`photon_ml_tpu.ops.pallas_glm` eliminated for the fixed effect (1.36x
f32, ~1.95x bf16 on TPU v5e). This kernel is the vmapped-entity
generalization of that module's ``fused_value_and_grad_multi`` shape:
stream a block of whole entity slabs through VMEM once and compute margins,
weighted loss, AND per-entity gradients while the slab is resident:

    per entity block i (BE entities):
        m[e, s]  = Σ_d x[e, s, d]·w[e, d] + off[e, s]   (VPU lane reduce)
        val[e]   = Σ_s wt[e, s]·loss(m, y)[e, s]        (VPU)
        grad[e,d]= Σ_s dvec[e, s]·x[e, s, d]            (VPU sublane reduce)

Formulation notes (why no MXU): each entity's contraction is an
independent (S, D)·(D,) matvec — a block-diagonal batched matmul the MXU
has no single-program shape for. The M=1 matvec form already leaves
127/128 MXU rows idle in the fixed-effect kernel (its measured issue
wall), and random-effect dims are small (D is the per-entity local dim,
typically 4–64, padded to one 128-lane tile), so the rank-3
multiply-and-reduce on the VPU meets the HBM stream at full rate while
the slab is read exactly once. Everything stays in the layout it arrives
in — x blocks ``(BE, S, D)`` with the array's own trailing dims, vectors
``(BE, S)``, coefficients ``(BE, D)`` — so there are no lane↔sublane
relayouts (the round-1 killer documented in pallas_glm.py). f32 math runs
on the VPU at full f32 precision — no MXU bf16-pass caveat, no
``Precision.HIGHEST`` needed; bf16 designs are upcast register-side after
the half-width DMA (the whole point of storing the design bf16).

Per-entity outputs land in their own block rows (no cross-step
accumulation), so grid steps are independent and Pallas double-buffers the
slab DMAs across steps.

Block selection: ``entity_plan`` picks the largest multiple-of-8 entity
block whose padded slab fits the scoped-VMEM budget. Entity counts rarely
divide it, and padding the batch INSIDE the traced objective would copy
the full (E, S, D) design on every L-BFGS evaluation (the measured
regression that shaped pallas_glm's auto mode) — so the SOLVER pre-pads
the bucket once per solve with weight-0 lanes (``entity_pad``), the
kernel's own pad path exists only as a correctness backstop, and padded
lanes converge immediately (zero data ⇒ gradient = L2 at w0=0 = 0).

Engagement: ``GLMObjective(fused_entity=True)`` (set by
``RandomEffectSolver(fused=True)``, the default) dispatches here through a
``custom_vmap`` rule when EVERY operand carries the entity batch axis —
the bucket-solve shape. Any other batching combination, projected or
streaming datasets, and non-TPU backends (without the test-only
interpreter flag) fall back to the XLA closed form transparently.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.ops.pallas_glm import _out_struct

#: resident bytes budgeted for one grid step's entity slab (x + vectors +
#: outputs); Pallas double-buffers the next step's DMA on top, and the
#: 16 MB scoped-VMEM limit caps the sum — 4 MiB keeps 2x pipelining plus
#: headroom at the largest block
VMEM_BUDGET_BYTES = 4 * 1024 * 1024

#: entity blocks are multiples of this: the f32 vector/output blocks
#: ``(BE, S)`` / ``(BE, D)`` carry BE in the sublane dim, whose Mosaic
#: tile is 8 for f32 (the x slab's BE rides an untiled leading dim)
ENTITY_TILE = 8


def _round_up(n: int, k: int) -> int:
    return -(-n // k) * k


def _entity_bytes(s: int, d: int, dtype) -> int:
    """VMEM bytes one entity lane occupies in a kernel block, tile padding
    included: the (S, D) design slab pads S to the dtype's sublane tile and
    D to one or more 128-wide lane tiles; the f32 label/offset/weight/
    margin vectors and the coefficient/gradient rows ride alongside."""
    sub = 16 if jnp.dtype(dtype) == jnp.bfloat16 else 8
    s_pad = _round_up(max(s, 1), sub)
    d_pad = _round_up(max(d, 1), 128)
    s_vec = _round_up(max(s, 1), 128)
    slab = s_pad * d_pad * jnp.dtype(dtype).itemsize
    vectors = 3 * 4 * s_vec  # labels / offsets / weights, f32
    rows = 2 * 4 * d_pad  # w + grad, f32
    return slab + vectors + rows


def entity_plan(e: int, s: int, d: int, dtype) -> "tuple[int, int] | None":
    """``(block_entities, padded_e)`` for an ``(e, s, d)`` bucket, or
    ``None`` when even a minimum (8-entity) block would blow the VMEM
    budget — callers then keep the XLA closed form. Idempotent on its own
    padded size (``entity_plan(padded_e, ...)[1] == padded_e``), which is
    what lets the solver pre-pad once and the kernel re-derive the same
    plan with zero further copies."""
    per = _entity_bytes(s, d, dtype)
    cap = (VMEM_BUDGET_BYTES // per) // ENTITY_TILE * ENTITY_TILE
    if cap < ENTITY_TILE:
        return None
    be = min(cap, _round_up(max(e, 1), ENTITY_TILE))
    return be, _round_up(max(e, 1), be)


def lane_fits_vmem(s: int, d: int, dtype) -> bool:
    """The E-independent eligibility half of :func:`entity_plan` — the
    per-lane gate ``GLMObjective._entity_fused_eligible`` checks (under
    vmap the objective sees one (S, D) lane, never the batch size)."""
    return entity_plan(ENTITY_TILE, s, d, dtype) is not None


def entity_pad(e: int, s: int, d: int, dtype) -> int:
    """Extra weight-0 entity lanes the SOLVER should append before the
    batched solve so the kernel's block plan divides the batch — padding
    inside the traced objective instead would copy the full design every
    L-BFGS evaluation (see module docstring)."""
    plan = entity_plan(e, s, d, dtype)
    return 0 if plan is None else plan[1] - e


def _kernel(loss: PointwiseLoss, x_ref, y_ref, off_ref, wt_ref, w_ref,
            val_ref, grad_ref):
    x = x_ref[:]  # (BE, S, D) — read once, used by both contractions
    w = w_ref[:]  # (BE, D) f32
    y = y_ref[:]  # (BE, S) f32
    off = off_ref[:]
    wt = wt_ref[:]
    # bf16 designs upcast register-side after the half-width DMA; all math
    # is f32 on the VPU (exact — no MXU single-bf16-pass precision caveat)
    xf = x.astype(jnp.float32)
    m = jnp.sum(xf * w[:, None, :], axis=2) + off  # (BE, S)
    # padded rows carry weight 0: evaluate them at margin 0 (finite) AND
    # zero-weight the output — the double-where guard of GLMObjective.value
    live = wt > 0
    m_safe = jnp.where(live, m, 0.0)
    lvec = loss.loss(m_safe, y)
    dvec = jnp.where(live, loss.d1(m_safe, y) * wt, 0.0)
    val_ref[:] = jnp.sum(jnp.where(live, wt * lvec, 0.0),
                         axis=1).reshape(-1, 1)  # (BE, 1)
    grad_ref[:] = jnp.sum(dvec[:, :, None] * xf, axis=1)  # (BE, D)


@functools.partial(jax.jit,
                   static_argnames=("loss", "block_entities", "interpret"))
def fused_entity_value_and_grad(loss: PointwiseLoss, x, ws, labels, offsets,
                                weights, *, block_entities: int | None = None,
                                interpret: bool = False):
    """``(values (E,), grads (E, D))`` of the per-entity GLM objectives
    ``Σ_s weights[e,s]·loss(x[e,s]·w[e] + offsets[e,s], y[e,s])`` in ONE
    pass over the ``(E, S, D)`` design (no L2 — coefficient-space term,
    the caller adds it). ``x`` is f32 or bf16; everything else f32.
    """
    e, s, d = x.shape
    if block_entities is None:
        plan = entity_plan(e, s, d, x.dtype)
        if plan is None:
            raise ValueError(
                f"entity slab ({s}, {d}, {jnp.dtype(x.dtype).name}) exceeds "
                f"the VMEM block budget — the eligibility gate "
                f"(lane_fits_vmem) should have kept the XLA closed form")
        be, e_pad = plan
    else:
        be = _round_up(block_entities, ENTITY_TILE)
        e_pad = _round_up(max(e, 1), be)
    if e_pad != e:
        # correctness backstop only — the solver pre-pads (entity_pad) so
        # this copy never runs inside a production optimizer loop
        pad = e_pad - e
        x = jnp.pad(x, ((0, pad), (0, 0), (0, 0)))
        labels = jnp.pad(labels, ((0, pad), (0, 0)))
        offsets = jnp.pad(offsets, ((0, pad), (0, 0)))
        weights = jnp.pad(weights, ((0, pad), (0, 0)))
        ws = jnp.pad(ws, ((0, pad), (0, 0)))

    f32 = jnp.float32
    itemsize = jnp.dtype(x.dtype).itemsize
    out = pl.pallas_call(
        functools.partial(_kernel, loss),
        grid=(e_pad // be,),
        in_specs=[
            pl.BlockSpec((be, s, d), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((be, s), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((be, s), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((be, s), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((be, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((be, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((be, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _out_struct(x, (e_pad, 1), f32),
            _out_struct(x, (e_pad, d), f32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * e_pad * s * d,
            transcendentals=2 * e_pad * s,
            bytes_accessed=e_pad * s * d * itemsize,
        ),
        interpret=interpret,
    )(
        x,
        labels.astype(f32),
        offsets.astype(f32),
        weights.astype(f32),
        ws.astype(f32),
    )
    values, grads = out
    return values[:e, 0], grads[:e]


def _closed_one(loss: PointwiseLoss, x, w, labels, offsets, weights):
    """Single-entity closed form — the custom_vmap primal (and its
    sequential fallback body). Mirrors GLMObjective._closed_value_and_grad
    at identity normalization (the eligibility gate guarantees it), so an
    unbatched call through the wrapper is numerically the path the gate
    would otherwise have taken."""
    live = weights > 0
    m = jnp.dot(x, w.astype(x.dtype),
                preferred_element_type=jnp.float32) + offsets
    m_safe = jnp.where(live, m, 0.0)
    lvec = loss.loss(m_safe, labels)
    value = jnp.sum(jnp.where(live, weights * lvec, 0.0))
    dvec = jnp.where(live, weights * loss.d1(m_safe, labels), 0.0)
    grad = jnp.dot(dvec.astype(x.dtype), x,
                   preferred_element_type=jnp.float32)
    return value, grad.astype(jnp.float32)


@functools.lru_cache(maxsize=None)
def vmappable_entity_value_and_grad(loss: PointwiseLoss,
                                    interpret: bool = False):
    """The entity-batched (value, grad) with a custom vmap rule: a vmap
    carrying the batch axis on EVERY operand — the random-effect bucket
    solve's ``vmap(solve_one)`` shape — dispatches to the single-pass
    entity kernel; any other combination falls back to a sequential lane
    map of the closed form (no production path hits it; the rule must
    merely stay total)."""

    @jax.custom_batching.custom_vmap
    def vag(x, w, labels, offsets, weights):
        return _closed_one(loss, x, w, labels, offsets, weights)

    @vag.def_vmap
    def _rule(axis_size, in_batched, x, w, labels, offsets, weights):
        xb, wb, lb, ob, wtb = in_batched
        if xb and wb and lb and ob and wtb:
            values, grads = fused_entity_value_and_grad(
                loss, x, w, labels, offsets, weights, interpret=interpret)
            return (values, grads), (True, True)

        def body(i):
            return _closed_one(
                loss, x[i] if xb else x, w[i] if wb else w,
                labels[i] if lb else labels, offsets[i] if ob else offsets,
                weights[i] if wtb else weights)

        values, grads = jax.lax.map(body, jnp.arange(axis_size))
        return (values, grads), (True, True)

    return vag
