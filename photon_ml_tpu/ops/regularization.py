"""L1 / L2 / elastic-net regularization contexts.

Re-design of ``photon-lib/.../optimization/RegularizationContext.scala`` and
``ElasticNetRegularizationContext``: a regularization *type* plus an elastic-net
mixing weight ``alpha`` split one scalar ``regularization_weight`` (lambda) into

- a smooth L2 part, folded into the differentiable objective
  (value and gradient) exactly as the reference's ``L2RegularizationDiff``, and
- a non-smooth L1 part handled by the optimizer (OWLQN pseudo-gradient /
  orthant projection), never differentiated.
"""

from __future__ import annotations

import dataclasses

from photon_ml_tpu.types import RegularizationType


@dataclasses.dataclass(frozen=True)
class RegularizationContext:
    """How a single lambda is split between L1 and L2 penalties.

    ``alpha`` follows the reference/glmnet convention: the fraction of the
    penalty that is L1. ``alpha=1`` is pure L1 (lasso), ``alpha=0`` pure L2
    (ridge). For ``RegularizationType.L1``/``L2`` alpha is forced to 1/0.
    """

    reg_type: RegularizationType = RegularizationType.NONE
    alpha: float = 0.0

    def __post_init__(self) -> None:
        if self.reg_type == RegularizationType.ELASTIC_NET:
            if not 0.0 <= self.alpha <= 1.0:
                raise ValueError(f"elastic-net alpha must be in [0,1], got {self.alpha}")
        elif self.reg_type == RegularizationType.L1:
            object.__setattr__(self, "alpha", 1.0)
        else:
            object.__setattr__(self, "alpha", 0.0)

    def l1_weight(self, regularization_weight: float) -> float:
        """The L1 coefficient handed to OWLQN (``alpha * lambda``)."""
        if self.reg_type == RegularizationType.NONE:
            return 0.0
        return self.alpha * regularization_weight

    def l2_weight(self, regularization_weight: float) -> float:
        """The smooth L2 coefficient folded into the objective
        (``(1 - alpha) * lambda``; 0 for ``NONE`` regardless of lambda)."""
        if self.reg_type == RegularizationType.NONE:
            return 0.0
        return (1.0 - self.alpha) * regularization_weight

    def check_weight(self, regularization_weight: float) -> None:
        """Reject a nonzero lambda paired with a NONE context — the weight
        would be silently ignored (every l1/l2 split maps it to 0), which
        turns a regularization sweep or hyperparameter search into identical
        unregularized fits. Call with *concrete* weights only (host side)."""
        if (self.reg_type == RegularizationType.NONE
                and float(regularization_weight) != 0.0):
            raise ValueError(
                f"regularization_weight={regularization_weight} has no effect "
                "under RegularizationType.NONE; configure an L1/L2/elastic-net "
                "RegularizationContext")

    @property
    def has_l1(self) -> bool:
        return self.reg_type in (RegularizationType.L1, RegularizationType.ELASTIC_NET) and self.alpha > 0.0


NoRegularization = RegularizationContext(RegularizationType.NONE)
L1Regularization = RegularizationContext(RegularizationType.L1, alpha=1.0)
L2Regularization = RegularizationContext(RegularizationType.L2, alpha=0.0)


def elastic_net(alpha: float) -> RegularizationContext:
    return RegularizationContext(RegularizationType.ELASTIC_NET, alpha=alpha)
