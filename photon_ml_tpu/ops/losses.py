"""Pointwise GLM losses: ``l(margin, label)`` plus first/second margin derivatives.

TPU-first re-design of the reference's pointwise loss hierarchy
(``photon-lib/.../function/PointwiseLossFunction.scala`` and
``photon-api/.../function/glm/{LogisticLossFunction, SquaredLossFunction,
PoissonLossFunction, SmoothedHingeLossFunction}.scala``).

The reference hand-writes ``l``, ``dl/dmargin``, ``d2l/dmargin2`` per loss and
feeds them into four aggregator classes per objective. Here each loss is a pure
scalar-vectorizable function of ``(margin, label)``; the full-objective
gradient and Hessian-vector product are derived by autodiff in
:mod:`photon_ml_tpu.ops.objective`. Closed-form ``d1``/``d2`` are still
provided — they are cheaper inside TRON's conjugate-gradient inner loop and are
cross-checked against autodiff in the test-suite
(finite-difference tests mirror the reference's ``*LossFunctionTest`` pattern).

Label conventions (matching the reference):
- logistic / smoothed hinge: binary labels in ``{0, 1}``,
- linear: real labels,
- Poisson: non-negative counts, exponential (log) link.

All functions are shape-polymorphic and safe under ``jit``/``vmap``.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class PointwiseLoss:
    """A per-sample loss ``l(margin, label)`` with margin derivatives.

    ``margin`` is the linear predictor ``w . x + offset``. The objective layer
    sums ``weight_i * loss(margin_i, label_i)`` over samples, matching the
    reference's sum-form (not mean-form) objective.
    """

    name: str
    loss: Callable[[Array, Array], Array]
    d1: Callable[[Array, Array], Array]
    d2: Callable[[Array, Array], Array]
    #: Inverse link: margin -> prediction on the response scale (for scoring).
    mean: Callable[[Array], Array]

    def __repr__(self) -> str:  # keep pytree-unfriendly object out of traces
        return f"PointwiseLoss({self.name})"


def _logistic_loss(margin: Array, label: Array) -> Array:
    # -log p(y|margin) = softplus(margin) - label * margin, numerically stable
    # via jax.nn.softplus (handles large |margin| without overflow).
    return jax.nn.softplus(margin) - label * margin


def _logistic_d1(margin: Array, label: Array) -> Array:
    return jax.nn.sigmoid(margin) - label


def _logistic_d2(margin: Array, label: Array) -> Array:
    s = jax.nn.sigmoid(margin)
    return s * (1.0 - s)


LogisticLoss = PointwiseLoss(
    name="logistic",
    loss=_logistic_loss,
    d1=_logistic_d1,
    d2=_logistic_d2,
    mean=jax.nn.sigmoid,
)


def _squared_loss(margin: Array, label: Array) -> Array:
    d = margin - label
    return 0.5 * d * d


SquaredLoss = PointwiseLoss(
    name="squared",
    loss=_squared_loss,
    d1=lambda margin, label: margin - label,
    d2=lambda margin, label: jnp.ones_like(margin),
    mean=lambda margin: margin,
)


def _poisson_loss(margin: Array, label: Array) -> Array:
    # Negative Poisson log-likelihood with exp link, dropping the
    # label-only log(label!) constant — identical to the reference's
    # PoissonLossFunction up to that constant.
    return jnp.exp(margin) - label * margin


PoissonLoss = PointwiseLoss(
    name="poisson",
    loss=_poisson_loss,
    d1=lambda margin, label: jnp.exp(margin) - label,
    d2=lambda margin, label: jnp.exp(margin),
    mean=jnp.exp,
)


def _smoothed_hinge_loss(margin: Array, label: Array) -> Array:
    # Rennie's smoothed hinge on the signed margin t = (2*label - 1) * margin:
    #   t <= 0      -> 0.5 - t
    #   0 < t < 1   -> 0.5 * (1 - t)^2
    #   t >= 1      -> 0
    # Twice-differentiable except at t in {0, 1}; branch-free for TPU.
    t = (2.0 * label - 1.0) * margin
    return jnp.where(
        t <= 0.0,
        0.5 - t,
        jnp.where(t < 1.0, 0.5 * jnp.square(1.0 - t), 0.0),
    )


def _smoothed_hinge_d1(margin: Array, label: Array) -> Array:
    z = 2.0 * label - 1.0
    t = z * margin
    dt = jnp.where(t <= 0.0, -1.0, jnp.where(t < 1.0, t - 1.0, 0.0))
    return z * dt


def _smoothed_hinge_d2(margin: Array, label: Array) -> Array:
    t = (2.0 * label - 1.0) * margin
    return jnp.where((t > 0.0) & (t < 1.0), 1.0, 0.0)


SmoothedHingeLoss = PointwiseLoss(
    name="smoothed_hinge",
    loss=_smoothed_hinge_loss,
    d1=_smoothed_hinge_d1,
    d2=_smoothed_hinge_d2,
    mean=lambda margin: margin,  # raw score; classification threshold at 0
)


def loss_for_task(task) -> PointwiseLoss:
    """Map a :class:`photon_ml_tpu.types.TaskType` to its pointwise loss.

    Mirrors the task->loss wiring in the reference's
    ``GeneralizedLinearOptimizationProblem`` factories.
    """
    from photon_ml_tpu.types import TaskType

    return {
        TaskType.LOGISTIC_REGRESSION: LogisticLoss,
        TaskType.LINEAR_REGRESSION: SquaredLoss,
        TaskType.POISSON_REGRESSION: PoissonLoss,
        TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: SmoothedHingeLoss,
    }[task]
