"""Fleet-scale serving: entity-sharded stores behind a thin routing tier.

One serving process holds one shard (``1/N``) of every random-effect
coordinate's dense coefficient table (``serve_game --fleet-shard I
--fleet-shard-count N``); a stdlib-HTTP router in front resolves each
record's shard from its raw entity ids, fans out over persistent per-host
connections, and merges per-coordinate margins through the same
``sum_coordinate_margins`` reduction the single-host engine runs — f32
scores stay bit-identical to an unsharded server. Model rollout is a
coordinated two-phase ``/reload`` (every host validates + canaries the
candidate, the router gates once, then activates everywhere; any refusal
aborts the epoch with the incumbent serving fleet-wide), so a fleet never
serves mixed lineages. See SERVING.md "Fleet serving".

- :mod:`~photon_ml_tpu.fleet.sharding` — the ONE deterministic
  entity-id→shard hashing home (lint rule ``res-shard-home``).
- :mod:`~photon_ml_tpu.fleet.router` — the routing tier: ``/score`` /
  ``/rank`` fan-out + merge, two-phase ``/reload``, fleet-folded
  ``GET /metrics`` (via :mod:`photon_ml_tpu.telemetry.aggregate`), and
  the ``fleet.fanout`` chaos site.
- ``python -m photon_ml_tpu serve_fleet`` — launch router + N local
  hosts in one process (the test/bench topology; production runs one
  ``serve_game --fleet-shard`` per machine plus a router).
"""

from photon_ml_tpu.fleet.sharding import (  # noqa: F401
    crc_bucket,
    owns_id,
    partition_by_shard,
    shard_of_id,
    stable_hash_u32,
)
