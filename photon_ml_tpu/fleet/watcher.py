"""Router-side activation: the freshness loop's last hop.

``serving/watcher.py`` gives ONE host self-service model pickup; a fleet
needs the same discovery at the ROUTER, because per-shard coefficient
patches (``refresh_game --fleet-shards``) are only correct as a SET —
activating shard 2's patch while shard 0 serves the old version skews
scores. This watcher polls a publish directory on the router and drives
every discovery through :meth:`~photon_ml_tpu.fleet.router.FleetRouter.
reload`'s two-phase prepare→activate epoch, so a fleet either moves to
the new version everywhere or refuses everywhere with the incumbent
serving (any host's canary or structural refusal aborts the epoch).

What an entry can be (the autopilot publishes refresh run dirs that are
both at once — the per-shard set wins, it is the zero-recompile path):

- a directory containing the COMPLETE ``patch-shard-0 … patch-shard-N-1``
  set for this fleet's N shards: each stamp is verified before any host
  is contacted — ``kind=coefficient-patch``, ``fleetShard`` matching its
  slot, ``fleetShardCount == N``, and one uniform ``modelId`` /
  ``parentModel`` across the set (a mixed set is two publishes
  interleaved; refuse it here, cheaply) — then activated via
  ``reload({"model_dirs": […]})``. Hosts whose shard has no touched rows
  activate with ZERO recompiles (``share_from=`` table reuse);
- a full model dir (or run dir with ``best/``): activated fleet-wide via
  ``reload({"model_dir": …})``;
- anything else: ignored without being marked seen (a run dir that
  publishes later must still be picked up).

Seen/rejected entries are keyed by CONTENT
(:func:`~photon_ml_tpu.serving.watcher.candidate_content_key`), same as
the single-host watcher: a corrected republish under the same name
re-attempts on the next poll. Waiting uses ``threading.Event.wait`` —
serving code never sleeps (hygiene rule 2).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

from photon_ml_tpu.resilience.faults import fault_point
from photon_ml_tpu.serving.watcher import candidate_content_key

logger = logging.getLogger(__name__)


class FleetPatchWatcher:
    """Polls ``watch_dir`` and drives each discovered per-shard patch set
    (or full model) through the router's two-phase fleet epoch."""

    def __init__(self, router, watch_dir: str, *, poll_s: float = 10.0):
        self.router = router
        self.watch_dir = watch_dir
        self.poll_s = float(poll_s)
        self._lock = threading.Lock()
        #: (entry name, content key) pairs already attempted — content
        #: keyed, so a republish in place re-attempts (module docstring)
        self._seen: set = set()  # guarded-by: _lock
        self._stop = threading.Event()
        #: start/stop are operator-lifecycle calls from one control thread
        self._thread: Optional[threading.Thread] = None  # guarded-by: caller
        self.n_applied = 0  # guarded-by: _lock
        self.n_rejected = 0  # guarded-by: _lock

    # --- stamp verification -----------------------------------------------
    def _verify_patch_set(self, shard_dirs: list) -> Optional[str]:
        """None when every stamp checks out, else why the set is refused
        (before any host sees a prepare)."""
        import json

        from photon_ml_tpu.io.model_io import PATCH_KIND

        n = self.router.n_shards
        stamps = []
        for i, d in enumerate(shard_dirs):
            try:
                with open(os.path.join(d, "model-metadata.json")) as f:
                    meta = json.load(f)
            except (OSError, ValueError) as e:
                return f"patch-shard-{i}: unreadable metadata ({e!r})"
            if meta.get("kind") != PATCH_KIND:
                return (f"patch-shard-{i}: kind {meta.get('kind')!r} is "
                        f"not a coefficient patch")
            if meta.get("fleetShard") != i:
                return (f"patch-shard-{i}: stamped for shard "
                        f"{meta.get('fleetShard')!r}, sits in slot {i}")
            if meta.get("fleetShardCount") != n:
                return (f"patch-shard-{i}: stamped for a "
                        f"{meta.get('fleetShardCount')!r}-shard fleet, "
                        f"this fleet has {n}")
            stamps.append((meta.get("modelId"), meta.get("parentModel")))
        if len(set(stamps)) != 1:
            return ("mixed lineage across the shard set (two publishes "
                    f"interleaved?): {sorted(set(stamps))}")
        return None

    # --- one poll ---------------------------------------------------------
    def scan_once(self) -> int:
        """Drive every unseen entry (sorted by name) through a fleet
        epoch; returns how many activated. Directly callable — the thread
        loop is just this on a timer, and tests drive it synchronously."""
        # chaos site: a faulted tick is swallowed by the poll loop and the
        # NEXT tick picks up whatever this one missed (nothing is marked
        # seen before its epoch attempt, so no candidate is lost)
        fault_point("serving.watch_tick", dir=self.watch_dir)
        try:
            names = sorted(
                n for n in os.listdir(self.watch_dir)
                if not n.startswith(".")
                and os.path.isdir(os.path.join(self.watch_dir, n)))
        except FileNotFoundError:
            return 0  # publish dir not created yet — nothing to do
        applied = 0
        for name in names:
            path = os.path.join(self.watch_dir, name)
            # key BEFORE the attempt: a publisher updating the entry
            # mid-attempt changes the key and the next poll re-tries
            key = (name, candidate_content_key(path))
            with self._lock:
                if key in self._seen:
                    continue
            payload = self._classify(path)
            if payload is None:
                continue  # not (yet) activatable; NOT marked seen
            with self._lock:
                self._seen.add(key)
            if "refused" in payload:
                with self._lock:
                    self.n_rejected += 1
                logger.warning("fleet watch-dir refused %s before any "
                               "prepare: %s", path, payload["refused"])
                continue
            try:
                self.router.reload(payload)
            except Exception as e:
                # the epoch aborted (prepare refusal, canary divergence,
                # activation fault) — the router already rolled the fleet
                # back to the incumbent everywhere
                with self._lock:
                    self.n_rejected += 1
                logger.warning("fleet watch-dir candidate %s rejected — "
                               "incumbent keeps serving fleet-wide: %r",
                               path, e)
                continue
            with self._lock:
                self.n_applied += 1
            applied += 1
            logger.info("fleet watch-dir activated %s across %d shards",
                        path, self.router.n_shards)
        return applied

    def _classify(self, path: str) -> Optional[dict]:
        """An entry's activation payload: ``model_dirs`` for a complete,
        verified per-shard patch set, ``model_dir`` for a full model,
        ``{"refused": why}`` for a present-but-invalid set, None for
        not-our-business (skipped without being marked seen)."""
        n = self.router.n_shards
        shard_dirs = [os.path.join(path, f"patch-shard-{i}")
                      for i in range(n)]
        present = sum(os.path.isdir(d) for d in shard_dirs)
        if present == n:
            why = self._verify_patch_set(shard_dirs)
            if why is not None:
                return {"refused": why}
            return {"model_dirs": shard_dirs}
        if present or any(
                e.startswith("patch-shard-")
                for e in os.listdir(path) if not e.startswith(".")):
            # partial or wrong-count set: publication is atomic (one
            # rename), so this was CUT for a different fleet shape —
            # refuse it rather than activate a subset
            return {"refused": (f"{present} of {n} patch shards present "
                                f"(stamped for a different fleet?)")}
        try:
            from photon_ml_tpu.io.model_io import resolve_game_model_dir

            resolve_game_model_dir(path)
        except FileNotFoundError:
            return None  # scratch, logs, staging …
        return {"model_dir": path}

    # --- lifecycle --------------------------------------------------------
    def start(self) -> "FleetPatchWatcher":
        def loop() -> None:
            # immediate first scan (catch-up on restart), then the timer
            while True:
                try:
                    self.scan_once()
                except Exception:
                    logger.exception("fleet watch-dir scan failed; will "
                                     "retry")
                if self._stop.wait(self.poll_s):
                    return

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="photon-fleet-watch")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
