"""The fleet routing tier: one thin HTTP front for N entity-sharded hosts.

Each serving host (``serve_game --fleet-shard I --fleet-shard-count N``)
packs ~1/N of every random-effect coordinate's dense coefficient table
(``fleet/sharding.py`` decides which ids land where). This router is the
piece that makes the fleet look like ONE server:

- ``POST /score`` — resolves each record's shard(s) from its raw entity
  ids and fans out over persistent per-host connections. Records whose
  entities all live on one shard are scored there outright (that host's
  f32 totals ARE the response — bit-identical to an unsharded server by
  construction). Records spanning shards are scored everywhere involved
  with ``margins=true`` and the router re-runs the ONE score-summation
  contract, :func:`photon_ml_tpu.game.model.sum_coordinate_margins`, over
  each coordinate's owner-shard margins — f32 margins widened to double
  in JSON are exact, and the f64-accumulate-then-f32 reduction is the
  same arithmetic the host's trace performs, so merged totals are
  bit-identical too.
- ``GET/POST /rank`` — fans the request to EVERY host (each ranks its own
  item shard) and merges the per-shard top-k by score. Exact per-item
  scores require the user side of the model to be host-invariant — the
  fixed effect is replicated, so this holds for the standard retrieval
  setup (item coordinate = the only random effect); a model with
  user-side RE coordinates is refused rather than silently mis-ranked.
- ``POST /reload`` — the coordinated two-phase activation: every host
  validates + canaries + warms the candidate (``phase=prepare``), the
  router gates ONCE over all verdicts (any refusal, or disagreeing
  candidate lineages, aborts the epoch with the incumbent serving
  fleet-wide), then activates everywhere. The single-host watcher +
  canary gate generalize exactly here: gate at the router, activate
  everywhere.
- ``GET /metrics`` — the fleet fold: every host's ``/metrics`` text,
  scraped over the SAME pooled leg connections, plus the router's own
  registry through
  :func:`photon_ml_tpu.telemetry.aggregate.aggregate_text` (counters and
  histogram series sum; host-owned gauges — queue depth, brownout level,
  rank items — are tagged ``shard="I"``, ``replica="J"`` and fan out).
  The same fold ``tools/metrics_fold.py`` runs offline, byte-identically.
  ``GET /statusz`` is the human topology page (``fleet/observe.py``).

**Elastic fleet** (PR 16): each shard can run a REPLICA GROUP of R hosts
(``serve_fleet --replicas R``; the host list is shard-major). A failed
primary leg retries on a backup replica instead of shedding; a merely
SLOW primary is hedged — the backup fires after a p99-derived delay,
first answer wins, the loser's outcome is consumed. Routing goes through
a versioned bucket→shard map (``fleet/sharding.py::ShardMap``: crc32 →
one of 4096 virtual buckets → owning shard); the map's content hash
rides every leg (``X-Photon-Shard-Map``) and every response next to
``lineage``, and a router/host disagreement is refused (503
``reason=shard_map_mismatch``) exactly like mixed lineage. ``POST
/reshard`` drives a NEW map through the same two-phase epoch machinery:
every host repacks its shard view under the candidate (phase 1 — any
refusal aborts with the incumbent map serving fleet-wide), then the
router drains its in-flight fan-outs, activates everywhere, swaps its
own map atomically and reopens — f32 responses stay bit-identical
before, during and after the move.

Failure mapping: a shard whose EVERY replica is dead (connection
failure, fan-out timeout, injected ``fleet.fanout`` fault) becomes a
typed :class:`~photon_ml_tpu.serving.overload.Shed` with
``reason="upstream"`` → **503** + a ``Retry-After`` jittered
deterministically per request id (no wall-clock randomness — lockstep
clients spread instead of stampeding); a request whose deadline budget
is already spent sheds ``reason="deadline"`` and a leg's socket timeout
is capped by the remaining budget, so a fan-out cannot outlive its own
deadline. A host's own 429/503 passes through with its reason. Every
response carries the model content lineage, and a fan-out whose legs
disagree is refused (503 ``reason=mixed_lineage``) — the
no-mixed-lineage invariant is enforced per response, not just promised
by the activation protocol.
"""

from __future__ import annotations

import collections
import contextlib
import http.client
import json
import threading
import time
import urllib.parse
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu.fleet.observe import (  # noqa: F401  (re-exported)
    FleetObserver,
    fold_fleet_snapshots,
    tag_host_owned,
)
from photon_ml_tpu.fleet.sharding import ShardMap, retry_jitter_s, stable_hash_u32
from photon_ml_tpu.game.model import sum_coordinate_margins
from photon_ml_tpu.resilience.faults import fault_point
from photon_ml_tpu.serving import overload as _overload
from photon_ml_tpu.serving.http import (
    DEADLINE_HEADER,
    LEG_SUMMARY_HEADER,
    REQUEST_ID_HEADER,
    SHARD_MAP_HEADER,
    ShardMapMismatch,
    new_request_id,
    parse_leg_summary,
    shed_status,
)
from photon_ml_tpu.telemetry import metrics as _metrics
from photon_ml_tpu.telemetry import tracing as _tracing

#: requests the router answered, by endpoint (score | rank | reload)
_FLEET_REQUESTS = _metrics.counter(
    "photon_fleet_requests_total",
    "Requests served by the fleet router, by endpoint",
    labels=("endpoint",))

#: one per-host fan-out leg's round trip (connect reuse included)
_FANOUT_SECONDS = _metrics.histogram(
    "photon_fleet_fanout_seconds",
    "Per-host leg latency of a fleet router fan-out", labels=("shard",))

#: legs lost to a dead/slow/faulted host (mapped to 503 reason=upstream)
_UPSTREAM_ERRORS = _metrics.counter(
    "photon_fleet_upstream_errors_total",
    "Fan-out legs that failed (connection error, timeout, injected "
    "fleet.fanout fault) — each maps to a typed 503 reason=upstream",
    labels=("shard",))

#: fan-outs refused because host legs answered with different model
#: content lineages — the invariant two-phase activation exists to keep
_MIXED_LINEAGE = _metrics.counter(
    "photon_fleet_mixed_lineage_total",
    "Fleet responses refused because fan-out legs disagreed on model "
    "lineage (503 reason=mixed_lineage)")

#: two-phase /reload outcomes (activated | aborted)
_EPOCHS = _metrics.counter(
    "photon_fleet_epochs_total",
    "Coordinated two-phase reload epochs, by outcome "
    "(activated | aborted)", labels=("outcome",))

#: configured host count (shards × replicas)
_FLEET_HOSTS = _metrics.gauge(
    "photon_fleet_hosts",
    "Serving hosts behind the fleet router (shard count × replicas)")

#: legs retried on a backup replica after the primary failed outright —
#: each retry is a shed AVOIDED (at R=1 the same failure is a 503)
_REPLICA_RETRIES = _metrics.counter(
    "photon_fleet_replica_retries_total",
    "Fan-out legs retried on a backup replica after the primary "
    "replica failed", labels=("shard",))

#: backups fired because the primary outlived the p99-derived hedge
#: delay (tail attack: first answer wins, the loser is consumed)
_HEDGES = _metrics.counter(
    "photon_fleet_hedges_total",
    "Hedge backups fired against a slow primary replica",
    labels=("shard",))

#: hedges where the BACKUP answered first — the hedge paid for itself
_HEDGE_WINS = _metrics.counter(
    "photon_fleet_hedge_wins_total",
    "Hedged legs won by the backup replica", labels=("shard",))

#: live-reshard epochs (two-phase shard-map activation), by outcome
_SHARDMAP_EPOCHS = _metrics.counter(
    "photon_fleet_shardmap_epochs_total",
    "Live reshard epochs (two-phase bucket→shard map activation), by "
    "outcome (activated | aborted)", labels=("outcome",))

#: version of the governing bucket→shard map (starts at 1; each
#: activated reshard epoch advances it)
_SHARDMAP_VERSION = _metrics.gauge(
    "photon_fleet_shardmap_version",
    "Version of the fleet's governing bucket-to-shard map")


def _consume_result(fut) -> None:
    """Done-callback for a hedge loser: the in-flight HTTP exchange
    cannot be cancelled, so it runs to completion in the hedge pool,
    returns its pooled connection through ``HostClient``'s normal
    give-back, and its outcome (including an exception) is consumed
    here — nothing strands, nothing double-counts."""
    if fut.cancelled():
        return
    fut.exception()


class MixedLineageError(RuntimeError):
    """Fan-out legs answered from different model generations — the
    response is refused (503 ``reason=mixed_lineage``) rather than
    stitched together from two models."""


class HostClient:
    """Persistent-connection JSON client for one serving host.

    Connections are pooled and reused across requests (the stdlib
    ``urllib`` one-connection-per-request pattern is exactly the socket
    churn the tail-latency push removed client-side). A request that dies
    on a stale keep-alive — the server closed an idle connection under
    us — is retried ONCE on a fresh connection; a fresh connection
    failing means the host is actually gone, and the caller maps that to
    the typed upstream 503.
    """

    def __init__(self, url: str, shard: int, *, timeout_s: float = 30.0):
        self.url = url.rstrip("/")
        self.shard = int(shard)
        self.timeout_s = float(timeout_s)
        parsed = urllib.parse.urlsplit(self.url)
        self._host = parsed.hostname
        self._port = parsed.port or 80
        self._lock = threading.Lock()
        self._free: list = []  # guarded-by: _lock

    def _take(self):
        with self._lock:
            if self._free:
                return self._free.pop()
        return http.client.HTTPConnection(self._host, self._port,
                                          timeout=self.timeout_s)

    def _give(self, conn) -> None:
        with self._lock:
            self._free.append(conn)

    def request(self, method: str, path: str, payload=None,
                headers: Optional[Mapping[str, str]] = None,
                timeout_s: Optional[float] = None,
                raw: bool = False,
                headers_out: Optional[dict] = None) -> "tuple[int, dict]":
        """One JSON request → ``(status, body)``. Raises ``OSError`` /
        ``http.client.HTTPException`` when the host is unreachable past
        the bounded reconnect (the caller owns the upstream mapping).
        ``timeout_s`` caps THIS exchange below the pool-wide default —
        the router passes the request's remaining deadline budget, so a
        leg can never outlive the deadline it is serving.
        ``raw=True`` returns the body as decoded TEXT instead of parsed
        JSON (the observer scrapes ``/metrics`` exposition over these
        same pooled connections — and through the same ``fleet.fanout``
        chaos site). ``headers_out`` receives the response headers the
        caller cares about (the leg-summary stage breakdown)."""
        # the fleet chaos site: one visit per LEG (not per reconnect
        # attempt) — an injected fault is a host that cannot be reached
        fault_point("fleet.fanout", host=self.url, path=path)
        budget = (self.timeout_s if timeout_s is None
                  else max(1e-3, min(float(timeout_s), self.timeout_s)))
        body = None if payload is None else json.dumps(payload).encode()
        hdrs = {"Content-Type": "application/json", **(headers or {})}
        last: Optional[BaseException] = None
        for attempt in range(2):
            conn = self._take()
            conn.timeout = budget
            if getattr(conn, "sock", None) is not None:
                # a pooled connection froze its timeout at connect time;
                # re-arm the live socket with this exchange's budget
                conn.sock.settimeout(budget)
            try:
                conn.request(method, path, body=body, headers=hdrs)
                resp = conn.getresponse()
                data = resp.read()
                if headers_out is not None:
                    summary = resp.getheader(LEG_SUMMARY_HEADER)
                    if summary is not None:
                        headers_out[LEG_SUMMARY_HEADER] = summary
                if raw:
                    self._give(conn)
                    return resp.status, data.decode()
                status, out = resp.status, json.loads(data or b"{}")
                if status == 503 and out.get("reason") == "stopping":
                    # the host is DRAINING: it answered a complete
                    # exchange but is closing this socket — don't pool
                    # it, and retry once on a provably fresh connection
                    # (a host restarted on the same port answers it; a
                    # truly gone host refuses → the upstream mapping)
                    conn.close()
                    last = ConnectionError(
                        f"host {self.url} is stopping")
                    continue
                self._give(conn)
                return status, out
            except (OSError, http.client.HTTPException) as e:
                # a pooled connection can be stale (server-side idle
                # close); retry once on a provably fresh one
                conn.close()
                last = e
        raise ConnectionError(
            f"host {self.url} unreachable after reconnect: {last!r}")

    def close(self) -> None:
        with self._lock:
            conns, self._free = self._free, []
        for conn in conns:
            conn.close()


class FleetRouter:
    """Endpoint logic of the routing tier, HTTP-free (the handler is
    thin, like ``serving/http.py``'s). One instance fronts N hosts; host
    *i* must be serving fleet shard ``(i, N)``."""

    def __init__(self, host_urls: Sequence[str], *,
                 replicas: int = 1,
                 hedge_delay_ms: float = 0.0,
                 fanout_timeout_s: float = 30.0,
                 default_timeout_ms: float = 0.0):
        if not host_urls:
            raise ValueError("a fleet router needs at least one host url")
        self.replicas = int(replicas)
        if self.replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if len(host_urls) % self.replicas:
            raise ValueError(
                f"{len(host_urls)} hosts cannot form replica groups of "
                f"{self.replicas} (the host list is shard-major: "
                f"[s0r0, s0r1, s1r0, s1r1, ...])")
        self.n_shards = len(host_urls) // self.replicas
        self.fanout_timeout_s = float(fanout_timeout_s)
        #: fixed hedge delay in ms; 0 = adaptive (p99 of this shard's
        #: recent leg latencies — a hedge should fire on TAIL legs only)
        self.hedge_delay_ms = float(hedge_delay_ms)
        #: ``clients[s][r]`` = replica r of shard s; every replica of a
        #: group serves the same shard view of the same model
        self.clients = [
            [HostClient(host_urls[s * self.replicas + r], shard=s,
                        timeout_s=fanout_timeout_s)
             for r in range(self.replicas)]
            for s in range(self.n_shards)]
        self.default_timeout_ms = float(default_timeout_ms)
        #: the governing bucket→shard map. Starts at the canonical
        #: default (bucket b → b mod N — crc32-equivalent whenever N
        #: divides the bucket count) and is swapped ATOMICALLY under the
        #: drain barrier by an activated reshard epoch (readers see one
        #: whole reference or the other — never a torn map).
        self.shard_map = ShardMap.default(
            self.n_shards)  # guarded-by: _epoch_lock
        #: fan-out worker pool — sized so every shard of two concurrent
        #: requests can be in flight; legs are short-lived, the pool is
        #: process-lifetime
        self._pool = ThreadPoolExecutor(
            max_workers=max(4, 2 * self.n_shards),
            thread_name_prefix="photon-fleet-fanout")
        #: replica attempts run on their OWN pool: a leg (running on
        #: _pool) blocks on its replica futures, so sharing one pool
        #: could deadlock with every worker waiting on a queued attempt
        self._hedge_pool = ThreadPoolExecutor(
            max_workers=max(8, 4 * self.n_shards * self.replicas),
            thread_name_prefix="photon-fleet-hedge")
        self._lock = threading.Lock()
        #: recent per-shard leg latencies (seconds) feeding the adaptive
        #: hedge delay; guarded-by: _lat_lock
        self._lat_lock = threading.Lock()
        self._latency = [collections.deque(maxlen=128)
                         for _ in range(self.n_shards)]
        #: legs in flight against each shard right now — the observer
        #: samples this into photon_fleet_shard_load at scrape time
        self._shard_inflight = [0] * self.n_shards  # guarded-by: _lat_lock
        #: serializes two-phase epochs (model reload / live reshard)
        self._epoch_lock = threading.Lock()
        #: the drain barrier: reshard activation waits for in-flight
        #: fan-outs to land and briefly parks new ones, so no response
        #: is ever assembled across two map generations
        self._flight = threading.Condition(threading.Lock())
        self._inflight = 0  # guarded-by: _flight
        self._paused = False  # guarded-by: _flight
        #: model coordinate walk [(cid, entity_type|None)] in order,
        #: fetched from a host's /healthz (refreshed after activation)
        self._coordinates: Optional[list] = None  # guarded-by: _lock
        self._rank_info: Optional[dict] = None  # guarded-by: _lock
        self.n_requests = 0  # guarded-by: _lock
        #: the observability plane — scrapes hosts over THESE pooled
        #: clients, owns /statusz and the optional SLO tracker (no
        #: threads until attach_slo asks for a tick loop)
        self.observer = FleetObserver(self)
        _FLEET_HOSTS.set(len(host_urls))
        _SHARDMAP_VERSION.set(self.shard_map.version)

    # --- observability taps ----------------------------------------------
    @property
    def fanout_pool(self) -> ThreadPoolExecutor:
        """The fan-out leg executor — exposed read-only so the capacity
        plane (telemetry.saturation.executor_probe, wired by
        cli/serve_fleet) can gauge router_pool occupancy."""
        return self._pool

    @property
    def hedge_pool(self) -> ThreadPoolExecutor:
        """The replica-attempt executor (hedge_pool resource)."""
        return self._hedge_pool

    def latency_snapshot(self) -> "list[list[float]]":
        """Copy of each shard's recent-leg latency window (seconds)."""
        with self._lat_lock:
            return [list(d) for d in self._latency]

    def shard_load(self) -> "list[int]":
        """Legs currently in flight against each shard."""
        with self._lat_lock:
            return list(self._shard_inflight)

    # --- deadlines (same contract as ServingService) ----------------------
    def resolve_deadline(self,
                         budget_ms: "str | float | None") -> Optional[float]:
        if budget_ms is None or budget_ms == "":
            budget_ms = (self.default_timeout_ms
                         if self.default_timeout_ms > 0 else None)
        if budget_ms is None:
            return None
        try:
            budget = float(budget_ms)
        except (TypeError, ValueError):
            raise ValueError(
                f"bad {DEADLINE_HEADER} header {budget_ms!r} (want a "
                f"millisecond budget)") from None
        return time.monotonic() + budget / 1e3

    @staticmethod
    def remaining_ms(deadline: Optional[float]) -> Optional[float]:
        if deadline is None:
            return None
        return max(0.0, (deadline - time.monotonic()) * 1e3)

    def _leg_headers(self, request_id: str,
                     deadline: Optional[float],
                     shard_map: Optional[ShardMap] = None) -> dict:
        """Propagated request identity + the REMAINING deadline budget —
        a downstream host spends the same budget the caller measures.
        ``shard_map`` stamps the map generation this fan-out was ROUTED
        under; a host serving a different map refuses the leg (503
        ``reason=shard_map_mismatch``) instead of answering for rows it
        may not own."""
        headers = {REQUEST_ID_HEADER: request_id}
        if deadline is not None:
            headers[DEADLINE_HEADER] = f"{self.remaining_ms(deadline):.1f}"
        if shard_map is not None:
            headers[SHARD_MAP_HEADER] = shard_map.map_hash
        return headers

    # --- the drain barrier ------------------------------------------------
    @contextlib.contextmanager
    def _traffic(self):
        """Every /score and /rank fan-out runs inside this gate. A
        reshard epoch's activation step drains it (waits for in-flight
        fan-outs, briefly parks arrivals), swaps the map, and reopens —
        so a response is never assembled across two map generations and
        no client sees an error for the swap."""
        with self._flight:
            while self._paused:
                self._flight.wait(timeout=1.0)
            self._inflight += 1
        try:
            yield
        finally:
            with self._flight:
                self._inflight -= 1
                self._flight.notify_all()

    def _pause_traffic(self, timeout_s: float) -> bool:
        """Park new fan-outs and wait for in-flight ones to land.
        Returns False (gate reopened by the caller) if the drain did not
        complete within ``timeout_s``."""
        limit = time.monotonic() + timeout_s
        with self._flight:
            self._paused = True
            while self._inflight:
                remaining = limit - time.monotonic()
                if remaining <= 0:
                    return False
                self._flight.wait(timeout=remaining)
        return True

    def _resume_traffic(self) -> None:
        with self._flight:
            self._paused = False
            self._flight.notify_all()

    # --- topology ---------------------------------------------------------
    def topology(self, refresh: bool = False) -> "tuple[list, dict]":
        """``([(cid, entity_type|None), ...], rank_info)`` from a host's
        /healthz — which entity types route, in which order margins
        merge, and whether fleet ranking is supportable."""
        with self._lock:
            if self._coordinates is not None and not refresh:
                return self._coordinates, self._rank_info
        body = self._leg(0, "GET", "/healthz")
        coords = body.get("coordinates")
        if not coords:
            raise RuntimeError(
                "host 0 reports no active model coordinates — is the "
                "fleet serving yet?")
        coordinates = [(cid, etype) for cid, etype in coords]
        rank_info = body.get("rank") or {}
        with self._lock:
            self._coordinates = coordinates
            self._rank_info = rank_info
        return coordinates, rank_info

    # --- fan-out machinery ------------------------------------------------
    def _replica_order(self, request_id: Optional[str]) -> tuple:
        """The deterministic replica walk for one request: primary =
        hash of the request id (spreads load across the group), backups
        in rotation. No wall-clock randomness — the same request id
        always lands on the same primary."""
        if self.replicas == 1:
            return (0,)
        start = (stable_hash_u32(f"replica:{request_id}") % self.replicas
                 if request_id else 0)
        return tuple((start + i) % self.replicas
                     for i in range(self.replicas))

    def _hedge_delay_s(self, shard: int) -> float:
        """When to fire the backup against a still-pending primary: the
        fixed ``hedge_delay_ms`` when configured, else the p99 of this
        shard's recent leg latencies (a hedge should chase TAIL legs —
        ~1% extra load by construction). Until enough samples exist the
        delay is the fan-out timeout, i.e. effectively no hedging."""
        if self.hedge_delay_ms > 0:
            return self.hedge_delay_ms / 1e3
        with self._lat_lock:
            samples = sorted(self._latency[shard])
        if len(samples) < 8:
            return self.fanout_timeout_s
        p99 = samples[min(len(samples) - 1, int(0.99 * len(samples)))]
        return max(0.005, p99)

    def _fanout_leg(self, shard: int, method: str, path: str, payload,
                    headers, request_id: Optional[str],
                    timeout_s: Optional[float],
                    parent_span: Optional[int] = None,
                    ) -> "tuple[int, dict]":
        """One shard's exchange across its replica group: primary first;
        a primary that FAILS is retried on the next replica (counted in
        ``photon_fleet_replica_retries_total``); a primary that is merely
        SLOW is hedged — the backup fires after the hedge delay, the
        first answer wins, and the loser's outcome is consumed (its
        pooled connection returns through the normal give-back).

        Every attempt — primary, retry, hedge — is a ``fleet.leg`` span
        parented on the request's fan-out span (``parent_span``; replica
        attempts run on the hedge pool, where contextvars don't follow),
        so the merged ``trace.jsonl`` shows hedges and retries as
        SIBLINGS under one tree. The host's stage breakdown rides back in
        the leg-summary header and lands as ``host.*`` child spans."""
        group = self.clients[shard]
        label = str(shard)

        def attempt(replica: int, kind: str) -> "tuple[int, dict]":
            with _tracing.span_under(parent_span, "fleet.leg",
                                     shard=label, replica=str(replica),
                                     kind=kind) as sp:
                headers_out: dict = {}
                t0 = time.monotonic()
                out = group[replica].request(method, path, payload,
                                             headers=headers,
                                             timeout_s=timeout_s,
                                             headers_out=headers_out)
                with self._lat_lock:
                    self._latency[shard].append(time.monotonic() - t0)
                summary = parse_leg_summary(
                    headers_out.get(LEG_SUMMARY_HEADER))
                host_span = summary.pop("span", None)
                if host_span is not None:
                    # the host-side span id: joins this leg to the
                    # host's OWN trace file when the two are merged
                    sp.set(host_span=host_span)
                for stage, seconds in summary.items():
                    _tracing.record_span("host." + stage,
                                         seconds=seconds,
                                         parent_id=sp.span_id,
                                         shard=label,
                                         replica=str(replica))
            return out

        if len(group) == 1:
            return attempt(0, "primary")
        order = self._replica_order(request_id)
        pending: dict = {}  # future -> replica
        errors: list = []
        next_i = 0

        def launch(kind: str) -> None:
            nonlocal next_i
            replica = order[next_i]
            next_i += 1
            if kind != "primary":
                try:
                    # the replica-failover chaos surface: an injected
                    # fault means the backup path itself is down, and
                    # the leg degrades to the R=1 outcome
                    fault_point("fleet.replica", shard=label,
                                replica=str(replica), path=path,
                                kind=kind)
                except Exception as e:
                    errors.append(e)
                    return
                if kind == "retry":
                    _REPLICA_RETRIES.labels(shard=label).inc()
            pending[self._hedge_pool.submit(attempt, replica,
                                            kind)] = replica

        launch("primary")
        hedged = False
        start = time.monotonic()
        while True:
            if not pending:
                if next_i < len(order):
                    launch("retry")
                    continue
                raise (errors[-1] if errors else
                       ConnectionError(f"every replica of shard {shard} "
                                       f"failed"))
            timeout = None
            if not hedged and next_i < len(order):
                timeout = max(0.0, self._hedge_delay_s(shard)
                              - (time.monotonic() - start))
            done, _ = wait(set(pending), timeout=timeout,
                           return_when=FIRST_COMPLETED)
            if not done:
                # the primary outlived the hedge delay: fire the backup,
                # first answer wins
                hedged = True
                _HEDGES.labels(shard=label).inc()
                launch("hedge")
                continue
            winner = None
            for fut in done:
                replica = pending.pop(fut)
                try:
                    winner = (replica, fut.result())
                except Exception as e:
                    errors.append(e)
            if winner is None:
                continue
            for loser in pending:
                loser.add_done_callback(_consume_result)
            replica, out = winner
            if hedged and replica != order[0]:
                _HEDGE_WINS.labels(shard=label).inc()
            return out

    @staticmethod
    def _check_status(shard: int, method: str, path: str, status: int,
                      body: dict) -> dict:
        if status in (429, 503):
            reason = body.get("reason", "queue_full")
            if reason == "shard_map_mismatch":
                # the host refused the map generation this fan-out was
                # routed under — surfaced like mixed lineage, not a shed
                raise ShardMapMismatch(
                    body.get("error",
                             f"shard {shard} refused the routed shard "
                             f"map"))
            # the HOST already counted this shed; re-raise the typed
            # refusal without double-counting
            raise _overload.Shed(reason,
                                 body.get("error", f"shard {shard} shed"))
        if status != 200:
            raise RuntimeError(f"fleet shard {shard} {method} {path} -> "
                               f"{status}: {body.get('error', body)!r}")
        return body

    def _leg(self, shard: int, method: str, path: str, payload=None,
             headers=None, request_id: Optional[str] = None,
             deadline: Optional[float] = None,
             parent_span: Optional[int] = None) -> dict:
        """One per-shard leg: timed, replica-failed-over, hedged,
        deadline-bounded, upstream-mapped, shed-passthrough."""
        timeout_s = None
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                # the budget is already spent — shedding here is a
                # DEADLINE refusal, not an upstream failure: no host was
                # lost, the caller simply ran out of time
                raise _overload.shed(
                    "deadline",
                    message=f"deadline expired before shard {shard} leg")
            timeout_s = remaining
        with self._lat_lock:
            self._shard_inflight[shard] += 1
        try:
            return self._timed_leg(shard, method, path, payload, headers,
                                   request_id, timeout_s, deadline,
                                   parent_span)
        finally:
            with self._lat_lock:
                self._shard_inflight[shard] -= 1

    def _timed_leg(self, shard: int, method: str, path: str, payload,
                   headers, request_id: Optional[str],
                   timeout_s: Optional[float],
                   deadline: Optional[float],
                   parent_span: Optional[int]) -> dict:
        with _FANOUT_SECONDS.labels(shard=str(shard)).time() as timer:
            try:
                status, body = self._fanout_leg(shard, method, path,
                                                payload, headers,
                                                request_id, timeout_s,
                                                parent_span=parent_span)
            except Exception as e:
                timer.discard()
                if deadline is not None and time.monotonic() >= deadline:
                    raise _overload.shed(
                        "deadline",
                        message=f"deadline expired during shard {shard} "
                                f"leg: {e!r}") from e
                _UPSTREAM_ERRORS.labels(shard=str(shard)).inc()
                raise _overload.shed(
                    "upstream",
                    message=f"fleet shard {shard} unreachable on every "
                            f"replica: {e!r}",
                    # deterministic per-request jitter (no wall-clock
                    # randomness): synchronized clients spread their
                    # retries instead of stampeding in lockstep
                    retry_after_s=retry_jitter_s(
                        request_id or f"{method} {path}")) from e
        return self._check_status(shard, method, path, status, body)

    def _host_leg(self, shard: int, replica: int, method: str, path: str,
                  payload=None, headers=None) -> dict:
        """One SPECIFIC host's leg (no failover, no hedge): two-phase
        epochs must reach every replica of every shard — preparing 'any
        one replica of shard s' would split the group's lineage."""
        client = self.clients[shard][replica]
        with _FANOUT_SECONDS.labels(shard=str(shard)).time() as timer:
            try:
                status, body = client.request(method, path, payload,
                                              headers=headers)
            except Exception as e:
                timer.discard()
                _UPSTREAM_ERRORS.labels(shard=str(shard)).inc()
                raise _overload.shed(
                    "upstream",
                    message=f"fleet shard {shard} replica {replica} "
                            f"({client.url}) unreachable: {e!r}",
                    retry_after_s=2.0) from e
        return self._check_status(shard, method, path, status, body)

    def _gather(self, legs: "list[tuple]") -> list:
        """Run legs concurrently; returns bodies in leg order, raising
        the FIRST leg failure (after every future settles — no leg is
        left running against a dead request). The caller's open span
        (fleet.score / fleet.rank) is captured HERE, on the request
        thread, and handed to each leg explicitly — pool threads don't
        inherit the tracing contextvars."""
        parent = _tracing.current_span_id()
        futures = [self._pool.submit(self._leg, *leg, parent_span=parent)
                   for leg in legs]
        results, first_error = [], None
        for fut in futures:
            try:
                results.append(fut.result())
            except BaseException as e:  # re-raised below, nothing swallowed
                results.append(None)
                if first_error is None:
                    first_error = e
        if first_error is not None:
            raise first_error
        return results

    @staticmethod
    def _check_lineage(bodies: Sequence[dict]) -> Optional[str]:
        lineages = {body.get("lineage") for body in bodies}
        if len(lineages) > 1:
            _MIXED_LINEAGE.inc()
            raise MixedLineageError(
                f"fan-out legs answered from different model lineages "
                f"{sorted(str(x) for x in lineages)} — refusing to stitch "
                f"a mixed response (is a reload epoch half-activated?)")
        return next(iter(lineages)) if lineages else None

    # --- /score -----------------------------------------------------------
    @staticmethod
    def _shards_of(record: dict, coordinates: Sequence[tuple],
                   shard_map: ShardMap) -> tuple:
        """The sorted shard set a record's present entity ids map to
        under ``shard_map`` — crc32 → virtual bucket → owning shard
        (empty metadata → shard 0: any host scores it exactly — every
        coordinate falls back to the replicated fixed effect + zeros)."""
        meta = record.get("metadataMap") or {}
        shards = {shard_map.shard_of(str(meta[etype]))
                  for _cid, etype in coordinates
                  if etype is not None and meta.get(etype) not in (None, "")}
        return tuple(sorted(shards)) if shards else (0,)

    def _check_shard_map(self, expected: ShardMap,
                         bodies: Sequence[dict]) -> None:
        """Every leg must have answered under the map this fan-out was
        routed with — the shard-map twin of :meth:`_check_lineage`
        (defense in depth: hosts already refuse a mismatched
        ``X-Photon-Shard-Map`` header)."""
        hashes = {body.get("shard_map") for body in bodies}
        hashes.discard(None)  # unsharded hosts don't stamp one
        if hashes - {expected.map_hash}:
            raise ShardMapMismatch(
                f"fan-out routed under shard map {expected.map_hash} but "
                f"legs answered under {sorted(hashes)} — refusing a "
                f"mixed-map response (is a reshard epoch half-activated?)")

    def score(self, payload: dict,
              request_id: Optional[str] = None,
              deadline: Optional[float] = None) -> dict:
        """Fleet ``/score``: partition → fan out → merge. Single-shard
        records use the owner host's totals verbatim; cross-shard records
        merge per-coordinate margins through ``sum_coordinate_margins``
        (bit-identical either way — SERVING.md "Fleet serving")."""
        if request_id is None:
            request_id = new_request_id()
        if "record" in payload:
            records = [payload["record"]]
        else:
            records = payload.get("records")
        if not isinstance(records, list) or not records:
            raise ValueError("payload needs 'records': [non-empty list] "
                             "or 'record': {...}")
        if deadline is not None and time.monotonic() >= deadline:
            raise _overload.shed(
                "deadline", message="deadline expired before fan-out")
        coordinates, _ = self.topology()
        with self._traffic():
            # the map snapshot, the routing decisions and the fan-out all
            # happen inside the drain barrier: a reshard epoch cannot
            # swap the map under a half-routed request
            shard_map = self.shard_map
            groups: dict[tuple, list[int]] = {}
            for i, rec in enumerate(records):
                groups.setdefault(
                    self._shards_of(rec, coordinates, shard_map),
                    []).append(i)
            headers = self._leg_headers(request_id, deadline,
                                        shard_map=shard_map)
            legs, plans = [], []
            for shard_set, idxs in groups.items():
                recs = [records[i] for i in idxs]
                if len(shard_set) == 1:
                    plans.append(("direct", shard_set, idxs, [len(legs)]))
                    legs.append((shard_set[0], "POST", "/score",
                                 {"records": recs}, headers,
                                 request_id, deadline))
                else:
                    # the record spans shards: every involved host scores
                    # it and returns per-coordinate margins; the router
                    # keeps, per coordinate, the margin of the shard that
                    # OWNS that coordinate's entity id
                    plans.append(("margins", shard_set, idxs,
                                  list(range(len(legs),
                                             len(legs) + len(shard_set)))))
                    for s in shard_set:
                        legs.append((s, "POST", "/score",
                                     {"records": recs, "margins": True},
                                     headers, request_id, deadline))
            with _tracing.span("fleet.score", request_id=request_id,
                               batch=len(records), legs=len(legs)):
                bodies = self._gather(legs)
        lineage = self._check_lineage(bodies)
        self._check_shard_map(shard_map, bodies)
        scores: list = [None] * len(records)
        merged = 0
        version = None
        for mode, shard_set, idxs, leg_ids in plans:
            if mode == "direct":
                body = bodies[leg_ids[0]]
                if version is None or shard_set[0] == 0:
                    version = body.get("version")
                for j, i in enumerate(idxs):
                    scores[i] = body["scores"][j]
                continue
            merged += len(idxs)
            by_shard = {s: bodies[leg_id]
                        for s, leg_id in zip(shard_set, leg_ids)}
            primary = by_shard[shard_set[0]]
            if version is None:
                version = primary.get("version")
            margins_of = {s: dict(b["margins"])
                          for s, b in by_shard.items()}
            offsets = np.asarray(primary["offsets"], np.float32)
            merged_margins = []
            for cid, etype in coordinates:
                vals = np.empty(len(idxs), np.float32)
                for j, i in enumerate(idxs):
                    meta = records[i].get("metadataMap") or {}
                    raw = None if etype is None else meta.get(etype)
                    owner = (shard_set[0] if raw in (None, "")
                             else shard_map.shard_of(str(raw)))
                    vals[j] = np.float32(margins_of[owner][cid][j])
                merged_margins.append(vals)
            # THE score-summation contract, re-run over the owner-shard
            # margins: same f64 accumulation, same coordinate order, same
            # f32 inputs ⇒ the same f32 totals the hosts would produce
            totals = sum_coordinate_margins(offsets, merged_margins, xp=np)
            for j, i in enumerate(idxs):
                scores[i] = float(totals[j])
        with self._lock:
            self.n_requests += 1
        _FLEET_REQUESTS.labels(endpoint="score").inc()
        out = {"scores": scores, "version": version, "lineage": lineage,
               "shard_map": shard_map.map_hash,
               "request_id": request_id,
               "fanout": {"legs": len(legs), "merged": merged}}
        if deadline is not None:
            out["deadline_ms"] = round(self.remaining_ms(deadline), 1)
        return out

    # --- /rank ------------------------------------------------------------
    def rank(self, payload: dict,
             request_id: Optional[str] = None,
             deadline: Optional[float] = None) -> dict:
        """Fleet ``/rank``: fan the request to every host (each ranks its
        own item shard) and merge the top-k by score (ties break by shard
        then within-shard rank — single-host tie order is the global item
        axis, unrecoverable across a hash partition; real trained scores
        are distinct). Models with user-side random-effect coordinates
        are refused: a sharded user store would zero the user's margin on
        foreign hosts."""
        if request_id is None:
            request_id = new_request_id()
        _coordinates, rank_info = self.topology()
        if not rank_info:
            raise ValueError("ranking is not enabled on the fleet's hosts "
                             "(start them with --rank-item-coordinate)")
        if rank_info.get("user_re_coordinates"):
            raise ValueError(
                f"fleet ranking requires the item coordinate to be the "
                f"only random effect; user-side RE coordinates "
                f"{rank_info['user_re_coordinates']} would rank with the "
                f"user's margin zeroed on foreign shards")
        try:
            k = int(payload.get("k", min(10, int(rank_info["max_k"]))))
        except (TypeError, ValueError):
            raise ValueError(
                f"bad k {payload.get('k')!r} (want an integer)") from None
        if deadline is not None and time.monotonic() >= deadline:
            raise _overload.shed(
                "deadline", message="deadline expired before fan-out")
        leg_payload = {key: payload[key]
                       for key in ("record", "user") if key in payload}
        leg_payload["k"] = k
        with self._traffic():
            shard_map = self.shard_map
            headers = self._leg_headers(request_id, deadline,
                                        shard_map=shard_map)
            legs = [(s, "POST", "/rank", leg_payload, headers,
                     request_id, deadline)
                    for s in range(self.n_shards)]
            with _tracing.span("fleet.rank", request_id=request_id, k=k,
                               legs=len(legs)):
                bodies = self._gather(legs)
        lineage = self._check_lineage(bodies)
        self._check_shard_map(shard_map, bodies)
        ranked = []  # (-score, shard, within-shard rank, id)
        for shard, body in enumerate(bodies):
            for pos, (item, score) in enumerate(zip(body["ids"],
                                                    body["scores"])):
                ranked.append((-float(score), shard, pos, str(item)))
        ranked.sort()
        top = ranked[:k]
        with self._lock:
            self.n_requests += 1
        _FLEET_REQUESTS.labels(endpoint="rank").inc()
        out = {"ids": [item for _s, _sh, _p, item in top],
               "scores": [-neg for neg, _sh, _p, _i in top],
               "k": k, "lineage": lineage,
               "shard_map": shard_map.map_hash,
               "request_id": request_id,
               "version": bodies[0].get("version")}
        if deadline is not None:
            out["deadline_ms"] = round(self.remaining_ms(deadline), 1)
        return out

    # --- two-phase /reload ------------------------------------------------
    def reload(self, payload: dict,
               request_id: Optional[str] = None) -> dict:
        """Coordinated two-phase activation. ``model_dir`` names one
        candidate for every host; ``model_dirs`` (length N) names
        per-host candidates — the ``refresh_game --fleet-shards`` patch
        layout. Phase 1 (``prepare``) runs each host's full
        validate+canary+warm gate; ANY refusal — or the prepared
        candidates disagreeing on lineage — aborts the epoch (prepared
        versions retired, incumbent serving fleet-wide, 409 up). Phase 2
        activates every host's prepared version."""
        if request_id is None:
            request_id = new_request_id()
        dirs = payload.get("model_dirs")
        if dirs is None:
            model_dir = payload.get("model_dir")
            if not model_dir:
                raise ValueError("payload needs 'model_dir' (one for the "
                                 "whole fleet) or 'model_dirs' (one per "
                                 "host)")
            dirs = [model_dir] * self.n_shards
        if len(dirs) != self.n_shards:
            raise ValueError(f"'model_dirs' must name {self.n_shards} "
                             f"dirs (one per shard), got {len(dirs)}")
        headers = self._leg_headers(request_id, None)
        _FLEET_REQUESTS.labels(endpoint="reload").inc()
        with self._epoch_lock, \
                _tracing.span("fleet.reload", request_id=request_id):
            # --- phase 1: EVERY host (all replicas of all shards)
            # validates, canaries and warms — preparing only one replica
            # per group would split the group's lineage on failover
            prepared, errors = self._prepare_epoch(
                {(s, r): {"model_dir": dirs[s], "phase": "prepare"}
                 for s in range(self.n_shards)
                 for r in range(self.replicas)}, headers)
            lineages = {body["lineage"] for body in prepared.values()}
            if not errors and len(lineages) > 1:
                errors[(-1, -1)] = (
                    f"prepared candidates disagree on lineage "
                    f"{sorted(str(x) for x in lineages)}")
            if errors:
                # --- abort: retire whatever prepared; incumbent serves
                self._abort(prepared, headers)
                _EPOCHS.labels(outcome="aborted").inc()
                raise RuntimeError(
                    f"two-phase reload aborted — incumbent keeps serving "
                    f"fleet-wide; refusals: "
                    + "; ".join(self._host_name(s, r) + f": {err}"
                                for (s, r), err in sorted(errors.items())))
            # --- phase 2: activate everywhere ---------------------------
            activations = self._activate_epoch(prepared, headers)
        _EPOCHS.labels(outcome="activated").inc()
        # coordinate structure may have changed (it rarely does) — the
        # next request routes on the fresh topology either way
        self.topology(refresh=True)
        hosts = sorted(activations)
        return {"lineage": next(iter(lineages)),
                "versions": [activations[h]["version"] for h in hosts],
                "previous": [activations[h].get("previous")
                             for h in hosts],
                "request_id": request_id}

    def _host_name(self, shard: int, replica: int) -> str:
        if shard < 0:
            return "fleet"
        if self.replicas == 1:
            return f"shard {shard}"
        return f"shard {shard} replica {replica}"

    def _prepare_epoch(self, payloads: "dict[tuple, dict]",
                       headers: dict) -> "tuple[dict, dict]":
        """Fan a phase-1 prepare to every named host; returns
        ``(prepared, errors)`` keyed by ``(shard, replica)``."""
        futures = {key: self._pool.submit(self._host_leg, key[0], key[1],
                                          "POST", "/reload", body, headers)
                   for key, body in payloads.items()}
        prepared: dict = {}
        errors: dict = {}
        for key, fut in futures.items():
            try:
                prepared[key] = fut.result()
            except Exception as e:
                errors[key] = repr(e)
        return prepared, errors

    def _activate_epoch(self, prepared: "dict[tuple, dict]",
                        headers: dict) -> "dict[tuple, dict]":
        """Fan phase 2 to every prepared host, raising the first
        failure (after every future settles)."""
        futures = {key: self._pool.submit(
            self._host_leg, key[0], key[1], "POST", "/reload",
            {"phase": "activate", "version": body["version"]}, headers)
            for key, body in prepared.items()}
        activations: dict = {}
        first_error = None
        for key, fut in futures.items():
            try:
                activations[key] = fut.result()
            except BaseException as e:
                if first_error is None:
                    first_error = e
        if first_error is not None:
            raise first_error
        return activations

    def _abort(self, prepared: "dict[tuple, dict]",
               headers: dict) -> None:
        """Best-effort retire of every prepared-but-unactivated version.
        A host that cannot be reached keeps the version registered (never
        ACTIVE — it pins some memory until the next successful epoch or
        restart, it cannot serve)."""
        for (s, r), body in prepared.items():
            try:
                self._host_leg(s, r, "POST", "/reload",
                               {"phase": "abort",
                                "version": body["version"]},
                               headers)
            except Exception:
                pass  # the abort is advisory; the version was never active

    # --- live resharding --------------------------------------------------
    def reshard(self, payload: dict,
                request_id: Optional[str] = None) -> dict:
        """LIVE RESHARD: drive a new bucket→shard map through the same
        two-phase epoch as a model reload. ``payload`` carries either
        ``moves`` ({bucket: new_shard} — the explicit O(moved) form) or a
        full ``shard_map`` dict. Phase 1 has every host repack its shard
        view under the candidate map (the active model's content,
        re-bucketed — hosts report per-direction row-movement counters);
        ANY refusal aborts fleet-wide with the incumbent map serving.
        Phase 2 drains the router's in-flight fan-outs (the drain
        barrier), activates everywhere, swaps the router's map
        atomically, and reopens — f32 responses are bit-identical
        before, during and after, and no response ever mixes maps."""
        if request_id is None:
            request_id = new_request_id()
        incumbent = self.shard_map
        moves = payload.get("moves")
        if moves is not None:
            if not isinstance(moves, Mapping) or not moves:
                raise ValueError("'moves' must be a non-empty mapping of "
                                 "{bucket: new_shard}")
            try:
                candidate = incumbent.with_moves(
                    {int(b): int(s) for b, s in moves.items()})
            except (TypeError, ValueError) as e:
                raise ValueError(f"bad reshard moves: {e}") from None
        elif payload.get("shard_map") is not None:
            candidate = ShardMap.from_dict(payload["shard_map"])
            if candidate.n_shards != self.n_shards:
                raise ValueError(
                    f"candidate map names {candidate.n_shards} shards, "
                    f"this fleet has {self.n_shards}")
        else:
            raise ValueError("payload needs 'moves' ({bucket: new_shard}) "
                             "or a full 'shard_map'")
        n_moved_buckets = len(incumbent.moved_buckets(candidate))
        headers = self._leg_headers(request_id, None)
        _FLEET_REQUESTS.labels(endpoint="reshard").inc()
        with self._epoch_lock, \
                _tracing.span("fleet.reshard", request_id=request_id,
                              moved_buckets=n_moved_buckets):
            # --- phase 1: every host repacks under the candidate map ----
            prepared, errors = self._prepare_epoch(
                {(s, r): {"phase": "prepare",
                          "shard_map": candidate.as_dict()}
                 for s in range(self.n_shards)
                 for r in range(self.replicas)}, headers)
            if errors:
                self._abort(prepared, headers)
                _SHARDMAP_EPOCHS.labels(outcome="aborted").inc()
                raise RuntimeError(
                    f"reshard epoch aborted — incumbent map "
                    f"{incumbent.map_hash} keeps serving fleet-wide; "
                    f"refusals: "
                    + "; ".join(self._host_name(s, r) + f": {err}"
                                for (s, r), err in sorted(errors.items())))
            # --- phase 2: drain, activate everywhere, swap, reopen ------
            if not self._pause_traffic(self.fanout_timeout_s):
                self._resume_traffic()
                self._abort(prepared, headers)
                _SHARDMAP_EPOCHS.labels(outcome="aborted").inc()
                raise RuntimeError(
                    f"reshard epoch aborted — in-flight fan-outs did not "
                    f"drain within {self.fanout_timeout_s}s; incumbent "
                    f"map {incumbent.map_hash} keeps serving fleet-wide")
            try:
                activations = self._activate_epoch(prepared, headers)
                self.shard_map = candidate
                _SHARDMAP_VERSION.set(candidate.version)
            finally:
                # on an activation failure the router keeps the incumbent
                # map: hosts that did activate will REFUSE its hash
                # (shard_map_mismatch) rather than serve mixed — refusal,
                # never silent wrongness
                self._resume_traffic()
        _SHARDMAP_EPOCHS.labels(outcome="activated").inc()
        moved = {"moved_in": 0, "moved_out": 0, "retained": 0}
        for body in prepared.values():
            for key in moved:
                moved[key] += int((body.get("moved") or {}).get(key, 0))
        hosts = sorted(activations)
        return {"shard_map": candidate.map_hash,
                "map_version": candidate.version,
                "previous": incumbent.map_hash,
                "moved_buckets": n_moved_buckets,
                "moved": moved,
                "moved_hosts": {self._host_name(s, r):
                                prepared[(s, r)].get("moved")
                                for (s, r) in hosts},
                "versions": [activations[h]["version"] for h in hosts],
                "request_id": request_id}

    # --- health + metrics -------------------------------------------------
    def healthz(self) -> dict:
        hosts = []
        for s in range(self.n_shards):
            for r in range(self.replicas):
                client = self.clients[s][r]
                entry = {"shard": s, "replica": r, "url": client.url}
                try:
                    status, body = client.request("GET", "/healthz")
                    if status != 200:
                        raise RuntimeError(f"/healthz -> {status}")
                    entry.update(
                        status=body.get("status"),
                        version=body.get("version"),
                        lineage=body.get("model_lineage_id"),
                        fleet_shard=body.get("fleet_shard"),
                        shard_map=(body.get("shard_map") or {}).get("hash"))
                except Exception as e:
                    entry.update(status="unreachable", error=repr(e))
                hosts.append(entry)
        lineages = {h.get("lineage") for h in hosts
                    if h.get("status") == "ok"}
        maps = {h.get("shard_map") for h in hosts
                if h.get("status") == "ok"} - {None}
        # per-shard replica coverage — the operator's first question
        # about a degraded fleet is "which shard, how much redundancy
        # left", not "which host"
        replicas_up = [0] * self.n_shards
        for h in hosts:
            if h.get("status") == "ok":
                replicas_up[h["shard"]] += 1
        return {"status": "ok" if all(h.get("status") == "ok"
                                      for h in hosts) else "degraded",
                "n_shards": self.n_shards,
                "replicas": self.replicas,
                "requests": self.n_requests,
                "mixed_lineage": len(lineages) > 1,
                "shard_map": {"hash": self.shard_map.map_hash,
                              "version": self.shard_map.version,
                              "mixed": bool(maps
                                            - {self.shard_map.map_hash})},
                "shard_replicas_up": replicas_up,
                "hosts": hosts,
                "shed": _overload.shed_counts()}

    def readyz(self) -> "tuple[int, dict]":
        """Ready iff every SHARD has at least one ready replica — a
        fleet missing a whole shard serves wrong-by-omission scores for
        that shard's entities, so it is not ready, merely alive. A group
        down to fewer replicas than configured is degraded-but-ready
        (that is exactly what the redundancy is for)."""
        reasons = []
        uncovered = []
        for s in range(self.n_shards):
            group_reasons = []
            for r in range(self.replicas):
                try:
                    status, body = self.clients[s][r].request("GET",
                                                              "/readyz")
                    if status == 200:
                        group_reasons = []
                        break
                    group_reasons.append(
                        f"{self._host_name(s, r)}: "
                        f"{','.join(body.get('reasons', []))}")
                except Exception as e:
                    group_reasons.append(
                        f"{self._host_name(s, r)}: unreachable ({e!r})")
            if group_reasons:
                uncovered.append(s)
            reasons.extend(group_reasons)
        body = {"ready": not reasons, "reasons": reasons,
                "n_shards": self.n_shards, "replicas": self.replicas}
        if uncovered:
            # the typed refusal: a shard with ZERO live replicas means
            # wrong-by-omission scores, the one thing /readyz gates
            body["reason"] = "shard_uncovered"
            body["uncovered_shards"] = uncovered
        return (200 if not reasons else 503), body

    def metrics_text(self) -> str:
        """The fleet-folded exposition: the router's own registry first
        (chief semantics), then every live host's snapshot — scraped
        over the POOLED leg connections — with host-owned gauges tagged
        ``shard="I"``, ``replica="J"`` so they fan out per host. The
        same fold, fed the same texts, as ``tools/metrics_fold.py``
        offline (byte-identical; the tier-1 fold-consistency test locks
        it). A host failing mid-scrape leaves a
        ``photon_fleet_scrape_errors_total`` annotation, never a 500."""
        return self.observer.metrics_text()

    def statusz(self) -> dict:
        """The fleet topology page (``GET /statusz``) — delegated to the
        observability plane."""
        return self.observer.statusz()

    def close(self) -> None:
        self.observer.close()
        self._pool.shutdown(wait=True)
        self._hedge_pool.shutdown(wait=True)
        for group in self.clients:
            for client in group:
                client.close()


# ---------------------------------------------------------------------------
# the HTTP front (thin marshaling, like serving/http.py's handler)
# ---------------------------------------------------------------------------


def _make_handler(router: FleetRouter):
    class Handler(BaseHTTPRequestHandler):
        # persistent connections, like the serving front end (every
        # reply carries Content-Length)
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # noqa: D102
            pass

        def _request_id(self) -> str:
            inbound = self.headers.get(REQUEST_ID_HEADER)
            self.request_id = inbound.strip() if inbound \
                else new_request_id()
            return self.request_id

        def _reply(self, status: int, body: dict,
                   headers: Optional[dict] = None) -> None:
            data = json.dumps(body).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            rid = getattr(self, "request_id", None)
            if rid is not None:
                self.send_header(REQUEST_ID_HEADER, rid)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def _payload(self) -> dict:
            length = int(self.headers.get("Content-Length") or 0)
            if not length:
                return {}
            return json.loads(self.rfile.read(length) or b"{}")

        def _dispatch(self, rid: str, fn, payload: dict,
                      deadline: Optional[float]) -> None:
            # the root of the merged trace: fleet.score/rank and every
            # fleet.leg (hedges and retries included) nest under this
            # one request-id-tagged span; its outcome feeds the SLO
            # burn tracker when one is attached
            headers = None
            t0 = time.monotonic()
            with _tracing.span("fleet.request", request_id=rid):
                try:
                    out = fn(payload, request_id=rid, deadline=deadline)
                    status = 200
                except _overload.Shed as e:
                    out = {"error": str(e), "reason": e.reason,
                           "request_id": rid}
                    status = shed_status(e)
                    headers = {"Retry-After":
                               str(max(1, round(e.retry_after_s)))}
                except MixedLineageError as e:
                    out = {"error": str(e), "reason": "mixed_lineage",
                           "request_id": rid}
                    status = 503
                except ShardMapMismatch as e:
                    out = {"error": str(e), "reason": "shard_map_mismatch",
                           "request_id": rid}
                    status = 503
                except ValueError as e:
                    out, status = {"error": str(e)}, 400
                except Exception as e:
                    out, status = {"error": repr(e)}, 500
            router.observer.observe_request(time.monotonic() - t0,
                                            ok=status == 200)
            self._reply(status, out, headers=headers)

        def do_GET(self):  # noqa: N802
            rid = self._request_id()
            parsed = urllib.parse.urlsplit(self.path)
            if parsed.path == "/rank":
                qs = urllib.parse.parse_qs(parsed.query)
                payload = {key: values[0] for key, values in qs.items()
                           if values}
                try:
                    deadline = router.resolve_deadline(
                        self.headers.get(DEADLINE_HEADER))
                except ValueError as e:
                    self._reply(400, {"error": str(e)})
                    return
                self._dispatch(rid, router.rank, payload, deadline)
            elif parsed.path == "/healthz":
                self._reply(200, router.healthz())
            elif parsed.path == "/readyz":
                status, body = router.readyz()
                self._reply(status, body)
            elif parsed.path == "/metrics":
                from photon_ml_tpu.telemetry.prometheus import CONTENT_TYPE

                data = router.metrics_text().encode()
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            elif parsed.path == "/statusz":
                self._reply(200, router.statusz())
            elif parsed.path == "/history":
                # the fleet timeline: per-host retained rings folded
                # against the router's own ring with the metrics_fold
                # merge semantics (fleet/observe.py::FleetObserver.history)
                qs = urllib.parse.parse_qs(parsed.query)
                try:
                    window = int((qs.get("window") or ["0"])[0])
                    series = tuple(
                        s for s in (qs.get("series") or [""])[0].split(",")
                        if s)
                    raw = (qs.get("raw") or ["0"])[0] not in ("", "0")
                    body = router.observer.history(
                        window=window, series=series, include_prom=raw)
                except ValueError as e:
                    self._reply(400, {"error": str(e)})
                    return
                except RuntimeError as e:
                    self._reply(404, {"error": str(e)})
                    return
                self._reply(200, body)
            elif parsed.path == "/advisor":
                advisor = getattr(router, "advisor", None)
                if advisor is None:
                    self._reply(404, {"error": "hot-shard advisor "
                                               "not armed"})
                    return
                self._reply(200, advisor.status())
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):  # noqa: N802
            rid = self._request_id()
            try:
                payload = self._payload()
                deadline = router.resolve_deadline(
                    self.headers.get(DEADLINE_HEADER))
            except (ValueError, json.JSONDecodeError) as e:
                self._reply(400, {"error": f"bad request: {e}"})
                return
            if self.path == "/score":
                self._dispatch(rid, router.score, payload, deadline)
            elif self.path == "/rank":
                self._dispatch(rid, router.rank, payload, deadline)
            elif self.path == "/reload":
                try:
                    self._reply(200, router.reload(payload,
                                                   request_id=rid))
                except Exception as e:
                    # an aborted epoch is a CONFLICT: the incumbent is
                    # untouched on every host, exactly like a single
                    # host's rejected /reload
                    self._reply(409, {"error": repr(e)})
            elif self.path == "/reshard":
                try:
                    self._reply(200, router.reshard(payload,
                                                    request_id=rid))
                except ValueError as e:
                    self._reply(400, {"error": str(e)})
                except Exception as e:
                    # an aborted reshard epoch is a CONFLICT too: the
                    # incumbent map keeps serving fleet-wide
                    self._reply(409, {"error": repr(e)})
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

    return Handler


class RouterServer:
    """Threaded HTTP wrapper for :class:`FleetRouter` — the same
    test-friendly lifecycle as ``serving/http.py::GameServer``."""

    def __init__(self, router: FleetRouter, *, host: str = "127.0.0.1",
                 port: int = 0):
        self.router = router
        self._httpd = ThreadingHTTPServer((host, port),
                                          _make_handler(router))
        #: start/stop are operator-lifecycle calls from one control thread
        self._thread: Optional[threading.Thread] = None  # guarded-by: caller

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "RouterServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="photon-fleet-router")
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join()
        self.router.close()
