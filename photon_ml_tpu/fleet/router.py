"""The fleet routing tier: one thin HTTP front for N entity-sharded hosts.

Each serving host (``serve_game --fleet-shard I --fleet-shard-count N``)
packs ~1/N of every random-effect coordinate's dense coefficient table
(``fleet/sharding.py`` decides which ids land where). This router is the
piece that makes the fleet look like ONE server:

- ``POST /score`` — resolves each record's shard(s) from its raw entity
  ids and fans out over persistent per-host connections. Records whose
  entities all live on one shard are scored there outright (that host's
  f32 totals ARE the response — bit-identical to an unsharded server by
  construction). Records spanning shards are scored everywhere involved
  with ``margins=true`` and the router re-runs the ONE score-summation
  contract, :func:`photon_ml_tpu.game.model.sum_coordinate_margins`, over
  each coordinate's owner-shard margins — f32 margins widened to double
  in JSON are exact, and the f64-accumulate-then-f32 reduction is the
  same arithmetic the host's trace performs, so merged totals are
  bit-identical too.
- ``GET/POST /rank`` — fans the request to EVERY host (each ranks its own
  item shard) and merges the per-shard top-k by score. Exact per-item
  scores require the user side of the model to be host-invariant — the
  fixed effect is replicated, so this holds for the standard retrieval
  setup (item coordinate = the only random effect); a model with
  user-side RE coordinates is refused rather than silently mis-ranked.
- ``POST /reload`` — the coordinated two-phase activation: every host
  validates + canaries + warms the candidate (``phase=prepare``), the
  router gates ONCE over all verdicts (any refusal, or disagreeing
  candidate lineages, aborts the epoch with the incumbent serving
  fleet-wide), then activates everywhere. The single-host watcher +
  canary gate generalize exactly here: gate at the router, activate
  everywhere.
- ``GET /metrics`` — the fleet fold: every host's ``/metrics`` text plus
  the router's own registry through
  :func:`photon_ml_tpu.telemetry.aggregate.aggregate_text` (counters and
  histogram series sum; host-owned gauges — queue depth, brownout level,
  rank items — are tagged ``process="<shard>"`` and fan out). The same
  fold ``tools/metrics_fold.py`` runs offline, byte-identically.

Failure mapping: a dead/slow host leg (connection failure, fan-out
timeout, injected ``fleet.fanout`` fault) becomes a typed
:class:`~photon_ml_tpu.serving.overload.Shed` with ``reason="upstream"``
→ **503** + ``Retry-After``; a host's own 429/503 passes through with its
reason. Every response carries the model content lineage, and a fan-out
whose legs disagree is refused (503 ``reason=mixed_lineage``) — the
no-mixed-lineage invariant is enforced per response, not just promised by
the activation protocol.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
import urllib.parse
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu.fleet.sharding import shard_of_id
from photon_ml_tpu.game.model import sum_coordinate_margins
from photon_ml_tpu.resilience.faults import fault_point
from photon_ml_tpu.serving import overload as _overload
from photon_ml_tpu.serving.http import (
    DEADLINE_HEADER,
    REQUEST_ID_HEADER,
    new_request_id,
    shed_status,
)
from photon_ml_tpu.telemetry import metrics as _metrics
from photon_ml_tpu.telemetry import tracing as _tracing

#: requests the router answered, by endpoint (score | rank | reload)
_FLEET_REQUESTS = _metrics.counter(
    "photon_fleet_requests_total",
    "Requests served by the fleet router, by endpoint",
    labels=("endpoint",))

#: one per-host fan-out leg's round trip (connect reuse included)
_FANOUT_SECONDS = _metrics.histogram(
    "photon_fleet_fanout_seconds",
    "Per-host leg latency of a fleet router fan-out", labels=("shard",))

#: legs lost to a dead/slow/faulted host (mapped to 503 reason=upstream)
_UPSTREAM_ERRORS = _metrics.counter(
    "photon_fleet_upstream_errors_total",
    "Fan-out legs that failed (connection error, timeout, injected "
    "fleet.fanout fault) — each maps to a typed 503 reason=upstream",
    labels=("shard",))

#: fan-outs refused because host legs answered with different model
#: content lineages — the invariant two-phase activation exists to keep
_MIXED_LINEAGE = _metrics.counter(
    "photon_fleet_mixed_lineage_total",
    "Fleet responses refused because fan-out legs disagreed on model "
    "lineage (503 reason=mixed_lineage)")

#: two-phase /reload outcomes (activated | aborted)
_EPOCHS = _metrics.counter(
    "photon_fleet_epochs_total",
    "Coordinated two-phase reload epochs, by outcome "
    "(activated | aborted)", labels=("outcome",))

#: configured host count (the fleet's N)
_FLEET_HOSTS = _metrics.gauge(
    "photon_fleet_hosts",
    "Serving hosts behind the fleet router (the shard count N)")


class MixedLineageError(RuntimeError):
    """Fan-out legs answered from different model generations — the
    response is refused (503 ``reason=mixed_lineage``) rather than
    stitched together from two models."""


class HostClient:
    """Persistent-connection JSON client for one serving host.

    Connections are pooled and reused across requests (the stdlib
    ``urllib`` one-connection-per-request pattern is exactly the socket
    churn the tail-latency push removed client-side). A request that dies
    on a stale keep-alive — the server closed an idle connection under
    us — is retried ONCE on a fresh connection; a fresh connection
    failing means the host is actually gone, and the caller maps that to
    the typed upstream 503.
    """

    def __init__(self, url: str, shard: int, *, timeout_s: float = 30.0):
        self.url = url.rstrip("/")
        self.shard = int(shard)
        self.timeout_s = float(timeout_s)
        parsed = urllib.parse.urlsplit(self.url)
        self._host = parsed.hostname
        self._port = parsed.port or 80
        self._lock = threading.Lock()
        self._free: list = []  # guarded-by: _lock

    def _take(self):
        with self._lock:
            if self._free:
                return self._free.pop()
        return http.client.HTTPConnection(self._host, self._port,
                                          timeout=self.timeout_s)

    def _give(self, conn) -> None:
        with self._lock:
            self._free.append(conn)

    def request(self, method: str, path: str, payload=None,
                headers: Optional[Mapping[str, str]] = None,
                ) -> "tuple[int, dict]":
        """One JSON request → ``(status, body)``. Raises ``OSError`` /
        ``http.client.HTTPException`` when the host is unreachable past
        the bounded reconnect (the caller owns the upstream mapping)."""
        # the fleet chaos site: one visit per LEG (not per reconnect
        # attempt) — an injected fault is a host that cannot be reached
        fault_point("fleet.fanout", host=self.url, path=path)
        body = None if payload is None else json.dumps(payload).encode()
        hdrs = {"Content-Type": "application/json", **(headers or {})}
        last: Optional[BaseException] = None
        for attempt in range(2):
            conn = self._take()
            try:
                conn.request(method, path, body=body, headers=hdrs)
                resp = conn.getresponse()
                data = resp.read()
                self._give(conn)
                return resp.status, json.loads(data or b"{}")
            except (OSError, http.client.HTTPException) as e:
                # a pooled connection can be stale (server-side idle
                # close); retry once on a provably fresh one
                conn.close()
                last = e
        raise ConnectionError(
            f"host {self.url} unreachable after reconnect: {last!r}")

    def close(self) -> None:
        with self._lock:
            conns, self._free = self._free, []
        for conn in conns:
            conn.close()


class FleetRouter:
    """Endpoint logic of the routing tier, HTTP-free (the handler is
    thin, like ``serving/http.py``'s). One instance fronts N hosts; host
    *i* must be serving fleet shard ``(i, N)``."""

    def __init__(self, host_urls: Sequence[str], *,
                 fanout_timeout_s: float = 30.0,
                 default_timeout_ms: float = 0.0):
        if not host_urls:
            raise ValueError("a fleet router needs at least one host url")
        self.clients = [HostClient(url, shard=i, timeout_s=fanout_timeout_s)
                        for i, url in enumerate(host_urls)]
        self.n_shards = len(self.clients)
        self.default_timeout_ms = float(default_timeout_ms)
        #: fan-out worker pool — sized so every shard of two concurrent
        #: requests can be in flight; legs are short-lived, the pool is
        #: process-lifetime
        self._pool = ThreadPoolExecutor(
            max_workers=max(4, 2 * self.n_shards),
            thread_name_prefix="photon-fleet-fanout")
        self._lock = threading.Lock()
        #: model coordinate walk [(cid, entity_type|None)] in order,
        #: fetched from a host's /healthz (refreshed after activation)
        self._coordinates: Optional[list] = None  # guarded-by: _lock
        self._rank_info: Optional[dict] = None  # guarded-by: _lock
        self.n_requests = 0  # guarded-by: _lock
        _FLEET_HOSTS.set(self.n_shards)

    # --- deadlines (same contract as ServingService) ----------------------
    def resolve_deadline(self,
                         budget_ms: "str | float | None") -> Optional[float]:
        if budget_ms is None or budget_ms == "":
            budget_ms = (self.default_timeout_ms
                         if self.default_timeout_ms > 0 else None)
        if budget_ms is None:
            return None
        try:
            budget = float(budget_ms)
        except (TypeError, ValueError):
            raise ValueError(
                f"bad {DEADLINE_HEADER} header {budget_ms!r} (want a "
                f"millisecond budget)") from None
        return time.monotonic() + budget / 1e3

    @staticmethod
    def remaining_ms(deadline: Optional[float]) -> Optional[float]:
        if deadline is None:
            return None
        return max(0.0, (deadline - time.monotonic()) * 1e3)

    def _leg_headers(self, request_id: str,
                     deadline: Optional[float]) -> dict:
        """Propagated request identity + the REMAINING deadline budget —
        a downstream host spends the same budget the caller measures."""
        headers = {REQUEST_ID_HEADER: request_id}
        if deadline is not None:
            headers[DEADLINE_HEADER] = f"{self.remaining_ms(deadline):.1f}"
        return headers

    # --- topology ---------------------------------------------------------
    def topology(self, refresh: bool = False) -> "tuple[list, dict]":
        """``([(cid, entity_type|None), ...], rank_info)`` from a host's
        /healthz — which entity types route, in which order margins
        merge, and whether fleet ranking is supportable."""
        with self._lock:
            if self._coordinates is not None and not refresh:
                return self._coordinates, self._rank_info
        body = self._leg(0, "GET", "/healthz")
        coords = body.get("coordinates")
        if not coords:
            raise RuntimeError(
                "host 0 reports no active model coordinates — is the "
                "fleet serving yet?")
        coordinates = [(cid, etype) for cid, etype in coords]
        rank_info = body.get("rank") or {}
        with self._lock:
            self._coordinates = coordinates
            self._rank_info = rank_info
        return coordinates, rank_info

    # --- fan-out machinery ------------------------------------------------
    def _leg(self, shard: int, method: str, path: str, payload=None,
             headers=None) -> dict:
        """One per-host leg: timed, upstream-mapped, shed-passthrough."""
        client = self.clients[shard]
        with _FANOUT_SECONDS.labels(shard=str(shard)).time() as timer:
            try:
                status, body = client.request(method, path, payload,
                                              headers=headers)
            except Exception as e:
                timer.discard()
                _UPSTREAM_ERRORS.labels(shard=str(shard)).inc()
                raise _overload.shed(
                    "upstream",
                    message=f"fleet shard {shard} ({client.url}) "
                            f"unreachable: {e!r}",
                    retry_after_s=2.0) from e
        if status in (429, 503):
            # the HOST already counted this shed; re-raise the typed
            # refusal without double-counting
            raise _overload.Shed(body.get("reason", "queue_full"),
                                 body.get("error", f"shard {shard} shed"))
        if status != 200:
            raise RuntimeError(f"fleet shard {shard} {method} {path} -> "
                               f"{status}: {body.get('error', body)!r}")
        return body

    def _gather(self, legs: "list[tuple]") -> list:
        """Run legs concurrently; returns bodies in leg order, raising
        the FIRST leg failure (after every future settles — no leg is
        left running against a dead request)."""
        futures = [self._pool.submit(self._leg, *leg) for leg in legs]
        results, first_error = [], None
        for fut in futures:
            try:
                results.append(fut.result())
            except BaseException as e:  # re-raised below, nothing swallowed
                results.append(None)
                if first_error is None:
                    first_error = e
        if first_error is not None:
            raise first_error
        return results

    @staticmethod
    def _check_lineage(bodies: Sequence[dict]) -> Optional[str]:
        lineages = {body.get("lineage") for body in bodies}
        if len(lineages) > 1:
            _MIXED_LINEAGE.inc()
            raise MixedLineageError(
                f"fan-out legs answered from different model lineages "
                f"{sorted(str(x) for x in lineages)} — refusing to stitch "
                f"a mixed response (is a reload epoch half-activated?)")
        return next(iter(lineages)) if lineages else None

    # --- /score -----------------------------------------------------------
    def _shards_of(self, record: dict,
                   coordinates: Sequence[tuple]) -> tuple:
        """The sorted shard set a record's present entity ids hash to
        (empty metadata → shard 0: any host scores it exactly — every
        coordinate falls back to the replicated fixed effect + zeros)."""
        meta = record.get("metadataMap") or {}
        shards = {shard_of_id(str(meta[etype]), self.n_shards)
                  for _cid, etype in coordinates
                  if etype is not None and meta.get(etype) not in (None, "")}
        return tuple(sorted(shards)) if shards else (0,)

    def score(self, payload: dict,
              request_id: Optional[str] = None,
              deadline: Optional[float] = None) -> dict:
        """Fleet ``/score``: partition → fan out → merge. Single-shard
        records use the owner host's totals verbatim; cross-shard records
        merge per-coordinate margins through ``sum_coordinate_margins``
        (bit-identical either way — SERVING.md "Fleet serving")."""
        if request_id is None:
            request_id = new_request_id()
        if "record" in payload:
            records = [payload["record"]]
        else:
            records = payload.get("records")
        if not isinstance(records, list) or not records:
            raise ValueError("payload needs 'records': [non-empty list] "
                             "or 'record': {...}")
        if deadline is not None and time.monotonic() >= deadline:
            raise _overload.shed(
                "deadline", message="deadline expired before fan-out")
        coordinates, _ = self.topology()
        groups: dict[tuple, list[int]] = {}
        for i, rec in enumerate(records):
            groups.setdefault(self._shards_of(rec, coordinates),
                              []).append(i)
        headers = self._leg_headers(request_id, deadline)
        legs, plans = [], []
        for shard_set, idxs in groups.items():
            recs = [records[i] for i in idxs]
            if len(shard_set) == 1:
                plans.append(("direct", shard_set, idxs, [len(legs)]))
                legs.append((shard_set[0], "POST", "/score",
                             {"records": recs}, headers))
            else:
                # the record spans shards: every involved host scores it
                # and returns per-coordinate margins; the router keeps,
                # per coordinate, the margin of the shard that OWNS that
                # coordinate's entity id
                plans.append(("margins", shard_set, idxs,
                              list(range(len(legs),
                                         len(legs) + len(shard_set)))))
                for s in shard_set:
                    legs.append((s, "POST", "/score",
                                 {"records": recs, "margins": True},
                                 headers))
        with _tracing.span("fleet.score", request_id=request_id,
                           batch=len(records), legs=len(legs)):
            bodies = self._gather(legs)
        lineage = self._check_lineage(bodies)
        scores: list = [None] * len(records)
        merged = 0
        version = None
        for mode, shard_set, idxs, leg_ids in plans:
            if mode == "direct":
                body = bodies[leg_ids[0]]
                if version is None or shard_set[0] == 0:
                    version = body.get("version")
                for j, i in enumerate(idxs):
                    scores[i] = body["scores"][j]
                continue
            merged += len(idxs)
            by_shard = {s: bodies[leg_id]
                        for s, leg_id in zip(shard_set, leg_ids)}
            primary = by_shard[shard_set[0]]
            if version is None:
                version = primary.get("version")
            margins_of = {s: dict(b["margins"])
                          for s, b in by_shard.items()}
            offsets = np.asarray(primary["offsets"], np.float32)
            merged_margins = []
            for cid, etype in coordinates:
                vals = np.empty(len(idxs), np.float32)
                for j, i in enumerate(idxs):
                    meta = records[i].get("metadataMap") or {}
                    raw = None if etype is None else meta.get(etype)
                    owner = (shard_set[0] if raw in (None, "")
                             else shard_of_id(str(raw), self.n_shards))
                    vals[j] = np.float32(margins_of[owner][cid][j])
                merged_margins.append(vals)
            # THE score-summation contract, re-run over the owner-shard
            # margins: same f64 accumulation, same coordinate order, same
            # f32 inputs ⇒ the same f32 totals the hosts would produce
            totals = sum_coordinate_margins(offsets, merged_margins, xp=np)
            for j, i in enumerate(idxs):
                scores[i] = float(totals[j])
        with self._lock:
            self.n_requests += 1
        _FLEET_REQUESTS.labels(endpoint="score").inc()
        out = {"scores": scores, "version": version, "lineage": lineage,
               "request_id": request_id,
               "fanout": {"legs": len(legs), "merged": merged}}
        if deadline is not None:
            out["deadline_ms"] = round(self.remaining_ms(deadline), 1)
        return out

    # --- /rank ------------------------------------------------------------
    def rank(self, payload: dict,
             request_id: Optional[str] = None,
             deadline: Optional[float] = None) -> dict:
        """Fleet ``/rank``: fan the request to every host (each ranks its
        own item shard) and merge the top-k by score (ties break by shard
        then within-shard rank — single-host tie order is the global item
        axis, unrecoverable across a hash partition; real trained scores
        are distinct). Models with user-side random-effect coordinates
        are refused: a sharded user store would zero the user's margin on
        foreign hosts."""
        if request_id is None:
            request_id = new_request_id()
        _coordinates, rank_info = self.topology()
        if not rank_info:
            raise ValueError("ranking is not enabled on the fleet's hosts "
                             "(start them with --rank-item-coordinate)")
        if rank_info.get("user_re_coordinates"):
            raise ValueError(
                f"fleet ranking requires the item coordinate to be the "
                f"only random effect; user-side RE coordinates "
                f"{rank_info['user_re_coordinates']} would rank with the "
                f"user's margin zeroed on foreign shards")
        try:
            k = int(payload.get("k", min(10, int(rank_info["max_k"]))))
        except (TypeError, ValueError):
            raise ValueError(
                f"bad k {payload.get('k')!r} (want an integer)") from None
        if deadline is not None and time.monotonic() >= deadline:
            raise _overload.shed(
                "deadline", message="deadline expired before fan-out")
        leg_payload = {key: payload[key]
                       for key in ("record", "user") if key in payload}
        leg_payload["k"] = k
        headers = self._leg_headers(request_id, deadline)
        legs = [(s, "POST", "/rank", leg_payload, headers)
                for s in range(self.n_shards)]
        with _tracing.span("fleet.rank", request_id=request_id, k=k,
                           legs=len(legs)):
            bodies = self._gather(legs)
        lineage = self._check_lineage(bodies)
        ranked = []  # (-score, shard, within-shard rank, id)
        for shard, body in enumerate(bodies):
            for pos, (item, score) in enumerate(zip(body["ids"],
                                                    body["scores"])):
                ranked.append((-float(score), shard, pos, str(item)))
        ranked.sort()
        top = ranked[:k]
        with self._lock:
            self.n_requests += 1
        _FLEET_REQUESTS.labels(endpoint="rank").inc()
        out = {"ids": [item for _s, _sh, _p, item in top],
               "scores": [-neg for neg, _sh, _p, _i in top],
               "k": k, "lineage": lineage, "request_id": request_id,
               "version": bodies[0].get("version")}
        if deadline is not None:
            out["deadline_ms"] = round(self.remaining_ms(deadline), 1)
        return out

    # --- two-phase /reload ------------------------------------------------
    def reload(self, payload: dict,
               request_id: Optional[str] = None) -> dict:
        """Coordinated two-phase activation. ``model_dir`` names one
        candidate for every host; ``model_dirs`` (length N) names
        per-host candidates — the ``refresh_game --fleet-shards`` patch
        layout. Phase 1 (``prepare``) runs each host's full
        validate+canary+warm gate; ANY refusal — or the prepared
        candidates disagreeing on lineage — aborts the epoch (prepared
        versions retired, incumbent serving fleet-wide, 409 up). Phase 2
        activates every host's prepared version."""
        if request_id is None:
            request_id = new_request_id()
        dirs = payload.get("model_dirs")
        if dirs is None:
            model_dir = payload.get("model_dir")
            if not model_dir:
                raise ValueError("payload needs 'model_dir' (one for the "
                                 "whole fleet) or 'model_dirs' (one per "
                                 "host)")
            dirs = [model_dir] * self.n_shards
        if len(dirs) != self.n_shards:
            raise ValueError(f"'model_dirs' must name {self.n_shards} "
                             f"dirs (one per host), got {len(dirs)}")
        headers = self._leg_headers(request_id, None)
        _FLEET_REQUESTS.labels(endpoint="reload").inc()
        with _tracing.span("fleet.reload", request_id=request_id):
            # --- phase 1: every host validates, canaries and warms ------
            futures = [self._pool.submit(
                self._leg, s, "POST", "/reload",
                {"model_dir": dirs[s], "phase": "prepare"}, headers)
                for s in range(self.n_shards)]
            prepared: list = [None] * self.n_shards
            errors: dict[int, str] = {}
            for s, fut in enumerate(futures):
                try:
                    prepared[s] = fut.result()
                except Exception as e:
                    errors[s] = repr(e)
            lineages = {body["lineage"] for body in prepared
                        if body is not None}
            if not errors and len(lineages) > 1:
                errors[-1] = (f"prepared candidates disagree on lineage "
                              f"{sorted(str(x) for x in lineages)}")
            if errors:
                # --- abort: retire whatever prepared; incumbent serves
                self._abort(prepared, dirs, headers)
                _EPOCHS.labels(outcome="aborted").inc()
                raise RuntimeError(
                    f"two-phase reload aborted — incumbent keeps serving "
                    f"fleet-wide; refusals: "
                    + "; ".join(f"shard {s}: {err}"
                                for s, err in sorted(errors.items())))
            # --- phase 2: activate everywhere ---------------------------
            activations = self._gather([
                (s, "POST", "/reload",
                 {"phase": "activate", "version": prepared[s]["version"]},
                 headers)
                for s in range(self.n_shards)])
        _EPOCHS.labels(outcome="activated").inc()
        # coordinate structure may have changed (it rarely does) — the
        # next request routes on the fresh topology either way
        self.topology(refresh=True)
        return {"lineage": next(iter(lineages)),
                "versions": [a["version"] for a in activations],
                "previous": [a.get("previous") for a in activations],
                "request_id": request_id}

    def _abort(self, prepared: Sequence[Optional[dict]],
               dirs: Sequence[str], headers: dict) -> None:
        """Best-effort retire of every prepared-but-unactivated version.
        A host that cannot be reached keeps the version registered (never
        ACTIVE — it pins some memory until the next successful epoch or
        restart, it cannot serve)."""
        for s, body in enumerate(prepared):
            if body is None:
                continue
            try:
                self._leg(s, "POST", "/reload",
                          {"phase": "abort", "version": body["version"]},
                          headers)
            except Exception:
                pass  # the abort is advisory; the version was never active

    # --- health + metrics -------------------------------------------------
    def healthz(self) -> dict:
        hosts = []
        for s in range(self.n_shards):
            try:
                body = self._leg(s, "GET", "/healthz")
                hosts.append({"shard": s, "url": self.clients[s].url,
                              "status": body.get("status"),
                              "version": body.get("version"),
                              "lineage": body.get("model_lineage_id"),
                              "fleet_shard": body.get("fleet_shard")})
            except Exception as e:
                hosts.append({"shard": s, "url": self.clients[s].url,
                              "status": "unreachable", "error": repr(e)})
        lineages = {h.get("lineage") for h in hosts
                    if h.get("status") == "ok"}
        return {"status": "ok" if all(h.get("status") == "ok"
                                      for h in hosts) else "degraded",
                "n_shards": self.n_shards,
                "requests": self.n_requests,
                "mixed_lineage": len(lineages) > 1,
                "hosts": hosts,
                "shed": _overload.shed_counts()}

    def readyz(self) -> "tuple[int, dict]":
        """Ready iff EVERY shard's host is ready — a fleet missing a
        shard serves wrong-by-omission scores for that shard's entities,
        so it is not ready, merely alive."""
        reasons = []
        for s in range(self.n_shards):
            try:
                status, body = self.clients[s].request("GET", "/readyz")
                if status != 200:
                    reasons.append(
                        f"shard {s}: {','.join(body.get('reasons', []))}")
            except Exception as e:
                reasons.append(f"shard {s}: unreachable ({e!r})")
        body = {"ready": not reasons, "reasons": reasons,
                "n_shards": self.n_shards}
        return (200 if not reasons else 503), body

    def host_metrics_texts(self) -> "list[str]":
        """Each host's raw ``/metrics`` exposition text, in shard order
        (unreachable hosts contribute an empty snapshot — a scrape must
        not fail because one host is down)."""
        import urllib.request

        texts = []
        for s in range(self.n_shards):
            client = self.clients[s]
            try:
                with urllib.request.urlopen(client.url + "/metrics",
                                            timeout=client.timeout_s
                                            ) as resp:
                    texts.append(resp.read().decode())
            except Exception:
                texts.append("")
        return texts

    def metrics_text(self) -> str:
        """The fleet-folded exposition: the router's own registry first
        (chief semantics), then every host's snapshot tagged
        ``process="<shard>"`` so host-owned gauges fan out — the same
        fold, fed the same texts, as ``tools/metrics_fold.py`` offline
        (byte-identical; the tier-1 fold-consistency test locks it)."""
        from photon_ml_tpu.telemetry.prometheus import render

        return fold_fleet_texts(render(), self.host_metrics_texts())

    def close(self) -> None:
        self._pool.shutdown(wait=True)
        for client in self.clients:
            client.close()


def fold_fleet_texts(router_text: str, host_texts: Sequence[str]) -> str:
    """The fleet metric fold: router snapshot (chief-first) + per-host
    snapshots with host-owned gauges tagged ``process="<shard>"``,
    through the ONE merge code path (``telemetry/aggregate.py``)."""
    from photon_ml_tpu.telemetry.aggregate import aggregate_text

    texts = [router_text]
    for shard, text in enumerate(host_texts):
        if text:
            texts.append(tag_host_owned(text, ("process", str(shard))))
    return aggregate_text(texts)


def tag_host_owned(text: str, tag: "tuple[str, str]") -> str:
    """Append ``tag`` to every host-owned gauge series of an exposition
    text (``metrics.mark_host_owned`` declares which). Training renders
    do this at render time (``render(host_tag=...)``); the router
    re-tags hosts' already-rendered scrapes — same label, same fan-out
    semantics."""
    from photon_ml_tpu.telemetry.metrics import host_owned_gauges
    from photon_ml_tpu.telemetry.prometheus import parse_text, render

    snapshot = parse_text(text)
    owned = host_owned_gauges()
    key, value = tag
    for name, fam in snapshot.families.items():
        if fam.get("type") != "gauge" or name not in owned:
            continue
        snapshot[name] = [({**labels, key: value}, v)
                          for labels, v in snapshot.get(name, ())]
    return render(snapshot)


# ---------------------------------------------------------------------------
# the HTTP front (thin marshaling, like serving/http.py's handler)
# ---------------------------------------------------------------------------


def _make_handler(router: FleetRouter):
    class Handler(BaseHTTPRequestHandler):
        # persistent connections, like the serving front end (every
        # reply carries Content-Length)
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # noqa: D102
            pass

        def _request_id(self) -> str:
            inbound = self.headers.get(REQUEST_ID_HEADER)
            self.request_id = inbound.strip() if inbound \
                else new_request_id()
            return self.request_id

        def _reply(self, status: int, body: dict,
                   headers: Optional[dict] = None) -> None:
            data = json.dumps(body).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            rid = getattr(self, "request_id", None)
            if rid is not None:
                self.send_header(REQUEST_ID_HEADER, rid)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def _payload(self) -> dict:
            length = int(self.headers.get("Content-Length") or 0)
            if not length:
                return {}
            return json.loads(self.rfile.read(length) or b"{}")

        def _dispatch(self, rid: str, fn, payload: dict,
                      deadline: Optional[float]) -> None:
            headers = None
            try:
                out = fn(payload, request_id=rid, deadline=deadline)
                status = 200
            except _overload.Shed as e:
                out = {"error": str(e), "reason": e.reason,
                       "request_id": rid}
                status = shed_status(e)
                headers = {"Retry-After":
                           str(max(1, round(e.retry_after_s)))}
            except MixedLineageError as e:
                out = {"error": str(e), "reason": "mixed_lineage",
                       "request_id": rid}
                status = 503
            except ValueError as e:
                out, status = {"error": str(e)}, 400
            except Exception as e:
                out, status = {"error": repr(e)}, 500
            self._reply(status, out, headers=headers)

        def do_GET(self):  # noqa: N802
            rid = self._request_id()
            parsed = urllib.parse.urlsplit(self.path)
            if parsed.path == "/rank":
                qs = urllib.parse.parse_qs(parsed.query)
                payload = {key: values[0] for key, values in qs.items()
                           if values}
                try:
                    deadline = router.resolve_deadline(
                        self.headers.get(DEADLINE_HEADER))
                except ValueError as e:
                    self._reply(400, {"error": str(e)})
                    return
                self._dispatch(rid, router.rank, payload, deadline)
            elif parsed.path == "/healthz":
                self._reply(200, router.healthz())
            elif parsed.path == "/readyz":
                status, body = router.readyz()
                self._reply(status, body)
            elif parsed.path == "/metrics":
                from photon_ml_tpu.telemetry.prometheus import CONTENT_TYPE

                data = router.metrics_text().encode()
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):  # noqa: N802
            rid = self._request_id()
            try:
                payload = self._payload()
                deadline = router.resolve_deadline(
                    self.headers.get(DEADLINE_HEADER))
            except (ValueError, json.JSONDecodeError) as e:
                self._reply(400, {"error": f"bad request: {e}"})
                return
            if self.path == "/score":
                self._dispatch(rid, router.score, payload, deadline)
            elif self.path == "/rank":
                self._dispatch(rid, router.rank, payload, deadline)
            elif self.path == "/reload":
                try:
                    self._reply(200, router.reload(payload,
                                                   request_id=rid))
                except Exception as e:
                    # an aborted epoch is a CONFLICT: the incumbent is
                    # untouched on every host, exactly like a single
                    # host's rejected /reload
                    self._reply(409, {"error": repr(e)})
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

    return Handler


class RouterServer:
    """Threaded HTTP wrapper for :class:`FleetRouter` — the same
    test-friendly lifecycle as ``serving/http.py::GameServer``."""

    def __init__(self, router: FleetRouter, *, host: str = "127.0.0.1",
                 port: int = 0):
        self.router = router
        self._httpd = ThreadingHTTPServer((host, port),
                                          _make_handler(router))
        #: start/stop are operator-lifecycle calls from one control thread
        self._thread: Optional[threading.Thread] = None  # guarded-by: caller

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "RouterServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="photon-fleet-router")
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join()
        self.router.close()
