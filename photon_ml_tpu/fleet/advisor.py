"""Hot-shard advisor: the observation half of autonomous elasticity.

ROADMAP's "elastic fleet, next steps" names a load-watcher that notices
a shard running hot and proposes a rebalance. This module is that
watcher — deliberately **read-only**: it consumes the router's retained
history ring (:mod:`photon_ml_tpu.telemetry.history`), never fresh
scrapes, so advice is a pure function of evidence the operator can
replay (``GET /history`` shows exactly the ticks that tripped it), and
it *recommends* a :meth:`~photon_ml_tpu.fleet.sharding.ShardMap.rebalanced`
move list without ever driving ``/reshard`` itself — acting stays a
human (or a later PR's autopilot) decision.

Detection is hysteresis-latched like the SLO burn tracker: a shard must
hold a skew ratio (its p99 — or smoothed in-flight load — versus the
median of its peers) at or above ``enter_ratio`` for ``sustain_ticks``
CONSECUTIVE history ticks to latch hot (one edge-triggered
``hot_shard_detected`` event, ``photon_hot_shard{shard}`` → 1), and must
hold BELOW ``exit_ratio`` for ``sustain_ticks`` ticks to unlatch — the
enter/exit gap is what makes a ratio oscillating between the thresholds
produce zero flaps.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from photon_ml_tpu.telemetry import metrics as _metrics

#: 1 while the advisor currently flags the shard hot (hysteresis-latched
#: skew vs peer shards), 0 after it cools — the edge-triggered
#: ``hot_shard_detected`` event marks each rising edge
_HOT = _metrics.gauge(
    "photon_hot_shard",
    "1 while the hot-shard advisor flags the shard (sustained p99/load "
    "skew vs peer shards, hysteresis-latched), else 0",
    labels=("shard",))

#: smoothing constant for the load ratio — in-flight leg counts are
#: small integers, so compare (load+1)/(median+1) rather than divide by
#: a frequently-zero median
_LOAD_SMOOTH = 1.0

#: latency floor for the p99 ratio denominator: below this the fleet is
#: effectively idle and a "ratio" is noise, not skew
DEFAULT_MIN_P99_S = 1e-4


class HotShardAdvisor:
    """Sustained per-shard skew detection over the history ring.

    ``tick()`` consumes the ring's NEWEST snapshot (at most once per
    snapshot — re-ticks on the same history tick are no-ops, so wiring
    it as a sampler listener and calling it from a poll loop cannot
    double-count sustain evidence) and returns the list of rising-edge
    detections. ``status()`` is the ``GET /advisor`` body.
    """

    def __init__(self, *, history, shard_map_fn: Callable,
                 bus=None, enter_ratio: float = 2.0,
                 exit_ratio: float = 1.25, sustain_ticks: int = 3,
                 min_p99_s: float = DEFAULT_MIN_P99_S):
        if exit_ratio >= enter_ratio:
            raise ValueError(
                f"hysteresis needs exit_ratio < enter_ratio, got "
                f"exit={exit_ratio} enter={enter_ratio}")
        if sustain_ticks <= 0:
            raise ValueError(
                f"sustain_ticks must be > 0, got {sustain_ticks}")
        self._history = history
        self._shard_map_fn = shard_map_fn
        self._bus = bus
        self.enter_ratio = float(enter_ratio)
        self.exit_ratio = float(exit_ratio)
        self.sustain_ticks = int(sustain_ticks)
        self.min_p99_s = float(min_p99_s)
        self._lock = threading.Lock()
        self._last_history_tick = 0  # guarded-by: _lock
        self._above: dict[int, int] = {}  # guarded-by: _lock
        self._below: dict[int, int] = {}  # guarded-by: _lock
        self._hot: set[int] = set()  # guarded-by: _lock
        self._last_skew: dict[int, dict] = {}  # guarded-by: _lock
        self._ticks = 0  # guarded-by: _lock
        self._detections = 0  # guarded-by: _lock

    # ------------------------------------------------------------------
    # skew
    # ------------------------------------------------------------------

    @staticmethod
    def _median(values: "list[float]") -> float:
        ordered = sorted(values)
        n = len(ordered)
        mid = n // 2
        if n % 2:
            return ordered[mid]
        return (ordered[mid - 1] + ordered[mid]) / 2.0

    def _skew_of(self, snapshot: dict) -> dict[int, dict]:
        """Per-shard skew evidence from one history snapshot: each
        shard's p99 and smoothed load against the MEDIAN of the other
        shards (median, not mean — one hot shard must not drag the
        baseline it is measured against)."""
        series = snapshot.get("series", {})
        p99 = {int(k): float(v)
               for k, v in (series.get("shard_p99") or {}).items()}
        load = {int(k): float(v)
                for k, v in (series.get("shard_load") or {}).items()}
        # the capacity plane's bottleneck attribution (history series
        # ``shard_binding``): which resource is most utilized on each
        # shard's hosts — "unknown" when the fleet predates the
        # saturation sampler or it is not armed
        binding = {str(k): str(v)
                   for k, v in (series.get("shard_binding") or {}).items()}
        shards = sorted(set(p99) | set(load))
        out: dict[int, dict] = {}
        if len(shards) < 2:
            return out  # skew needs peers to be skewed against
        for s in shards:
            peer_p99 = [p99.get(o, 0.0) for o in shards if o != s]
            peer_load = [load.get(o, 0.0) for o in shards if o != s]
            p99_base = max(self._median(peer_p99), self.min_p99_s)
            p99_ratio = p99.get(s, 0.0) / p99_base
            load_ratio = ((load.get(s, 0.0) + _LOAD_SMOOTH)
                          / (self._median(peer_load) + _LOAD_SMOOTH))
            out[s] = {"p99_s": p99.get(s, 0.0),
                      "p99_ratio": round(p99_ratio, 4),
                      "load": load.get(s, 0.0),
                      "load_ratio": round(load_ratio, 4),
                      "skew": round(max(p99_ratio, load_ratio), 4),
                      "binding_resource": binding.get(str(s), "unknown")}
        return out

    # ------------------------------------------------------------------
    # the tick
    # ------------------------------------------------------------------

    def tick(self) -> "list[dict]":
        """Consume the newest history snapshot; return rising-edge
        detections (also posted as ``hot_shard_detected`` bus events and
        reflected in ``photon_hot_shard{shard}``)."""
        snaps = self._history.snapshots(window=1)
        if not snaps:
            return []
        snap = snaps[-1]
        detections: list[dict] = []
        cleared: list[int] = []
        with self._lock:
            if snap["tick"] <= self._last_history_tick:
                return []  # already consumed — sustain needs NEW evidence
            self._last_history_tick = snap["tick"]
            self._ticks += 1
            skew = self._skew_of(snap)
            self._last_skew = skew
            for s, evidence in skew.items():
                score = evidence["skew"]
                if score >= self.enter_ratio:
                    self._above[s] = self._above.get(s, 0) + 1
                else:
                    self._above[s] = 0
                if score < self.exit_ratio:
                    self._below[s] = self._below.get(s, 0) + 1
                else:
                    self._below[s] = 0
                if (s not in self._hot
                        and self._above[s] >= self.sustain_ticks):
                    self._hot.add(s)
                    self._detections += 1
                    detections.append({
                        "shard": s, "history_tick": snap["tick"],
                        "sustained_ticks": self._above[s], **evidence})
                elif (s in self._hot
                        and self._below[s] >= self.sustain_ticks):
                    self._hot.discard(s)
                    cleared.append(s)
            for s in list(self._above):
                if s not in skew:  # shard left the topology
                    self._above.pop(s, None)
                    self._below.pop(s, None)
                    if s in self._hot:
                        self._hot.discard(s)
                        cleared.append(s)
        for s in cleared:
            _HOT.labels(shard=str(s)).set(0.0)
            if self._bus is not None:
                self._bus.post("hot_shard_cleared", shard=s)
        for det in detections:
            _HOT.labels(shard=str(det["shard"])).set(1.0)
            if self._bus is not None:
                self._bus.post("hot_shard_detected", **det)
        return detections

    # ------------------------------------------------------------------
    # advice
    # ------------------------------------------------------------------

    def recommendation(self) -> Optional[dict]:
        """The advised (NOT executed) move list while any shard is hot:
        the minimal-movement ``ShardMap.rebalanced(n_shards + 1)``
        scale-out, i.e. exactly the buckets an operator would POST to
        ``/reshard`` after standing up one more shard. ``None`` while
        the fleet is cool."""
        with self._lock:
            hot = sorted(self._hot)
            skew = self._last_skew
            bindings = {str(s): skew[s].get("binding_resource", "unknown")
                        for s in hot if s in skew}
        if not hot:
            return None
        smap = self._shard_map_fn()
        target = smap.rebalanced(smap.n_shards + 1)
        moves = {b: target.buckets[b] for b in smap.moved_buckets(target)}
        from_hot = sum(1 for b in moves if smap.buckets[b] in hot)
        return {
            "kind": "scale_out",
            "n_shards": target.n_shards,
            "base_version": smap.version,
            "base_hash": smap.map_hash,
            "n_moves": len(moves),
            "moves_from_hot": from_hot,
            # the binding resource of each hot shard, so the operator
            # reading the advice knows WHAT the extra shard relieves
            # (scale-out cures device/queue pressure; a connection-bound
            # shard may want --max-connections raised instead)
            "binding_resources": bindings,
            "moves": {str(b): moves[b] for b in sorted(moves)},
        }

    def status(self) -> dict:
        """The ``GET /advisor`` body — hot set, per-shard evidence from
        the last consumed tick, hysteresis parameters, and the current
        recommendation."""
        with self._lock:
            hot = sorted(self._hot)
            skew = {str(s): dict(v) for s, v in self._last_skew.items()}
            ticks = self._ticks
            detections = self._detections
            history_tick = self._last_history_tick
        return {
            "hot": hot,
            "shards": skew,
            "ticks": ticks,
            "detections": detections,
            "history_tick": history_tick,
            "params": {"enter_ratio": self.enter_ratio,
                       "exit_ratio": self.exit_ratio,
                       "sustain_ticks": self.sustain_ticks},
            "recommendation": self.recommendation(),
        }
