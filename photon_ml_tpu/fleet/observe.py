"""The fleet observability plane: one endpoint observes N×R hosts.

PRs 15–17 grew an N-shard × R-replica serving fleet, but each host still
exposed only its OWN registry, router fan-out legs vanished from the
request's span tree at the process boundary, and shard heat lived in
private router deques. This module is the missing plane
(OBSERVABILITY.md "Fleet observability"):

- **Live fleet metrics fold** — :class:`FleetObserver` scrapes every
  host's ``/metrics`` over the router's EXISTING pooled connections
  (``HostClient.request(raw=True)``; each scrape visits the
  ``fleet.fanout`` fault site like any other leg) and folds the texts
  through :func:`photon_ml_tpu.telemetry.aggregate.aggregate_text` —
  counters/histograms sum, host-owned gauges fan out tagged
  ``shard="I"``, ``replica="J"`` (:func:`tag_host_owned`). The SAME
  tagging feeds ``tools/metrics_fold.py`` offline over dumped host
  snapshots, so live and offline folds are byte-identical. A host that
  fails mid-scrape is annotated in
  ``photon_fleet_scrape_errors_total{shard,replica}`` and the PARTIAL
  fold is served — one dead replica must not 500 fleet observability.
- **Per-shard heat** — the router's latency deques and in-flight leg
  counts surface as ``photon_fleet_shard_{p50,p99}_seconds{shard}`` and
  ``photon_fleet_shard_load{shard}``, refreshed at scrape time. This is
  the signal surface ROADMAP's *autonomous elasticity* load-watcher
  reads.
- **SLO burn rate** — :class:`SloBurnTracker`: multi-window, tick-driven
  (monotonic clock, injectable for tests), edge-triggered
  ``slo_burn_alert`` EventBus posts that the telemetry bridge counts
  into ``photon_slo_burn_total{window}``.
- **Topology** — :meth:`FleetObserver.statusz` (router ``GET
  /statusz``): shard-map hash/version, per-host lineage/health/
  last-scrape age, per-shard replica-up counts and heat, SLO status.
  ``tools/fleet_report.py`` renders it deterministically.

Cross-host trace stitching lives in the router itself (``fleet.leg``
spans + the ``X-Photon-Leg-Summary`` header contract from
``serving/http.py``); this module only owns the metrics/SLO half.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Optional, Sequence

from photon_ml_tpu.telemetry import metrics as _metrics

#: host scrapes that failed during a fleet /metrics fold — the partial
#: fold is served with this annotation instead of a 500
_SCRAPE_ERRORS = _metrics.counter(
    "photon_fleet_scrape_errors_total",
    "Host registry scrapes that failed during a fleet /metrics fold "
    "(the partial fold is served; the hole is annotated here)",
    labels=("shard", "replica"))

#: per-shard leg-latency percentiles from the router's hedging deques —
#: the hot-shard signal the autonomous-elasticity watcher will read
_SHARD_P50 = _metrics.gauge(
    "photon_fleet_shard_p50_seconds",
    "Median fan-out leg latency per shard (router's recent-leg window)",
    labels=("shard",))
_SHARD_P99 = _metrics.gauge(
    "photon_fleet_shard_p99_seconds",
    "p99 fan-out leg latency per shard (the hedge-delay signal)",
    labels=("shard",))

#: legs in flight against each shard right now (sampled at scrape)
_SHARD_LOAD = _metrics.gauge(
    "photon_fleet_shard_load",
    "Fan-out legs currently in flight against each shard",
    labels=("shard",))


# ---------------------------------------------------------------------------
# the fold
# ---------------------------------------------------------------------------


def tag_host_owned(text: str, tags) -> str:
    """Append label ``tags`` — one ``(key, value)`` pair or a sequence of
    them — to every host-owned gauge series of an exposition text
    (``metrics.mark_host_owned`` declares which). Training renders tag at
    render time (``render(host_tag=...)``); the fleet re-tags hosts'
    already-rendered scrapes — same labels, same fan-out semantics."""
    from photon_ml_tpu.telemetry.metrics import host_owned_gauges
    from photon_ml_tpu.telemetry.prometheus import parse_text, render

    if tags and isinstance(tags[0], str):
        tags = (tags,)
    extra = dict(tags)
    snapshot = parse_text(text)
    owned = host_owned_gauges()
    for name, fam in snapshot.families.items():
        if fam.get("type") != "gauge" or name not in owned:
            continue
        snapshot[name] = [({**labels, **extra}, v)
                          for labels, v in snapshot.get(name, ())]
    return render(snapshot)


def fold_fleet_snapshots(router_text: str,
                         host_snapshots: Sequence[tuple]) -> str:
    """The fleet metric fold: router snapshot (chief-first), then each
    ``(shard, replica, text)`` host snapshot in shard-major order with
    host-owned gauges tagged ``shard="I"``, ``replica="J"`` (distinct
    label sets, so every replica's gauge survives the merge's gauge
    owner semantics), through the ONE merge code path
    (``telemetry/aggregate.py``). Feeding the same texts in the same
    order offline (``tools/metrics_fold.py``) is byte-identical."""
    from photon_ml_tpu.telemetry.aggregate import aggregate_text

    texts = [router_text]
    for shard, replica, text in host_snapshots:
        if text:
            texts.append(tag_host_owned(
                text, (("shard", str(shard)), ("replica", str(replica)))))
    return aggregate_text(texts)


# ---------------------------------------------------------------------------
# SLO burn rate
# ---------------------------------------------------------------------------


class SloBurnTracker:
    """Multi-window SLO burn-rate tracking, tick-driven and pure.

    ``observe(seconds, ok)`` classifies each request against the latency
    objective (an error is always bad). ``tick(now=...)`` closes the
    current accumulation bucket and evaluates every window: burn rate =
    (bad fraction over the window) / (1 - target) — burn 1.0 spends the
    error budget exactly at the sustainable rate; the default thresholds
    (14.4× over the short window, 6× over the long) are the classic
    fast/slow-burn paging pair. Crossing a threshold posts ONE
    edge-triggered ``slo_burn_alert`` on the bus (→
    ``photon_slo_burn_total{window}`` via the telemetry bridge) and
    re-arms when the window drops back under.

    Time is ``time.monotonic()`` by default and injectable everywhere
    (``tick(now=...)``), so tests drive synthetic regressions through
    real code without sleeping. Windows are ``(name, span_s,
    threshold)`` triples; bucket history is bounded by the longest
    window.
    """

    DEFAULT_WINDOWS = (("5m", 300.0, 14.4), ("1h", 3600.0, 6.0))

    def __init__(self, bus, *, objective_s: float, target: float = 0.999,
                 windows: Optional[Sequence[tuple]] = None):
        if not 0.0 < target < 1.0:
            raise ValueError(f"target must be in (0, 1), got {target}")
        self.bus = bus
        self.objective_s = float(objective_s)
        self.target = float(target)
        self.windows = tuple(windows if windows is not None
                             else self.DEFAULT_WINDOWS)
        self._horizon = max(span for _name, span, _thr in self.windows)
        self._lock = threading.Lock()
        self._good = 0  # guarded-by: _lock
        self._bad = 0  # guarded-by: _lock
        #: closed (tick_time, good, bad) buckets, newest last
        self._buckets: collections.deque = collections.deque()  # guarded-by: _lock  # photon-lint: disable=res-bounded-queue -- pruned to the longest window at every tick below
        #: per-window "currently burning" latch (edge-triggered alerts)
        self._active = {name: False
                        for name, _s, _t in self.windows}  # guarded-by: _lock
        self._status: list = []  # guarded-by: _lock

    def observe(self, seconds: float, ok: bool = True) -> None:
        """One request's outcome: bad = an error OR a latency past the
        objective. Cheap (a lock + an increment) — safe on the hot path."""
        bad = (not ok) or float(seconds) > self.objective_s
        with self._lock:
            if bad:
                self._bad += 1
            else:
                self._good += 1

    def tick(self, now: Optional[float] = None) -> list:
        """Close the current bucket and evaluate every window; returns
        the alerts fired THIS tick (also posted on the bus)."""
        now = time.monotonic() if now is None else float(now)
        alerts = []
        with self._lock:
            self._buckets.append((now, self._good, self._bad))
            self._good = self._bad = 0
            while self._buckets and self._buckets[0][0] < now - self._horizon:
                self._buckets.popleft()
            status = []
            for name, span, threshold in self.windows:
                good = bad = 0
                for t, g, b in self._buckets:
                    if t >= now - span:
                        good += g
                        bad += b
                total = good + bad
                bad_fraction = bad / total if total else 0.0
                burn = bad_fraction / (1.0 - self.target)
                burning = total > 0 and burn >= threshold
                if burning and not self._active[name]:
                    alerts.append({"window": name,
                                   "burn_rate": round(burn, 3),
                                   "threshold": threshold,
                                   "bad": bad, "total": total})
                self._active[name] = burning
                status.append({"window": name, "span_s": span,
                               "burn_rate": round(burn, 3),
                               "threshold": threshold,
                               "burning": burning,
                               "bad": bad, "total": total})
            self._status = status
        for alert in alerts:
            self.bus.post("slo_burn_alert",
                          objective_ms=self.objective_s * 1e3,
                          target=self.target, **alert)
        return alerts

    def status(self) -> list:
        """Per-window burn state as of the last tick (for ``/statusz``)."""
        with self._lock:
            return [dict(entry) for entry in self._status]


# ---------------------------------------------------------------------------
# the observer
# ---------------------------------------------------------------------------


class FleetObserver:
    """The router's observability surface: pooled-connection scrapes,
    heat-gauge refresh, scrape bookkeeping for ``/statusz``, and the
    optional SLO tracker. Constructed by every :class:`~photon_ml_tpu.
    fleet.router.FleetRouter` (no threads, no cost until scraped);
    :meth:`attach_slo` adds burn-rate tracking and, with ``tick_s > 0``,
    the tick thread the serve_fleet driver runs it on."""

    def __init__(self, router):
        self.router = router
        #: attach_slo/close are operator-lifecycle calls from one
        #: control thread (like RouterServer start/stop)
        self.slo: Optional[SloBurnTracker] = None  # guarded-by: caller
        #: router-side history ring (serve_fleet arms it) — the fleet
        #: timeline folds per-host rings against this one's snapshots
        self.history_sampler = None  # guarded-by: caller
        self._lock = threading.Lock()
        #: (shard, replica) -> {"t": monotonic stamp, "ok", "error"}
        self._last_scrape: dict = {}  # guarded-by: _lock
        self._tick_thread: Optional[
            threading.Thread] = None  # guarded-by: caller
        self._stop = threading.Event()

    # --- SLO --------------------------------------------------------------
    def attach_slo(self, tracker: SloBurnTracker,
                   tick_s: float = 0.0) -> "FleetObserver":
        self.slo = tracker
        if tick_s > 0:
            self._tick_thread = threading.Thread(
                target=self._tick_loop, args=(float(tick_s),),
                daemon=True, name="photon-fleet-slo")
            self._tick_thread.start()
        return self

    def _tick_loop(self, tick_s: float) -> None:
        while not self._stop.wait(tick_s):
            self.slo.tick()

    def observe_request(self, seconds: float, ok: bool = True) -> None:
        """Feed one routed request's outcome to the SLO tracker (no-op
        without one attached)."""
        if self.slo is not None:
            self.slo.observe(seconds, ok=ok)

    def close(self) -> None:
        self._stop.set()
        if self._tick_thread is not None:
            self._tick_thread.join()
            self._tick_thread = None

    # --- scraping ---------------------------------------------------------
    def scrape(self) -> "list[tuple[int, int, str]]":
        """Every live host's raw ``/metrics`` text over the pooled
        connections, shard-major ``(shard, replica, text)``. A failed or
        timed-out host contributes NOTHING except a
        ``photon_fleet_scrape_errors_total{shard,replica}`` increment
        and a failed last-scrape entry — the fold stays partial, never
        raises. Each scrape is a leg: it visits the ``fleet.fanout``
        fault site, so chaos coverage includes scraping through
        faults."""
        snapshots = []
        for s, group in enumerate(self.router.clients):
            for r, client in enumerate(group):
                try:
                    status, text = client.request("GET", "/metrics",
                                                  raw=True)
                    if status != 200:
                        raise RuntimeError(f"/metrics -> {status}")
                    snapshots.append((s, r, text))
                    self._note(s, r, ok=True)
                except Exception as e:
                    _SCRAPE_ERRORS.labels(shard=str(s),
                                          replica=str(r)).inc()
                    self._note(s, r, ok=False, error=repr(e))
        return snapshots

    def _note(self, shard: int, replica: int, ok: bool,
              error: Optional[str] = None) -> None:
        with self._lock:
            self._last_scrape[(shard, replica)] = {
                "t": time.monotonic(), "ok": ok, "error": error}

    # --- retained history -------------------------------------------------
    def attach_history(self, sampler) -> "FleetObserver":
        """Arm the router-side history ring (a
        :class:`~photon_ml_tpu.telemetry.history.HistorySampler` whose
        ``pre_sample`` refreshes the heat gauges, so every snapshot
        carries shard p50/p99/load)."""
        self.history_sampler = sampler
        return self

    def scrape_history(self) -> "list[tuple[int, int, list]]":
        """Every live host's retained ring (``GET /history?raw=1`` over
        the pooled connections), shard-major ``(shard, replica,
        snapshots)``. Failure semantics mirror :meth:`scrape`: a dead
        host is annotated and skipped, the fold stays partial."""
        import json as _json

        rings = []
        for s, group in enumerate(self.router.clients):
            for r, client in enumerate(group):
                try:
                    status, text = client.request(
                        "GET", "/history?raw=1", raw=True)
                    if status != 200:
                        raise RuntimeError(f"/history -> {status}")
                    rings.append((s, r, _json.loads(text)["snapshots"]))
                    self._note(s, r, ok=True)
                except Exception as e:
                    _SCRAPE_ERRORS.labels(shard=str(s),
                                          replica=str(r)).inc()
                    self._note(s, r, ok=False, error=repr(e))
        return rings

    def history(self, *, window: int = 0, series=(),
                include_prom: bool = False) -> dict:
        """The fleet timeline (router ``GET /history``): per-host rings
        folded against the router's own ring through
        :func:`fold_fleet_snapshots` — the EXACT merge semantics
        ``tools/metrics_fold.py`` applies offline — then re-derived into
        the closed series vocabulary
        (:func:`photon_ml_tpu.telemetry.history.fold_history`)."""
        from photon_ml_tpu.telemetry.history import (
            fold_history,
            history_payload,
        )

        sampler = self.history_sampler
        if sampler is None:
            raise RuntimeError("history sampler not armed on the router")
        folded = fold_history(fold_fleet_snapshots, sampler.snapshots(),
                              self.scrape_history())
        return history_payload(folded, source="fleet",
                               capacity=sampler.capacity, window=window,
                               series=series, include_prom=include_prom)

    # --- heat -------------------------------------------------------------
    @staticmethod
    def _quantile(ordered: "list[float]", q: float) -> float:
        return ordered[min(len(ordered) - 1, int(q * len(ordered)))]

    def refresh_heat(self) -> None:
        """Publish the router's per-shard latency window and in-flight
        leg counts as gauges — sampled at scrape time, so the exported
        heat is exactly what the fold serves."""
        latencies = self.router.latency_snapshot()
        loads = self.router.shard_load()
        for s, samples in enumerate(latencies):
            label = str(s)
            _SHARD_LOAD.labels(shard=label).set(float(loads[s]))
            if samples:
                ordered = sorted(samples)
                _SHARD_P50.labels(shard=label).set(
                    self._quantile(ordered, 0.50))
                _SHARD_P99.labels(shard=label).set(
                    self._quantile(ordered, 0.99))

    # --- the fold ---------------------------------------------------------
    def metrics_text(self) -> str:
        """The fleet-folded exposition. Scrapes FIRST (so this round's
        scrape errors are already in the router registry), refreshes the
        heat gauges, then folds — the same texts, same order, same
        tagging as ``tools/metrics_fold.py`` offline."""
        from photon_ml_tpu.telemetry.prometheus import render

        snapshots = self.scrape()
        self.refresh_heat()
        return fold_fleet_snapshots(render(), snapshots)

    # --- topology ---------------------------------------------------------
    def statusz(self) -> dict:
        """The fleet topology page: shard map generation, per-host
        health/lineage/last-scrape, per-shard replica coverage and heat,
        SLO burn state."""
        router = self.router
        health = router.healthz()
        now = time.monotonic()
        with self._lock:
            scrape = {key: dict(info)
                      for key, info in self._last_scrape.items()}
        hosts = []
        for entry in health["hosts"]:
            entry = dict(entry)
            info = scrape.get((entry["shard"], entry["replica"]))
            if info is None:
                entry["last_scrape"] = None
            else:
                last = {"age_s": round(now - info["t"], 3),
                        "ok": info["ok"]}
                if info["error"]:
                    last["error"] = info["error"]
                entry["last_scrape"] = last
            hosts.append(entry)
        latencies = router.latency_snapshot()
        loads = router.shard_load()
        shards = []
        for s, samples in enumerate(latencies):
            heat = {"shard": s, "load": loads[s],
                    "samples": len(samples)}
            if samples:
                ordered = sorted(samples)
                heat["p50_s"] = round(self._quantile(ordered, 0.50), 6)
                heat["p99_s"] = round(self._quantile(ordered, 0.99), 6)
            shards.append(heat)
        return {
            "status": health["status"],
            "n_shards": router.n_shards,
            "replicas": router.replicas,
            "requests": health["requests"],
            "shard_map": health["shard_map"],
            "shard_replicas_up": health["shard_replicas_up"],
            "mixed_lineage": health["mixed_lineage"],
            "hosts": hosts,
            "shards": shards,
            "slo": None if self.slo is None else self.slo.status(),
        }
