"""Deterministic entity-id sharding: the ONE crc32 bucketing home.

A serving fleet splits "hundreds of millions of entity coefficient rows"
(PAPER.md, the GLMix production premise) across N hosts by hashing each
RAW entity id. Everything downstream depends on every participant —
serving store packing, the routing tier, ``refresh_game --fleet-shards``
patch partitioning, offline joins against the request log — computing the
SAME shard for the same id, forever:

- the hash is ``crc32`` of the UTF-8 id string — stable across processes,
  Python versions and machines (unlike ``hash()``), cheap, and already
  the fleet-joinable discipline the request log samples by;
- ids map to one of :data:`N_BUCKETS` fixed **virtual buckets**
  (``crc32(id) % 4096``), and a bucket→shard table (:class:`ShardMap`)
  names the owner. The default table is ``bucket % n_shards`` — when
  ``n_shards`` divides 4096 that reproduces the historical
  ``crc32(id) % n_shards`` placement exactly — and a RESIZE moves only
  the reassigned buckets' ids (~1/N of keys) instead of rehashing
  everything. No seeding, no salting, so two components that never
  exchange configuration still agree.

This module is the one sanctioned home of that bucketing (lint rule
``res-shard-home``, ``analysis/rules_resilience.py``): a second crc32
call site could silently disagree — a different encoding, a signedness
slip, a salt — and "disagree" here means a router sending a user to a
host that holds none of their coefficients, or a refresh patching rows a
host refuses. The pre-existing crc32 users (request-log sampling, the
rank-probe sample, fault-plan seeding) route through here for the same
reason; Avro container checksums (``io/avro.py``) are data integrity,
not identity bucketing, and stay put.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Iterable, Mapping, Optional, Sequence

#: the fixed virtual-bucket count every id hashes into — a power of two
#: large enough that per-bucket movement is fine-grained (a reshard moves
#: whole buckets) and divisible by every practical small fleet size, so
#: the DEFAULT bucket→shard table reproduces the historical
#: ``crc32(id) % n_shards`` placement bit-for-bit
N_BUCKETS = 4096


def stable_hash_u32(key: str) -> int:
    """The one identity hash: unsigned crc32 of the UTF-8 key. Every
    bucketing decision in the system (shard placement, request-log
    sampling, probe selection, fault-plan seeding) derives from this
    value, so they all join on the same id universe."""
    return zlib.crc32(str(key).encode("utf-8")) & 0xFFFFFFFF


def crc_bucket(key: str, mod: int) -> int:
    """``stable_hash_u32(key) % mod`` — the generic bucketing primitive
    (request-log sampling uses ``mod = 1 << 16``; sharding uses
    ``mod = n_shards`` via :func:`shard_of_id`)."""
    return stable_hash_u32(key) % int(mod)


def bucket_of_id(raw_id: str) -> int:
    """The id's fixed virtual bucket (``crc32 % N_BUCKETS``) — stable
    forever; only the bucket→shard TABLE ever moves."""
    return crc_bucket(str(raw_id), N_BUCKETS)


def shard_of_id(raw_id: str, n_shards: int) -> int:
    """The DEFAULT-map fleet placement function: which of ``n_shards``
    hosts owns this raw entity id's coefficient row, routed through the
    virtual-bucket layer (``bucket_of_id(id) % n_shards`` — identical to
    the historical ``crc32(id) % n_shards`` whenever ``n_shards``
    divides :data:`N_BUCKETS`). Deterministic and configuration-free —
    the serving store, the router and the refresh partitioner all call
    this and therefore always agree. A fleet running a NON-default
    :class:`ShardMap` routes through ``ShardMap.shard_of`` instead."""
    n = int(n_shards)
    if n < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    return bucket_of_id(raw_id) % n


def retry_jitter_s(request_id: str, base_s: float = 1.0,
                   spread_s: float = 2.0) -> float:
    """Deterministic per-request-id ``Retry-After`` jitter: ``base_s``
    plus a hash-derived fraction of ``spread_s``. Seeded from
    :func:`stable_hash_u32` (no wall clock, no global RNG) so the same
    refused request always gets the same hint while DIFFERENT requests
    spread over the window — synchronized clients stop retrying in
    lockstep without the router growing any mutable state."""
    frac = (stable_hash_u32(f"retry:{request_id}") % 1024) / 1024.0
    return float(base_s) + float(spread_s) * frac


def check_shard(shard: "tuple[int, int] | None") -> "tuple[int, int] | None":
    """Validate an ``(index, count)`` shard assignment (None = unsharded,
    the single-host identity). The one place the invariant
    ``0 <= index < count`` is spelled out."""
    if shard is None:
        return None
    index, count = int(shard[0]), int(shard[1])
    if count < 1:
        raise ValueError(f"shard count must be >= 1, got {count}")
    if not 0 <= index < count:
        raise ValueError(
            f"shard index must be in [0, {count}), got {index}")
    return (index, count)


def owns_id(raw_id: str, shard: "tuple[int, int] | None") -> bool:
    """Does the host holding ``shard`` own this raw id? ``None`` (an
    unsharded store) owns everything — the single-host degenerate."""
    if shard is None:
        return True
    index, count = shard
    return shard_of_id(raw_id, count) == index


def partition_by_shard(raw_ids: Iterable[str],
                       n_shards: int) -> "dict[int, list[str]]":
    """Split raw ids into per-shard lists (every shard present, possibly
    empty) — the ``refresh_game --fleet-shards`` patch partitioner and
    the router's batch splitter share this shape."""
    out: dict[int, list[str]] = {i: [] for i in range(int(n_shards))}
    for raw in raw_ids:
        out[shard_of_id(raw, n_shards)].append(raw)
    return out


def shard_vocab(entity_vocab: Mapping[str, int],
                shard: "tuple[int, int] | None") -> "dict[str, int]":
    """Restrict a raw→dense entity vocabulary to one shard's slice,
    preserving iteration order (the store packs rows in vocab order, so
    a shard's item axis stays a subsequence of the global one)."""
    if shard is None:
        return dict(entity_vocab)
    return {raw: dense for raw, dense in entity_vocab.items()
            if owns_id(raw, shard)}


def shard_counts(raw_ids: Sequence[str], n_shards: int) -> "list[int]":
    """Per-shard id counts — the balance diagnostic ``serve_fleet`` logs
    at startup (crc32 is uniform enough that a heavy skew means
    duplicated or constant ids, not bad luck)."""
    counts = [0] * int(n_shards)
    for raw in raw_ids:
        counts[shard_of_id(raw, n_shards)] += 1
    return counts


# ---------------------------------------------------------------------------
# the versioned bucket→shard table (live resharding's unit of movement)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ShardMap:
    """A versioned bucket→shard table: ``buckets[b]`` names the shard
    owning virtual bucket ``b``. The map — not the hash — is what a
    reshard changes, so growing the fleet moves only the reassigned
    buckets' ids. ``map_hash`` is a content fingerprint (buckets +
    n_shards + version, crc32 over the packed table — this module IS the
    crc32 home) that rides every fleet response next to ``lineage``; a
    router and a host disagreeing on it is refused like a mixed-lineage
    response."""

    buckets: "tuple[int, ...]"
    n_shards: int
    version: int = 1

    def __post_init__(self):
        object.__setattr__(self, "buckets", tuple(int(b)
                                                  for b in self.buckets))
        object.__setattr__(self, "n_shards", int(self.n_shards))
        object.__setattr__(self, "version", int(self.version))
        if self.n_shards < 1:
            raise ValueError(
                f"shard map needs n_shards >= 1, got {self.n_shards}")
        if len(self.buckets) != N_BUCKETS:
            raise ValueError(f"shard map needs exactly {N_BUCKETS} "
                             f"buckets, got {len(self.buckets)}")
        bad = [b for b, s in enumerate(self.buckets)
               if not 0 <= s < self.n_shards]
        if bad:
            raise ValueError(
                f"shard map assigns buckets {bad[:5]} outside "
                f"[0, {self.n_shards})")
        packed = b"".join(s.to_bytes(2, "big") for s in self.buckets)
        digest = zlib.crc32(
            packed + f"|{self.n_shards}|{self.version}".encode("utf-8"))
        object.__setattr__(
            self, "map_hash",
            f"sm{self.version}-{digest & 0xFFFFFFFF:08x}")

    @classmethod
    def default(cls, n_shards: int, version: int = 1) -> "ShardMap":
        """The round-robin table ``bucket % n_shards`` — reproduces
        :func:`shard_of_id` (and, when ``n_shards`` divides
        :data:`N_BUCKETS`, the historical ``crc32 % n_shards``) exactly,
        so a fresh fleet needs no configured map to agree with every
        incumbent component."""
        n = int(n_shards)
        if n < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        return cls(buckets=tuple(b % n for b in range(N_BUCKETS)),
                   n_shards=n, version=version)

    def shard_of(self, raw_id: str) -> int:
        """Map placement: the shard owning this id's bucket."""
        return self.buckets[bucket_of_id(raw_id)]

    def owns(self, raw_id: str, shard_index: int) -> bool:
        return self.shard_of(raw_id) == int(shard_index)

    def moved_buckets(self, other: "ShardMap") -> "list[int]":
        """Bucket indices assigned differently by ``other`` — the exact
        movement set of a reshard (every id outside these buckets stays
        put, the O(moved) contract chaos asserts)."""
        return [b for b in range(N_BUCKETS)
                if self.buckets[b] != other.buckets[b]]

    def with_moves(self, moves: "Mapping[int, int]") -> "ShardMap":
        """A successor map (version + 1) with the named buckets
        reassigned — the reshard driver's constructor."""
        buckets = list(self.buckets)
        for bucket, shard in moves.items():
            b = int(bucket)
            if not 0 <= b < N_BUCKETS:
                raise ValueError(f"bucket {bucket} outside "
                                 f"[0, {N_BUCKETS})")
            buckets[b] = int(shard)
        return ShardMap(buckets=tuple(buckets), n_shards=self.n_shards,
                        version=self.version + 1)

    def rebalanced(self, n_shards: int) -> "ShardMap":
        """A successor map resized to ``n_shards`` with MINIMAL bucket
        movement: buckets keep their owner where possible; only the
        excess above each shard's fair share moves (deterministically,
        highest bucket indices first) to under-full shards — growing N
        therefore moves ~1/N of buckets, never a full rehash."""
        n = int(n_shards)
        if n < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        per_shard: "dict[int, list[int]]" = {s: [] for s in range(n)}
        homeless: "list[int]" = []
        for b, s in enumerate(self.buckets):
            (per_shard[s] if s < n else homeless).append(b)
        base, extra = divmod(N_BUCKETS, n)
        targets = [base + (1 if s < extra else 0) for s in range(n)]
        for s in range(n):
            over = len(per_shard[s]) - targets[s]
            if over > 0:
                # shed the highest buckets first: deterministic, and a
                # later shrink tends to move the same buckets back
                homeless.extend(per_shard[s][-over:])
                del per_shard[s][-over:]
        homeless.sort()
        buckets = list(self.buckets)
        for s in range(n):
            need = targets[s] - len(per_shard[s])
            for b in homeless[:need]:
                buckets[b] = s
            homeless = homeless[need:]
        return ShardMap(buckets=tuple(buckets), n_shards=n,
                        version=self.version + 1)

    def as_dict(self) -> dict:
        return {"version": self.version, "nShards": self.n_shards,
                "mapHash": self.map_hash, "buckets": list(self.buckets)}

    @classmethod
    def from_dict(cls, data: Mapping) -> "ShardMap":
        sm = cls(buckets=tuple(data["buckets"]),
                 n_shards=int(data["nShards"]),
                 version=int(data.get("version", 1)))
        want = data.get("mapHash")
        if want is not None and want != sm.map_hash:
            raise ValueError(
                f"shard map content hash mismatch: payload says {want}, "
                f"content is {sm.map_hash} — refusing a tampered or "
                f"mis-versioned map")
        return sm


def map_shard_vocab(entity_vocab: Mapping[str, int],
                    shard_map: "Optional[ShardMap]",
                    shard: "tuple[int, int] | None") -> "dict[str, int]":
    """:func:`shard_vocab` under an explicit map: restrict a raw→dense
    vocabulary to the ids the map assigns to ``shard`` (falling back to
    the default-map hash when no map is given). Order-preserving, like
    the default path."""
    if shard is None:
        return dict(entity_vocab)
    if shard_map is None:
        return shard_vocab(entity_vocab, shard)
    index = int(shard[0])
    return {raw: dense for raw, dense in entity_vocab.items()
            if shard_map.owns(raw, index)}
