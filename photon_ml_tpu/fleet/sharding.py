"""Deterministic entity-id sharding: the ONE crc32 bucketing home.

A serving fleet splits "hundreds of millions of entity coefficient rows"
(PAPER.md, the GLMix production premise) across N hosts by hashing each
RAW entity id. Everything downstream depends on every participant —
serving store packing, the routing tier, ``refresh_game --fleet-shards``
patch partitioning, offline joins against the request log — computing the
SAME shard for the same id, forever:

- the hash is ``crc32`` of the UTF-8 id string — stable across processes,
  Python versions and machines (unlike ``hash()``), cheap, and already
  the fleet-joinable discipline the request log samples by;
- the shard is ``crc32(id) % n_shards`` — no seeding, no salting, so two
  components that never exchange configuration still agree.

This module is the one sanctioned home of that bucketing (lint rule
``res-shard-home``, ``analysis/rules_resilience.py``): a second crc32
call site could silently disagree — a different encoding, a signedness
slip, a salt — and "disagree" here means a router sending a user to a
host that holds none of their coefficients, or a refresh patching rows a
host refuses. The pre-existing crc32 users (request-log sampling, the
rank-probe sample, fault-plan seeding) route through here for the same
reason; Avro container checksums (``io/avro.py``) are data integrity,
not identity bucketing, and stay put.
"""

from __future__ import annotations

import zlib
from typing import Iterable, Mapping, Sequence


def stable_hash_u32(key: str) -> int:
    """The one identity hash: unsigned crc32 of the UTF-8 key. Every
    bucketing decision in the system (shard placement, request-log
    sampling, probe selection, fault-plan seeding) derives from this
    value, so they all join on the same id universe."""
    return zlib.crc32(str(key).encode("utf-8")) & 0xFFFFFFFF


def crc_bucket(key: str, mod: int) -> int:
    """``stable_hash_u32(key) % mod`` — the generic bucketing primitive
    (request-log sampling uses ``mod = 1 << 16``; sharding uses
    ``mod = n_shards`` via :func:`shard_of_id`)."""
    return stable_hash_u32(key) % int(mod)


def shard_of_id(raw_id: str, n_shards: int) -> int:
    """The fleet placement function: which of ``n_shards`` hosts owns
    this raw entity id's coefficient row. Deterministic and
    configuration-free — the serving store, the router and the refresh
    partitioner all call this and therefore always agree."""
    n = int(n_shards)
    if n < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    return crc_bucket(str(raw_id), n)


def check_shard(shard: "tuple[int, int] | None") -> "tuple[int, int] | None":
    """Validate an ``(index, count)`` shard assignment (None = unsharded,
    the single-host identity). The one place the invariant
    ``0 <= index < count`` is spelled out."""
    if shard is None:
        return None
    index, count = int(shard[0]), int(shard[1])
    if count < 1:
        raise ValueError(f"shard count must be >= 1, got {count}")
    if not 0 <= index < count:
        raise ValueError(
            f"shard index must be in [0, {count}), got {index}")
    return (index, count)


def owns_id(raw_id: str, shard: "tuple[int, int] | None") -> bool:
    """Does the host holding ``shard`` own this raw id? ``None`` (an
    unsharded store) owns everything — the single-host degenerate."""
    if shard is None:
        return True
    index, count = shard
    return shard_of_id(raw_id, count) == index


def partition_by_shard(raw_ids: Iterable[str],
                       n_shards: int) -> "dict[int, list[str]]":
    """Split raw ids into per-shard lists (every shard present, possibly
    empty) — the ``refresh_game --fleet-shards`` patch partitioner and
    the router's batch splitter share this shape."""
    out: dict[int, list[str]] = {i: [] for i in range(int(n_shards))}
    for raw in raw_ids:
        out[shard_of_id(raw, n_shards)].append(raw)
    return out


def shard_vocab(entity_vocab: Mapping[str, int],
                shard: "tuple[int, int] | None") -> "dict[str, int]":
    """Restrict a raw→dense entity vocabulary to one shard's slice,
    preserving iteration order (the store packs rows in vocab order, so
    a shard's item axis stays a subsequence of the global one)."""
    if shard is None:
        return dict(entity_vocab)
    return {raw: dense for raw, dense in entity_vocab.items()
            if owns_id(raw, shard)}


def shard_counts(raw_ids: Sequence[str], n_shards: int) -> "list[int]":
    """Per-shard id counts — the balance diagnostic ``serve_fleet`` logs
    at startup (crc32 is uniform enough that a heavy skew means
    duplicated or constant ids, not bad luck)."""
    counts = [0] * int(n_shards)
    for raw in raw_ids:
        counts[shard_of_id(raw, n_shards)] += 1
    return counts
