"""Lock-discipline rules (``lock-*``): a static race detector scoped to
what AST analysis can actually prove.

The serving stack is heavily threaded — the MicroBatcher worker, the
watch-dir poller, the drift evaluator, the ``BackgroundSaver`` pools, the
HTTP handler threads — and PR 11's dead-worker bug was exactly a
concurrency defect no tool could flag. These rules enforce an
*annotation convention* that makes a class's locking contract checkable:

**The ``guarded-by`` convention.** In ``__init__`` (or the class body),
tag an attribute's initializing assignment with the lock that protects
it::

    self._queue = collections.deque()   # guarded-by: _cond
    self._pending = []                  # guarded-by: _lock

Any lock-like context manager attribute works (``threading.Lock``,
``RLock``, ``Condition``). Two rules then hold:

- ``lock-guarded-write`` — every write to an annotated attribute outside
  ``__init__`` (assignment, augmented assignment, ``self.x[...] = ...``
  subscript stores, and mutating container calls like ``self.x.append``)
  must occur lexically inside ``with self.<lock>:`` of the named lock.
  Lexically: a nested ``def`` resets the check (a closure defined under a
  ``with`` does NOT run under it).
- ``lock-missing-guard`` — any class that starts a ``threading.Thread``,
  constructs a ``ThreadPoolExecutor``, or ``.submit(...)``\\ s work must
  annotate every attribute it mutates outside ``__init__``: in a threaded
  class an unannotated mutation is an undocumented cross-thread write.

Two escape hatches, both deliberate and both visible in the source:

- a method whose name ends in ``_locked`` asserts "caller holds the
  lock" — its writes are exempt (the name is the contract; reqlog's
  ``_take_buffer_locked`` is the canonical example);
- ``# guarded-by: caller`` marks an attribute whose mutation is
  serialized by the owner's lifecycle contract rather than a lock (the
  ``self._thread`` start/stop idiom): the annotation satisfies
  completeness, and no ``with`` is required.

Anything else that is genuinely safe but unprovable (single-writer
stats, trace-time-only state) carries a justified
``# photon-lint: disable=lock-* -- reason`` suppression.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from photon_ml_tpu.analysis.engine import FileContext, rule

GUARD_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")

#: the "caller serializes mutation" pseudo-lock (lifecycle attributes)
CALLER_GUARD = "caller"

#: container-mutator method names counted as writes to the receiver
MUTATOR_METHODS = frozenset({
    "append", "extend", "insert", "remove", "pop", "popleft", "appendleft",
    "clear", "add", "discard", "update", "setdefault", "sort",
})

#: method-name suffix asserting the caller holds the lock
LOCKED_SUFFIX = "_locked"


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.<attr>`` → attr name, else None."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _is_thread_launch(node: ast.Call) -> bool:
    f = node.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    if name in ("Thread", "ThreadPoolExecutor", "ProcessPoolExecutor"):
        return True
    return isinstance(f, ast.Attribute) and f.attr == "submit"


def _guard_annotations(ctx: FileContext, cls: ast.ClassDef) -> dict[str, str]:
    """``{attr: lock_name}`` from ``# guarded-by:`` comments on attribute
    assignments in ``__init__`` (and class-body assignments)."""
    out: dict[str, str] = {}

    def scan_assign(stmt) -> None:
        targets = (stmt.targets if isinstance(stmt, ast.Assign)
                   else [stmt.target])
        attrs = [a for a in (_self_attr(t) for t in targets)
                 if a is not None]
        if not attrs:
            return
        for lineno in range(stmt.lineno, (stmt.end_lineno or stmt.lineno) + 1):
            m = GUARD_RE.search(ctx.line_text(lineno))
            if m:
                for attr in attrs:
                    out[attr] = m.group(1)
                return

    for stmt in cls.body:
        if isinstance(stmt, ast.FunctionDef) and stmt.name == "__init__":
            for node in ast.walk(stmt):
                if isinstance(node, (ast.Assign, ast.AnnAssign)):
                    scan_assign(node)
        elif isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            scan_assign(stmt)
    return out


def _is_threaded(cls: ast.ClassDef) -> bool:
    return any(isinstance(node, ast.Call) and _is_thread_launch(node)
               for node in ast.walk(cls))


def _with_locks(item_exprs) -> set[str]:
    out = set()
    for expr in item_exprs:
        attr = _self_attr(expr)
        if attr is not None:
            out.add(attr)
    return out


def _iter_writes(body, held: frozenset[str]
                 ) -> Iterator[tuple[str, ast.AST, frozenset[str]]]:
    """Yield ``(attr, node, locks_held)`` for every lexical write to a
    ``self`` attribute under ``body``. ``with self.<lock>:`` adds to the
    held set for its block; entering a nested function RESETS it (the
    closure runs later, lock not held)."""
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield from _iter_writes(stmt.body, frozenset())
            continue
        if isinstance(stmt, ast.Lambda):
            continue
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            locks = _with_locks(i.context_expr for i in stmt.items)
            yield from _iter_writes(stmt.body, held | locks)
            continue
        # direct writes on this statement itself
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for t in targets:
                yield from _target_writes(t, stmt, held)
            if stmt.value is not None:
                yield from _expr_writes(stmt.value, held)
            continue
        # recurse into compound statements (if/for/while/try/match...),
        # scanning their expressions; except-handlers and match-cases are
        # AST nodes that hold statement lists without being statements
        for _, value in ast.iter_fields(stmt):
            for v in (value if isinstance(value, list) else [value]):
                if isinstance(v, ast.stmt):
                    yield from _iter_writes([v], held)
                elif isinstance(v, ast.expr):
                    yield from _expr_writes(v, held)
                elif isinstance(v, ast.AST) and hasattr(v, "body") \
                        and isinstance(getattr(v, "body"), list):
                    yield from _iter_writes(v.body, held)


def _target_writes(t: ast.AST, stmt: ast.AST, held: frozenset[str]
                   ) -> Iterator[tuple[str, ast.AST, frozenset[str]]]:
    attr = _self_attr(t)
    if attr is not None:
        yield attr, stmt, held
        return
    if isinstance(t, ast.Subscript):
        attr = _self_attr(t.value)
        if attr is not None:
            yield attr, stmt, held
        return
    if isinstance(t, (ast.Tuple, ast.List)):
        for elt in t.elts:
            yield from _target_writes(elt, stmt, held)
    if isinstance(t, ast.Starred):
        yield from _target_writes(t.value, stmt, held)


def _expr_writes(expr: ast.expr, held: frozenset[str]
                 ) -> Iterator[tuple[str, ast.AST, frozenset[str]]]:
    """Mutating container calls (``self.x.append(...)``) inside an
    expression tree; nested lambdas reset the held set."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute):
            if node.func.attr in MUTATOR_METHODS:
                attr = _self_attr(node.func.value)
                if attr is not None:
                    yield attr, node, held


def _class_methods(cls: ast.ClassDef):
    for stmt in cls.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield stmt


def _check_class(ctx: FileContext, cls: ast.ClassDef):
    annotations = _guard_annotations(ctx, cls)
    threaded = _is_threaded(cls)
    if not annotations and not threaded:
        return
    for method in _class_methods(cls):
        if method.name == "__init__":
            continue
        if method.name.endswith(LOCKED_SUFFIX):
            # name-asserted contract: the caller holds the lock
            continue
        for attr, node, held in _iter_writes(method.body, frozenset()):
            lock = annotations.get(attr)
            if lock == CALLER_GUARD:
                continue
            if lock is not None:
                if lock not in held:
                    yield ctx.finding(
                        "lock-guarded-write", node,
                        f"write to self.{attr} (guarded-by: {lock}) "
                        f"outside `with self.{lock}:` in "
                        f"{cls.name}.{method.name} — either take the "
                        f"lock around the write or rename the method "
                        f"*{LOCKED_SUFFIX} if the caller holds it")
            elif threaded:
                yield ctx.finding(
                    "lock-missing-guard", node,
                    f"{cls.name} runs threads but mutates unannotated "
                    f"self.{attr} outside __init__ (in {method.name}) — "
                    f"annotate its __init__ assignment with "
                    f"`# guarded-by: <lock>` and take that lock here, or "
                    f"`# guarded-by: caller` for lifecycle-serialized "
                    f"state")


@rule("lock-guarded-write",
      "writes to guarded-by-annotated attributes happen under the named "
      "lock", scope="all")
def check_guarded_write(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ClassDef):
            for f in _check_class(ctx, node):
                if f.rule == "lock-guarded-write":
                    yield f


@rule("lock-missing-guard",
      "thread-running classes annotate every attribute they mutate "
      "outside __init__", scope="all")
def check_missing_guard(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ClassDef):
            for f in _check_class(ctx, node):
                if f.rule == "lock-missing-guard":
                    yield f
