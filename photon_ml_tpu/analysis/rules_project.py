"""Whole-tree consistency rules — invariants no single file can prove.

- ``obs-metric-catalog``: OBSERVABILITY.md's metric catalog and the
  ``photon_*`` families registered with literal names in code must agree
  in BOTH directions. A metric registered but undocumented is a scrape
  nobody can interpret; a documented name no code registers is an
  operator chasing a series that does not exist (dashboards and alerts
  are written from the catalog).
- ``res-fault-coverage``: every site string in
  ``resilience/faults.py::SITES`` must appear in at least one
  ``fault_point``/``fault_value`` injection call site in the package AND
  in at least one test under ``tests/`` — a registered-but-never-
  exercised fault site is resilience coverage that silently is not.
"""

from __future__ import annotations

import ast
import os
import re

from photon_ml_tpu.analysis.engine import Finding, Project, project_rule
from photon_ml_tpu.analysis.rules_telemetry import _factory_calls

OBSERVABILITY_DOC = "OBSERVABILITY.md"
FAULTS_MODULE = os.path.join("photon_ml_tpu", "resilience", "faults.py")

_METRIC_TOKEN_RE = re.compile(r"photon_[a-z0-9_]+")

#: ``photon_``-prefixed tokens that are not metric families (the package
#: name shows up in paths/imports inside catalog cells)
_NON_METRIC_TOKENS = frozenset({"photon_ml_tpu", "photon_lint"})


def _doc_catalog(project: Project) -> dict[str, int]:
    """``{metric_name: first_line}`` from OBSERVABILITY.md's catalog — the
    first cell of every markdown table row (that is the catalog contract:
    a family is documented by owning a row, not by a passing mention in
    prose)."""
    text = project.read_text(OBSERVABILITY_DOC)
    out: dict[str, int] = {}
    if text is None:
        return out
    for lineno, line in enumerate(text.splitlines(), start=1):
        stripped = line.strip()
        if not stripped.startswith("|"):
            continue
        cells = stripped.split("|")
        if len(cells) < 2:
            continue
        first = cells[1]
        # strip label selectors: photon_compiles_total{fn="..."} → name
        for token in _METRIC_TOKEN_RE.findall(first.split("{")[0] if "{"
                                              in first else first):
            if token not in _NON_METRIC_TOKENS:
                out.setdefault(token, lineno)
    return out


def _registered_metrics(project: Project) -> dict[str, tuple[str, int]]:
    """``{name: (path, line)}`` of every metric family registered with a
    literal ``photon_*`` name."""
    out: dict[str, tuple[str, int]] = {}
    for ctx in project.contexts.values():
        for call in _factory_calls(ctx):
            if (call.args and isinstance(call.args[0], ast.Constant)
                    and isinstance(call.args[0].value, str)):
                name = call.args[0].value
                if name.startswith("photon_"):
                    out.setdefault(name, (ctx.path, call.lineno))
    return out


def _string_literals(project: Project) -> set[str]:
    out: set[str] = set()
    for ctx in project.contexts.values():
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                out.add(node.value)
    return out


@project_rule("obs-metric-catalog",
              "OBSERVABILITY.md's metric catalog and literal photon_* "
              "registrations agree both ways")
def check_metric_catalog(project: Project):
    documented = _doc_catalog(project)
    registered = _registered_metrics(project)
    literals = None  # computed lazily — only needed for the doc direction
    for name, (path, line) in sorted(registered.items()):
        if name not in documented:
            yield Finding(
                path, line, "obs-metric-catalog",
                f"metric {name!r} is registered here but missing from "
                f"{OBSERVABILITY_DOC}'s catalog — add a table row (an "
                f"undocumented family is a scrape nobody can interpret)")
    for name, line in sorted(documented.items()):
        if name in registered:
            continue
        if literals is None:
            literals = _string_literals(project)
        # dynamically-named families (registry plumbing) still count as
        # registered if the exact name appears as a literal anywhere
        if name in literals:
            continue
        yield Finding(
            OBSERVABILITY_DOC, line, "obs-metric-catalog",
            f"catalog documents {name!r} but no code registers that "
            f"family — fix the name or drop the row (operators alert on "
            f"series that must exist)")


def _declared_sites(project: Project) -> list[tuple[str, int]]:
    ctx = project.contexts.get(FAULTS_MODULE)
    if ctx is None:
        return []
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "SITES":
                    if isinstance(node.value, (ast.Tuple, ast.List)):
                        return [(elt.value, elt.lineno)
                                for elt in node.value.elts
                                if isinstance(elt, ast.Constant)
                                and isinstance(elt.value, str)]
    return []


def _injection_sites(project: Project) -> set[str]:
    out: set[str] = set()
    for ctx in project.contexts.values():
        if ctx.path == FAULTS_MODULE:
            continue
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if name in ("fault_point", "fault_value") and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                out.add(node.args[0].value)
    return out


@project_rule("res-fault-coverage",
              "every registered fault site is injected somewhere and "
              "exercised by a test")
def check_fault_coverage(project: Project):
    declared = _declared_sites(project)
    if not declared:
        return
    injected = _injection_sites(project)
    test_texts = list(project.iter_texts("tests"))
    for site, line in declared:
        if site not in injected:
            yield Finding(
                FAULTS_MODULE, line, "res-fault-coverage",
                f"fault site {site!r} is registered in SITES but no "
                f"fault_point/fault_value call injects it — a site the "
                f"framework never visits is chaos coverage that silently "
                f"is not")
        if not any(site in text for _, text in test_texts):
            yield Finding(
                FAULTS_MODULE, line, "res-fault-coverage",
                f"fault site {site!r} appears in no test under tests/ — "
                f"a never-exercised site can rot (the hook can drift off "
                f"the code path without any signal)")
