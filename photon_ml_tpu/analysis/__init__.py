"""Unified static-analysis framework (see ANALYSIS.md).

One engine (:mod:`photon_ml_tpu.analysis.engine`) behind every lint pass:

- :mod:`~photon_ml_tpu.analysis.rules_resilience` — the five resilience
  hygiene rules (``res-*``), formerly ``tools/check_resilience_hygiene.py``
- :mod:`~photon_ml_tpu.analysis.rules_telemetry` — the seven telemetry
  hygiene rules (``tel-*``), formerly ``tools/check_telemetry_hygiene.py``
- :mod:`~photon_ml_tpu.analysis.rules_trace` — jit/trace purity
  (``trace-*``): Python side effects inside traced code
- :mod:`~photon_ml_tpu.analysis.rules_concurrency` — lock discipline
  (``lock-*``): the ``# guarded-by:`` annotation convention
- :mod:`~photon_ml_tpu.analysis.rules_project` — whole-tree consistency
  (``obs-metric-catalog``, ``res-fault-coverage``)

CLI: ``python tools/photon_lint.py`` (all passes) or the legacy hygiene
shims (their original subsets, unchanged output).
"""

from photon_ml_tpu.analysis.engine import (
    Finding,
    Project,
    FileContext,
    Report,
    all_rules,
    check_source,
    run,
)

__all__ = [
    "Finding",
    "FileContext",
    "Project",
    "Report",
    "all_rules",
    "check_source",
    "run",
]
