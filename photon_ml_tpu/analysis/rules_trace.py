"""Trace-safety rules (``trace-*``): Python side effects inside jit-traced
code — the static half of the zero-recompile and bit-parity contracts.

A jitted function's Python body runs at TRACE time only; anything impure
there either silently runs once per compile (a print that "works" in a
unit test and never fires in production), reads a clock/rng that bakes a
trace-time value into every execution, or forces a host sync that defeats
async dispatch. None of those break a test — they rot silently until a
recompile or a refactor changes behavior. These rules walk every function
*reachable from a trace-registration site* in the same module and flag
what AST analysis can actually prove:

- registration sites: ``@jax.jit`` / ``jax.jit(f)`` (incl.
  ``functools.partial(jax.jit, ...)`` decorators and ``jit(vmap(f))``
  nesting), ``profile_jit(f, name)``, ``pl.pallas_call(kernel, ...)``,
  ``@jax.custom_batching.custom_vmap``;
- reachability: same-file calls from a traced function to a named
  function (module-level or nested) mark the callee traced too —
  cross-module reachability is out of static reach and out of scope;
- ``trace-print`` — ``print()`` inside traced code;
- ``trace-clock`` — any ``time.*`` call inside traced code (a trace-time
  clock read is a constant baked into the executable);
- ``trace-random`` — stdlib ``random.*`` / ``np.random.*`` calls (host
  RNG state read at trace time; use ``jax.random`` with explicit keys);
- ``trace-host-sync`` — ``.item()`` calls, ``np.asarray``/``np.array``
  over traced values, and ``float(x)``/``int(x)`` applied directly to a
  function parameter (almost certainly a tracer): each forces the device
  to sync mid-trace or fails under jit;
- ``trace-mutable-global`` — a ``global`` statement, or a read of a
  module-level name bound to a mutable literal (``list``/``dict``/``set``
  and friends): closure-captured mutable state makes the traced program
  depend on when tracing happened.

Intentional trace-time effects exist (e.g. the serving engine counts
compiles from inside the traced body BECAUSE it runs once per trace) —
those carry a justified ``# photon-lint: disable=trace-* -- reason``
suppression, which is the point: the exception is written down where it
lives.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from photon_ml_tpu.analysis.engine import FileContext, rule

#: call/decorator heads that register a function for tracing
_TRACE_WRAPPER_ATTRS = frozenset({"jit", "pallas_call", "custom_vmap",
                                  "profile_jit"})

#: container constructors whose module-level result is mutable shared state
_MUTABLE_CTORS = frozenset({"list", "dict", "set", "deque", "defaultdict",
                            "OrderedDict", "Counter"})


def _head_name(expr: ast.AST) -> Optional[str]:
    """The trailing identifier of a Name/Attribute chain (``jax.jit`` →
    ``jit``; ``pl.pallas_call`` → ``pallas_call``)."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _is_trace_wrapper(expr: ast.AST) -> bool:
    """True when ``expr`` names a tracing entry point. Also looks through
    ``functools.partial(jax.jit, ...)`` decorator spellings."""
    if _head_name(expr) in _TRACE_WRAPPER_ATTRS:
        return True
    if isinstance(expr, ast.Call) and _head_name(expr.func) == "partial":
        return any(_is_trace_wrapper(a) for a in expr.args[:1])
    return False


def _unwrap_fn_arg(arg: ast.AST) -> ast.AST:
    """Look through wrapper calls (``jit(vmap(f))`` → ``f``)."""
    while isinstance(arg, ast.Call) and arg.args:
        arg = arg.args[0]
    return arg


class _Scopes:
    """Lexical scope index: resolve a bare function name at any node the
    way Python would (innermost def outward; class bodies are NOT in the
    chain — a method is never reachable by bare name from nested code)."""

    def __init__(self, tree: ast.Module):
        scope_types = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        #: id(node) -> innermost enclosing scope node (None = module)
        self.enclosing: dict[int, Optional[ast.AST]] = {}
        #: scope key -> {name: FunctionDef} of functions DIRECTLY inside
        self.defs: dict[Optional[int], dict[str, ast.AST]] = {None: {}}
        # BFS order puts outer scopes first, so inner walks overwrite —
        # the final value is the innermost enclosing scope
        scopes = [n for n in ast.walk(tree) if isinstance(n, scope_types)]
        for scope in scopes:
            parent = self.enclosing.get(id(scope))
            if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = None if parent is None else id(parent)
                self.defs.setdefault(key, {})[scope.name] = scope
            for n in ast.walk(scope):
                if n is not scope:
                    self.enclosing[id(n)] = scope
        self._parent = {id(s): self.enclosing.get(id(s)) for s in scopes}

    def resolve(self, name: str, at: ast.AST) -> Optional[ast.AST]:
        scope = self.enclosing.get(id(at))
        first = True
        while True:
            # class scopes resolve names only for code directly in the
            # class body, never for nested functions (Python scoping)
            if not isinstance(scope, ast.ClassDef) or first:
                fn = self.defs.get(None if scope is None
                                   else id(scope), {}).get(name)
                if fn is not None:
                    return fn
            first = False
            if scope is None:
                return None
            scope = self._parent.get(id(scope))


def traced_functions(ctx: FileContext) -> list:
    """Every function node reachable from a trace-registration site in
    this file (decorated, passed to a wrapper by name, or called by name
    from an already-traced function). Names resolve lexically, so a
    method that merely shares a name with a traced local function is not
    dragged in."""
    scopes = _Scopes(ctx.tree)
    traced: list = []
    seen: set[int] = set()

    def add(node) -> None:
        if id(node) not in seen:
            seen.add(id(node))
            traced.append(node)

    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_trace_wrapper(d) for d in node.decorator_list):
                add(node)
        elif isinstance(node, ast.Call) and _is_trace_wrapper(node.func):
            arg = _unwrap_fn_arg(node.args[0]) if node.args else None
            if isinstance(arg, ast.Name):
                fn = scopes.resolve(arg.id, node)
                if fn is not None:
                    add(fn)
            elif isinstance(arg, ast.Lambda):
                add(arg)
    # fixed point over same-file calls by name
    frontier = list(traced)
    while frontier:
        fn = frontier.pop()
        for node in _iter_traced_nodes(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)):
                callee = scopes.resolve(node.func.id, node)
                if callee is not None and id(callee) not in seen:
                    add(callee)
                    frontier.append(callee)
    return traced


def _mutable_globals(tree: ast.Module) -> set[str]:
    """Module-level names bound to a mutable literal or container
    constructor — the closure captures a traced function must not read."""
    out: set[str] = set()
    for stmt in tree.body:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            value = stmt.value
            mutable = isinstance(value, (ast.List, ast.Dict, ast.Set,
                                         ast.ListComp, ast.DictComp,
                                         ast.SetComp))
            if (isinstance(value, ast.Call)
                    and _head_name(value.func) in _MUTABLE_CTORS):
                mutable = True
            if not mutable:
                continue
            for t in targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _iter_traced_nodes(fn) -> Iterator[ast.AST]:
    """Walk a traced function's body — nested defs included (they trace
    with it)."""
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        yield from ast.walk(stmt)


def _param_names(fn) -> set[str]:
    args = fn.args
    names = [a.arg for a in (args.posonlyargs + args.args + args.kwonlyargs)]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return set(names)


def _fn_label(fn) -> str:
    return getattr(fn, "name", "<lambda>")


@rule("trace-print", "no print() inside jit-traced code", scope="all")
def check_trace_print(ctx: FileContext):
    for fn in traced_functions(ctx):
        for node in _iter_traced_nodes(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                yield ctx.finding(
                    "trace-print", node,
                    f"print() inside traced function {_fn_label(fn)}() — "
                    f"it runs at trace time only (once per compiled "
                    f"shape, never per call); use jax.debug.print or log "
                    f"outside the jit boundary")


@rule("trace-clock", "no time.* calls inside jit-traced code", scope="all")
def check_trace_clock(ctx: FileContext):
    time_aliases = ctx.module_aliases("time")
    time_fn_names = ctx.from_aliases("time", "time", "perf_counter",
                                     "monotonic", "sleep", "process_time",
                                     "monotonic_ns", "perf_counter_ns",
                                     "time_ns")
    for fn in traced_functions(ctx):
        for node in _iter_traced_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            hit = (isinstance(f, ast.Attribute)
                   and isinstance(f.value, ast.Name)
                   and f.value.id in time_aliases) \
                or (isinstance(f, ast.Name) and f.id in time_fn_names)
            if hit:
                yield ctx.finding(
                    "trace-clock", node,
                    f"clock read inside traced function {_fn_label(fn)}() "
                    f"— it executes at trace time and bakes that instant "
                    f"into the compiled program; measure outside the jit "
                    f"boundary (registry timers / spans)")


@rule("trace-random",
      "no host RNG (random.* / np.random.*) inside jit-traced code",
      scope="all")
def check_trace_random(ctx: FileContext):
    random_aliases = ctx.module_aliases("random")
    np_aliases = ctx.module_aliases("numpy") | ctx.from_aliases("jax",
                                                                "numpy")
    random_fn_names = ctx.from_aliases(
        "random", "random", "randint", "randrange", "uniform", "choice",
        "shuffle", "sample", "gauss", "normalvariate")
    for fn in traced_functions(ctx):
        for node in _iter_traced_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            hit = False
            if isinstance(f, ast.Attribute):
                v = f.value
                # random.<fn>(...)
                if isinstance(v, ast.Name) and v.id in random_aliases:
                    hit = True
                # np.random.<fn>(...)
                elif (isinstance(v, ast.Attribute) and v.attr == "random"
                      and isinstance(v.value, ast.Name)
                      and v.value.id in np_aliases):
                    hit = True
            elif isinstance(f, ast.Name) and f.id in random_fn_names:
                hit = True
            if hit:
                yield ctx.finding(
                    "trace-random", node,
                    f"host RNG call inside traced function "
                    f"{_fn_label(fn)}() — the draw happens at trace time "
                    f"and freezes into the executable (bit-parity breaks "
                    f"across recompiles); thread a jax.random key instead")


@rule("trace-host-sync",
      "no host syncs (.item(), np.asarray, float(param)) inside jit-traced "
      "code", scope="all")
def check_trace_host_sync(ctx: FileContext):
    np_aliases = ctx.module_aliases("numpy")
    for fn in traced_functions(ctx):
        params = _param_names(fn)
        for node in _iter_traced_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "item":
                yield ctx.finding(
                    "trace-host-sync", node,
                    f".item() inside traced function {_fn_label(fn)}() — "
                    f"forces a device sync mid-trace (and fails on "
                    f"abstract tracers); keep values on device or move "
                    f"the read outside the jit boundary")
            elif (isinstance(f, ast.Attribute)
                  and f.attr in ("asarray", "array")
                  and isinstance(f.value, ast.Name)
                  and f.value.id in np_aliases):
                yield ctx.finding(
                    "trace-host-sync", node,
                    f"np.{f.attr}() inside traced function "
                    f"{_fn_label(fn)}() — materializes the value on the "
                    f"host at trace time; use jnp.{f.attr} (stays on "
                    f"device) or hoist the conversion out of the trace")
            elif (isinstance(f, ast.Name) and f.id in ("float", "int")
                  and len(node.args) == 1
                  and isinstance(node.args[0], ast.Name)
                  and node.args[0].id in params):
                yield ctx.finding(
                    "trace-host-sync", node,
                    f"{f.id}() over parameter {node.args[0].id!r} inside "
                    f"traced function {_fn_label(fn)}() — concretizes a "
                    f"tracer (host sync, or ConcretizationTypeError under "
                    f"jit); keep the value abstract or mark the argument "
                    f"static")


@rule("trace-mutable-global",
      "no mutable module-global capture inside jit-traced code",
      scope="all")
def check_trace_mutable_global(ctx: FileContext):
    mutable = _mutable_globals(ctx.tree)
    for fn in traced_functions(ctx):
        local_stores: set[str] = set()
        for node in _iter_traced_nodes(fn):
            if isinstance(node, ast.Name) and isinstance(node.ctx,
                                                         ast.Store):
                local_stores.add(node.id)
        for node in _iter_traced_nodes(fn):
            if isinstance(node, ast.Global):
                yield ctx.finding(
                    "trace-mutable-global", node,
                    f"`global` inside traced function {_fn_label(fn)}() — "
                    f"trace-time writes to module state run once per "
                    f"compile, not per call; return the value instead")
            elif (isinstance(node, ast.Name)
                  and isinstance(node.ctx, ast.Load)
                  and node.id in mutable and node.id not in local_stores):
                yield ctx.finding(
                    "trace-mutable-global", node,
                    f"traced function {_fn_label(fn)}() reads mutable "
                    f"module global {node.id!r} — the closure captures "
                    f"whatever it held at trace time (silent staleness "
                    f"after mutation, and a recompile changes behavior); "
                    f"pass it as an argument or make it immutable")
