"""Telemetry hygiene rules (``tel-*``) — the seven passes that used to be
``tools/check_telemetry_hygiene.py`` (now a thin shim over this module;
output format, exit codes and tier-1 test unchanged).

Messages are byte-identical to the pre-engine tool — the shim-compat test
locks that.
"""

from __future__ import annotations

import ast
import os
import re

from photon_ml_tpu.analysis.engine import FileContext, rule

#: stdout owners: the CLI drivers and the module runner
PRINT_ALLOWED_PREFIXES = (
    os.path.join("photon_ml_tpu", "cli") + os.sep,
)
PRINT_ALLOWED_FILES = {os.path.join("photon_ml_tpu", "__main__.py")}

#: the one subtree whose job IS timing: the sanctioned timers live here
TIMING_ALLOWED_PREFIX = os.path.join("photon_ml_tpu", "telemetry") + os.sep

#: the one place allowed to construct MetricsRegistry instances
REGISTRY_ALLOWED_PREFIX = os.path.join("photon_ml_tpu", "telemetry") + os.sep

#: metric-family registration methods/functions
METRIC_FACTORIES = frozenset({"counter", "gauge", "histogram"})

METRIC_NAME_RE = re.compile(r"photon_[a-z0-9_]+\Z")

#: the one subtree whose job IS score binning + drift statistics
QUALITY_ALLOWED_PREFIX = os.path.join("photon_ml_tpu", "quality") + os.sep

#: numpy/jax.numpy histogram-binning entry points
HISTOGRAM_ATTRS = frozenset({"histogram", "histogram2d", "histogramdd",
                             "histogram_bin_edges"})

#: drift-statistic names whose DEFINITION outside quality/ forks the
#: arithmetic (calling quality's exported functions is of course fine)
DRIFT_STAT_NAMES = frozenset({"population_stability_index", "psi",
                              "ks_statistic", "kolmogorov_smirnov"})

#: the one request-id mint (serving/http.py) and the request-id
#: generation primitives whose CALL anywhere else forks request identity
REQUEST_ID_ALLOWED_FILES = {os.path.join("photon_ml_tpu", "serving",
                                         "http.py")}
ID_GEN_UUID_FNS = frozenset({"uuid1", "uuid3", "uuid4", "uuid5"})
ID_GEN_SECRETS_FNS = frozenset({"token_hex", "token_urlsafe"})

#: the one RequestLogAvro writer (serving/reqlog.py) plus the schema's
#: definition site
REQLOG_SCHEMA_NAME = "REQUEST_LOG_AVRO"
REQLOG_ALLOWED_FILES = {
    os.path.join("photon_ml_tpu", "serving", "reqlog.py"),
    os.path.join("photon_ml_tpu", "io", "schemas.py"),
}


def _print_ok(ctx: FileContext) -> bool:
    return (ctx.path in PRINT_ALLOWED_FILES
            or any(ctx.path.startswith(p) for p in PRINT_ALLOWED_PREFIXES))


@rule("tel-print",
      "no print() outside CLI entry points — stdout belongs to the drivers")
def check_print(ctx: FileContext):
    if _print_ok(ctx):
        return
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "print"):
            yield ctx.finding(
                "tel-print", node,
                "print() outside a CLI entry point — library code logs, "
                "counts (telemetry.metrics) or spans (telemetry.tracing); "
                "stdout belongs to the drivers")


def _is_perf_counter(node: ast.AST, time_aliases: set[str],
                     pc_names: set[str]) -> bool:
    if isinstance(node, ast.Attribute) and node.attr == "perf_counter":
        return (isinstance(node.value, ast.Name)
                and node.value.id in time_aliases)
    if isinstance(node, ast.Name):
        return node.id in pc_names
    return False


@rule("tel-perf-counter",
      "no time.perf_counter outside telemetry/ — durations route through "
      "registry timers/spans")
def check_perf_counter(ctx: FileContext):
    if ctx.path.startswith(TIMING_ALLOWED_PREFIX):
        return
    time_aliases = ctx.module_aliases("time")
    pc_names = ctx.from_aliases("time", "perf_counter")
    for node in ast.walk(ctx.tree):
        if _is_perf_counter(node, time_aliases, pc_names):
            yield ctx.finding(
                "tel-perf-counter", node,
                "time.perf_counter outside telemetry/ — measure durations "
                "through the metrics registry's Histogram.time() or a "
                "tracing span so /metrics and trace.jsonl see them")


@rule("tel-wall-clock",
      "no wall-clock duration arithmetic — time.time() is a timestamp, "
      "not a timer")
def check_wall_clock(ctx: FileContext):
    if ctx.path.startswith(TIMING_ALLOWED_PREFIX):
        return
    time_aliases = ctx.module_aliases("time")
    tt_names = ctx.from_aliases("time", "time")

    def _is_wall_clock_call(node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "time":
            return (isinstance(f.value, ast.Name)
                    and f.value.id in time_aliases)
        return isinstance(f, ast.Name) and f.id in tt_names

    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub)
                and (_is_wall_clock_call(node.left)
                     or _is_wall_clock_call(node.right))):
            yield ctx.finding(
                "tel-wall-clock", node,
                "duration computed from time.time() — the wall clock is "
                "for timestamps (it jumps); measure durations with a "
                "registry timer or a tracing span")


def _metric_call_args(node: ast.Call):
    """(name, help) literals of a metric-factory call; non-literal fields
    come back as None (dynamic names/helps are out of the lint's reach —
    the registry's internal plumbing passes them through variables)."""
    name = help_ = None
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        name = node.args[0].value
    if len(node.args) > 1 and isinstance(node.args[1], ast.Constant) \
            and isinstance(node.args[1].value, str):
        help_ = node.args[1].value
    for kw in node.keywords:
        if kw.arg == "help_" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            help_ = kw.value.value
    has_help_arg = len(node.args) > 1 or any(kw.arg == "help_"
                                             for kw in node.keywords)
    return name, help_, has_help_arg


def _factory_calls(ctx: FileContext):
    """Every metric-factory call node in the file (attribute spelling on
    any receiver, or a from-imported factory name)."""
    metric_fn_names = ctx.from_aliases("photon_ml_tpu.telemetry.metrics",
                                       *METRIC_FACTORIES)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if ((isinstance(func, ast.Attribute)
             and func.attr in METRIC_FACTORIES)
                or (isinstance(func, ast.Name)
                    and func.id in metric_fn_names)):
            yield node


@rule("tel-metric-name",
      "literal metric names match photon_[a-z0-9_]+ and carry help text")
def check_metric_name(ctx: FileContext):
    for node in _factory_calls(ctx):
        name, help_, has_help = _metric_call_args(node)
        if name is None:
            continue
        if not METRIC_NAME_RE.fullmatch(name):
            yield ctx.finding(
                "tel-metric-name", node,
                f"metric name {name!r} must match photon_[a-z0-9_]+ — the "
                f"fleet aggregate merges by family name, so every family "
                f"carries the photon_ prefix")
        if not has_help or (help_ is not None and not help_.strip()):
            yield ctx.finding(
                "tel-metric-name", node,
                f"metric {name!r} registered without help text — a scrape "
                f"nobody can interpret; say what the number means")


@rule("tel-registry",
      "no MetricsRegistry() outside telemetry/ — one process-global "
      "registry")
def check_registry(ctx: FileContext):
    if ctx.path.startswith(REGISTRY_ALLOWED_PREFIX):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if ((isinstance(func, ast.Name) and func.id == "MetricsRegistry")
                or (isinstance(func, ast.Attribute)
                    and func.attr == "MetricsRegistry")):
            yield ctx.finding(
                "tel-registry", node,
                "MetricsRegistry() outside photon_ml_tpu/telemetry/ — the "
                "process-global default_registry() is the only sanctioned "
                "registry outside tests; a private one forks the namespace "
                "away from /metrics and the fleet fold")


def _np_aliases(ctx: FileContext) -> set[str]:
    out = ctx.module_aliases("numpy")
    out |= {a for a in ctx.module_aliases("jax.numpy")}
    out |= ctx.from_aliases("jax", "numpy")
    return out


@rule("tel-drift-home",
      "score binning + PSI/KS live in quality/ — one drift arithmetic")
def check_drift_home(ctx: FileContext):
    if ctx.path.startswith(QUALITY_ALLOWED_PREFIX):
        return
    np_aliases = _np_aliases(ctx)

    def _is_np_module(v: ast.AST) -> bool:
        if isinstance(v, ast.Name):
            return v.id in np_aliases
        # the bare `import jax.numpy` spelling: jax.numpy.histogram(...)
        return (isinstance(v, ast.Attribute) and v.attr == "numpy"
                and isinstance(v.value, ast.Name) and v.value.id == "jax")

    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in HISTOGRAM_ATTRS
                and _is_np_module(node.func.value)):
            yield ctx.finding(
                "tel-drift-home", node,
                f"{node.func.attr}() outside photon_ml_tpu/quality/ — "
                f"score-histogram binning lives in quality/baseline.py "
                f"(bin_scores/quantile_edges) so live and baseline "
                f"distributions always share bin edges; a second binning "
                f"silently redefines drift")
        elif (isinstance(node, ast.FunctionDef)
              and node.name in DRIFT_STAT_NAMES):
            yield ctx.finding(
                "tel-drift-home", node,
                f"drift statistic {node.name}() defined outside "
                f"photon_ml_tpu/quality/ — PSI/KS have ONE implementation "
                f"(quality/baseline.py); import it instead of re-deriving "
                f"the arithmetic")


@rule("tel-request-identity",
      "request ids are minted in serving/http.py only; RequestLogAvro is "
      "written by serving/reqlog.py only")
def check_request_identity(ctx: FileContext):
    uuid_aliases = ctx.module_aliases("uuid")
    secrets_aliases = ctx.module_aliases("secrets")
    id_gen_names = (ctx.from_aliases("uuid", *ID_GEN_UUID_FNS)
                    | ctx.from_aliases("secrets", *ID_GEN_SECRETS_FNS))

    def _is_id_gen_call(node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        f = node.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            return ((f.value.id in uuid_aliases
                     and f.attr in ID_GEN_UUID_FNS)
                    or (f.value.id in secrets_aliases
                        and f.attr in ID_GEN_SECRETS_FNS))
        return isinstance(f, ast.Name) and f.id in id_gen_names

    def _is_reqlog_schema_ref(node: ast.AST) -> bool:
        if isinstance(node, ast.Name) and node.id == REQLOG_SCHEMA_NAME:
            return True
        if isinstance(node, ast.Attribute) and node.attr == REQLOG_SCHEMA_NAME:
            return True
        return (isinstance(node, ast.ImportFrom)
                and any(a.name == REQLOG_SCHEMA_NAME for a in node.names))

    id_gen_banned = ctx.path not in REQUEST_ID_ALLOWED_FILES
    reqlog_banned = ctx.path not in REQLOG_ALLOWED_FILES
    for node in ast.walk(ctx.tree):
        if id_gen_banned and _is_id_gen_call(node):
            yield ctx.finding(
                "tel-request-identity", node,
                "request-id generation outside photon_ml_tpu/serving/"
                "http.py — a serving request is identified ONCE "
                "(new_request_id); a second mint breaks the span/reqlog/"
                "response join (hygiene rule 7)")
        elif reqlog_banned and _is_reqlog_schema_ref(node):
            yield ctx.finding(
                "tel-request-identity", node,
                f"{REQLOG_SCHEMA_NAME} referenced outside "
                f"photon_ml_tpu/serving/reqlog.py — the request log has "
                f"ONE writer; a second one forks the on-disk format away "
                f"from tools/reqlog_replay.py (hygiene rule 7)")


#: names that carry raw REQUEST payload — a subscript/.get() on one of
#: these reaching a span attribute or metric label is unbounded
#: cardinality (every distinct entity id becomes its own series/tag)
REQUEST_PAYLOAD_NAMES = frozenset({
    "meta", "metadata", "metadatamap", "record", "records", "payload",
    "body", "params", "qs", "query",
})

#: bare local names that obviously hold a per-request entity identity
ENTITY_ID_NAME_RE = re.compile(
    r"\A(user|entity|item|song|member)_?id\Z", re.IGNORECASE)

#: span/annotation call names whose KEYWORDS become span attributes
SPAN_ATTR_CALLS = frozenset({"span", "span_under", "record_span",
                             "annotate", "set"})

#: keywords that are sanctioned tags: the request id is the designed
#: per-request join key (hygiene rule 7), and span_under/record_span
#: plumbing keywords aren't attributes at all
SANCTIONED_ATTR_KEYWORDS = frozenset({"request_id", "parent_id",
                                      "seconds", "ts"})


def _payload_root(node: ast.AST) -> bool:
    """True when the expression reads a raw request-payload field:
    ``meta["userId"]``, ``payload.get("memberId")``, ``record[...]`` —
    chased through attribute chains (``self.payload[...]``)."""
    if isinstance(node, ast.Subscript):
        return _payload_base(node.value)
    if (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"):
        return _payload_base(node.func.value)
    return False


def _payload_base(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return node.id.lower() in REQUEST_PAYLOAD_NAMES
    if isinstance(node, ast.Attribute):
        return node.attr.lower() in REQUEST_PAYLOAD_NAMES
    return False


def _unbounded_value(node: ast.AST) -> bool:
    """An attribute/label VALUE expression with unbounded request-derived
    cardinality: a payload subscript/get, an entity-id-named local, or
    an f-string / str() / concat wrapping one."""
    if _payload_root(node):
        return True
    if isinstance(node, ast.Name) and ENTITY_ID_NAME_RE.match(node.id):
        return True
    if isinstance(node, ast.JoinedStr):
        return any(_unbounded_value(v.value) for v in node.values
                   if isinstance(v, ast.FormattedValue))
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id in ("str", "repr") and node.args):
        return _unbounded_value(node.args[0])
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return (_unbounded_value(node.left)
                or _unbounded_value(node.right))
    return False


@rule("tel-span-attr-cardinality",
      "no span attributes or metric label values derived from unbounded "
      "request fields — tags index storage, payloads don't belong there")
def check_span_attr_cardinality(ctx: FileContext):
    """Span attributes and metric labels are INDEXED: every distinct
    value is a new series (metrics) or a new tag value (trace tooling
    group-bys). A value read off the raw request payload — an entity id,
    a metadata field — is unbounded, so one hot user explodes the
    registry and the span tree's group keys. Bounded request identity
    already has sanctioned homes: the request id (hygiene rule 7) and
    the closed leg-summary stage vocabulary
    (``serving/http.py::parse_leg_summary`` — the parser DROPS unknown
    keys precisely so fleet trace stitching can never import a host's
    unbounded field names as span data)."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not node.keywords:
            continue
        func = node.func
        if isinstance(func, ast.Attribute):
            call_name = func.attr
        elif isinstance(func, ast.Name):
            call_name = func.id
        else:
            continue
        if call_name == "labels":
            kind = "metric label"
        elif call_name in SPAN_ATTR_CALLS:
            kind = "span attribute"
        else:
            continue
        for kw in node.keywords:
            if kw.arg is None or kw.arg in SANCTIONED_ATTR_KEYWORDS:
                continue
            if _unbounded_value(kw.value):
                yield ctx.finding(
                    "tel-span-attr-cardinality", node,
                    f"{kind} {kw.arg!r} set from a raw request field — "
                    f"unbounded cardinality: every distinct value becomes "
                    f"its own series/tag. Count it under a bounded label, "
                    f"or join through the request id (the sanctioned "
                    f"per-request key)")


#: the retained-telemetry plane's own plumbing: history/flightrec pass
#: names through variables they validate at runtime (SERIES_NAME_RE,
#: RECORD_KINDS) — the lint covers their CALLERS
RETAINED_ALLOWED_FILES = {
    os.path.join("photon_ml_tpu", "telemetry", "history.py"),
    os.path.join("photon_ml_tpu", "telemetry", "flightrec.py"),
}

#: retained-telemetry writers whose NAME argument joins the black box /
#: history vocabulary (FlightRecorder.note / record_event)
RETAINED_NAME_CALLS = frozenset({"note", "record_event"})

#: the static twin of telemetry.history.SERIES_NAME_RE
RETAINED_NAME_RE = re.compile(r"\A[a-z][a-z0-9_]{0,59}\Z")


@rule("tel-retained-vocab",
      "flight-recorder note/event names and history series names come "
      "from a closed literal vocabulary; payload fields stay out of the "
      "black box")
def check_retained_vocab(ctx: FileContext):
    """The retained-telemetry plane (telemetry/history.py ring,
    telemetry/flightrec.py black box) is indexed storage exactly like
    span attributes: ``tools/postmortem.py`` and the ``/history`` fold
    group by record names, so a COMPUTED name is an unbounded vocabulary
    (every distinct value becomes its own report key) and a payload-
    derived field value ships request data into crash dumps. Mirrors
    ``tel-span-attr-cardinality``: names must be literal snake_case,
    values may carry the request id (the sanctioned join key) but never
    raw payload reads; requested history series must be members of
    ``telemetry.history.HISTORY_SERIES``."""
    if ctx.path in RETAINED_ALLOWED_FILES:
        return
    from photon_ml_tpu.telemetry.history import HISTORY_SERIES
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute):
            call_name = func.attr
        elif isinstance(func, ast.Name):
            call_name = func.id
        else:
            continue
        if call_name == "history_payload":
            for kw in node.keywords:
                if kw.arg != "series":
                    continue
                if not isinstance(kw.value, (ast.List, ast.Tuple)):
                    continue  # computed lists are checked at runtime
                for elt in kw.value.elts:
                    if (isinstance(elt, ast.Constant)
                            and isinstance(elt.value, str)
                            and elt.value not in HISTORY_SERIES):
                        yield ctx.finding(
                            "tel-retained-vocab", elt,
                            f"history series {elt.value!r} outside the "
                            f"closed vocabulary (telemetry.history."
                            f"HISTORY_SERIES) — the fold and /history "
                            f"only serve derived series they can "
                            f"recompute")
            continue
        if call_name not in RETAINED_NAME_CALLS:
            continue
        if node.args:
            name_arg = node.args[0]
            if not (isinstance(name_arg, ast.Constant)
                    and isinstance(name_arg.value, str)):
                yield ctx.finding(
                    "tel-retained-vocab", node,
                    f"{call_name}() name computed at runtime — flight "
                    f"records are grouped by name in postmortems, so the "
                    f"vocabulary is closed: pass a literal snake_case "
                    f"string")
            elif not RETAINED_NAME_RE.match(name_arg.value):
                yield ctx.finding(
                    "tel-retained-vocab", node,
                    f"{call_name}() name {name_arg.value!r} outside the "
                    f"closed vocabulary — flight record names are "
                    f"snake_case literals")
        for kw in node.keywords:
            if kw.arg is None:
                yield ctx.finding(
                    "tel-retained-vocab", node,
                    f"{call_name}(**...) splats computed field names "
                    f"into the black box — the field vocabulary is "
                    f"closed; spell the fields as literal keywords")
            elif (kw.arg not in SANCTIONED_ATTR_KEYWORDS
                    and _unbounded_value(kw.value)):
                yield ctx.finding(
                    "tel-retained-vocab", node,
                    f"flight record field {kw.arg!r} set from a raw "
                    f"request field — crash dumps are retained and "
                    f"shared; join through the request id (the "
                    f"sanctioned per-request key) instead of shipping "
                    f"payload data")


#: the ONE connection-accounting home: socket-lifecycle metric families
#: (``photon_connection*``) and the ConnectionTracker primitive live in
#: serving/http.py; everything else observes connections through the
#: tracker's stats()/utilization() or the capacity plane's probes
CONN_HOME_FILE = os.path.join("photon_ml_tpu", "serving", "http.py")
CONN_METRIC_PREFIX = "photon_connection"

#: static twin of ``telemetry.saturation.RESOURCES`` — the closed
#: USE-method resource vocabulary (a test asserts the copies agree, the
#: same pattern as RETAINED_NAME_RE vs SERIES_NAME_RE)
SATURATION_RESOURCES = frozenset({
    "device", "batcher_queue", "rank_batcher_queue", "http_connections",
    "handler_threads", "saver_pool", "router_pool", "hedge_pool",
    "reqlog",
})


@rule("tel-conn-home",
      "connection accounting lives in serving/http.py only; saturation "
      "probes register closed-vocabulary resource names")
def check_conn_home(ctx: FileContext):
    """The capacity plane's contracts (ISSUE 20). Connection accounting
    holds an identity (``accepted == closed + open``) that only survives
    because ONE tracker under ONE lock mutates it — a second
    ``photon_connection*`` family or a re-derived ConnectionTracker
    forks the arithmetic away from ``/healthz`` and the fold. And the
    USE-method gauges are keyed by resource name: ``add_probe`` with a
    computed or out-of-vocabulary name opens the label set that
    ``tools/capacity_report.py`` and the ``resource_util`` history
    series group by."""
    conn_banned = ctx.path != CONN_HOME_FILE
    for node in ast.walk(ctx.tree):
        if (conn_banned and isinstance(node, ast.ClassDef)
                and node.name == "ConnectionTracker"):
            yield ctx.finding(
                "tel-conn-home", node,
                "ConnectionTracker defined outside photon_ml_tpu/serving/"
                "http.py — connection accounting has ONE home so the "
                "accepted == closed + open identity holds under one "
                "lock; import serving.http.ConnectionTracker instead")
            continue
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if (isinstance(func, ast.Attribute) and func.attr == "add_probe"
                and node.args):
            name_arg = node.args[0]
            if not (isinstance(name_arg, ast.Constant)
                    and isinstance(name_arg.value, str)):
                yield ctx.finding(
                    "tel-conn-home", node,
                    "add_probe() resource name computed at runtime — "
                    "the USE-method resource vocabulary is closed "
                    "(telemetry.saturation.RESOURCES); pass one of its "
                    "members as a literal")
            elif name_arg.value not in SATURATION_RESOURCES:
                yield ctx.finding(
                    "tel-conn-home", node,
                    f"add_probe() resource {name_arg.value!r} outside "
                    f"the closed vocabulary (telemetry.saturation."
                    f"RESOURCES) — capacity_report and the "
                    f"resource_util history series group by these "
                    f"names; additions are a reviewed vocabulary "
                    f"change, not a call-site invention")
    if conn_banned:
        for node in _factory_calls(ctx):
            name, _, _ = _metric_call_args(node)
            if name is not None and name.startswith(CONN_METRIC_PREFIX):
                yield ctx.finding(
                    "tel-conn-home", node,
                    f"connection metric {name!r} registered outside "
                    f"photon_ml_tpu/serving/http.py — the socket-"
                    f"lifecycle families have ONE writer (the "
                    f"ConnectionTracker); a second family double-counts "
                    f"connections in the fleet fold")


#: the shim's rule subset, in the legacy tool's documented order
#: (``tel-span-attr-cardinality`` and ``tel-retained-vocab`` are
#: engine-only — they postdate the legacy tool)
TELEMETRY_RULE_IDS = ("tel-print", "tel-perf-counter", "tel-metric-name",
                      "tel-registry", "tel-wall-clock", "tel-drift-home",
                      "tel-request-identity")
