"""One static-analysis engine behind every photon lint pass.

The repo grew two ad-hoc AST walkers (``tools/check_resilience_hygiene.py``,
``tools/check_telemetry_hygiene.py``) that each reimplemented file
discovery, AST walking and reporting. This module is the shared core they
— and the newer trace-safety / lock-discipline / project-consistency
passes — now plug into:

- **Rule registry**: a rule is a generator function registered with
  :func:`rule` (per-file, receives a :class:`FileContext`) or
  :func:`project_rule` (whole-tree, receives a :class:`Project` — for
  cross-file invariants like doc/catalog drift). Every rule has a stable
  id (``res-*``, ``tel-*``, ``trace-*``, ``lock-*``, ``obs-*``) that
  findings, ``--rules`` selection and suppression comments all use.
- **Findings**: ``path:line rule-id message`` (``Finding.render``), plus
  the legacy ``path:line: message`` spelling (``Finding.legacy``) the
  hygiene shims keep emitting, plus machine-readable JSON
  (:meth:`Report.to_json`).
- **Suppressions**: ``# photon-lint: disable=<rule-id>[,<rule-id>] --
  <reason>`` on the offending line silences that rule THERE; on a
  ``def``/``class`` line it covers the whole lexical body. The
  justification is mandatory — a suppression without one (or naming an
  unknown rule id) is itself a finding (``lint-suppression``), so every
  sanctioned violation carries its why in the source.

Run through ``tools/photon_lint.py`` (all passes) or the legacy shims
(their original rule subsets, unchanged output and exit codes). See
ANALYSIS.md for the rule catalog and conventions.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Callable, Iterable, Iterator, Mapping, Optional, Sequence

#: scopes a per-file rule may declare: "package" = photon_ml_tpu/ only (the
#: legacy hygiene rules — tools/ prints and sleeps on purpose), "all" =
#: photon_ml_tpu/ + tools/
SCOPES = ("package", "all")

#: directory prefixes the engine scans (relative to the repo root)
SCAN_PREFIXES = ("photon_ml_tpu", "tools")

PACKAGE_PREFIX = "photon_ml_tpu" + os.sep

#: the engine's own rule id for malformed suppression comments
SUPPRESSION_RULE_ID = "lint-suppression"

_SUPPRESS_RE = re.compile(
    r"#\s*photon-lint:\s*disable=([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
    r"(?:\s*--\s*(.*\S))?\s*$")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violation: where, which rule, and why it matters."""

    path: str
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line} {self.rule} {self.message}"

    def legacy(self) -> str:
        """The pre-engine hygiene-tool spelling (no rule id) — the two
        shim CLIs keep this byte-identical output format."""
        return f"{self.path}:{self.line}: {self.message}"

    def to_dict(self) -> dict:
        return {"path": self.path, "line": self.line, "rule": self.rule,
                "message": self.message}


@dataclasses.dataclass(frozen=True)
class Rule:
    """A registered pass: ``check`` yields :class:`Finding`\\ s."""

    id: str
    summary: str
    scope: str  # "package" | "all" | "project"
    check: Callable[..., Iterable[Finding]]

    @property
    def is_project(self) -> bool:
        return self.scope == "project"


_REGISTRY: dict[str, Rule] = {}


def rule(rule_id: str, summary: str, *, scope: str = "package"):
    """Register a per-file rule: ``fn(ctx: FileContext) -> Iterable[Finding]``."""
    if scope not in SCOPES:
        raise ValueError(f"scope must be one of {SCOPES}, got {scope!r}")

    def wrap(fn):
        if rule_id in _REGISTRY:
            raise ValueError(f"duplicate rule id {rule_id!r}")
        _REGISTRY[rule_id] = Rule(rule_id, summary, scope, fn)
        return fn

    return wrap


def project_rule(rule_id: str, summary: str):
    """Register a whole-tree rule: ``fn(project: Project) -> Iterable[Finding]``."""

    def wrap(fn):
        if rule_id in _REGISTRY:
            raise ValueError(f"duplicate rule id {rule_id!r}")
        _REGISTRY[rule_id] = Rule(rule_id, summary, "project", fn)
        return fn

    return wrap


def all_rules() -> dict[str, Rule]:
    """The full registry (imports the rule modules on first use)."""
    from photon_ml_tpu.analysis import (  # noqa: F401
        rules_concurrency,
        rules_project,
        rules_resilience,
        rules_telemetry,
        rules_trace,
    )

    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# per-file context
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Suppression:
    """One ``# photon-lint: disable=...`` comment. ``end_line`` extends the
    cover to a whole ``def``/``class`` body when the comment sits on its
    header line."""

    line: int
    ids: tuple[str, ...]
    reason: Optional[str]
    end_line: int

    def covers(self, finding: Finding) -> bool:
        return (finding.rule in self.ids
                and self.line <= finding.line <= self.end_line)


class FileContext:
    """One parsed source file plus the per-file facts rules share."""

    def __init__(self, rel_path: str, source: str):
        self.path = os.path.normpath(rel_path)
        self.source = source
        self.tree = ast.parse(source, filename=rel_path)
        self.lines = source.splitlines()
        # raw import facts; each rule resolves the aliases it cares about
        # (the resolution semantics are rule contracts — e.g. the numpy
        # rule intentionally treats `import jax.numpy` differently from
        # `import jax.numpy as jnp`)
        self.imports: list[tuple[str, Optional[str]]] = []
        self.from_imports: list[tuple[str, str, Optional[str]]] = []
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imports.append((a.name, a.asname))
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    self.from_imports.append((node.module or "", a.name,
                                              a.asname))

    @property
    def in_package(self) -> bool:
        return self.path.startswith(PACKAGE_PREFIX)

    def finding(self, rule_id: str, node: "ast.AST | int",
                message: str) -> Finding:
        line = node if isinstance(node, int) else node.lineno
        return Finding(self.path, line, rule_id, message)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def module_aliases(self, module: str) -> set[str]:
        """Names this file binds to ``module`` via ``import module [as x]``.
        Dotted modules are matched exactly and only contribute their
        ``as`` alias (a bare ``import a.b`` binds ``a``, not ``a.b``)."""
        out = set()
        for name, asname in self.imports:
            if name == module:
                if asname is not None:
                    out.add(asname)
                elif "." not in module:
                    out.add(module)
        return out

    def from_aliases(self, module: str, *names: str) -> set[str]:
        """Local names bound via ``from module import name [as x]``."""
        want = set(names)
        return {asname or name for mod, name, asname in self.from_imports
                if mod == module and name in want}

    def suppressions(self) -> list[Suppression]:
        """Parse suppression comments; header-line comments cover the whole
        ``def``/``class`` body."""
        regions: dict[int, int] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                regions[node.lineno] = node.end_lineno or node.lineno
        out = []
        for i, text in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            ids = tuple(s.strip() for s in m.group(1).split(","))
            out.append(Suppression(line=i, ids=ids, reason=m.group(2),
                                   end_line=regions.get(i, i)))
        return out


class Project:
    """Whole-tree view handed to project rules: every scanned
    :class:`FileContext` plus raw access to non-Python files (docs,
    tests) under the root."""

    def __init__(self, root: str, contexts: Mapping[str, FileContext]):
        self.root = root
        self.contexts = dict(contexts)

    def read_text(self, rel_path: str) -> Optional[str]:
        path = os.path.join(self.root, rel_path)
        if not os.path.exists(path):
            return None
        with open(path, encoding="utf-8") as f:
            return f.read()

    def iter_texts(self, rel_dir: str,
                   suffix: str = ".py") -> Iterator[tuple[str, str]]:
        """Yield ``(rel_path, text)`` for matching files under ``rel_dir``
        (sorted; used by coverage-style rules over tests/)."""
        base = os.path.join(self.root, rel_dir)
        if not os.path.isdir(base):
            return
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
            for name in sorted(filenames):
                if not name.endswith(suffix):
                    continue
                path = os.path.join(dirpath, name)
                rel = os.path.normpath(os.path.relpath(path, self.root))
                with open(path, encoding="utf-8") as f:
                    yield rel, f.read()


# ---------------------------------------------------------------------------
# discovery + execution
# ---------------------------------------------------------------------------


def iter_python_files(root: str,
                      prefixes: Sequence[str] = SCAN_PREFIXES,
                      ) -> Iterator[str]:
    """Relative paths of every ``.py`` under ``root/<prefix>`` in a
    deterministic (sorted) order."""
    for prefix in prefixes:
        base = os.path.join(root, prefix)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.normpath(os.path.relpath(
                        os.path.join(dirpath, name), root))


@dataclasses.dataclass
class Report:
    """One engine run: surviving findings + the suppression audit trail."""

    root: str
    rule_ids: tuple[str, ...]
    findings: list[Finding]
    suppressed: list[tuple[Finding, str]]

    @property
    def clean(self) -> bool:
        return not self.findings

    def to_json(self, indent: Optional[int] = 2) -> str:
        return json.dumps({
            "version": 1,
            "rules": list(self.rule_ids),
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [dict(f.to_dict(), reason=reason)
                           for f, reason in self.suppressed],
            "counts": {
                "findings": len(self.findings),
                "suppressed": len(self.suppressed),
            },
        }, indent=indent, sort_keys=True)


def _sort_key(f: Finding):
    return (f.path, f.line, f.rule, f.message)


def check_context(ctx: FileContext, rules: Sequence[Rule],
                  known_ids: Iterable[str],
                  ) -> tuple[list[Finding], list[tuple[Finding, str]]]:
    """Run per-file rules over one context and apply its suppressions.
    Returns ``(findings, suppressed)`` — malformed suppressions come back
    as ``lint-suppression`` findings."""
    raw: list[Finding] = []
    for r in rules:
        if r.is_project:
            continue
        if r.scope == "package" and not ctx.in_package:
            continue
        raw.extend(r.check(ctx))
    suppressions = ctx.suppressions()
    known = set(known_ids) | {SUPPRESSION_RULE_ID}
    for s in suppressions:
        if s.reason is None:
            raw.append(ctx.finding(
                SUPPRESSION_RULE_ID, s.line,
                "suppression without justification — write `# photon-lint: "
                "disable=<rule-id> -- <why this violation is sanctioned>`"))
        for rid in s.ids:
            if rid not in known:
                raw.append(ctx.finding(
                    SUPPRESSION_RULE_ID, s.line,
                    f"suppression names unknown rule id {rid!r} (see "
                    f"`python tools/photon_lint.py --list-rules`)"))
    findings: list[Finding] = []
    suppressed: list[tuple[Finding, str]] = []
    for f in raw:
        sup = next((s for s in suppressions
                    if s.reason is not None and s.covers(f)), None)
        if sup is None:
            findings.append(f)
        else:
            suppressed.append((f, sup.reason))
    return findings, suppressed


def run(root: str = ".", rule_ids: Optional[Sequence[str]] = None,
        prefixes: Sequence[str] = SCAN_PREFIXES) -> Report:
    """Run the selected rules (default: all) over ``root`` and report."""
    registry = all_rules()
    if rule_ids is None:
        selected = list(registry.values())
    else:
        unknown = [rid for rid in rule_ids if rid not in registry]
        if unknown:
            raise KeyError(f"unknown rule id(s) {unknown}; see --list-rules")
        selected = [registry[rid] for rid in rule_ids]
    contexts: dict[str, FileContext] = {}
    for rel in iter_python_files(root, prefixes):
        with open(os.path.join(root, rel), encoding="utf-8") as f:
            contexts[rel] = FileContext(rel, f.read())
    findings: list[Finding] = []
    suppressed: list[tuple[Finding, str]] = []
    for ctx in contexts.values():
        got, sup = check_context(ctx, selected, registry)
        findings.extend(got)
        suppressed.extend(sup)
    project = Project(root, contexts)
    by_path = {ctx.path: ctx.suppressions() for ctx in contexts.values()}
    for r in selected:
        if not r.is_project:
            continue
        for f in r.check(project):
            sup = next((s for s in by_path.get(f.path, ())
                        if s.reason is not None and s.covers(f)), None)
            if sup is None:
                findings.append(f)
            else:
                suppressed.append((f, sup.reason))
    findings.sort(key=_sort_key)
    suppressed.sort(key=lambda pair: _sort_key(pair[0]))
    return Report(root=root,
                  rule_ids=tuple(r.id for r in selected),
                  findings=findings, suppressed=suppressed)


def check_source(source: str, rel_path: str,
                 rule_ids: Sequence[str]) -> list[Finding]:
    """Run a rule subset over one in-memory source (the shim/fixture entry
    point; suppressions apply, project rules are not available here)."""
    registry = all_rules()
    ctx = FileContext(rel_path, source)
    findings, _ = check_context(ctx, [registry[rid] for rid in rule_ids],
                                registry)
    findings.sort(key=_sort_key)
    return findings
