"""Resilience hygiene rules (``res-*``) — the five passes that used to be
``tools/check_resilience_hygiene.py`` (that file is now a thin shim over
this module; its output format, exit codes and tier-1 test are unchanged).

All five are load-bearing for the resilience subsystem; each rule's
docstring below is the contract. Messages are byte-identical to the
pre-engine tool — the shim-compat test locks that.
"""

from __future__ import annotations

import ast
import os

from photon_ml_tpu.analysis.engine import FileContext, rule

#: the one module allowed to sleep (it owns backoff + injected stalls)
SLEEP_ALLOWED = {os.path.join("photon_ml_tpu", "resilience", "retry.py")}

#: the package prefix allowed to write model part-files (it owns the
#: atomic staged publish)
PART_WRITE_ALLOWED_PREFIX = os.path.join("photon_ml_tpu", "io") + os.sep

#: the one module allowed to spawn or signal processes (it owns the
#: fleet's process lifecycle)
PROCESS_ALLOWED = {os.path.join("photon_ml_tpu", "resilience",
                                "supervisor.py")}

#: the one module allowed to write/derive serving coefficient tables
#: (EntityCoefficientStore.build / apply_patch)
STORE_ALLOWED = {os.path.join("photon_ml_tpu", "serving", "store.py")}


@rule("res-bare-except",
      "no bare `except:` — it swallows KeyboardInterrupt/SystemExit")
def check_bare_except(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            yield ctx.finding(
                "res-bare-except", node,
                "bare `except:` — catch a type (it swallows "
                "KeyboardInterrupt/SystemExit)")


def _is_time_sleep(node: ast.AST, time_aliases: set[str],
                   sleep_names: set[str]) -> bool:
    if isinstance(node, ast.Attribute) and node.attr == "sleep":
        return isinstance(node.value, ast.Name) and node.value.id in time_aliases
    if isinstance(node, ast.Name):
        return node.id in sleep_names
    return False


@rule("res-sleep",
      "no time.sleep outside resilience/retry.py — one wait chokepoint")
def check_sleep(ctx: FileContext):
    if ctx.path in {os.path.normpath(p) for p in SLEEP_ALLOWED}:
        return
    time_aliases = ctx.module_aliases("time")
    sleep_names = ctx.from_aliases("time", "sleep")
    for node in ast.walk(ctx.tree):
        if _is_time_sleep(node, time_aliases, sleep_names):
            yield ctx.finding(
                "res-sleep", node,
                "time.sleep outside resilience/retry.py — route waits "
                "through the retry module so deadlines and the watchdog "
                "see them")


def _is_part_file_write(node: ast.AST) -> bool:
    """True for ``open(..)`` / ``write_avro_file(..)`` calls whose argument
    tree contains a ``part-*.avro`` string literal (the model part-file
    naming contract — ``os.path.join(..., "part-00000.avro")`` included)."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    name = fn.id if isinstance(fn, ast.Name) else (
        fn.attr if isinstance(fn, ast.Attribute) else None)
    if name not in ("open", "write_avro_file"):
        return False
    for sub in ast.walk(node):
        if (isinstance(sub, ast.Constant) and isinstance(sub.value, str)
                and "part-" in sub.value and sub.value.endswith(".avro")):
            # reads are fine: only flag an explicit write mode / the writer
            if name == "write_avro_file":
                return True
            mode = None
            if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
                mode = node.args[1].value
            for kw in node.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    mode = kw.value.value
            return isinstance(mode, str) and ("w" in mode or "a" in mode
                                              or "x" in mode)
    return False


@rule("res-part-write",
      "no model part-file writes outside io/ — atomic staged publish only")
def check_part_write(ctx: FileContext):
    if ctx.path.startswith(PART_WRITE_ALLOWED_PREFIX):
        return
    for node in ast.walk(ctx.tree):
        if _is_part_file_write(node):
            yield ctx.finding(
                "res-part-write", node,
                "model part-file write outside io/ — a bare part-*.avro "
                "write bypasses the atomic staged publish; route through "
                "io.model_io.save_game_model / io.pipeline.BackgroundSaver")


def _is_process_call(node: ast.AST, subprocess_aliases: set[str],
                     os_aliases: set[str], popen_names: set[str],
                     kill_names: set[str]) -> bool:
    """True for ``subprocess.Popen(..)`` / ``os.kill``/``os.killpg`` calls
    (module- and from-import aliases included)."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        if fn.attr == "Popen" and fn.value.id in subprocess_aliases:
            return True
        if fn.attr in ("kill", "killpg") and fn.value.id in os_aliases:
            return True
    if isinstance(fn, ast.Name):
        return fn.id in popen_names or fn.id in kill_names
    return False


@rule("res-process",
      "no subprocess.Popen/os.kill outside resilience/supervisor.py")
def check_process(ctx: FileContext):
    if ctx.path in {os.path.normpath(p) for p in PROCESS_ALLOWED}:
        return
    subprocess_aliases = ctx.module_aliases("subprocess")
    os_aliases = ctx.module_aliases("os")
    popen_names = ctx.from_aliases("subprocess", "Popen")
    kill_names = ctx.from_aliases("os", "kill", "killpg")
    for node in ast.walk(ctx.tree):
        if _is_process_call(node, subprocess_aliases, os_aliases,
                            popen_names, kill_names):
            yield ctx.finding(
                "res-process", node,
                "subprocess.Popen/os.kill outside resilience/supervisor.py "
                "— process lifecycle must stay visible to the fleet "
                "supervisor (an untracked child survives _kill_fleet or "
                "dies without a liveness signal); route process management "
                "through FleetSupervisor")


def _is_table_attr(node: ast.AST) -> bool:
    return isinstance(node, ast.Attribute) and node.attr == "table"


def _contains_table_attr(node: ast.AST) -> bool:
    return any(_is_table_attr(sub) for sub in ast.walk(node))


def _store_table_writes(tree: ast.AST) -> list[ast.AST]:
    """Nodes mutating/deriving a serving ``.table``: subscript or attribute
    assignment targets over ``<expr>.table``, and functional
    ``<expr>.table.at[...]`` updates."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if _is_table_attr(t):
                    out.append(t)
                elif isinstance(t, ast.Subscript) and _is_table_attr(t.value):
                    out.append(t)
        elif (isinstance(node, ast.Attribute) and node.attr == "at"
              and _is_table_attr(node.value)):
            out.append(node)
    return out


def _store_table_quant(tree: ast.AST) -> list[ast.AST]:
    """Quantization half of the table rule: an ``.astype(...)`` cast whose
    receiver involves ``.table``, or a ``*`` / ``/`` arithmetic expression
    with a ``.table`` operand (a scale multiply/divide) — either is an
    ad-hoc quantize/dequantize outside the store's one sanctioned format
    home (``quantize_rows`` / ``gather_rows``)."""
    out = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "astype"
                and _contains_table_attr(node.func.value)):
            out.append(node)
        elif (isinstance(node, ast.BinOp)
              and isinstance(node.op, (ast.Mult, ast.Div))
              and (_contains_table_attr(node.left)
                   or _contains_table_attr(node.right))):
            out.append(node)
    return out


@rule("res-table-home",
      "serving coefficient-table writes and quantize/dequantize math stay "
      "in serving/store.py")
def check_table_home(ctx: FileContext):
    if ctx.path in {os.path.normpath(p) for p in STORE_ALLOWED}:
        return
    for node in _store_table_writes(ctx.tree):
        yield ctx.finding(
            "res-table-home", node,
            "serving coefficient-table write outside serving/store.py — "
            "version tables are immutable (hot-swap/rollback and the "
            "delta path depend on it); derive new tables through "
            "EntityCoefficientStore.build/apply_patch")
    for node in _store_table_quant(ctx.tree):
        yield ctx.finding(
            "res-table-home", node,
            "quantize/dequantize of a serving .table array outside "
            "serving/store.py — table storage format (dtype + per-row "
            "scales) is a store.py-private contract; read rows through "
            "store.gather_rows / device_params")


#: the one module allowed to call crc32 (it owns identity bucketing:
#: entity→shard placement, request-log sampling, probe selection, fault
#: seeding all derive from its one hash)
SHARD_HOME = {os.path.join("photon_ml_tpu", "fleet", "sharding.py")}

#: crc32 over raw BYTES for Avro container integrity is a checksum, not
#: an identity bucket — the codec keeps its own call
SHARD_EXEMPT = {os.path.join("photon_ml_tpu", "io", "avro.py")}


def _is_crc32_call(node: ast.AST, zlib_aliases: set[str],
                   binascii_aliases: set[str],
                   crc_names: set[str]) -> bool:
    """True for ``zlib.crc32(..)`` / ``binascii.crc32(..)`` calls
    (module- and from-import aliases included)."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr == "crc32":
        return (isinstance(fn.value, ast.Name)
                and fn.value.id in zlib_aliases | binascii_aliases)
    if isinstance(fn, ast.Name):
        return fn.id in crc_names
    return False


#: the virtual-bucket count (``fleet.sharding.N_BUCKETS``); a literal
#: ``% 4096`` outside the home is ad-hoc bucket math
_N_BUCKETS_LITERAL = 4096


def _is_bucket_mod(node: ast.AST, bucket_names: set[str],
                   sharding_aliases: set[str]) -> bool:
    """True for a ``<expr> % 4096`` / ``<expr> % N_BUCKETS`` modulo — the
    virtual-bucket half of the placement hash recomputed outside the home
    (``N_BUCKETS`` matched via its from-import alias or as an attribute of
    an imported ``fleet.sharding`` module alias)."""
    if not (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod)):
        return False
    right = node.right
    if (isinstance(right, ast.Constant)
            and right.value == _N_BUCKETS_LITERAL):
        return True
    if isinstance(right, ast.Name) and right.id in bucket_names:
        return True
    return (isinstance(right, ast.Attribute)
            and right.attr == "N_BUCKETS"
            and isinstance(right.value, ast.Name)
            and right.value.id in sharding_aliases)


@rule("res-shard-home",
      "entity→shard hashing primitives (crc32 + virtual-bucket math) stay "
      "in fleet/sharding.py")
def check_shard_home(ctx: FileContext):
    if ctx.path in {os.path.normpath(p) for p in SHARD_HOME | SHARD_EXEMPT}:
        return
    zlib_aliases = ctx.module_aliases("zlib")
    binascii_aliases = ctx.module_aliases("binascii")
    crc_names = (ctx.from_aliases("zlib", "crc32")
                 | ctx.from_aliases("binascii", "crc32"))
    bucket_names = ctx.from_aliases("photon_ml_tpu.fleet.sharding",
                                    "N_BUCKETS")
    sharding_aliases = ctx.module_aliases("photon_ml_tpu.fleet.sharding")
    for node in ast.walk(ctx.tree):
        if _is_crc32_call(node, zlib_aliases, binascii_aliases, crc_names):
            yield ctx.finding(
                "res-shard-home", node,
                "crc32 call outside fleet/sharding.py — identity "
                "bucketing (entity→shard placement, id sampling) must "
                "come from the one hashing home or two components can "
                "silently disagree on which host owns an id; call "
                "fleet.sharding.shard_of_id/crc_bucket/stable_hash_u32")
        elif _is_bucket_mod(node, bucket_names, sharding_aliases):
            yield ctx.finding(
                "res-shard-home", node,
                "virtual-bucket modulo outside fleet/sharding.py — "
                "bucket→shard placement goes through the versioned "
                "ShardMap (id → bucket → shard); recomputing "
                "`% N_BUCKETS` elsewhere silently disagrees with a "
                "resharded map; call fleet.sharding.bucket_of_id/"
                "ShardMap.shard_of")


#: serving/ — the one package where every queue must be bounded (the
#: admission-control contract: overload sheds loudly, it never queues
#: forever)
SERVING_PREFIX = os.path.join("photon_ml_tpu", "serving") + os.sep


def _const_zero(node: ast.AST) -> bool:
    return (isinstance(node, ast.Constant)
            and isinstance(node.value, int) and node.value == 0)


def _has_bound(node: ast.Call, kwarg: str, pos: int) -> bool:
    """Does this constructor call carry a bound — ``kwarg=`` (non-zero
    when a constant) or a positional argument at ``pos``?"""
    for kw in node.keywords:
        if kw.arg == kwarg:
            return not _const_zero(kw.value)
    if len(node.args) > pos:
        return not _const_zero(node.args[pos])
    return False


def _fifo_attrs(tree: ast.AST) -> set[str]:
    """``self.<attr>`` names used FIFO-style: ``.pop(0)`` or
    ``.insert(0, ...)`` — a plain list serving as a queue."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        recv = node.func.value
        if not (isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "self"):
            continue
        if (node.func.attr in ("pop", "insert") and node.args
                and _const_zero(node.args[0])):
            out.add(recv.attr)
    return out


@rule("res-bounded-queue",
      "no unbounded deque()/queue.Queue()/list-as-queue construction "
      "inside serving/ — overload must shed, not queue forever")
def check_bounded_queue(ctx: FileContext):
    if not ctx.path.startswith(SERVING_PREFIX):
        return
    deque_names = ctx.from_aliases("collections", "deque")
    collections_aliases = ctx.module_aliases("collections")
    queue_cls_names = ctx.from_aliases("queue", "Queue", "LifoQueue",
                                       "PriorityQueue")
    simple_names = ctx.from_aliases("queue", "SimpleQueue")
    queue_aliases = ctx.module_aliases("queue")
    fifo = _fifo_attrs(ctx.tree)
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            fn = node.func
            is_deque = (
                (isinstance(fn, ast.Name) and fn.id in deque_names)
                or (isinstance(fn, ast.Attribute) and fn.attr == "deque"
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in collections_aliases))
            is_queue = (
                (isinstance(fn, ast.Name)
                 and fn.id in queue_cls_names)
                or (isinstance(fn, ast.Attribute)
                    and fn.attr in ("Queue", "LifoQueue", "PriorityQueue")
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in queue_aliases))
            is_simple = (
                (isinstance(fn, ast.Name) and fn.id in simple_names)
                or (isinstance(fn, ast.Attribute)
                    and fn.attr == "SimpleQueue"
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in queue_aliases))
            if is_deque and not _has_bound(node, "maxlen", 1):
                yield ctx.finding(
                    "res-bounded-queue", node,
                    "unbounded deque() in serving/ — a request queue with "
                    "no bound degrades overload into unbounded latency; "
                    "pass maxlen= or justify the explicit admission check "
                    "with a suppression")
            elif is_queue and not _has_bound(node, "maxsize", 0):
                yield ctx.finding(
                    "res-bounded-queue", node,
                    "unbounded queue.Queue() in serving/ — pass a "
                    "positive maxsize (or justify with a suppression)")
            elif is_simple:
                yield ctx.finding(
                    "res-bounded-queue", node,
                    "queue.SimpleQueue() in serving/ has no capacity "
                    "bound at all — use queue.Queue(maxsize=N)")
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            value = node.value
            is_empty_list = (isinstance(value, ast.List) and not value.elts
                             ) or (isinstance(value, ast.Call)
                                   and isinstance(value.func, ast.Name)
                                   and value.func.id == "list"
                                   and not value.args and not value.keywords)
            if not is_empty_list:
                continue
            for t in targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self" and t.attr in fifo):
                    yield ctx.finding(
                        "res-bounded-queue", t,
                        f"list-as-queue in serving/: self.{t.attr} is "
                        f"drained with pop(0)/insert(0, ..) but "
                        f"constructed with no bound — bound it or "
                        f"justify the bounding logic with a suppression")


#: the shim's rule subset, in the legacy tool's documented order
#: (``res-bounded-queue`` is engine-only — it postdates the legacy tool)
RESILIENCE_RULE_IDS = ("res-bare-except", "res-sleep", "res-part-write",
                       "res-process", "res-table-home")


#: the sanctioned request-log READ paths: the feedback joiner (the one
#: label-join surface) and the replay audit tool; reqlog.py itself owns
#: the reader it exports
REQLOG_READ_ALLOWED = {
    os.path.join("photon_ml_tpu", "serving", "reqlog.py"),
    os.path.join("photon_ml_tpu", "feedback", "joiner.py"),
    os.path.join("tools", "reqlog_replay.py"),
}


def _is_iter_reqlog_call(node: ast.AST, reader_names: set[str],
                         reqlog_aliases: set[str]) -> bool:
    """True for ``iter_reqlog(..)`` calls — by imported name or as an
    attribute on an alias of the reqlog (or serving) module."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Name) and f.id in reader_names:
        return True
    return (isinstance(f, ast.Attribute) and f.attr == "iter_reqlog"
            and isinstance(f.value, ast.Name)
            and f.value.id in reqlog_aliases)


@rule("res-reqlog-read-home",
      "request-log READS stay in feedback/joiner.py and "
      "tools/reqlog_replay.py", scope="all")
def check_reqlog_read_home(ctx: FileContext):
    if ctx.path in {os.path.normpath(p) for p in REQLOG_READ_ALLOWED}:
        return
    reader_names = (
        ctx.from_aliases("photon_ml_tpu.serving.reqlog", "iter_reqlog")
        | ctx.from_aliases("photon_ml_tpu.serving", "iter_reqlog"))
    reqlog_aliases = (
        ctx.module_aliases("photon_ml_tpu.serving.reqlog")
        | ctx.module_aliases("photon_ml_tpu.serving"))
    for node in ast.walk(ctx.tree):
        if _is_iter_reqlog_call(node, reader_names, reqlog_aliases):
            yield ctx.finding(
                "res-reqlog-read-home", node,
                "iter_reqlog call outside the sanctioned read paths — "
                "the log's schema, segment order and join/duplicate "
                "semantics are one contract owned by feedback/joiner.py "
                "(training joins) and tools/reqlog_replay.py (replay "
                "audits); a third reader silently forks that contract. "
                "Join through feedback.join_feedback instead")
