"""Per-entity data fingerprints: the refresh loop's change detector.

A refresh must answer one question per random-effect entity: *did this
entity's training data change since the model I am warm-starting from?*
The answer has to be cheap at "hundreds of millions of entities" scale and
independent of row order (file splits, shard merges and multi-file reads
reorder rows freely), so the fingerprint is an order-invariant combine of
per-row hashes, computed fully vectorized:

- each nonzero of the coordinate's feature shard contributes a mixed
  ``(column, value-bits)`` word, summed per row (a row's feature VECTOR is
  a set — duplicates accumulate identically in the reader);
- each row's feature sum is mixed with its label/offset/weight bits;
- each entity's fingerprint is the XOR of its mixed row hashes plus its
  row count (XOR alone would miss duplicated rows).

The manifest (``data-manifest.json``, written next to every published
model by the training drivers) maps RAW entity ids → fingerprints per
coordinate; raw ids are the stable identity across runs (dense ids are a
per-run artifact of vocabulary order). :func:`entity_delta` diffs two
manifests into the touched/carried split the incremental refit consumes.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu.game.data import GameData

#: the manifest's file name at a run-directory root (next to ``best/``)
MANIFEST_NAME = "data-manifest.json"

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer, vectorized (wrapping uint64 arithmetic)."""
    x = np.asarray(x, np.uint64).copy()
    x ^= x >> np.uint64(30)
    x *= np.uint64(0xBF58476D1CE4E5B9)
    x ^= x >> np.uint64(27)
    x *= np.uint64(0x94D049BB133111EB)
    x ^= x >> np.uint64(31)
    return x


def _f32_bits(a: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(a, np.float32).view(np.uint32).astype(
        np.uint64)


def entity_fingerprints(data: GameData, random_effect_type: str,
                        feature_shard_id: str) -> dict[int, str]:
    """``dense entity id → fingerprint`` over the entity's training rows.

    The fingerprint covers exactly what the entity's solve consumes: its
    rows' labels, offsets, weights and this shard's feature vectors.
    Row-order invariant (XOR combine) and partition invariant — the same
    rows under any file split fingerprint identically.
    """
    with np.errstate(over="ignore"):
        entities = data.id_columns[random_effect_type]
        shard = data.shards[feature_shard_id]
        n = data.n_samples
        # per-row feature content: sum of mixed (col, value) words
        contrib = _mix64((shard.cols.astype(np.uint64) + np.uint64(1))
                         * _GOLDEN ^ _mix64(_f32_bits(shard.vals)))
        feat = np.zeros(n, np.uint64)
        np.add.at(feat, shard.rows(), contrib)
        row_h = _mix64(
            feat
            ^ _mix64(_f32_bits(data.labels))
            ^ _mix64(_f32_bits(data.offsets) * _GOLDEN)
            ^ _mix64(_f32_bits(data.weights) + _GOLDEN))
        present = np.flatnonzero(entities >= 0)
        if not len(present):
            return {}
        order = np.argsort(entities[present], kind="stable")
        rows = present[order]
        ents = entities[rows]
        bound = np.empty(len(ents), bool)
        bound[0] = True
        np.not_equal(ents[1:], ents[:-1], out=bound[1:])
        seg_start = np.flatnonzero(bound)
        uniq = ents[seg_start]
        counts = np.diff(np.append(seg_start, len(ents)))
        agg = np.bitwise_xor.reduceat(_mix64(row_h[rows]), seg_start)
    return {int(e): f"{int(h):016x}:{int(c)}"
            for e, h, c in zip(uniq, agg, counts)}


def build_manifest(data: GameData,
                   re_coordinates: Mapping[str, tuple[str, str]],
                   vocabs: Mapping[str, Mapping[str, int]]) -> dict:
    """The run's data manifest: per random-effect coordinate, RAW entity id
    → fingerprint. ``re_coordinates`` maps coordinate id → (random effect
    type, feature shard id); coordinates sharing both reuse one
    fingerprint pass."""
    out: dict = {"version": 1, "nSamples": data.n_samples,
                 "coordinates": {}}
    cache: dict[tuple[str, str], dict[int, str]] = {}
    for cid, (re_type, shard_id) in re_coordinates.items():
        key = (re_type, shard_id)
        fps = cache.get(key)
        if fps is None:
            fps = cache[key] = entity_fingerprints(data, re_type, shard_id)
        reverse = {v: k for k, v in vocabs.get(re_type, {}).items()}
        out["coordinates"][cid] = {
            "randomEffectType": re_type,
            "featureShardId": shard_id,
            "entities": {reverse.get(e, str(e)): fp
                         for e, fp in fps.items()},
        }
    return out


def manifest_digest(manifest: dict) -> str:
    """Content digest of a manifest (the ``dataManifest`` lineage field in
    ``model-metadata.json``) — canonical-JSON blake2b."""
    return hashlib.blake2b(
        json.dumps(manifest, sort_keys=True).encode(), digest_size=16
    ).hexdigest()


def save_manifest(path: str, manifest: dict) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)


def load_manifest(path: str) -> Optional[dict]:
    """The manifest at ``path``, or None when absent (a parent run that
    pre-dates manifests: the refresh then treats EVERY entity as touched —
    a correct, if cold, refresh)."""
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def manifest_path_for(model_dir: str) -> str:
    """The manifest location for a resolved model dir: at the RUN root
    (the manifest describes the run's training data; ``best/`` and
    ``all/config-i`` are siblings under it)."""
    model_dir = os.path.normpath(model_dir)
    root = (os.path.dirname(model_dir)
            if os.path.basename(model_dir) == "best" else model_dir)
    return os.path.join(root, MANIFEST_NAME)


@dataclasses.dataclass(frozen=True)
class EntityDelta:
    """The touched/carried split of one coordinate's entities (raw ids).

    ``touched``: entities whose fingerprint changed, plus entities new to
    this run — these re-solve. ``carried``: entities whose data is
    unchanged, plus entities with no data this run — their coefficients
    carry forward untouched.
    """

    touched: tuple[str, ...]
    carried: tuple[str, ...]


def entity_delta(previous: Optional[Mapping[str, str]],
                 current: Mapping[str, str]) -> EntityDelta:
    """Diff two per-entity fingerprint maps (raw id → fingerprint).
    ``previous=None`` (no manifest recorded) touches everything."""
    if previous is None:
        return EntityDelta(touched=tuple(sorted(current)), carried=())
    touched = [raw for raw, fp in current.items()
               if previous.get(raw) != fp]
    carried = [raw for raw, fp in previous.items()
               if raw not in current or current[raw] == fp]
    return EntityDelta(touched=tuple(sorted(touched)),
                       carried=tuple(sorted(carried)))


def coordinate_deltas(previous_manifest: Optional[dict],
                      current_manifest: dict) -> dict[str, EntityDelta]:
    """Per-coordinate :func:`entity_delta` between two manifests. A
    coordinate absent from the previous manifest (renamed, added) touches
    all of its entities."""
    out = {}
    prev_coords = (previous_manifest or {}).get("coordinates", {})
    for cid, info in current_manifest["coordinates"].items():
        prev = prev_coords.get(cid)
        prev_entities = None
        if prev is not None and \
                prev.get("randomEffectType") == info["randomEffectType"] \
                and prev.get("featureShardId") == info["featureShardId"]:
            prev_entities = prev.get("entities", {})
        out[cid] = entity_delta(prev_entities, info["entities"])
    return out
